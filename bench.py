#!/usr/bin/env python
"""Headline benchmark: batched ECDSA-P256 verification throughput.

Prints ONE JSON line:
  {"metric": "ecdsa_p256_verify_throughput", "value": <verifies/s on the
   accelerator>, "unit": "verifies/s", "vs_baseline": <x over the
   single-core CPU software path>}

Baseline config #1 (BASELINE.md): SW BCCSP ECDSA-P256 verify over 10k
pre-generated (msg, sig, pubkey) triples. The CPU baseline is measured
here with the `cryptography` package (OpenSSL) — the same order as Go
crypto/ecdsa (~1e4/s/core), i.e. an honest stand-in for the reference's
bccsp/sw hot loop. North-star target: >= 50k verifies/s per host.
"""

import json
import os
import sys
import time

os.environ.setdefault("FABRIC_TPU_CIOS_UNROLL", "1")

import numpy as np

from fabric_tpu.utils.jaxcache import enable_compile_cache

enable_compile_cache()


def gen_triples(n, num_keys=8):
    """(key, der_sig, digest) triples signed with the fast OpenSSL path,
    normalized to low-S like the reference signer."""
    import hashlib

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    from fabric_tpu.crypto import der, p256
    from fabric_tpu.crypto.bccsp import ECDSAPublicKey

    keys = []
    for _ in range(num_keys):
        sk = ec.generate_private_key(ec.SECP256R1())
        nums = sk.public_key().public_numbers()
        keys.append((sk, ECDSAPublicKey(nums.x, nums.y)))

    triples = []
    for i in range(n):
        sk, pub = keys[i % num_keys]
        msg = f"benchmark tx payload {i}".encode() * 8
        digest = hashlib.sha256(msg).digest()
        r, s = decode_dss_signature(sk.sign(msg, ec.ECDSA(hashes.SHA256())))
        if not p256.is_low_s(s):
            s = p256.N - s
        triples.append((pub, der.marshal_signature(r, s), digest))
    return triples


def bench_cpu_baseline(triples, budget_s=2.0):
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed,
        encode_dss_signature,
    )

    from fabric_tpu.crypto import der as der_mod

    pubkeys = {}
    count = 0
    start = time.perf_counter()
    while time.perf_counter() - start < budget_s:
        pub, sig, digest = triples[count % len(triples)]
        key = pubkeys.get(id(pub))
        if key is None:
            key = ec.EllipticCurvePublicNumbers(
                pub.x, pub.y, ec.SECP256R1()
            ).public_key()
            pubkeys[id(pub)] = key
        r, s = der_mod.unmarshal_signature(sig)
        try:
            key.verify(
                encode_dss_signature(r, s),
                digest,
                ec.ECDSA(Prehashed(hashes.SHA256())),
            )
        except InvalidSignature:
            raise RuntimeError("benchmark signature should verify")
        count += 1
    return count / (time.perf_counter() - start)


def main():
    n = int(os.environ.get("BENCH_N", "16384"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))

    import jax

    from fabric_tpu.crypto.tpu_provider import TPUProvider

    triples = gen_triples(n)
    keys = [t[0] for t in triples]
    sigs = [t[1] for t in triples]
    digests = [t[2] for t in triples]

    prov = TPUProvider()
    # warmup / compile
    out = prov.batch_verify(keys, sigs, digests)
    if not all(out):
        raise RuntimeError("verification failed in warmup — kernel bug")

    start = time.perf_counter()
    for _ in range(iters):
        prov.batch_verify(keys, sigs, digests)
    device_rate = n * iters / (time.perf_counter() - start)

    cpu_rate = bench_cpu_baseline(triples)

    print(
        json.dumps(
            {
                "metric": "ecdsa_p256_verify_throughput",
                "value": round(device_rate, 1),
                "unit": "verifies/s",
                "vs_baseline": round(device_rate / cpu_rate, 2),
                "detail": {
                    "batch": n,
                    "iters": iters,
                    "cpu_baseline_verifies_per_s": round(cpu_rate, 1),
                    "device": str(jax.devices()[0]),
                    "target_verifies_per_s": 50000,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
