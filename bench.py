#!/usr/bin/env python
"""Benchmarks for every BASELINE.md config, printed as ONE JSON line.

Headline metric (BASELINE config #1): batched ECDSA-P256 verification
throughput on the accelerator vs the single-core OpenSSL software path.
The `detail.configs` object carries the measured numbers for configs
#2-#5:

  block_1k   — 1k-tx 2-of-3 endorsement block through the full
               BlockValidator: TPU provider vs SW provider ms/block,
               bit-exact TRANSACTIONS_FILTER asserted (config #2).  The
               SW column is the OpenSSL-backed provider (the reference
               SW BCCSP's speed class), NOT the pure-Python oracle.
  idemix     — batched Idemix verify: the hostbn->scheme backend
               ladder per-rung ms/sig at batch 8/64/256, plus the
               device Ate2 pairing column, all vs the scheme oracle
               (config #3).
  mvcc_5k    — 5k-tx MVCC validate-and-prepare, ms/block (config #4).
  multi_4ch  — 4 channels x 2k-tx blocks in one channel-axis device
               step, aggregate tx/s (config #5; sharding across chips is
               validated on the virtual CPU mesh by dryrun_multichip —
               the bench machine has one chip).
  batcher_4ch_small — P7 coalescing: concurrent SMALL blocks across
               channels, direct per-channel launches vs the shared
               VerifyBatcher (launches + lanes/launch reported).

Output discipline (hardened after round 4, where one UNAVAILABLE raise at
first device dispatch produced rc=1 and zero data):
- the CPU columns are measured FIRST and a complete JSON line is emitted
  before the device is touched at all;
- the device is reached only through the bounded probe
  (utils/deviceprobe) — a dead tunnel records device="unavailable" plus
  an error field and every config still reports its CPU column;
- device dispatches retry with backoff and degrade to the software path
  inside TPUProvider (degraded runs are labeled, never mistaken for
  device numbers);
- a watchdog thread re-emits the latest line and exits 0 if anything
  hangs past BENCH_BUDGET_S + BENCH_WATCHDOG_GRACE_S;
- the line is re-emitted after every config completes or fails, so a
  driver that kills the process mid-run still captures the latest
  complete line.  The last line is the most complete.
BENCH_BUDGET_S (default 1500) is the wall-clock budget: configs that
would start after the deadline are recorded as skipped.  Heavy configs
can be skipped entirely with BENCH_HEADLINE_ONLY=1.
"""

import json
import os
import sys
import time

os.environ.setdefault("FABRIC_TPU_CIOS_UNROLL", "1")

import numpy as np

from fabric_tpu.utils.jaxcache import enable_compile_cache

enable_compile_cache()


def gen_triples(n, num_keys=8):
    """(key, der_sig, digest) triples signed through the SW provider's
    ACTIVE EC backend (fastec when cryptography is installed, else the
    vectorized hostec tier), normalized to low-S like the reference
    signer.  Never the oracle: its ~5 signs/s would eat the budget."""
    import hashlib

    from fabric_tpu.crypto import der
    from fabric_tpu.crypto.bccsp import (
        ECDSAPublicKey,
        ec_backend,
        ec_backend_name,
    )

    ec = ec_backend()
    if ec_backend_name() == "p256":  # oracle pinned: sign via hostec
        from fabric_tpu.crypto import hostec as ec
    keys = [ec.generate_keypair() for _ in range(num_keys)]
    triples = []
    for i in range(n):
        kp = keys[i % num_keys]
        msg = f"benchmark tx payload {i}".encode() * 8
        digest = hashlib.sha256(msg).digest()
        r, s = ec.sign_digest(kp.priv, digest)
        triples.append(
            (ECDSAPublicKey(*kp.pub), der.marshal_signature(r, s), digest)
        )
    return triples


def bench_obs_overhead(triples, n_lanes=4096, passes=2):
    """The fabobs acceptance microbench: the 4096-lane host verify with
    the obs registry disabled vs enabled, best-of-``passes`` each,
    interleaved D E D E so background drift hits both modes equally.
    The disabled mode must cost <= 2% over the pre-instrumentation
    baseline — disabled obs is one module-global load per obs point, so
    the honest comparison here is disabled-vs-enabled on the SAME
    binary (recorded in NOTES_BUILD next to the pre-PR absolute)."""
    from fabric_tpu.common import fabobs
    from fabric_tpu.crypto.bccsp import SoftwareProvider, ec_backend_name

    lanes = triples[:n_lanes]
    keys = [t[0] for t in lanes]
    sigs = [t[1] for t in lanes]
    digests = [t[2] for t in lanes]
    sw = SoftwareProvider()
    prev = fabobs.active()
    times = {"disabled": [], "enabled": []}
    try:
        sw.batch_verify(keys[:256], sigs[:256], digests[:256])  # warm pools
        for _ in range(passes):
            for mode in ("disabled", "enabled"):
                if mode == "disabled":
                    fabobs.disable()
                else:
                    fabobs.enable()
                t0 = time.perf_counter()
                mask = sw.batch_verify(keys, sigs, digests)
                times[mode].append(time.perf_counter() - t0)
                if not all(mask):
                    raise RuntimeError("overhead bench lanes must verify")
    finally:
        with fabobs._OBS_LOCK:
            fabobs._OBS = prev
    dis, ena = min(times["disabled"]), min(times["enabled"])
    return {
        "backend": ec_backend_name(),
        "lanes": len(lanes),
        "passes": passes,
        "disabled_s": round(dis, 4),
        "enabled_s": round(ena, 4),
        "disabled_verifies_per_s": round(len(lanes) / dis, 1),
        "enabled_verifies_per_s": round(len(lanes) / ena, 1),
        "enabled_overhead_pct": round((ena - dis) / dis * 100.0, 2),
    }


def bench_cpu_baseline(triples, budget_s=2.0):
    """Single-core CPU column: the ACTUAL SoftwareProvider verify path
    (DER parse + low-S gate + OpenSSL curve math), i.e. the same code the
    validator runs when no accelerator is present — so detail.sw_ec_backend
    labels exactly what was measured."""
    from fabric_tpu.crypto.bccsp import SoftwareProvider

    sw = SoftwareProvider()
    count = 0
    start = time.perf_counter()
    while time.perf_counter() - start < budget_s:
        pub, sig, digest = triples[count % len(triples)]
        if not sw.verify(pub, sig, digest):
            raise RuntimeError("benchmark signature should verify")
        count += 1
    return count / (time.perf_counter() - start)


# The bounded OUT-OF-PROCESS device probe lives in utils/deviceprobe
# (round-5 postmortem: the in-process daemon-thread probe timed out but
# left the thread wedged inside backend init, and the verdict was
# re-derived per call; the subprocess probe gets a HARD kernel-enforced
# timeout and a per-run cached verdict).  bench.py is its batch-entry
# consumer — the library path keeps the cheap in-process probe.


def bench_host_ladder(triples, budget_s=None):
    """hostec vs hostec_np verifies/s at 1k/4k/16k lanes — the host
    backend-ladder column the numpy tier is judged by.  Both engines
    run their production sharded entrypoints (process pools warm, one
    timed pass per size) on the SAME parsed batch; the 4096-lane ratio
    is the acceptance number."""
    from fabric_tpu.crypto import hostec
    from fabric_tpu.crypto.bccsp import SoftwareProvider

    if budget_s is None:
        budget_s = float(os.environ.get("BENCH_LADDER_BUDGET_S", "150"))
    try:
        from fabric_tpu.crypto import hostec_np
    except Exception:  # pragma: no cover - broken partial install
        hostec_np = None
    have_np = hostec_np is not None and hostec_np.HAVE_NUMPY

    sw = SoftwareProvider()
    out = {"engines": ["hostec"] + (["hostec_np"] if have_np else [])}
    if not have_np:
        out["hostec_np"] = {"skipped": "numpy not installed"}
    else:
        hostec_np.warm_tables()  # one-time comb build out of the timing
    start = time.monotonic()
    sizes = [n for n in (1024, 4096, 16384) if n <= len(triples)]
    if not sizes:
        out["skipped"] = (
            f"BENCH_N={len(triples)} below the smallest ladder size"
        )
        return out
    # one DER parse of the largest batch; the smaller sizes are strict
    # prefixes of it
    sub = triples[: sizes[-1]]
    parsed = sw._parse_lanes(
        [t[0] for t in sub], [t[1] for t in sub], [t[2] for t in sub]
    )
    for lanes_n in sizes:
        out[str(lanes_n)] = {}
    engines = [("hostec", hostec)]
    if have_np:
        engines.append(("hostec_np", hostec_np))
    # engine-major: exactly ONE engine's process pool is alive at a
    # time (on a 2-vCPU box two pools' workers thrash each other), and
    # each engine pays its pool boot once, untimed.  The warm pass uses
    # the LARGEST size: hostec_np only touches its pool from
    # MIN_POOL_LANES lanes up, so a small warm batch would leave the
    # spawn cost inside the first big timed pass.
    for name, mod in engines:
        if time.monotonic() - start > budget_s:
            # don't pay an engine's warm pass (pool boot + a full
            # largest-size verify) when every timed pass would be
            # skipped anyway
            for lanes_n in sizes:
                out[str(lanes_n)][name] = "skipped: ladder budget exhausted"
            continue
        try:
            mod.verify_parsed_batch_sharded(parsed)()
            for lanes_n in sizes:
                if time.monotonic() - start > budget_s:
                    out[str(lanes_n)][name] = (
                        "skipped: ladder budget exhausted"
                    )
                    continue
                # best of two passes: this box's wall clock is noisy
                # enough (shared gVisor host) that one pass swings 1.5x
                best = None
                for _pass in range(2):
                    t0 = time.perf_counter()
                    verdicts = mod.verify_parsed_batch_sharded(
                        parsed[:lanes_n]
                    )()
                    dt = time.perf_counter() - t0
                    if not all(verdicts):
                        raise RuntimeError(
                            f"{name}: benchmark sig rejected"
                        )
                    best = dt if best is None else min(best, dt)
                    if time.monotonic() - start > budget_s:
                        break
                out[str(lanes_n)][name] = round(lanes_n / best, 1)
        finally:
            # a raise mid-pass must not leave this engine's workers
            # alive to compete with every later bench config
            mod.shutdown_pool()
    for lanes_n in sizes:
        row = out[str(lanes_n)]
        if (
            have_np
            and isinstance(row.get("hostec"), float)
            and isinstance(row.get("hostec_np"), float)
        ):
            row["np_speedup"] = round(row["hostec_np"] / row["hostec"], 2)
    r4096 = out.get("4096", {})
    if isinstance(r4096, dict) and "np_speedup" in r4096:
        out["acceptance_ratio_4096"] = r4096["np_speedup"]
    return out


def bench_headline_device(triples, iters):
    """Device half of config #1. Returns (device_rate, degraded) — the
    caller already owns the CPU column. Any raise is caught by main()
    and recorded as an error field, never rc=1 (round-4 postmortem)."""
    from fabric_tpu.crypto.tpu_provider import TPUProvider

    n = len(triples)
    keys = [t[0] for t in triples]
    sigs = [t[1] for t in triples]
    digests = [t[2] for t in triples]

    prov = TPUProvider()
    out = prov.batch_verify(keys, sigs, digests)
    if not all(out):
        raise RuntimeError("verification failed in warmup — kernel bug")
    if TPUProvider.degraded:
        # the warmup batch was actually served by the software fallback:
        # there is no device column to measure
        return 0.0, True

    # depth-3 software pipeline (the peer's P4 discipline, one deeper):
    # keep up to two launches in flight so the tunnel's per-launch RTT
    # hides behind device compute of the neighbours
    from collections import deque

    in_flight = max(int(os.environ.get("BENCH_DEPTH", "3")) - 1, 0)

    def timed_pass() -> float:
        start = time.perf_counter()
        pending: "deque" = deque()
        for _ in range(iters):
            pending.append(prov.batch_verify_async(keys, sigs, digests))
            while len(pending) > in_flight:
                if not all(pending.popleft()()):
                    raise RuntimeError("verification failed mid-bench")
        while pending:
            if not all(pending.popleft()()):
                raise RuntimeError("verification failed mid-bench")
        return n * iters / (time.perf_counter() - start)

    # best of three passes (~2.5s each): the device rate is stable but
    # the tunnel's RTT is not — transient stalls mid-pass would
    # misreport the kernel (same-day spread without this: 43-90k)
    device_rate = max(timed_pass() for _ in range(3))
    return device_rate, TPUProvider.degraded


# ----------------------------------------------------------------------
# shared network fixture for configs #2 and #5
# ----------------------------------------------------------------------


class _Net:
    def __init__(self):
        from fabric_tpu.crypto.bccsp import SoftwareProvider
        from fabric_tpu.msp.cryptogen import generate_org
        from fabric_tpu.msp.identity import MSPManager
        from fabric_tpu.msp.signer import SigningIdentity
        from fabric_tpu.policy import from_dsl
        from fabric_tpu.validation.validator import (
            ChaincodeDefinition,
            ChaincodeRegistry,
        )

        self.sw = SoftwareProvider()
        org1 = generate_org("org1.bench", "Org1MSP")
        org2 = generate_org("org2.bench", "Org2MSP")
        org3 = generate_org("org3.bench", "Org3MSP")
        self.mgr = MSPManager(
            [o.msp(provider=self.sw) for o in (org1, org2, org3)]
        )
        # 2-of-3 endorsement policy (BASELINE config #2)
        self.registry = ChaincodeRegistry(
            [
                ChaincodeDefinition(
                    "benchcc",
                    from_dsl(
                        "OutOf(2,'Org1MSP.member','Org2MSP.member',"
                        "'Org3MSP.member')"
                    ),
                )
            ]
        )
        self.client = SigningIdentity(org1.users[0], self.sw)
        self.endorsers = [
            SigningIdentity(o.peers[0], self.sw) for o in (org1, org2)
        ]

    def make_block(self, channel, n_txs, number=1):
        from fabric_tpu.endorser import (
            create_proposal,
            create_signed_tx,
            endorse_proposal,
        )
        from fabric_tpu.ledger import rwset as rw
        from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset
        from fabric_tpu.protos import protoutil

        block = protoutil.new_block(number, b"\x33" * 32)
        for i in range(n_txs):
            results = serialize_tx_rwset(
                rw.TxRwSet(
                    (
                        rw.NsRwSet(
                            "benchcc",
                            (),
                            (rw.KVWrite(f"k{i}", False, b"v"),),
                        ),
                    )
                )
            )
            bundle = create_proposal(
                self.client, channel, "benchcc", [b"invoke", b"%d" % i]
            )
            responses = [
                endorse_proposal(bundle, e, results) for e in self.endorsers
            ]
            env = create_signed_tx(bundle, self.client, responses)
            block.data.data.append(env.SerializeToString())
        protoutil.seal_block(block)
        return block

    def validator(self, channel, provider):
        from fabric_tpu.validation.validator import BlockValidator

        return BlockValidator(channel, self.mgr, provider, self.registry)


def bench_block_1k(net, device_ok=True, n_txs=1000):
    """Config #2: full validator ms/block, TPU vs SW provider, bit-exact
    masks (reference timers v20/validator.go:261-262)."""
    from fabric_tpu.protos import common_pb2

    block = net.make_block("benchchan", n_txs)

    def run(provider):
        b = common_pb2.Block()
        b.CopyFrom(block)
        v = net.validator("benchchan", provider)
        start = time.perf_counter()
        flags = v.validate(b)
        return (time.perf_counter() - start) * 1000.0, flags.tobytes()

    (sw_ms, sw_mask) = min(run(net.sw), run(net.sw))
    if set(sw_mask) != {0}:
        raise RuntimeError("config #2 expected all-VALID block")
    if not device_ok:
        return {
            "txs": n_txs,
            "cpu_ms_per_block": round(sw_ms, 1),
            "error": "device unavailable — CPU column only",
        }
    from fabric_tpu.crypto.tpu_provider import TPUProvider

    tpu_prov = TPUProvider()
    run(tpu_prov)  # compile warmup
    # best of two measured runs, like the headline: per-launch tunnel
    # RTT is noisy (same-day spread 190-500 ms/block) while the actual
    # device+host work is stable at ~190-210 ms
    (tpu_ms, tpu_mask) = min(run(tpu_prov), run(tpu_prov))
    if tpu_mask != sw_mask:
        raise RuntimeError("config #2 mask mismatch TPU vs SW")
    out = {
        "txs": n_txs,
        "tpu_ms_per_block": round(tpu_ms, 1),
        "cpu_ms_per_block": round(sw_ms, 1),
        "speedup": round(sw_ms / tpu_ms, 2),
        "mask_bit_exact": True,
    }
    if TPUProvider.degraded:
        out["error"] = (
            "device degraded mid-config: some lanes fell back to the "
            "software path; tpu_ms is not a pure device number"
        )
    return out


def bench_idemix(device_ok=True, n_sigs=None):
    """Config #3: batched Idemix verify across the idemix backend
    ladder (hostbn numpy lanes -> scheme oracle; crypto/bccsp.py
    IDEMIX_TIERS), per-rung ms/sig at batch 8/64/256 — mirroring the
    host_ladder/sw_ec_backend reporting discipline so an oracle-rung
    fallback can never masquerade as a hostbn number — plus the device
    Ate2 pairing column when a chip answers.  Setup is
    cryptography-free (ALG_NO_REVOCATION with an unsigned CRI, which
    Ver with rev_pk=None never reads), so this config measures on any
    box; host signature GENERATION costs ~1-2s each, so lanes are
    tiled from 8 unique signatures."""
    import random

    from fabric_tpu import idemix
    from fabric_tpu.crypto import fp256bn as bncurve
    from fabric_tpu.crypto.bccsp import (
        available_idemix_backends,
        idemix_backend_name,
    )
    from fabric_tpu.idemix.batch import verify_signatures_batch
    from fabric_tpu.protos import idemix_pb2

    if n_sigs is None:
        n_sigs = int(os.environ.get("BENCH_IDEMIX_SIGS", "64"))
    rng = random.Random(1234)
    attrs = ["OU", "Role", "EnrollmentID", "RevocationHandle"]
    rh_index = 3
    ik = idemix.new_issuer_key(attrs, rng)
    sk = bncurve.rand_mod_order(rng)
    nonce = bncurve.big_to_bytes(bncurve.rand_mod_order(rng))
    req = idemix.new_cred_request(sk, nonce, ik.ipk, rng)
    cred = idemix.new_credential(ik, req, [11, 22, 33, 44], rng)
    cri = idemix_pb2.CredentialRevocationInformation()
    cri.revocation_alg = idemix.ALG_NO_REVOCATION
    disclosure = [0, 0, 0, 0]
    msg = b"idemix bench message"
    uniq = []
    for _ in range(min(n_sigs, 8)):
        nym, r_nym = idemix.make_nym(sk, ik.ipk, rng)
        uniq.append(
            idemix.new_signature(
                cred, sk, nym, r_nym, ik.ipk, disclosure, msg, rh_index, cri, rng
            )
        )

    def batch_args(count):
        sigs_c = [uniq[i % len(uniq)] for i in range(count)]
        return (
            sigs_c,
            [disclosure] * count,
            ik.ipk,
            [msg] * count,
            [[None, None, None, None]] * count,
            rh_index,
        )

    def run(device, count):
        start = time.perf_counter()
        out = verify_signatures_batch(
            *batch_args(count), device_pairing=device
        )
        return (time.perf_counter() - start) * 1000.0, out

    # the oracle column is the PURE-HOST scheme rung
    # (scheme.verify_signature — the reference's signature.go Ver path),
    # timed over a small sample (it runs ~1s/sig here); one warm-up
    # verify amortizes one-time table builds.
    n_host = min(int(os.environ.get("BENCH_IDEMIX_ORACLE_SIGS", "4")), n_sigs)
    verify_signatures_batch(*batch_args(1), backend="scheme")  # warm-up
    start = time.perf_counter()
    host_out = verify_signatures_batch(*batch_args(n_host), backend="scheme")
    host_ms = (time.perf_counter() - start) * 1000.0
    if not all(host_out):
        raise RuntimeError("config #3 host verification failed")
    oracle_ms_per_sig = host_ms / n_host

    active = idemix_backend_name()
    result = {
        "sigs": n_sigs,
        "idemix_backend": active,
        "idemix_tiers_available": available_idemix_backends(),
        "host_ms_per_sig": round(oracle_ms_per_sig, 1),
        "host_sample_sigs": n_host,
        "reference_cpu_ms_per_sig_class": "5-20",
        "note": "host column is the PURE-host oracle (the scheme rung, "
        "python bignum) — honest about THIS implementation but ~2 "
        "orders slower than the reference's compiled amcl Go Ver "
        "(idemix/signature.go:243; reference_cpu_ms_per_sig_class "
        "cites that class: a few pairings at ~1-5ms each on modern "
        "x86). Read the hostbn ladder and device columns against BOTH "
        "numbers. Lanes are tiled from 8 unique signatures.",
    }
    if active == "scheme":
        # never let an oracle-rung run pass as a batch-engine number
        result["idemix_backend_warning"] = (
            "running on the scheme ORACLE rung (~1 s/sig) — the hostbn "
            "numpy tier is unavailable; batch columns are NOT "
            "comparable to hostbn numbers"
        )
        print(
            "bench: WARNING: idemix backend is the scheme oracle rung; "
            "batch verify will be ~2 orders of magnitude slow",
            file=sys.stderr,
            flush=True,
        )

    # per-rung ladder: hostbn ms/sig at batch 8/64/256 (production
    # entrypoint: the pool shards batches >= its threshold), masks
    # asserted against the oracle sample each size
    ladder = {"oracle_ms_per_sig": round(oracle_ms_per_sig, 1)}
    if available_idemix_backends().get("hostbn"):
        from fabric_tpu.crypto import hostbn
        from fabric_tpu.idemix.scheme import ecp2_from_proto

        hostbn.warm_schedules(ecp2_from_proto(ik.ipk.w))  # untimed build
        sizes = [
            int(s)
            for s in os.environ.get(
                "BENCH_IDEMIX_LADDER", "8,64,256"
            ).split(",")
            if s.strip()
        ]
        for size in sizes:
            # acceptance sizes (>= 64, where the pool shards) get best
            # of two passes: the first pays the cold worker spawn +
            # per-worker schedule build, and this box's wall clock is
            # noisy (host_ladder's discipline)
            ms = None
            for _pass in range(2 if size >= 64 else 1):
                start = time.perf_counter()
                out = verify_signatures_batch(
                    *batch_args(size), backend="hostbn"
                )
                elapsed = (time.perf_counter() - start) * 1000.0
                ms = elapsed if ms is None else min(ms, elapsed)
                if out[:n_host] != host_out[: min(n_host, size)] or not all(
                    out
                ):
                    raise RuntimeError(
                        f"config #3 hostbn/oracle mask mismatch at {size}"
                    )
            ladder[str(size)] = {"hostbn_ms_per_sig": round(ms / size, 1)}
            if size >= 64:
                ladder[str(size)]["speedup_vs_oracle"] = round(
                    oracle_ms_per_sig / (ms / size), 1
                )
        from fabric_tpu.idemix import batch as idemix_batch

        idemix_batch.shutdown_pool()
    else:
        ladder["hostbn"] = "skipped (numpy not installed)"
    result["ladder"] = ladder
    # The device Ate2 kernel's first compile is ~3.5 min on the TPU
    # (then cached; this bench's issuer key is seed-fixed so the program
    # caches across runs). BENCH_IDEMIX_DEVICE=0 opts out.
    if device_ok and os.environ.get("BENCH_IDEMIX_DEVICE", "1") == "1":
        run(True, n_sigs)  # compile warmup
        dev_ms, dev_out = run(True, n_sigs)
        if dev_out[:n_host] != host_out or not all(dev_out):
            raise RuntimeError("config #3 device/host mismatch")
        result["device_ms_per_sig"] = round(dev_ms / n_sigs, 1)
        result["speedup"] = round(
            (host_ms / n_host) / (dev_ms / n_sigs), 1
        )
        result["mask_bit_exact"] = True
    elif not device_ok:
        result["device"] = "skipped (device unavailable)"
    else:
        result["device"] = "skipped (BENCH_IDEMIX_DEVICE=0)"
    return result


def bench_mvcc(device_ok=True, n_txs=5000):
    """Config #4: MVCC validate-and-prepare over a 5k-tx block, host
    sequential scan vs the device fixpoint resolver (reference
    validateAndPrepareBatch, validation/validator.go:82; SURVEY P5)."""
    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.ledger.mvcc import Validator
    from fabric_tpu.ledger.mvcc_device import DeviceValidator
    from fabric_tpu.ledger.statedb import UpdateBatch, VersionedDB
    from fabric_tpu.validation.txflags import TxValidationCode

    db = VersionedDB()
    seed = UpdateBatch()
    for i in range(n_txs):
        seed.put("cc", f"k{i}", b"v0", rw.Version(0, i))
    db.apply_updates(seed)

    # every tx reads its own key at the committed version and writes it;
    # every 10th tx reads a key another in-block tx already wrote ->
    # MVCC_READ_CONFLICT, so the run exercises both outcomes
    rwsets = []
    for i in range(n_txs):
        read_key = f"k{i - 1}" if i % 10 == 5 else f"k{i}"
        read_ver = rw.Version(0, i - 1 if i % 10 == 5 else i)
        rwsets.append(
            rw.TxRwSet(
                (
                    rw.NsRwSet(
                        "cc",
                        (rw.KVRead(read_key, read_ver),),
                        (rw.KVWrite(f"k{i}", False, b"v1"),),
                    ),
                )
            )
        )
    incoming = [TxValidationCode.VALID] * n_txs

    def run(validator):
        start = time.perf_counter()
        codes, _updates, _hashed = validator.validate_and_prepare_batch(
            1, rwsets, list(incoming)
        )
        ms = (time.perf_counter() - start) * 1000.0
        n_conflicts = sum(
            1 for c in codes if c == TxValidationCode.MVCC_READ_CONFLICT
        )
        if n_conflicts != n_txs // 10:
            raise RuntimeError(
                f"config #4 expected {n_txs // 10} conflicts, got {n_conflicts}"
            )
        return ms, codes

    host_ms, host_codes = run(Validator(db))
    if not device_ok:
        return {
            "txs": n_txs,
            "host_ms_per_block": round(host_ms, 1),
            "error": "device unavailable — host column only",
        }
    dev = DeviceValidator(db)
    run(dev)  # compile warmup
    dev_ms, dev_codes = run(dev)
    if dev.last_path != "device" or dev_codes != host_codes:
        raise RuntimeError("config #4 device path mismatch")
    # RESIDENT variant (VERDICT r4 #4): the table persists across
    # blocks, so the measurement is a real multi-block sequence — block
    # 1 pays one-time slot seeding + compile; steady state (block >= 2)
    # runs committed checks + fixpoint + table update in ONE launch
    # with no per-read host probes. Timed section is the validate call.
    from fabric_tpu.ledger.mvcc_device import ResidentDeviceValidator

    res = ResidentDeviceValidator(db)
    ver = {i: (0, i) for i in range(n_txs)}

    def resident_block(j):
        rwsets2 = []
        for i in range(n_txs):
            rk = i - 1 if i % 10 == 5 else i  # in-block conflict pattern
            rwsets2.append(
                rw.TxRwSet(
                    (
                        rw.NsRwSet(
                            "cc",
                            (rw.KVRead(f"k{rk}", rw.Version(*ver[rk])),),
                            (rw.KVWrite(f"k{i}", False, b"v1"),),
                        ),
                    )
                )
            )
        start = time.perf_counter()
        codes, _u, _h = res.validate_and_prepare_batch(
            j, rwsets2, [TxValidationCode.VALID] * n_txs
        )
        ms = (time.perf_counter() - start) * 1000.0
        n_conf = sum(
            1 for c in codes if c == TxValidationCode.MVCC_READ_CONFLICT
        )
        if n_conf != n_txs // 10 or res.last_path != "device":
            raise RuntimeError(
                f"config #4 resident block {j}: {n_conf} conflicts, "
                f"path {res.last_path}"
            )
        for i in range(n_txs):
            if i % 10 != 5:
                ver[i] = (j, i)
        return ms

    resident_block(1)  # seeding + compile
    res_ms = min(resident_block(2), resident_block(3))
    return {
        "txs": n_txs,
        "host_ms_per_block": round(host_ms, 1),
        "device_ms_per_block": round(dev_ms, 1),
        "speedup": round(host_ms / dev_ms, 2),
        "resident_ms_per_block": round(res_ms, 1),
        "resident_speedup": round(host_ms / res_ms, 2),
        "note": "codes bit-identical; host scan stays the default "
        "(ledger.deviceMVCC opts in). resident_* is the round-5 "
        "device-RESIDENT version table (steady-state block: committed "
        "checks + fixpoint + table update in ONE launch, no per-read "
        "host get_version probes — the win condition round 3 named); "
        "crossover still requires an attached chip if the launch RTT "
        "exceeds the host scan",
    }


def bench_multichannel(net, device_ok=True, n_channels=4, txs_per_channel=2000):
    """Config #5: one channel-axis device step validating one block per
    channel (sharding over real chips is exercised by dryrun_multichip
    on the virtual mesh; this machine has a single chip). The CPU
    aggregate column (BASELINE config #5 "CPU aggregate tx/s") runs the
    same four blocks through plain per-channel SW-provider validators —
    the reference's process-parallel shape collapsed onto this host's
    single core."""
    from fabric_tpu.protos import common_pb2

    channels = [f"bench{i}" for i in range(n_channels)]
    blocks = {
        ch: net.make_block(ch, txs_per_channel) for ch in channels
    }
    total = n_channels * txs_per_channel

    def copy_blocks():
        out = {}
        for ch, b in blocks.items():
            c = common_pb2.Block()
            c.CopyFrom(b)
            out[ch] = c
        return out

    # CPU aggregate: per-channel sequential validation, software provider
    cpu_copies = copy_blocks()
    start = time.perf_counter()
    for ch in channels:
        flags = net.validator(ch, net.sw).validate(cpu_copies[ch])
        if set(flags.tobytes()) != {0}:
            raise RuntimeError(f"config #5 invalid txs in {ch} (cpu)")
    cpu_elapsed = time.perf_counter() - start
    result = {
        "channels": n_channels,
        "txs_per_channel": txs_per_channel,
        "cpu_aggregate_tx_per_s": round(total / cpu_elapsed, 1),
        "cpu_ms_total": round(cpu_elapsed * 1000.0, 1),
    }
    if not device_ok:
        result["error"] = "device unavailable — CPU column only"
        return result

    import jax

    from fabric_tpu.parallel import MultiChannelValidator
    from fabric_tpu.parallel.mesh import grid_mesh

    devices = jax.devices()
    mesh = grid_mesh(1, 1, devices[:1])
    mc = MultiChannelValidator(
        mesh, {ch: net.validator(ch, net.sw) for ch in channels}
    )
    mc.validate(copy_blocks())  # compile warmup
    start = time.perf_counter()
    flags = mc.validate(copy_blocks())
    elapsed = time.perf_counter() - start
    for ch in channels:
        if set(flags[ch].tobytes()) != {0}:
            raise RuntimeError(f"config #5 invalid txs in {ch}")
    result.update(
        {
            "aggregate_tx_per_s": round(total / elapsed, 1),
            "ms_total": round(elapsed * 1000.0, 1),
            "speedup": round(cpu_elapsed / elapsed, 2),
            # duty cycle: share of the wall clock the sharded device step
            # (launch -> masks back) occupied; the rest is host phases
            "device_busy_ms": round(mc.last_device_ms, 1),
            "device_duty_cycle": round(
                mc.last_device_ms / (elapsed * 1000.0), 3
            ),
        }
    )
    return result


def bench_host_tiers(triples, budget_s=6.0):
    """Per-tier host EC batch throughput (the backend ladder column):
    every *available* tier verifies the same batch through the
    SoftwareProvider batch path; the p256 oracle is extrapolated from a
    few lanes (full batch would eat minutes).  Output keys are tier
    names, so an oracle-tier number can never masquerade as fastec."""
    from fabric_tpu.crypto.bccsp import (
        SoftwareProvider,
        available_ec_backends,
        ec_backend_name,
        select_ec_backend,
    )

    keys = [t[0] for t in triples]
    sigs = [t[1] for t in triples]
    digests = [t[2] for t in triples]
    active = ec_backend_name()
    out = {"active": active}
    avail = available_ec_backends()
    # the oracle rides a fixed 4 lanes (~0.8s), the timed tiers split the
    # rest of the budget so the function honors its budget_s contract
    timed_tiers = sum(
        1 for t, ok in avail.items() if ok and t != "p256"
    )
    per_tier_s = max(budget_s - 1.0, 1.0) / max(timed_tiers, 1)
    try:
        for tier, ok in avail.items():
            if not ok:
                out[tier] = {"skipped": "backend unavailable"}
                continue
            select_ec_backend(tier)
            sw = SoftwareProvider()
            # 1024 lanes (the acceptance batch size) bounds one pass to a
            # couple of seconds on the slowest timed tier, so the budget
            # check — which fires between whole batches — actually binds
            lanes = keys[:1024] if tier != "p256" else keys[:4]
            if tier != "p256":
                # untimed warmup: first call pays one-off process-pool
                # spawn (hostec) — the column reports steady state
                sw.batch_verify(lanes, sigs[: len(lanes)], digests[: len(lanes)])
            t0 = time.perf_counter()
            done = 0
            while True:
                verdicts = sw.batch_verify(
                    lanes, sigs[: len(lanes)], digests[: len(lanes)]
                )
                if not all(verdicts):
                    raise RuntimeError(f"{tier}: benchmark sig rejected")
                done += len(lanes)
                elapsed = time.perf_counter() - t0
                if elapsed >= per_tier_s or (tier == "p256" and done >= 4):
                    break
            out[tier] = {
                "verifies_per_s": round(done / elapsed, 1),
                "lanes": len(lanes),
            }
            if tier == "p256":
                out[tier]["note"] = "oracle tier, extrapolated from 4 lanes"
    finally:
        select_ec_backend(active)
    return out


def bench_chaos(device_ok=True, seed=None):
    """fabchaos smoke scorecard: seeded fault-injection scenarios with
    per-stage p50/p99 latency — the trajectory files capture scenario
    coverage and SLO shape, not just a clean-batch headline.  Device
    availability is irrelevant (the harness drives the host planes);
    BENCH_CHAOS_SEED overrides the seed."""
    from fabric_tpu.tools.fabchaos import scorecard_for_bench

    if seed is None:
        seed = int(os.environ.get("BENCH_CHAOS_SEED", "7"))
    return scorecard_for_bench(seed=seed)


def bench_serve(device_ok=True, n_requests=None, lanes_per_request=256):
    """configs.serve: the resident validation sidecar.

    Two measurements:

    1. **cold-vs-warm compile ms per bucket** through the bucketed
       program registry (fresh AOT dir -> cold trace+compile; second
       registry against the same dir -> AOT-loaded warm start) and the
       ladder-level warm speedup.  Uses the CI-able demo limb ladder by
       default; BENCH_SERVE_LADDER=verify runs the REAL ECDSA limb
       kernel (minutes cold — real-silicon runs only).
    2. **per-request p50/p99** through a live sidecar: an in-process
       host-engine sidecar serves mixed batches over the real socket
       protocol via the SidecarProvider client shim, masks asserted
       bit-exact against the in-process provider.
    """
    import hashlib
    import shutil
    import tempfile

    from fabric_tpu.common.metrics import latency_summary
    from fabric_tpu.crypto import der as _der
    from fabric_tpu.crypto.bccsp import (
        ECDSAPublicKey,
        SoftwareProvider,
        ec_backend,
    )
    from fabric_tpu.serve.client import SidecarProvider
    from fabric_tpu.serve.registry import BucketProgramRegistry
    from fabric_tpu.serve.server import SidecarServer

    out = {}

    # ---- 1: cold vs warm compile per bucket (AOT registry) --------------
    ladder = os.environ.get("BENCH_SERVE_LADDER", "demo")
    buckets = tuple(
        int(b)
        for b in os.environ.get("BENCH_SERVE_BUCKETS", "128,256,512").split(",")
    )
    aot_dir = tempfile.mkdtemp(prefix="bench-serve-aot-")
    try:
        from fabric_tpu.serve.registry import (
            demo_limb_program,
            verify_limb_program,
        )

        fn, shapes_for = (
            verify_limb_program() if ladder == "verify" else demo_limb_program()
        )
        cold = BucketProgramRegistry.for_jax_program(
            fn, shapes_for, buckets=buckets, label=f"bench-{ladder}",
            aot_dir=aot_dir,
        )
        cold.warm()
        warm = BucketProgramRegistry.for_jax_program(
            fn, shapes_for, buckets=buckets, label=f"bench-{ladder}",
            aot_dir=aot_dir,
        )
        warm.warm()
        per_bucket = {}
        cold_total = warm_total = 0.0
        for b in buckets:
            c = cold.warm_report[b]
            w = warm.warm_report[b]
            cold_total += c["warm_ms"]
            warm_total += w["warm_ms"]
            per_bucket[str(b)] = {
                "cold_ms": c["warm_ms"],
                "cold_compile_ms": c.get("compile_ms"),
                "warm_ms": w["warm_ms"],
                "warm_aot_hit": bool(w.get("aot_hit")),
            }
        out["compile_ladder"] = {
            "ladder": ladder,
            "buckets": list(buckets),
            "per_bucket": per_bucket,
            "cold_total_ms": round(cold_total, 1),
            "warm_total_ms": round(warm_total, 1),
            "warm_speedup": round(cold_total / max(warm_total, 1e-3), 1),
            "warm_traces": warm.traces,
        }
    except Exception as exc:  # noqa: BLE001 - ladder column is best-effort
        out["compile_ladder"] = {"error": str(exc)[:300]}
    finally:
        shutil.rmtree(aot_dir, ignore_errors=True)

    # ---- 2: request p50/p99 through a live sidecar ----------------------
    if n_requests is None:
        n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "32"))
    sock = os.path.join(tempfile.mkdtemp(prefix="bench-serve-"), "b.sock")
    server = SidecarServer(sock, engine="host", warm_ladder="off")
    provider = None
    try:
        warm_report = server.warm()
        server.start()
        provider = SidecarProvider(address=sock)
        ec = ec_backend()
        kp = ec.generate_keypair()
        pub = ECDSAPublicKey(*kp.pub)
        keys, sigs, digs, expected = [], [], [], []
        for i in range(lanes_per_request):
            digest = hashlib.sha256(b"serve bench lane %d" % i).digest()
            r, s = ec.sign_digest(kp.priv, digest)
            sig = _der.marshal_signature(r, s)
            if i % 5 == 0:  # mixed batch: every 5th lane invalid
                bad = bytearray(sig)
                bad[-1] ^= 0x5A
                sig = bytes(bad)
            keys.append(pub)
            sigs.append(sig)
            digs.append(digest)
            expected.append(i % 5 != 0)
        client_lat = []
        for _ in range(n_requests):
            t0 = time.perf_counter()
            mask = provider.batch_verify(keys, sigs, digs)
            client_lat.append(time.perf_counter() - t0)
            if list(mask) != expected:
                raise RuntimeError("sidecar mask != ground truth")
        inproc = SoftwareProvider().batch_verify(keys, sigs, digs)
        if list(inproc) != expected:
            raise RuntimeError("in-process mask != ground truth")
        client_summary = latency_summary(client_lat)
        described = server.describe()
        out["sidecar"] = {
            "engine": server.engine,
            "requests": n_requests,
            "lanes_per_request": lanes_per_request,
            "host_warm_ms": warm_report.get("host_warm_ms"),
            "client_p50_ms": client_summary["p50_ms"],
            "client_p99_ms": client_summary["p99_ms"],
            "server_latency": described["stats"]["request_latency"],
            "rejects": described["stats"]["rejects"],
            "lanes_per_s": round(
                n_requests * lanes_per_request / max(sum(client_lat), 1e-9), 1
            ),
            "degraded": provider.degraded,
            "mask_exact": True,
        }
    except Exception as exc:  # noqa: BLE001 - emit partial results
        out["sidecar"] = {"error": str(exc)[:300]}
    finally:
        if provider is not None:
            provider.stop()
        server.stop()
        shutil.rmtree(os.path.dirname(sock), ignore_errors=True)
    return out


def bench_fleet(device_ok=True, n_peers=None, requests_per_peer=None):
    """configs.fleet: the multi-peer shared-sidecar soak (ROADMAP
    fleet-scale acceptance).  One warm host-engine sidecar, >= 4 REAL
    peer processes (``fabric_tpu.serve.fleetload`` subprocesses) with a
    zipf channel skew — one paying high-priority channel, the rest
    spam/bulk with 10:1 aggregate request skew — reporting aggregate
    verifies/s across the fleet and per-class p99 off the sidecar's
    per-class stats.  Every peer asserts its masks bit-exact; a
    mismatch fails the column."""
    import shutil
    import subprocess
    import tempfile

    from fabric_tpu.serve.server import SidecarServer

    if n_peers is None:
        n_peers = max(4, int(os.environ.get("BENCH_FLEET_PEERS", "4")))
    if requests_per_peer is None:
        requests_per_peer = int(os.environ.get("BENCH_FLEET_REQUESTS", "6"))
    sock = os.path.join(tempfile.mkdtemp(prefix="bench-fleet-"), "f.sock")
    server = SidecarServer(sock, engine="host", warm_ladder="off")
    out = {}
    try:
        server.warm()
        server.start()
        # zipf-ish skew: peer 0 is the paying channel; spam peers carry
        # 10x its aggregate request count between them
        specs = []
        for i in range(n_peers):
            if i == 0:
                specs.append(("paychan", "high", requests_per_peer, 256))
            else:
                spam_reqs = max(
                    1,
                    (10 * requests_per_peer) // max(1, n_peers - 1),
                )
                specs.append((f"spam{i}", "bulk", spam_reqs, 128))
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-m", "fabric_tpu.serve.fleetload",
                    "--address", sock, "--channel", chan, "--qos", qos,
                    "--requests", str(reqs), "--lanes", str(lanes),
                    "--seed", str(i),
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            for i, (chan, qos, reqs, lanes) in enumerate(specs)
        ]
        peers = []
        try:
            for p, (chan, _q, _r, _l) in zip(procs, specs):
                stdout, stderr = p.communicate(timeout=240)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"fleet peer {chan} rc={p.returncode}: "
                        f"{stderr.decode()[-200:]}"
                    )
                peers.append(
                    json.loads(stdout.decode().strip().splitlines()[-1])
                )
        except BaseException:
            # one peer failed/timed out: reap the rest before the
            # finally block stops the server out from under them
            for p in procs:
                if p.poll() is None:
                    p.kill()
                try:
                    p.communicate(timeout=10)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            raise
        wall_s = time.perf_counter() - t0
        total_lanes = sum(
            p["requests"] * p["lanes_per_request"] for p in peers
        )
        per_class = server.stats.summary()["per_class"]
        out = {
            "peers": n_peers,
            "skew": "10:1 spam:paying",
            "aggregate_verifies_per_s": round(total_lanes / wall_s, 1),
            "wall_s": round(wall_s, 2),
            "mask_mismatches": sum(p["mask_mismatches"] for p in peers),
            "busy_rejects": sum(p["busy_rejects"] for p in peers),
            "degraded_peers": sum(1 for p in peers if p["degraded"]),
            # tail-tolerance counters (fabtail): the soak quantifies
            # hedge/deadline/eviction behavior, not just throughput
            "hedges": sum(p.get("hedges", 0) for p in peers),
            "hedge_wins": sum(p.get("hedge_wins", 0) for p in peers),
            "deadline_expired": sum(
                p.get("deadline_expired", 0) for p in peers
            ),
            "slow_evictions": sum(
                p.get("slow_evictions", 0) for p in peers
            ),
            "server_deadline_shed": server.stats.summary()["deadline_shed"],
            "per_peer": peers,
            "per_class_p99_ms": {
                cls: row["latency"].get("p99_ms")
                for cls, row in per_class.items()
            },
            "per_class_served": {
                cls: row["served"] for cls, row in per_class.items()
            },
        }
        if out["mask_mismatches"]:
            raise RuntimeError("fleet soak produced mask mismatches")
    except Exception as exc:  # noqa: BLE001 - emit partial results
        out["error"] = str(exc)[:300]
    finally:
        server.stop()
        shutil.rmtree(os.path.dirname(sock), ignore_errors=True)
    return out


def _ndev_child(n_devices: int, lanes: int) -> None:
    """Subprocess body of the n_devices sweep: pin a hermetic CPU mesh
    of `n_devices` virtual devices BEFORE any backend init, run the
    sharded limb-matrix verify kernel, print one JSON line."""
    import hashlib

    from fabric_tpu.utils.jaxcache import pin_cpu_mesh

    pin_cpu_mesh(n_devices)
    import jax

    have = len(jax.devices())
    if have < n_devices:
        print(json.dumps({"error": f"only {have} devices materialized"}))
        return
    from fabric_tpu.crypto.tpu_provider import TPUProvider, _bucket
    from fabric_tpu.parallel.mesh import flat_mesh
    from fabric_tpu.parallel.sharded import ShardedVerify, pad_lanes

    # sign a small distinct set and tile it: the sweep times the device
    # step, not host signing
    base = gen_triples(min(lanes, 64))
    triples = [base[i % len(base)] for i in range(lanes)]
    provider = TPUProvider()  # safe here: JAX_PLATFORMS=cpu is pinned
    limbs = provider.prep_limbs(
        [t[0] for t in triples], [t[1] for t in triples], [t[2] for t in triples]
    )
    mesh = flat_mesh(jax.devices()[:n_devices])
    sharded = ShardedVerify(mesh)
    size = pad_lanes(_bucket(lanes), sharded.data_size)
    padded = TPUProvider.pad_limbs(limbs, size)
    t0 = time.perf_counter()
    mask = sharded.verify_flat(*padded)[:lanes]
    warm_s = time.perf_counter() - t0
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        mask = sharded.verify_flat(*padded)[:lanes]
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    print(
        json.dumps(
            {
                "n_devices": n_devices,
                "lanes": lanes,
                "first_call_s": round(warm_s, 2),
                "verifies_per_s": round(lanes / best, 1),
                "mask_sha": hashlib.sha256(
                    bytes(1 if b else 0 for b in mask)
                ).hexdigest()[:16],
            }
        )
    )


def bench_n_devices(device_ok=True, deadline=None):
    """configs.n_devices: the ROADMAP multi-chip sweep column.  Each
    device count runs in a SUBPROCESS that pins a hermetic CPU mesh
    (pin_cpu_mesh) before backend init, so the sweep never touches a
    possibly version-skewed accelerator client; the parent additionally
    asserts the verify mask is bit-exact ACROSS shardings.  On real
    multi-chip silicon the same column is the scaling headline; on the
    CI box it mostly measures XLA:CPU virtual-device overhead (and the
    real kernel's compile may exceed the per-child timeout — recorded,
    not fatal)."""
    import subprocess

    if os.environ.get("BENCH_NDEV", "1") == "0":
        return {"skipped": "BENCH_NDEV=0"}
    lanes = int(os.environ.get("BENCH_NDEV_LANES", "512"))
    counts = [
        int(c)
        for c in os.environ.get("BENCH_NDEV_SWEEP", "1,2,4,8").split(",")
    ]
    child_timeout = float(os.environ.get("BENCH_NDEV_TIMEOUT_S", "600"))
    out = {"lanes": lanes, "sweep": {}}
    mask_shas = set()
    for n in counts:
        if deadline is not None and time.monotonic() > deadline:
            out["sweep"][str(n)] = {"skipped": "bench budget exhausted"}
            continue
        budget = child_timeout
        if deadline is not None:
            budget = min(budget, max(deadline - time.monotonic(), 30.0))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # the child pins its own device count dynamically; a forced
        # host-device-count flag from the parent env would override it
        env["XLA_FLAGS"] = " ".join(
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        )
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    f"import bench; bench._ndev_child({n}, {lanes})",
                ],
                capture_output=True,
                text=True,
                timeout=budget,
                env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
            if proc.returncode != 0 or not line:
                out["sweep"][str(n)] = {
                    "error": (proc.stderr or "no output")[-300:]
                }
                continue
            row = json.loads(line)
            out["sweep"][str(n)] = row
            if "mask_sha" in row:
                mask_shas.add(row["mask_sha"])
        except subprocess.TimeoutExpired:
            out["sweep"][str(n)] = {
                "error": f"timeout after {budget:.0f}s (cold XLA compile "
                "exceeds the child budget on this box)"
            }
        except Exception as exc:  # noqa: BLE001 - sweep column best-effort
            out["sweep"][str(n)] = {"error": str(exc)[:300]}
    # only claim cross-sharding bit-exactness when at least two device
    # counts actually produced a mask; with 0-1 successful children the
    # property was never tested (null, not a vacuous True)
    out["mask_bit_exact_across_shardings"] = (
        len(mask_shas) == 1 if sum(
            1 for r in out["sweep"].values() if "mask_sha" in r
        ) >= 2 else None
    )
    rows = [
        r for r in out["sweep"].values() if isinstance(r.get("verifies_per_s"), (int, float))
    ]
    if len(rows) >= 2:
        # baseline against the SMALLEST successful device count, and say
        # which it was: if the n=1 child timed out, ratios labeled
        # "vs 1 device" would silently be ratios vs the 2-device row
        base_row = min(rows, key=lambda r: r["n_devices"])
        base = base_row["verifies_per_s"]
        out["scaling_baseline_n_devices"] = base_row["n_devices"]
        out[f"scaling_vs_{base_row['n_devices']}dev"] = {
            str(r["n_devices"]): round(r["verifies_per_s"] / base, 2)
            for r in rows
        }
    return out


def bench_batcher(net, device_ok=True, n_channels=4, txs_per_channel=128):
    """P7 coalescing: four channels deliver SMALL blocks concurrently.
    Direct mode launches one small device program per channel; the shared
    VerifyBatcher coalesces them into few large launches (reference
    analog: broadcast.go:163 backpressure discipline + the validator
    semaphore's batching effect)."""
    import threading

    from fabric_tpu.crypto.tpu_provider import TPUProvider
    from fabric_tpu.parallel.batcher import BatchingProvider
    from fabric_tpu.protos import common_pb2

    channels = [f"small{i}" for i in range(n_channels)]
    blocks = {ch: net.make_block(ch, txs_per_channel) for ch in channels}

    def run(provider):
        validators = {ch: net.validator(ch, provider) for ch in channels}
        copies = {}
        for ch, b in blocks.items():
            c = common_pb2.Block()
            c.CopyFrom(b)
            copies[ch] = c
        errs = []

        def work(ch):
            try:
                flags = validators[ch].validate(copies[ch])
                if set(flags.tobytes()) != {0}:
                    errs.append(f"{ch}: invalid txs")
            except Exception as e:  # noqa: BLE001
                errs.append(f"{ch}: {e}")

        threads = [
            threading.Thread(target=work, args=(ch,)) for ch in channels
        ]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError("; ".join(errs))
        return (time.perf_counter() - start) * 1000.0

    tpu = TPUProvider()
    run(tpu)  # compile warmup (per-channel bucket)
    direct_ms = min(run(tpu), run(tpu))  # tunnel-stall robustness
    shared = BatchingProvider(tpu)
    try:
        run(shared)  # compile warmup (coalesced bucket)
        launches0, lanes0 = shared.batcher.launches, shared.batcher.lanes
        batched_ms = min(run(shared), run(shared))
        launches = (shared.batcher.launches - launches0) // 2
        lanes = (shared.batcher.lanes - lanes0) // 2
    finally:
        shared.stop()
    total = n_channels * txs_per_channel
    return {
        "channels": n_channels,
        "txs_per_channel": txs_per_channel,
        "direct_ms": round(direct_ms, 1),
        "batched_ms": round(batched_ms, 1),
        "launches": launches,
        "lanes_per_launch": round(lanes / max(launches, 1), 1),
        "batched_tx_per_s": round(total / (batched_ms / 1000.0), 1),
        "speedup": round(direct_ms / batched_ms, 2),
        "batcher_mode": shared.batcher.mode,
        "batcher_rtt_ema_ms": (
            round(shared.batcher.rtt_ema_ms, 1)
            if shared.batcher.rtt_ema_ms is not None
            else None
        ),
        "note": "transport-regime adaptive (round 5): the batcher "
        "measures its own small-launch RTT and coalesces only when the "
        "transport is low-latency; on high-RTT tunnels it passes "
        "requests through as independent overlapped launches (so "
        "batched ~= direct by construction). Bounded-queue backpressure "
        "(SURVEY P7) holds in both modes.",
    }


def main():
    # 32768 lanes/launch: the tunnel adds a fixed per-launch RTT, and the
    # bigger batch halves its share of the rate (measured on a slow-tunnel
    # day: 43.4k verifies/s at 16384 vs 57.5k at 32768; both programs are
    # cached)
    import threading

    n = int(os.environ.get("BENCH_N", "32768"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    headline_only = os.environ.get("BENCH_HEADLINE_ONLY", "") == "1"
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    t0 = time.monotonic()
    deadline = t0 + budget_s

    # ---- CPU columns FIRST: a complete JSON line exists before the
    # ---- device is touched at all (round-4 postmortem: UNAVAILABLE at
    # ---- first dispatch produced rc=1 and zero data)
    from fabric_tpu.crypto.bccsp import ec_backend_name

    configs = {}
    # observe the whole run: every emitted line carries the metrics
    # snapshot scraped at emit time (bench_obs_overhead disables the
    # registry around its own measurement passes and restores it)
    from fabric_tpu.common import fabobs as _fabobs

    _fabobs.ensure_enabled()
    triples = gen_triples(n)
    cpu_rate = bench_cpu_baseline(triples)
    # which scalar-EC tier the SW provider actually runs — guards against
    # a silent fallback mislabeling CPU columns as fastec numbers
    sw_backend = ec_backend_name()
    try:
        configs["host_ec_tiers"] = bench_host_tiers(triples)
    except Exception as exc:  # noqa: BLE001 - ladder column is best-effort
        configs["host_ec_tiers"] = {"error": str(exc)[:300]}
    try:
        configs["host_ladder"] = bench_host_ladder(triples)
    except Exception as exc:  # noqa: BLE001 - ladder column is best-effort
        configs["host_ladder"] = {"error": str(exc)[:300]}
    try:
        configs["obs_overhead"] = bench_obs_overhead(triples)
    except Exception as exc:  # noqa: BLE001 - obs column is best-effort
        configs["obs_overhead"] = {"error": str(exc)[:300]}
    try:
        import subprocess

        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip()
    except Exception:  # noqa: BLE001 - no git: omit
        rev = ""
    result = {
        "metric": "ecdsa_p256_verify_throughput",
        "value": round(cpu_rate, 1),
        "unit": "verifies/s",
        "vs_baseline": 1.0,
        "detail": {
            "rev": rev,
            "batch": n,
            "iters": iters,
            "cpu_baseline_verifies_per_s": round(cpu_rate, 1),
            "device": "pending",
            "error": "device not yet attempted",
            "target_verifies_per_s": 50000,
            "sw_ec_backend": sw_backend,
            "budget_s": budget_s,
            "elapsed_s": 0.0,
            "configs": configs,
        },
    }

    if sw_backend == "p256":
        # never let an oracle-tier run pass as a fast-tier number: the
        # warning rides every emitted line and stderr shouts once
        result["detail"]["sw_ec_backend_warning"] = (
            "running on the pure-Python ORACLE tier (~5 verifies/s) — "
            "CPU columns are NOT comparable to fastec/hostec numbers"
        )
        print(
            "bench: WARNING: EC backend is the p256 oracle tier; "
            "host columns will be ~3 orders of magnitude slow",
            file=sys.stderr,
            flush=True,
        )

    def emit():
        result["detail"]["elapsed_s"] = round(time.monotonic() - t0, 1)
        try:
            # rung counters + stage histograms ride BENCH_*.json next to
            # the throughput columns (ISSUE 10: configs.metrics_snapshot)
            configs["metrics_snapshot"] = _fabobs.snapshot()
        except Exception as exc:  # noqa: BLE001 - snapshot is best-effort
            configs["metrics_snapshot"] = {"error": str(exc)[:200]}
        print(json.dumps(result), flush=True)

    emit()  # valid line on disk before any device call can hang

    # ---- watchdog: if anything (usually a first device dispatch through
    # ---- a dead tunnel) hangs past the budget + grace, emit what we have
    # ---- and exit 0 — the driver still gets the latest complete line
    grace_s = float(os.environ.get("BENCH_WATCHDOG_GRACE_S", "120"))

    def _watchdog():
        while True:
            left = (deadline + grace_s) - time.monotonic()
            if left <= 0:
                break
            time.sleep(min(left, 10.0))
        # os._exit must run even if emit() races the main thread's dict
        # mutations (json.dumps over a changing dict raises) — a dead
        # watchdog would reintroduce the round-4 infinite hang
        try:
            result["detail"]["watchdog"] = (
                "budget+grace exhausted; a hung call was preempted"
            )
            emit()
        except Exception:  # noqa: BLE001
            pass
        finally:
            os._exit(0)

    threading.Thread(target=_watchdog, name="bench-watchdog", daemon=True).start()

    # ---- bounded device probe (subprocess: a hung backend init is
    # ---- KILLED by the kernel, and the verdict is cached for the run)
    probe_s = min(float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "300")),
                  max(budget_s * 0.3, 60.0))
    from fabric_tpu.utils.deviceprobe import probe_subprocess

    device_ok, probe_err = probe_subprocess(probe_s)
    result["detail"]["probe"] = "subprocess"
    if not device_ok:
        result["detail"]["device"] = "unavailable"
        result["detail"]["error"] = probe_err or "no accelerator device"
        emit()
    else:
        import jax

        result["detail"]["device"] = str(jax.devices()[0])
        try:
            device_rate, degraded = bench_headline_device(triples, iters)
            if degraded or device_rate <= 0.0:
                device_ok = False
                result["detail"]["error"] = (
                    "device dispatch degraded to the software fallback — "
                    "no valid device column"
                )
            else:
                result["value"] = round(device_rate, 1)
                result["vs_baseline"] = round(device_rate / cpu_rate, 2)
                result["detail"].pop("error", None)
        except Exception as exc:  # noqa: BLE001 - keep the CPU line
            device_ok = False
            result["detail"]["error"] = f"headline device error: {exc}"[:300]
        emit()

    if not headline_only:
        net = None
        for name, fn, needs_net in (
            ("block_1k", bench_block_1k, True),
            ("idemix", bench_idemix, False),
            ("mvcc_5k", bench_mvcc, False),
            ("multi_4ch", bench_multichannel, True),
            ("batcher_4ch_small", bench_batcher, True),
            ("serve", bench_serve, False),
            ("fleet", bench_fleet, False),
            ("n_devices", bench_n_devices, False),
            ("chaos", bench_chaos, False),
        ):
            if time.monotonic() > deadline:
                configs[name] = {
                    "skipped": f"wall-clock budget ({budget_s:.0f}s) exhausted"
                }
                emit()
                continue
            if name == "batcher_4ch_small" and not device_ok:
                configs[name] = {
                    "skipped": "device unavailable (coalescing is a "
                    "device-launch experiment)"
                }
                emit()
                continue
            try:
                if needs_net and net is None:
                    net = _Net()
                if name == "idemix" and not needs_net:
                    # cold 64-lane pairing compile costs minutes; with a
                    # tight remaining budget fall back to the proven
                    # 8-lane shape rather than risk a budget skip
                    remaining = deadline - time.monotonic()
                    n_sigs = (
                        None  # env/default (64)
                        if remaining > 420 or not device_ok
                        else 8
                    )
                    configs[name] = fn(device_ok, n_sigs=n_sigs)
                elif name == "n_devices":
                    configs[name] = fn(device_ok, deadline=deadline)
                else:
                    configs[name] = (
                        fn(net, device_ok) if needs_net else fn(device_ok)
                    )
            except Exception as exc:  # noqa: BLE001 - emit partial results
                configs[name] = {"error": str(exc)[:300]}
            emit()


if __name__ == "__main__":
    sys.exit(main())
