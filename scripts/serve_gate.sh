#!/usr/bin/env bash
# serve_gate: the resident-sidecar smoke (< 60s, jax-free).
#
# Starts the sidecar as a REAL subprocess (host engine), waits for its
# SERVE_READY line, drives one mixed valid/invalid batch through the
# SidecarProvider client shim, asserts the mask equals the in-process
# ground truth bit-exactly, then performs a clean protocol SHUTDOWN and
# requires the server process to exit 0.
set -uo pipefail

cd "$(dirname "$0")/.."

SOCK_DIR="$(mktemp -d)"
SOCK="${SOCK_DIR}/serve_gate.sock"
LOG="$(mktemp)"

cleanup() {
    [ -n "${SRV_PID:-}" ] && kill "${SRV_PID}" 2>/dev/null
    rm -rf "${SOCK_DIR}"
    rm -f "${LOG}"
}
trap cleanup EXIT

timeout -k 5 55 python -m fabric_tpu.serve \
    --address "${SOCK}" --engine host --warm off >"${LOG}" 2>&1 &
SRV_PID=$!

# wait for the READY line (warm-up done, socket bound)
for _ in $(seq 1 100); do
    grep -q "^SERVE_READY" "${LOG}" 2>/dev/null && break
    kill -0 "${SRV_PID}" 2>/dev/null || { echo "serve_gate: server died:" >&2; cat "${LOG}" >&2; exit 1; }
    sleep 0.2
done
if ! grep -q "^SERVE_READY" "${LOG}"; then
    echo "serve_gate: server never became ready:" >&2
    cat "${LOG}" >&2
    exit 1
fi

timeout -k 5 40 python - "${SOCK}" <<'EOF'
import hashlib
import sys

from fabric_tpu.common import p256
from fabric_tpu.crypto import der, hostec
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider
from fabric_tpu.serve.client import SidecarProvider

addr = sys.argv[1]
d_priv = 0x1D1E5F
pub = ECDSAPublicKey(*hostec.scalar_base_mult(d_priv))
keys, sigs, digests, expected = [], [], [], []
for i in range(48):
    digest = hashlib.sha256(b"serve gate lane %d" % i).digest()
    r, s = hostec.sign_digest(d_priv, digest)
    sig = der.marshal_signature(r, s)
    kind = i % 4
    if kind == 1:  # corrupt signature
        bad = bytearray(sig); bad[-1] ^= 0x5A; sig = bytes(bad)
    elif kind == 2:  # high-S violation
        sig = der.marshal_signature(r, p256.N - s)
    elif kind == 3:  # garbage DER
        sig = b"\x00garbage"
    keys.append(pub); sigs.append(sig); digests.append(digest)
    expected.append(kind == 0)

provider = SidecarProvider(address=addr)
mask = provider.batch_verify(keys, sigs, digests)
assert list(mask) == expected, f"sidecar mask != ground truth: {mask}"
assert not provider.degraded, "gate batch was served in-process, not by the sidecar"
inproc = SoftwareProvider().batch_verify(keys, sigs, digests)
assert list(mask) == list(inproc), "sidecar mask != in-process mask"
stats = provider.client.stats()
assert stats["stats"]["requests"] >= 1, stats
provider.client.shutdown()
print(f"serve_gate: mask exact over {len(mask)} mixed lanes "
      f"({sum(mask)} valid), served by {stats['engine']} engine")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "serve_gate: client smoke FAILED" >&2
    cat "${LOG}" >&2
    exit $rc
fi

# the SHUTDOWN opcode must produce a clean exit
wait "${SRV_PID}"
srv_rc=$?
SRV_PID=""
if [ $srv_rc -ne 0 ]; then
    echo "serve_gate: server exited rc=${srv_rc} after SHUTDOWN" >&2
    cat "${LOG}" >&2
    exit 1
fi

# ---------------------------------------------------------------------------
# two-sidecar fleet leg (<10s): route a mixed batch across BOTH through
# the SidecarRouter, kill the preferred endpoint mid-batch (SIGKILL, a
# real process death), assert bit-exact masks through the failover, and
# require a clean OP_DRAIN exit from the survivor.
# ---------------------------------------------------------------------------
SOCK_A="${SOCK_DIR}/fleet_a.sock"
SOCK_B="${SOCK_DIR}/fleet_b.sock"
LOG_A="$(mktemp)"
LOG_B="$(mktemp)"

cleanup2() {
    [ -n "${PID_A:-}" ] && kill -9 "${PID_A}" 2>/dev/null
    [ -n "${PID_B:-}" ] && kill -9 "${PID_B}" 2>/dev/null
    rm -f "${LOG_A}" "${LOG_B}"
}
trap 'cleanup2; cleanup' EXIT

# a 300ms dispatch delay pins the kill-mid-batch race deterministically.
# NO `timeout` wrapper here: $! must be the PYTHON pid (SIGKILLing a
# timeout wrapper leaves the sidecar alive and the failover untested);
# runaway protection is the bounded wait loop at the bottom + cleanup2.
env FABRIC_TPU_FAULTS="serve.dispatch=delay:1.0:ms=300" \
    FABRIC_TPU_FAULTS_SEED=1 python -m fabric_tpu.serve \
    --address "${SOCK_A}" --engine host --warm off >"${LOG_A}" 2>&1 &
PID_A=$!
env FABRIC_TPU_FAULTS="serve.dispatch=delay:1.0:ms=300" \
    FABRIC_TPU_FAULTS_SEED=1 python -m fabric_tpu.serve \
    --address "${SOCK_B}" --engine host --warm off >"${LOG_B}" 2>&1 &
PID_B=$!

for _ in $(seq 1 100); do
    grep -q "^SERVE_READY" "${LOG_A}" 2>/dev/null \
        && grep -q "^SERVE_READY" "${LOG_B}" 2>/dev/null && break
    sleep 0.1
done
if ! grep -q "^SERVE_READY" "${LOG_A}" || ! grep -q "^SERVE_READY" "${LOG_B}"; then
    echo "serve_gate: fleet sidecars never became ready" >&2
    cat "${LOG_A}" "${LOG_B}" >&2
    exit 1
fi

timeout -k 5 25 python - "${SOCK_A}" "${SOCK_B}" "${PID_A}" "${PID_B}" <<'EOF'
import os
import signal
import sys

from fabric_tpu.serve.fleetload import build_lanes
from fabric_tpu.serve.router import SidecarRouter

addr_a, addr_b, pid_a, pid_b = (
    sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
)
pid_of = {addr_a: pid_a, addr_b: pid_b}

def lanes(n, seed):
    # one corruption recipe repo-wide: fleetload.build_lanes
    return build_lanes(n, seed)

router = SidecarRouter(endpoints=[addr_a, addr_b])
# mixed batches across two buckets route over the fleet
for n, seed in ((48, 1), (400, 2)):
    k, s, d, e = lanes(n, seed)
    mask = router.batch_verify(k, s, d)
    assert list(mask) == e, f"fleet mask wrong for {n} lanes"
assert not router.degraded, "healthy fleet degraded"

# kill the PREFERRED endpoint for the next batch mid-dispatch
k, s, d, e = lanes(256, 3)
victim = router._order(256)[0].address
resolver = router.batch_verify_async(k, s, d)
os.kill(pid_of[victim], signal.SIGKILL)
mask = resolver()
assert list(mask) == e, "mask wrong after mid-batch SIGKILL"
assert not router.degraded, "router degraded with a live peer remaining"
survivor = addr_b if victim == addr_a else addr_a

# rolling-restart half: the survivor drains cleanly via OP_DRAIN
assert router.drain_endpoint(survivor), "survivor refused OP_DRAIN"
print(f"serve_gate fleet: failover exact over {len(mask)} lanes "
      f"({sum(mask)} valid), victim={os.path.basename(victim)}")
print("KILLED_PID=%d" % pid_of[victim])
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "serve_gate: fleet leg FAILED" >&2
    cat "${LOG_A}" "${LOG_B}" >&2
    exit $rc
fi

# the drained survivor must exit 0; the SIGKILLed victim must not
# (SIGKILL = 137) — bounded wait (no timeout wrapper on the pids), then
# sort out which was which
for _ in $(seq 1 60); do
    kill -0 "${PID_A}" 2>/dev/null || kill -0 "${PID_B}" 2>/dev/null || break
    sleep 0.25
done
if kill -0 "${PID_A}" 2>/dev/null || kill -0 "${PID_B}" 2>/dev/null; then
    echo "serve_gate: a fleet sidecar outlived the drain window" >&2
    cleanup2
    exit 1
fi
wait "${PID_A}"; rc_a=$?
wait "${PID_B}"; rc_b=$?
PID_A=""; PID_B=""
if [ $rc_a -eq 0 ] && [ $rc_b -eq 0 ]; then
    echo "serve_gate: both fleet sidecars exited 0 but one was SIGKILLed" >&2
    exit 1
fi
if [ $rc_a -ne 0 ] && [ $rc_b -ne 0 ]; then
    echo "serve_gate: drained survivor exited nonzero (${rc_a}/${rc_b})" >&2
    cat "${LOG_A}" "${LOG_B}" >&2
    exit 1
fi

# ---------------------------------------------------------------------------
# hedge leg (<10s, fabtail): two subprocess sidecars, ONE delay-faulted
# (gray: alive, answers PING, dead slow — a per-process env fault plan).
# The hedging router must win the race on the healthy peer with a mask
# bit-exact vs ground truth, bounded far below the injected delay.
# ---------------------------------------------------------------------------
SOCK_G="${SOCK_DIR}/hedge_gray.sock"
SOCK_H="${SOCK_DIR}/hedge_ok.sock"
LOG_G="$(mktemp)"
LOG_H="$(mktemp)"

cleanup3() {
    [ -n "${PID_G:-}" ] && kill -9 "${PID_G}" 2>/dev/null
    [ -n "${PID_H:-}" ] && kill -9 "${PID_H}" 2>/dev/null
    rm -f "${LOG_G}" "${LOG_H}"
}
trap 'cleanup3; cleanup2; cleanup' EXIT

# the router prefers endpoints by rendezvous hash on the lane bucket
# (96 lanes -> bucket 128): the PREFERRED one goes gray, so every
# batch routes into the delay fault and must be rescued by a hedge
SOCK_G=$(python -c "
import hashlib, sys
key = lambda a: hashlib.sha256(('128|' + a).encode()).digest()
print(min(sys.argv[1:], key=key))
" "${SOCK_DIR}/hedge_gray.sock" "${SOCK_DIR}/hedge_ok.sock")
if [ "${SOCK_G}" = "${SOCK_DIR}/hedge_gray.sock" ]; then
    SOCK_H="${SOCK_DIR}/hedge_ok.sock"
else
    SOCK_H="${SOCK_DIR}/hedge_gray.sock"
fi

env FABRIC_TPU_FAULTS="serve.dispatch=delay:1.0:ms=2000" \
    FABRIC_TPU_FAULTS_SEED=1 python -m fabric_tpu.serve \
    --address "${SOCK_G}" --engine host --warm off >"${LOG_G}" 2>&1 &
PID_G=$!
python -m fabric_tpu.serve \
    --address "${SOCK_H}" --engine host --warm off >"${LOG_H}" 2>&1 &
PID_H=$!

for _ in $(seq 1 100); do
    grep -q "^SERVE_READY" "${LOG_G}" 2>/dev/null \
        && grep -q "^SERVE_READY" "${LOG_H}" 2>/dev/null && break
    sleep 0.1
done
if ! grep -q "^SERVE_READY" "${LOG_G}" || ! grep -q "^SERVE_READY" "${LOG_H}"; then
    echo "serve_gate: hedge-leg sidecars never became ready" >&2
    cat "${LOG_G}" "${LOG_H}" >&2
    exit 1
fi

timeout -k 5 30 python - "${SOCK_G}" "${SOCK_H}" <<'EOF'
import sys
import time

from fabric_tpu.serve.fleetload import build_lanes
from fabric_tpu.serve.router import SidecarRouter

gray, healthy = sys.argv[1], sys.argv[2]
# EVERY batch that prefers the gray endpoint must be rescued by a
# hedge: generous budget, tiny learned-delay floor
router = SidecarRouter(endpoints=[gray, healthy],
                       hedge_fraction=1.0, hedge_min_ms=25.0)
k, s, d, e = build_lanes(96, 5)
walls = []
for _ in range(3):
    t0 = time.monotonic()
    mask = router.batch_verify(k, s, d)
    walls.append(time.monotonic() - t0)
    assert list(mask) == e, "mask wrong under gray failure"
assert not router.degraded, "router degraded with a healthy peer up"
# the gray endpoint answers only after its 2s delay fault: any verdict
# faster than that was won by a hedge or served direct post-eviction
assert max(walls) < 2.0, f"tail not bounded: {walls}"
assert router.hedges >= 1 and router.hedge_wins >= 1, router.describe()
print("serve_gate hedge: %d hedges, %d wins, %d slow evictions, "
      "max wall %.0fms (delay 2000ms), masks exact"
      % (router.hedges, router.hedge_wins, router.slow_evictions,
         max(walls) * 1e3))
router.stop()
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "serve_gate: hedge leg FAILED" >&2
    cat "${LOG_G}" "${LOG_H}" >&2
    exit $rc
fi
kill "${PID_G}" "${PID_H}" 2>/dev/null
for _ in $(seq 1 40); do
    kill -0 "${PID_G}" 2>/dev/null || kill -0 "${PID_H}" 2>/dev/null || break
    sleep 0.25
done
cleanup3
PID_G=""; PID_H=""

echo "serve_gate: OK (mixed batch exact, clean shutdown; fleet failover exact, clean drain; hedge wins over gray sidecar)"
