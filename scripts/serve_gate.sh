#!/usr/bin/env bash
# serve_gate: the resident-sidecar smoke (< 60s, jax-free).
#
# Starts the sidecar as a REAL subprocess (host engine), waits for its
# SERVE_READY line, drives one mixed valid/invalid batch through the
# SidecarProvider client shim, asserts the mask equals the in-process
# ground truth bit-exactly, then performs a clean protocol SHUTDOWN and
# requires the server process to exit 0.
set -uo pipefail

cd "$(dirname "$0")/.."

SOCK_DIR="$(mktemp -d)"
SOCK="${SOCK_DIR}/serve_gate.sock"
LOG="$(mktemp)"

cleanup() {
    [ -n "${SRV_PID:-}" ] && kill "${SRV_PID}" 2>/dev/null
    rm -rf "${SOCK_DIR}"
    rm -f "${LOG}"
}
trap cleanup EXIT

timeout -k 5 55 python -m fabric_tpu.serve \
    --address "${SOCK}" --engine host --warm off >"${LOG}" 2>&1 &
SRV_PID=$!

# wait for the READY line (warm-up done, socket bound)
for _ in $(seq 1 100); do
    grep -q "^SERVE_READY" "${LOG}" 2>/dev/null && break
    kill -0 "${SRV_PID}" 2>/dev/null || { echo "serve_gate: server died:" >&2; cat "${LOG}" >&2; exit 1; }
    sleep 0.2
done
if ! grep -q "^SERVE_READY" "${LOG}"; then
    echo "serve_gate: server never became ready:" >&2
    cat "${LOG}" >&2
    exit 1
fi

timeout -k 5 40 python - "${SOCK}" <<'EOF'
import hashlib
import sys

from fabric_tpu.common import p256
from fabric_tpu.crypto import der, hostec
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider
from fabric_tpu.serve.client import SidecarProvider

addr = sys.argv[1]
d_priv = 0x1D1E5F
pub = ECDSAPublicKey(*hostec.scalar_base_mult(d_priv))
keys, sigs, digests, expected = [], [], [], []
for i in range(48):
    digest = hashlib.sha256(b"serve gate lane %d" % i).digest()
    r, s = hostec.sign_digest(d_priv, digest)
    sig = der.marshal_signature(r, s)
    kind = i % 4
    if kind == 1:  # corrupt signature
        bad = bytearray(sig); bad[-1] ^= 0x5A; sig = bytes(bad)
    elif kind == 2:  # high-S violation
        sig = der.marshal_signature(r, p256.N - s)
    elif kind == 3:  # garbage DER
        sig = b"\x00garbage"
    keys.append(pub); sigs.append(sig); digests.append(digest)
    expected.append(kind == 0)

provider = SidecarProvider(address=addr)
mask = provider.batch_verify(keys, sigs, digests)
assert list(mask) == expected, f"sidecar mask != ground truth: {mask}"
assert not provider.degraded, "gate batch was served in-process, not by the sidecar"
inproc = SoftwareProvider().batch_verify(keys, sigs, digests)
assert list(mask) == list(inproc), "sidecar mask != in-process mask"
stats = provider.client.stats()
assert stats["stats"]["requests"] >= 1, stats
provider.client.shutdown()
print(f"serve_gate: mask exact over {len(mask)} mixed lanes "
      f"({sum(mask)} valid), served by {stats['engine']} engine")
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "serve_gate: client smoke FAILED" >&2
    cat "${LOG}" >&2
    exit $rc
fi

# the SHUTDOWN opcode must produce a clean exit
wait "${SRV_PID}"
srv_rc=$?
SRV_PID=""
if [ $srv_rc -ne 0 ]; then
    echo "serve_gate: server exited rc=${srv_rc} after SHUTDOWN" >&2
    cat "${LOG}" >&2
    exit 1
fi
echo "serve_gate: OK (mixed batch exact, clean shutdown)"
