#!/usr/bin/env bash
# Seeded chaos smoke (< 60s): run the fabchaos smoke scenarios TWICE
# with the same seed and require
#   1. both runs green (every scenario's mask bit-exact + fail-closed
#      assertions hold under injected faults), and
#   2. byte-identical deterministic scorecards (replayability gate),
# then the fabcrash single-kill-site leg: a subprocess peer is killed
# at a durability seam, restarted, and byte-diffed against the
# no-crash run (the fast row of the crash matrix; the full matrix is
# pytest-slow).
set -uo pipefail

cd "$(dirname "$0")/.."

seed="${FABCHAOS_SEED:-7}"
out1=$(mktemp /tmp/fabchaos.XXXXXX.json)
out2=$(mktemp /tmp/fabchaos.XXXXXX.json)
out3=$(mktemp /tmp/fabchaos.XXXXXX.json)
trap 'rm -f "$out1" "$out2" "$out3"' EXIT

run() {
    # 25s per run keeps the two-run worst case inside the stage's <60s
    # budget (a smoke run is ~5s on the 2-vCPU CI box)
    timeout -k 5 25 python -m fabric_tpu.tools.fabchaos \
        --seed "$seed" --scenario smoke --quiet > "$1"
}

if ! run "$out1"; then
    echo "chaos_gate: smoke run 1 FAILED (seed $seed)" >&2
    cat "$out1" >&2
    exit 1
fi
if ! run "$out2"; then
    echo "chaos_gate: smoke run 2 FAILED (seed $seed)" >&2
    exit 1
fi
if ! cmp -s "$out1" "$out2"; then
    echo "chaos_gate: scorecards DIVERGED across identical seeds" >&2
    diff "$out1" "$out2" >&2 || true
    exit 1
fi

# fabcrash leg: one kill site, subprocess kill + restart + byte-diff
# (~5s: 4 child processes)
if ! timeout -k 5 60 python -m fabric_tpu.tools.fabchaos \
        --seed "$seed" --scenario crash_single --quiet > "$out3"; then
    echo "chaos_gate: crash_single FAILED (seed $seed)" >&2
    cat "$out3" >&2
    exit 1
fi
echo "chaos_gate: OK (seed $seed, $(python -c "
import json,sys
card = json.load(open('$out1'))
crash = json.load(open('$out3'))['scenarios']['crash_single']
sites = ','.join(crash['sites'])
print(len(card['scenarios']), 'scenarios deterministic + green;',
      'crash_single converged at', sites, end='')
"))"
