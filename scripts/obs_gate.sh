#!/usr/bin/env bash
# obs_gate: the observability smoke (~10-15s, jax-free).
#
#   1. In-process sidecar with the ops server mounted and a fault plan
#      armed: drive one mixed verify batch through the client shim,
#      scrape /metrics and require EVERY family in the canonical fabobs
#      table present, with sane values on the exercised seams (serve
#      requests, ladder rung lanes, batcher launches, dispatch retry,
#      fault fire).  /healthz must be 200; after killing the batcher it
#      must flip 503 naming the "batcher" checker.
#   2. Replay the fabchaos smoke twice — once bare, once with
#      FABRIC_TPU_OBS=1 — and byte-diff the deterministic scorecards:
#      instrumentation must change NOTHING the determinism gate sees.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python - <<'EOF'
import hashlib
import json
import re
import sys
import tempfile
import urllib.error
import urllib.request

from fabric_tpu.common import der, fabobs
from fabric_tpu.common.faults import FaultPlan, plan_installed
from fabric_tpu.crypto import hostec
from fabric_tpu.crypto.bccsp import ECDSAPublicKey, SoftwareProvider
from fabric_tpu.serve.client import SidecarProvider
from fabric_tpu.serve.server import SidecarServer

# the rmtree rides a finally armed IMMEDIATELY after mkdtemp (the
# fablife fd-leak discipline): a failure while mounting obs or
# constructing the server must not leak the dir across CI runs
import shutil
tmp = tempfile.mkdtemp(prefix="obs_gate_")
try:
  with fabobs.obs_installed(dump_dir=tmp):
    server = SidecarServer(
        f"{tmp}/obs_gate.sock", engine="host", ops_address="127.0.0.1:0",
    )
    try:
        server.warm()
        addr = server.start()
        ops = server.ops_address
        assert server.ops is not None, "ops server did not mount"

        # mixed valid/invalid batch (the serve_gate lane recipe), with a
        # one-shot dispatch fault armed so the retry + fault-fire
        # families move too (the batcher's bounded retry rides it out)
        d_priv = 0x0B5
        pub = ECDSAPublicKey(*hostec.scalar_base_mult(d_priv))
        keys, sigs, digests, expected = [], [], [], []
        for i in range(48):
            digest = hashlib.sha256(b"obs gate lane %d" % i).digest()
            r, s = hostec.sign_digest(d_priv, digest)
            sig = der.marshal_signature(r, s)
            if i % 3 == 1:
                bad = bytearray(sig); bad[-1] ^= 0x5A; sig = bytes(bad)
            elif i % 3 == 2:
                sig = b"\x00garbage"
            keys.append(pub); sigs.append(sig); digests.append(digest)
            expected.append(i % 3 == 0)
        provider = SidecarProvider(address=addr)
        with plan_installed(FaultPlan.parse("batcher.dispatch=raise:1.0:max=1")):
            mask = provider.batch_verify(keys, sigs, digests)
        assert list(mask) == expected, f"mask != ground truth: {mask}"
        assert not provider.degraded, "batch was served in-process"
        assert list(mask) == list(
            SoftwareProvider().batch_verify(keys, sigs, digests)
        ), "sidecar mask != in-process mask"

        with urllib.request.urlopen(f"http://{ops}/metrics") as resp:
            text = resp.read().decode()

        missing = [
            s.name for s in fabobs.CANONICAL_METRICS
            if f"# TYPE {s.name} {s.kind}" not in text
        ]
        assert not missing, f"families missing from /metrics: {missing}"

        def value(pattern):
            m = re.search(pattern + r"\}? (\d+(?:\.\d+)?)", text)
            return float(m.group(1)) if m else None

        checks = {
            'fabric_serve_requests_total{status="ok"': (1, None),
            'fabric_serve_lanes_total': (48, None),
            'fabric_batcher_launches_total{mode="coalesce"': (1, None),
            'fabric_batcher_dispatch_retries_total': (1, 1),
            'fabric_fault_fired_total{site="batcher.dispatch"': (1, 1),
            'fabric_retry_attempts_total': (1, None),
            'fabric_serve_connections_total{event="open"': (1, None),
        }
        for key, (lo, hi) in checks.items():
            v = value(re.escape(key))
            assert v is not None and v >= lo and (hi is None or v <= hi), (
                f"{key}: got {v}, wanted >= {lo}"
                + (f" and <= {hi}" if hi is not None else "")
            )
        rung = re.search(r'fabric_verify_lanes_total\{rung="(\w+)"\} (\d+)', text)
        assert rung and int(rung.group(2)) >= 48 + 8, (  # batch + warm lanes
            f"ladder rung lanes missing: {rung}"
        )

        with urllib.request.urlopen(f"http://{ops}/healthz") as resp:
            assert json.load(resp)["status"] == "OK"
        with urllib.request.urlopen(f"http://{ops}/trace") as resp:
            trace = json.load(resp)
        assert any(e["name"] == "serve.verify" for e in trace["traceEvents"])

        server.batcher.stop()
        try:
            urllib.request.urlopen(f"http://{ops}/healthz")
            raise SystemExit("healthz stayed 200 after batcher death")
        except urllib.error.HTTPError as err:
            assert err.code == 503, err.code
            failed = {c["component"] for c in json.load(err)["failed_checks"]}
            assert "batcher" in failed, failed
        print(
            f"obs_gate: /metrics all {len(fabobs.CANONICAL_METRICS)} canonical "
            f"families live (rung {rung.group(1)}), healthz 200->503[batcher]"
        )
    finally:
        server.stop()
finally:
    shutil.rmtree(tmp, ignore_errors=True)
EOF
rc=$?
if [ $rc -ne 0 ]; then
    echo "obs_gate: sidecar/metrics smoke FAILED" >&2
    exit $rc
fi

# -- 2. instrumentation must not move the deterministic chaos scorecard --
seed="${FABCHAOS_SEED:-7}"
out_bare=$(mktemp /tmp/obsgate.XXXXXX.json)
out_obs=$(mktemp /tmp/obsgate.XXXXXX.json)
trap 'rm -f "$out_bare" "$out_obs"' EXIT

if ! timeout -k 5 30 env -u FABRIC_TPU_OBS python -m fabric_tpu.tools.fabchaos \
        --seed "$seed" --scenario smoke --quiet > "$out_bare"; then
    echo "obs_gate: bare chaos smoke FAILED (seed $seed)" >&2
    exit 1
fi
if ! timeout -k 5 30 env FABRIC_TPU_OBS=1 python -m fabric_tpu.tools.fabchaos \
        --seed "$seed" --scenario smoke --quiet > "$out_obs"; then
    echo "obs_gate: observed chaos smoke FAILED (seed $seed)" >&2
    exit 1
fi
if ! cmp -s "$out_bare" "$out_obs"; then
    echo "obs_gate: instrumentation CHANGED the deterministic scorecard" >&2
    diff "$out_bare" "$out_obs" >&2 || true
    exit 1
fi
echo "obs_gate: OK (canonical families live, healthz flips, chaos scorecard byte-identical under instrumentation)"
