#!/usr/bin/env bash
# Idemix backend ladder micro-bench: prints ms/signature for the scheme
# oracle (extrapolated from a few lanes) and the hostbn numpy rung at
# batch 8/64/256 — WITHOUT importing jax or requiring the cryptography
# package (setup uses an unsigned ALG_NO_REVOCATION CRI, which Ver with
# rev_pk=None never reads).  The full bench (bench.py) owns the device
# column and the JSON artifact; this script answers "what does the
# Idemix host ladder do on THIS box" in ~2 min.
#
#   HOSTBN_BENCH_SIZES  comma-separated batch sizes   (default 8,64,256)
#   HOSTBN_BENCH_POOL   1 = let the batch layer's process pool shard
#                       sizes past its threshold (default 1)
#
# The payload runs from a real file (not a heredoc on stdin): the
# process pool's spawn/forkserver workers re-import __main__, which
# must therefore be importable.
set -uo pipefail

cd "$(dirname "$0")/.."

payload="$(mktemp --suffix=.py)"
trap 'rm -f "$payload"' EXIT

cat >"$payload" <<'PY'
import os
import random
import time


def main():
    sizes = [
        int(s)
        for s in os.environ.get("HOSTBN_BENCH_SIZES", "8,64,256").split(",")
        if s.strip()
    ]
    if os.environ.get("HOSTBN_BENCH_POOL", "1") != "1":
        # plain assignment: an exported FABRIC_TPU_HOSTBN_PROCS must not
        # silently turn a requested inline run into a pooled one
        os.environ["FABRIC_TPU_HOSTBN_PROCS"] = "1"

    from fabric_tpu import idemix
    from fabric_tpu.crypto import fp256bn as bn
    from fabric_tpu.crypto.bccsp import (
        available_idemix_backends,
        idemix_backend_name,
    )
    from fabric_tpu.idemix import batch as ib
    from fabric_tpu.protos import idemix_pb2

    rng = random.Random(1234)
    attrs = ["OU", "Role", "EnrollmentID", "RevocationHandle"]
    rh_index = 3
    print("building issuer/credential/signatures (host bignum)...")
    ik = idemix.new_issuer_key(attrs, rng)
    sk = bn.rand_mod_order(rng)
    req = idemix.new_cred_request(
        sk, bn.big_to_bytes(bn.rand_mod_order(rng)), ik.ipk, rng
    )
    cred = idemix.new_credential(ik, req, [11, 22, 33, 44], rng)
    cri = idemix_pb2.CredentialRevocationInformation()
    cri.revocation_alg = idemix.ALG_NO_REVOCATION
    disclosure = [0, 0, 0, 0]
    msg = b"hostbn bench message"
    uniq = []
    for _ in range(8):
        nym, r_nym = idemix.make_nym(sk, ik.ipk, rng)
        uniq.append(
            idemix.new_signature(
                cred, sk, nym, r_nym, ik.ipk, disclosure, msg,
                rh_index, cri, rng,
            )
        )

    def args(count):
        return (
            [uniq[i % len(uniq)] for i in range(count)],
            [disclosure] * count,
            ik.ipk,
            [msg] * count,
            [[None] * 4] * count,
            rh_index,
        )

    rows = []
    # oracle: a few lanes, extrapolated (a 256 batch would eat minutes)
    ib.verify_signatures_batch(*args(1), backend="scheme")  # warm-up
    t0 = time.perf_counter()
    assert all(ib.verify_signatures_batch(*args(3), backend="scheme"))
    oracle_ms = (time.perf_counter() - t0) * 1000.0 / 3
    rows.append(("scheme (oracle, extrapolated)", "-", oracle_ms))

    if available_idemix_backends().get("hostbn"):
        from fabric_tpu.crypto import hostbn
        from fabric_tpu.idemix.scheme import ecp2_from_proto

        hostbn.warm_schedules(ecp2_from_proto(ik.ipk.w))
        for size in sizes:
            best = None
            for _ in range(2 if size >= 64 else 1):
                t0 = time.perf_counter()
                out = ib.verify_signatures_batch(*args(size), backend="hostbn")
                ms = (time.perf_counter() - t0) * 1000.0 / size
                best = ms if best is None else min(best, ms)
                assert all(out)
            rows.append((f"hostbn @ {size}", f"{oracle_ms / best:.1f}x", best))
        ib.shutdown_pool()

    print()
    print(f"idemix host ladder (active rung: {idemix_backend_name()})")
    print(f"{'tier':32s} {'vs oracle':>10s} {'ms/sig':>10s}")
    for name, speedup, ms in rows:
        print(f"{name:32s} {speedup:>10s} {ms:10.1f}")
    if not available_idemix_backends().get("hostbn"):
        print(f"{'hostbn':32s} {'(numpy not installed)':>21s}")


if __name__ == "__main__":
    main()
PY

PYTHONPATH="$(pwd)${PYTHONPATH:+:$PYTHONPATH}" \
    timeout -k 10 600 python "$payload"
