#!/usr/bin/env bash
# fablint gate: AST-walk fabric_tpu/ and fail on any rule violation.
#
# Dependency-free and import-free: fablint parses source with ast, it
# never imports the linted modules, so this gate passes/fails identically
# in minimal environments (no cryptography, no jax).  Runs in ~3s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python -m fabric_tpu.tools.fablint fabric_tpu/
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "lint_gate: FAIL (fablint rc=$rc)" >&2
    exit 1
fi
echo "lint_gate: OK"
