#!/usr/bin/env bash
# fabtrace gate: device-plane trace discipline — every jit/pjit call
# site statically compile-free (argument shapes provably drawn from the
# bucket ladder / module constants), static_argnums/static_argnames fed
# per-call-stable values, no hidden host sync (.item(), float()/int()/
# bool(), np.asarray, block_until_ready) inside a declared pipeline
# stage outside its boundary = true sync points, no host<->device
# conversion inside per-lane loops in the device tier (the
# vectorized-ingest worklist), no tracer escaping a traced body, and no
# impure host call / mutable-module-state read at trace time
# (tools/hotpath.toml is the stage/device/transfer table).
#
# Dependency-free and import-free: fabtrace abstractly interprets shape
# provenance and residency with ast on the shared toolkit chassis — it
# never imports the analyzed modules, so this gate passes/fails
# identically in minimal environments (no cryptography, no jax, no
# numpy).  Scans the package only: tests craft shape-polymorphic and
# syncing fixtures by design.  Runs in ~2s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python -m fabric_tpu.tools.fabtrace fabric_tpu/
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "trace_gate: FAIL (fabtrace rc=$rc)" >&2
    exit 1
fi
echo "trace_gate: OK"
