#!/usr/bin/env bash
# Combined fast CI gate (< 30s total), run before tier-1:
#
#   1. python -m compileall    -- every file byte-compiles
#   2. collect_gate.sh         -- every test module imports cleanly
#   3. fablint --json          -- every invariant rule passes
#
# Each stage runs even if an earlier one failed (one run reports ALL
# broken gates); the exit code is nonzero if ANY stage failed.
set -uo pipefail

cd "$(dirname "$0")/.."

report="$(mktemp)"
trap 'rm -f "$report"' EXIT
fail=0

echo "== ci_gate 1/3: compileall =="
if ! timeout -k 5 120 python -m compileall -q fabric_tpu; then
    echo "ci_gate: compileall FAIL" >&2
    fail=1
fi

echo "== ci_gate 2/3: collect_gate =="
if ! bash scripts/collect_gate.sh; then
    echo "ci_gate: collect_gate FAIL" >&2
    fail=1
fi

echo "== ci_gate 3/3: fablint =="
if ! timeout -k 5 60 python -m fabric_tpu.tools.fablint --json fabric_tpu/ \
        > "$report"; then
    echo "ci_gate: fablint FAIL" >&2
    REPORT="$report" python - <<'EOF' >&2 || true
import json, os
for f in json.load(open(os.environ["REPORT"]))["findings"]:
    print(f"{f['path']}:{f['line']}:{f['col']}: {f['rule']}: {f['message']}")
EOF
    fail=1
fi

if [ "$fail" -ne 0 ]; then
    echo "ci_gate: FAIL" >&2
    exit 1
fi
echo "ci_gate: OK (compileall + collect + fablint)"
