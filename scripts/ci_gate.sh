#!/usr/bin/env bash
# Combined fast CI gate (< 30s total), run before tier-1:
#
#   1. python -m compileall    -- every file byte-compiles
#   2. collect_gate.sh         -- every test module imports cleanly
#   3. fablint                 -- every per-file invariant rule passes
#   4. fabdep                  -- whole-program gates: the package import
#                                 graph is a layered DAG (tools/layers.toml)
#                                 and the concurrency/API-surface rules pass
#   5. fabflow                 -- value-range/dtype abstract interpreter:
#                                 the limb kernels are overflow-free under
#                                 the canonical-limb contract and the mask
#                                 paths fail closed
#   6. chaos_gate.sh           -- seeded fabchaos smoke, run twice: mask
#                                 bit-exact + fail-closed under injected
#                                 faults, scorecards byte-identical
#   7. serve_gate.sh           -- resident sidecar smoke: subprocess
#                                 server, mixed batch through the client
#                                 shim bit-exact, clean SHUTDOWN
#   8. obs_gate.sh            -- observability smoke: sidecar + mounted
#                                 ops server, every canonical metric
#                                 family live on /metrics, /healthz
#                                 flips on batcher death, chaos
#                                 scorecard byte-identical under
#                                 instrumentation
#
# Each stage runs even if an earlier one failed (one run reports ALL
# broken gates) and prints its wall-clock time; the exit code is nonzero
# if ANY stage failed.
set -uo pipefail

cd "$(dirname "$0")/.."

fail=0
failed_stages=""

run_stage() {
    # run_stage <label> <command...>
    local label="$1"
    shift
    echo "== ci_gate ${label} =="
    local t0=$SECONDS
    if ! "$@"; then
        echo "ci_gate: ${label} FAIL" >&2
        fail=1
        failed_stages="${failed_stages} ${label}"
    fi
    echo "-- ${label}: $((SECONDS - t0))s"
}

run_stage "1/8 compileall" timeout -k 5 120 python -m compileall -q fabric_tpu
run_stage "2/8 collect_gate" bash scripts/collect_gate.sh
# the linters' human output already prints findings as
# path:line:col: rule: message — no JSON round-trip needed
run_stage "3/8 fablint" timeout -k 5 60 python -m fabric_tpu.tools.fablint fabric_tpu/
run_stage "4/8 fabdep" timeout -k 5 60 python -m fabric_tpu.tools.fabdep fabric_tpu/
run_stage "5/8 fabflow" timeout -k 5 120 python -m fabric_tpu.tools.fabflow fabric_tpu/
run_stage "6/8 chaos_gate" bash scripts/chaos_gate.sh
run_stage "7/8 serve_gate" bash scripts/serve_gate.sh
run_stage "8/8 obs_gate" bash scripts/obs_gate.sh

if [ "$fail" -ne 0 ]; then
    echo "ci_gate: FAIL (stages:${failed_stages})" >&2
    exit 1
fi
echo "ci_gate: OK (compileall + collect + fablint + fabdep + fabflow + chaos + serve + obs)"
