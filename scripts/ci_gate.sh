#!/usr/bin/env bash
# Combined fast CI gate (< 30s total), run before tier-1:
#
#   1. python -m compileall    -- every file byte-compiles
#   2. collect_gate.sh         -- every test module imports cleanly
#   3. fablint                 -- every per-file invariant rule passes
#   4. fabdep                  -- whole-program gates: the package import
#                                 graph is a layered DAG (tools/layers.toml)
#                                 and the concurrency/API-surface rules pass
#
# Each stage runs even if an earlier one failed (one run reports ALL
# broken gates); the exit code is nonzero if ANY stage failed.
set -uo pipefail

cd "$(dirname "$0")/.."

fail=0

echo "== ci_gate 1/4: compileall =="
if ! timeout -k 5 120 python -m compileall -q fabric_tpu; then
    echo "ci_gate: compileall FAIL" >&2
    fail=1
fi

echo "== ci_gate 2/4: collect_gate =="
if ! bash scripts/collect_gate.sh; then
    echo "ci_gate: collect_gate FAIL" >&2
    fail=1
fi

# both linters' human output already prints findings as
# path:line:col: rule: message — no JSON round-trip needed
echo "== ci_gate 3/4: fablint =="
if ! timeout -k 5 60 python -m fabric_tpu.tools.fablint fabric_tpu/; then
    echo "ci_gate: fablint FAIL" >&2
    fail=1
fi

echo "== ci_gate 4/4: fabdep =="
if ! timeout -k 5 60 python -m fabric_tpu.tools.fabdep fabric_tpu/; then
    echo "ci_gate: fabdep FAIL" >&2
    fail=1
fi

if [ "$fail" -ne 0 ]; then
    echo "ci_gate: FAIL" >&2
    exit 1
fi
echo "ci_gate: OK (compileall + collect + fablint + fabdep)"
