#!/usr/bin/env bash
# Combined fast CI gate (< 60s total), run before tier-1:
#
#   1. compileall   -- every file byte-compiles
#   2. collect      -- every test module imports cleanly (collect_gate.sh)
#   3. fablint      -- every per-file invariant rule passes
#   4. fabdep       -- whole-program gates: the package import graph is
#                      a layered DAG (tools/layers.toml) and the
#                      concurrency/API-surface rules pass
#   5. fabflow      -- value-range/dtype abstract interpreter: the limb
#                      kernels are overflow-free under the canonical-limb
#                      contract and the mask paths fail closed
#   6. chaos        -- seeded fabchaos smoke, run twice: mask bit-exact +
#                      fail-closed under injected faults, scorecards
#                      byte-identical (chaos_gate.sh)
#   7. serve        -- resident sidecar smoke: subprocess server, mixed
#                      batch through the client shim bit-exact, clean
#                      SHUTDOWN (serve_gate.sh)
#   8. obs          -- observability smoke: sidecar + mounted ops server,
#                      every canonical metric family live on /metrics,
#                      /healthz flips on batcher death, chaos scorecard
#                      byte-identical under instrumentation (obs_gate.sh)
#   9. reg          -- declarative-contract drift: env registry, metric
#                      table, fault-site table, suppression staleness
#                      (reg_gate.sh)
#  10. life         -- resource-lifetime + wire-trust: threads joined
#                      from teardown, fd/tempdir releases on exception
#                      edges, pairs.toml acquire/release discharge,
#                      wire ints clamped, request-path waits budgeted
#                      (life_gate.sh)
#  11. wire         -- wire-format conformance: encode/decode layout
#                      symmetry per negotiated revision, rev-gated
#                      fields unreachable below their rev, wire lengths
#                      bounded, OP_*/ST_* dispatch total, store read
#                      twins re-verify frame crcs (wire_gate.sh)
#  12. trace        -- device-plane trace discipline: jit call sites
#                      statically compile-free (shapes from the bucket
#                      ladder), no hidden host sync in declared pipeline
#                      stages, no per-lane host<->device conversion in
#                      device-tier loops, no tracer leaks or trace-time
#                      impurity (trace_gate.sh, tools/hotpath.toml)
#  13. det          -- whole-program byte-determinism taint: no
#                      wall-clock, unseeded-random, hash/set-order,
#                      fs-order, unsorted-serialize, or environment
#                      value flows into a declared det surface
#                      (det_gate.sh, tools/det.toml)
#
# Each stage runs even if an earlier one failed (one run reports ALL
# broken gates) and prints its wall-clock time; the exit code is nonzero
# if ANY stage failed.
#
# --only <stage> re-runs a single stage (by number or name, e.g.
# `--only 5` or `--only fabflow`) so a builder can iterate on one
# failing gate without paying the full ~50s sweep.
set -uo pipefail

cd "$(dirname "$0")/.."

only=""
if [ "${1:-}" = "--only" ]; then
    if [ -z "${2:-}" ]; then
        echo "ci_gate: --only requires a stage number or name" >&2
        exit 2
    fi
    only="$2"
elif [ -n "${1:-}" ]; then
    echo "ci_gate: unknown argument: $1 (usage: ci_gate.sh [--only STAGE])" >&2
    exit 2
fi

STAGE_NAMES=(compileall collect fablint fabdep fabflow chaos serve obs reg life wire trace det)
total=${#STAGE_NAMES[@]}

fail=0
failed_stages=""
ran=0
stage_idx=0

run_stage() {
    # run_stage <name> <command...>  (index derived from call order —
    # one source of truth, no renumbering when a stage is inserted)
    local name="$1"
    shift
    stage_idx=$((stage_idx + 1))
    if [ -n "$only" ] && [ "$only" != "$stage_idx" ] && [ "$only" != "$name" ]; then
        return 0
    fi
    ran=$((ran + 1))
    echo "== ci_gate ${stage_idx}/${total} ${name} =="
    local t0=$SECONDS
    if ! "$@"; then
        echo "ci_gate: ${name} FAIL" >&2
        fail=1
        failed_stages="${failed_stages} ${name}"
    fi
    echo "-- ${name}: $((SECONDS - t0))s"
}

run_stage compileall timeout -k 5 120 python -m compileall -q fabric_tpu
run_stage collect bash scripts/collect_gate.sh
# the linters' human output already prints findings as
# path:line:col: rule: message — no JSON round-trip needed
run_stage fablint timeout -k 5 60 python -m fabric_tpu.tools.fablint fabric_tpu/
run_stage fabdep timeout -k 5 60 python -m fabric_tpu.tools.fabdep fabric_tpu/
run_stage fabflow timeout -k 5 120 python -m fabric_tpu.tools.fabflow fabric_tpu/
run_stage chaos bash scripts/chaos_gate.sh
run_stage serve bash scripts/serve_gate.sh
run_stage obs bash scripts/obs_gate.sh
run_stage reg bash scripts/reg_gate.sh
run_stage life bash scripts/life_gate.sh
run_stage wire bash scripts/wire_gate.sh
run_stage trace bash scripts/trace_gate.sh
run_stage det bash scripts/det_gate.sh

if [ "$stage_idx" -ne "$total" ]; then
    echo "ci_gate: BUG: ${stage_idx} run_stage calls but ${total} stage names" >&2
    exit 2
fi

if [ "$ran" -eq 0 ]; then
    echo "ci_gate: no stage matched --only '$only'" \
        "(stages: 1-${total} or ${STAGE_NAMES[*]})" >&2
    exit 2
fi
if [ "$fail" -ne 0 ]; then
    echo "ci_gate: FAIL (stages:${failed_stages})" >&2
    exit 1
fi
if [ -n "$only" ]; then
    echo "ci_gate: OK (--only ${only})"
else
    echo "ci_gate: OK (compileall + collect + fablint + fabdep + fabflow + chaos + serve + obs + reg + life + wire + trace + det)"
fi
