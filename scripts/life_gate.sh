#!/usr/bin/env bash
# fablife gate: resource-lifetime + wire-trust check — every started
# thread join-reachable from its owner's teardown, every
# socket/file/tempdir release guaranteed on exception edges, every bare
# lock acquire paired in a finally, every pairs.toml acquire
# (ClassLedger lanes, pool shards, CooldownGate verdicts, batcher
# admissions) discharged on every success path, no wire-decoded integer
# reaching a sleep/timeout/allocation unclamped, and no unbudgeted
# blocking call on the serve/router/batcher request paths.
#
# Dependency-free and import-free: fablife parses source with ast on
# the shared toolkit chassis — it never imports the analyzed modules,
# so this gate passes/fails identically in minimal environments (no
# cryptography, no jax, no numpy).  Scans tests/ and bench.py too: a
# leaked tempdir in a test helper accumulates across CI runs exactly
# like one in the serving plane.  Runs in ~5s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python -m fabric_tpu.tools.fablife \
    fabric_tpu/ tests/ bench.py
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "life_gate: FAIL (fablife rc=$rc)" >&2
    exit 1
fi
echo "life_gate: OK"
