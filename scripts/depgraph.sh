#!/usr/bin/env bash
# Dump the fabric_tpu package import graph for the bench artifacts:
#
#   scripts/depgraph.sh            -> depgraph.dot + depgraph.json in CWD
#   scripts/depgraph.sh out/prefix -> out/prefix.dot + out/prefix.json
#
# Nodes are packages annotated with their declared layer
# (fabric_tpu/tools/layers.toml); edges carry the import-site count.
# Render with `dot -Tsvg depgraph.dot -o depgraph.svg` where graphviz
# is available — the dump itself is dependency-free (pure fabdep).
set -euo pipefail

cd "$(dirname "$0")/.."

prefix="${1:-depgraph}"

timeout -k 5 60 python -m fabric_tpu.tools.fabdep --dot fabric_tpu/ \
    > "${prefix}.dot"
timeout -k 5 60 python -m fabric_tpu.tools.fabdep --graph-json fabric_tpu/ \
    > "${prefix}.json"

echo "depgraph: wrote ${prefix}.dot and ${prefix}.json" >&2
