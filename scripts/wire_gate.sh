#!/usr/bin/env bash
# fabwire gate: wire-format conformance — every declared encoder/decoder
# pair's field layout (order/width/endianness) symmetric at every
# negotiated revision, revision-gated fields unreachable below their
# introducing rev (tools/wire.toml is the revision table), no
# wire-decoded length reaching recv/read/range/allocation/sleep without
# a MAX_PAYLOAD-class dominating bound, every OP_*/ST_* dispatch total
# or fail-closed, and every durability-store read twin re-verifying the
# header/payload crc its write twin emits.
#
# Dependency-free and import-free: fabwire abstractly executes the
# encode/decode bodies with ast on the shared toolkit chassis — it
# never imports the analyzed modules, so this gate passes/fails
# identically in minimal environments (no cryptography, no jax, no
# numpy).  Scans the package only: tests craft deliberately skewed and
# truncated frames by design.  Runs in ~2s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python -m fabric_tpu.tools.fabwire fabric_tpu/
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "wire_gate: FAIL (fabwire rc=$rc)" >&2
    exit 1
fi
echo "wire_gate: OK"
