#!/usr/bin/env bash
# Host EC ladder micro-bench: prints verifies/s for every host tier that
# can load — p256 oracle (extrapolated from a few lanes), hostec, and
# hostec_np — WITHOUT importing jax or requiring the cryptography
# package (fastec is reported as skipped when absent).  The full bench
# (bench.py) owns the device columns and the JSON artifact; this script
# answers "what does the host ladder do on THIS box" in ~30s.
#
#   HOSTEC_BENCH_LANES  batch size per timed pass   (default 2048 —
#                       the smallest size where hostec_np actually
#                       exercises its shared-memory pool path)
#   HOSTEC_BENCH_POOL   1 = also time the sharded/pooled entrypoints
#
# The payload runs from a real file (not a heredoc on stdin): the
# process pools' spawn/forkserver workers re-import __main__, which
# must therefore be importable.
set -uo pipefail

cd "$(dirname "$0")/.."

payload="$(mktemp --suffix=.py)"
trap 'rm -f "$payload"' EXIT

cat >"$payload" <<'PY'
import hashlib
import os
import time


def main():
    lanes_n = int(os.environ.get("HOSTEC_BENCH_LANES", "2048"))
    do_pool = os.environ.get("HOSTEC_BENCH_POOL", "1") == "1"

    from fabric_tpu.common import p256
    from fabric_tpu.crypto import hostec

    try:
        from fabric_tpu.crypto import hostec_np
        have_np = hostec_np.HAVE_NUMPY
    except Exception:
        have_np = False

    try:
        import fabric_tpu.crypto.fastec  # noqa: F401
        have_fastec = True
    except ImportError:
        have_fastec = False

    kp = hostec.generate_keypair()
    lanes = []
    for i in range(lanes_n):
        d = hashlib.sha256(b"hostec_bench %d" % i).digest()
        r, s = hostec.sign_digest(kp.priv, d)
        lanes.append((kp.pub, d, r, s))

    rows = []

    # oracle: a few lanes, extrapolated (a full batch would eat minutes)
    t0 = time.perf_counter()
    for lane in lanes[:3]:
        assert p256.verify_digest(*lane)
    rows.append(
        ("p256 (oracle, extrapolated)", 3 / (time.perf_counter() - t0))
    )

    t0 = time.perf_counter()
    assert all(hostec.verify_parsed_batch(lanes))
    rows.append(("hostec (inline)", lanes_n / (time.perf_counter() - t0)))

    if have_np:
        hostec_np.warm_tables()
        t0 = time.perf_counter()
        assert all(hostec_np.verify_parsed_batch(lanes))
        rows.append(
            ("hostec_np (inline)", lanes_n / (time.perf_counter() - t0))
        )

    if do_pool:
        hostec.verify_parsed_batch_sharded(lanes)()  # pool boot untimed
        t0 = time.perf_counter()
        assert all(hostec.verify_parsed_batch_sharded(lanes)())
        rows.append(
            ("hostec (sharded pool)", lanes_n / (time.perf_counter() - t0))
        )
        hostec.shutdown_pool()
        if have_np:
            hostec_np.verify_parsed_batch_sharded(lanes)()
            t0 = time.perf_counter()
            assert all(hostec_np.verify_parsed_batch_sharded(lanes)())
            pooled = lanes_n >= hostec_np.MIN_POOL_LANES
            label = (
                "hostec_np (shm-sharded pool)"
                if pooled
                else "hostec_np (sharded entry, ran inline)"
            )
            rows.append((label, lanes_n / (time.perf_counter() - t0)))
            hostec_np.shutdown_pool()

    print()
    print(f"host EC backend ladder @ {lanes_n} lanes")
    print(f"{'tier':32s} {'verifies/s':>12s}")
    for name, rate in rows:
        print(f"{name:32s} {rate:12.1f}")
    if not have_fastec:
        print(f"{'fastec':32s} {'(cryptography not installed)':>28s}")
    if not have_np:
        print(f"{'hostec_np':32s} {'(numpy not installed)':>21s}")


if __name__ == "__main__":
    main()
PY

PYTHONPATH="$(pwd)${PYTHONPATH:+:$PYTHONPATH}" \
    timeout -k 10 600 python "$payload"
