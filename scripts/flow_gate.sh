#!/usr/bin/env bash
# fabflow gate: abstract-interpret fabric_tpu/ and fail on any
# value-range / dtype / mask-soundness violation.
#
# Dependency-free and import-free: fabflow parses source with ast and
# interprets it over an interval domain — it never imports the analyzed
# modules, so this gate passes/fails identically in minimal environments
# (no cryptography, no jax).  Runs in ~6s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 120 python -m fabric_tpu.tools.fabflow fabric_tpu/
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "flow_gate: FAIL (fabflow rc=$rc)" >&2
    exit 1
fi
echo "flow_gate: OK"
