#!/usr/bin/env bash
# Fast collection gate: every test module must IMPORT cleanly (module-scope
# dependency regressions fail here in seconds, instead of poisoning a
# 15-minute tier-1 run with dozens of collection errors).
#
# Run before tier-1. Exit 0 iff pytest reports zero collection errors.
set -uo pipefail

cd "$(dirname "$0")/.."

log="$(mktemp)"
trap 'rm -f "$log"' EXIT

# -o norecursedirs REPLACES pytest's defaults, so restate them (.* build
# dist venv node_modules *.egg ...) and add __pycache__ + native/: a
# stray artifact .py there must not poison collection
JAX_PLATFORMS=cpu timeout -k 10 240 python -m pytest tests/ --collect-only -q \
    -o 'norecursedirs=*.egg .* _darcs build CVS dist node_modules venv {arch} __pycache__ native' \
    -p no:cacheprovider -p no:xdist -p no:randomly >"$log" 2>&1
rc=$?

errors=$(grep -acE '^ERROR ' "$log" || true)
tail -n 3 "$log"

if [ "$rc" -ne 0 ] || [ "${errors:-0}" -ne 0 ]; then
    echo "collect_gate: FAIL (${errors:-?} collection errors, pytest rc=$rc)" >&2
    grep -aE '^ERROR ' "$log" >&2 || true
    exit 1
fi
echo "collect_gate: OK (0 collection errors)"
