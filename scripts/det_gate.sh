#!/usr/bin/env bash
# fabdet gate: whole-program byte-determinism taint discipline — no
# wall-clock read, unseeded random draw, PYTHONHASHSEED-dependent
# hash/set order, unsorted directory listing, unsorted json.dump(s), or
# pid/hostname/environ value flows into a declared det surface
# (tools/det.toml: crashchild digests, snapshot files + signable
# metadata, fabchaos det scorecards, blockstore/pvt frame writers,
# serve/protocol encoders, commit-hash rows, merkle digests, AOT
# artifact blobs).  New det surfaces extend the gate by adding a
# [[surface]] row, never by editing the analyzer.
#
# Dependency-free and import-free: fabdet propagates taint
# interprocedurally with ast on the shared toolkit chassis — it never
# imports the analyzed modules, so this gate passes/fails identically
# in minimal environments (no cryptography, no jax, no numpy).  Scans
# the package only: tests craft nondeterminism fixtures by design.
# Runs in ~2s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python -m fabric_tpu.tools.fabdet fabric_tpu/
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "det_gate: FAIL (fabdet rc=$rc)" >&2
    exit 1
fi
echo "det_gate: OK"
