#!/usr/bin/env bash
# fabreg gate: declarative-contract drift check — every FABRIC_TPU_*
# env read declared in common/envreg.py (and every row live), every
# fabobs emit site named + labeled per CANONICAL_METRICS (and every
# family emitted), every fault_point site in the README table and
# exercised by a fabchaos scenario, and every analyzer suppression
# still absorbing a finding.  (Det-surface taint, formerly the
# det-hazard rule here, is det_gate.sh / fabdet's whole-program job.)
#
# Dependency-free and import-free: fabreg parses source with
# ast/tokenize (re-running fablint/fabdep/fabflow rule subsets for the
# suppression-stale check), it never imports the analyzed modules, so
# this gate passes/fails identically in minimal environments (no
# cryptography, no jax, no numpy).  Runs in ~8s.
set -uo pipefail

cd "$(dirname "$0")/.."

timeout -k 5 60 python -m fabric_tpu.tools.fabreg \
    --readme README.md fabric_tpu/ tests/ bench.py
rc=$?

if [ "$rc" -ne 0 ]; then
    echo "reg_gate: FAIL (fabreg rc=$rc)" >&2
    exit 1
fi
echo "reg_gate: OK"
