"""Service discovery (reference discovery/service.go:88 +
discovery/endorsement/endorsement.go PeersForEndorsement).

Three query kinds, mirroring the reference's Request/Response surface:

* ``peers(channel)`` — membership view: per-org online peers with
  endpoints, ledger heights and installed chaincodes;
* ``config(channel)`` — MSP ids + orderer endpoints from channel config;
* ``endorsers(channel, chaincode)`` — an EndorsementDescriptor: peers
  grouped by principal, plus the minimal layouts (group -> quantity)
  that satisfy the chaincode's endorsement policy, computed with the
  principal-set algebra in fabric_tpu.discovery.inquire.

Access control: every query authenticates the client against the
channel's Readers policy (service.go authCache + acl support), with a
small result cache keyed by the raw identity.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.discovery.inquire import satisfied_by
from fabric_tpu.policy.ast import MSPPrincipal, Role, SignaturePolicyEnvelope
from fabric_tpu.policy.manager import PolicyError, SignedData


class DiscoveryError(Exception):
    pass


@dataclass(frozen=True)
class PeerInfo:
    """One online peer as gossip membership sees it (discovery's
    peers-of-channel input)."""

    msp_id: str
    endpoint: str
    ledger_height: int = 0
    chaincodes: Tuple[str, ...] = ()
    is_peer_role: bool = True


@dataclass
class EndorsementDescriptor:
    chaincode: str
    # group name ("G0", "G1", ...) -> peers
    endorsers_by_groups: Dict[str, List[PeerInfo]]
    # each layout: group name -> how many endorsements needed from it
    layouts: List[Dict[str, int]]


class DiscoveryService:
    def __init__(
        self,
        # channel -> live peers (gossip membership + identity mapping)
        peers_provider: Callable[[str], Sequence[PeerInfo]],
        # channel -> channelconfig Bundle (msps, orderer endpoints, policies)
        bundle_provider: Callable[[str], Optional[object]],
        # (chaincode, channel) -> endorsement policy envelope
        policy_provider: Callable[[str, str], Optional[SignaturePolicyEnvelope]],
    ):
        self._peers = peers_provider
        self._bundle = bundle_provider
        self._policy = policy_provider
        self._auth_cache: Dict[Tuple[str, bytes], bool] = {}

    # -- access control (service.go processQuery -> acl check) ----------
    def _authorize(self, channel: str, client: SignedData) -> None:
        bundle = self._bundle(channel)
        if bundle is None:
            raise DiscoveryError(f"channel {channel} not found")
        key = (channel, client.identity)
        cached = self._auth_cache.get(key)
        if cached is True:
            return
        if cached is False:
            raise DiscoveryError("access denied")
        policy, ok = bundle.policy_manager.get_policy(
            "/Channel/Application/Readers"
        )
        if not ok:
            policy, ok = bundle.policy_manager.get_policy("/Channel/Readers")
        try:
            policy.evaluate_signed_data([client])
            self._auth_cache[key] = True
        except PolicyError as e:
            self._auth_cache[key] = False
            raise DiscoveryError(f"access denied: {e}") from e

    # -- queries ----------------------------------------------------------
    def peers(self, channel: str, client: SignedData) -> List[PeerInfo]:
        self._authorize(channel, client)
        return sorted(
            self._peers(channel), key=lambda p: (p.msp_id, p.endpoint)
        )

    def config(self, channel: str, client: SignedData) -> Dict:
        self._authorize(channel, client)
        bundle = self._bundle(channel)
        orderers: Dict[str, List[str]] = {}
        if bundle.orderer is not None:
            for org in bundle.orderer.orgs:
                if org.ordererendpoints:
                    orderers[org.msp_id] = list(org.ordererendpoints)
        if not orderers and getattr(bundle, "orderer_addresses", None):
            orderers[""] = list(bundle.orderer_addresses)
        return {
            "msps": sorted(m.msp_id for m in bundle.msp_manager.msps()),
            "orderers": orderers,
        }

    def endorsers(
        self, channel: str, chaincode: str, client: SignedData
    ) -> EndorsementDescriptor:
        """PeersForEndorsement: minimal principal combinations -> layouts
        over groups of online peers (endorsement.go:84,221-240)."""
        self._authorize(channel, client)
        policy = self._policy(chaincode, channel)
        if policy is None:
            raise DiscoveryError(
                f"failed constructing descriptor for chaincode {chaincode}"
            )
        peers = [
            p
            for p in self._peers(channel)
            if chaincode in p.chaincodes and p.is_peer_role
        ]
        principal_sets = satisfied_by(policy)

        # group per distinct principal; membership = peers whose identity
        # satisfies it (role matching by MSP here — OU-level matching goes
        # through the MSP in the reference)
        principals: List[MSPPrincipal] = []
        for ps in principal_sets:
            for p in ps:
                if p not in principals:
                    principals.append(p)
        group_name = {p: f"G{i}" for i, p in enumerate(principals)}
        groups: Dict[str, List[PeerInfo]] = {}
        for principal, name in group_name.items():
            members = [
                peer for peer in peers if _peer_satisfies(peer, principal)
            ]
            groups[name] = sorted(
                members, key=lambda p: (-p.ledger_height, p.endpoint)
            )

        layouts: List[Dict[str, int]] = []
        for ps in principal_sets:
            layout: Dict[str, int] = {}
            for principal in ps:
                layout[group_name[principal]] = (
                    layout.get(group_name[principal], 0) + 1
                )
            # a layout is viable only if every group has enough peers
            if all(
                len(groups.get(g, [])) >= qty for g, qty in layout.items()
            ):
                if layout not in layouts:
                    layouts.append(layout)
        if not layouts:
            raise DiscoveryError(
                f"no endorsement combination can be satisfied for "
                f"{chaincode} on {channel}"
            )
        return EndorsementDescriptor(
            chaincode=chaincode,
            endorsers_by_groups={
                g: members for g, members in groups.items() if members
            },
            layouts=layouts,
        )


def _peer_satisfies(peer: PeerInfo, principal: MSPPrincipal) -> bool:
    if peer.msp_id != principal.msp_id:
        return False
    if principal.role in (Role.MEMBER, Role.PEER):
        return True
    return False  # admins/clients don't endorse
