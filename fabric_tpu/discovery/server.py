"""Discovery over gRPC + the client library (reference discovery/support
+ discovery/client: the SDK-facing service answering peers / config /
endorsers queries with a signed request).

Wire format: one `discovery.Discovery/Process` unary RPC carrying a
SignedRequest whose payload is a JSON query document signed by the
client identity — the reference's SignedRequest shape
(discovery/protocol.proto) with a JSON body standing in for the full
discovery proto tree:

  payload = {"channel": "...", "query": "peers|config|endorsers",
             "chaincode": "...", "identity": base64(SerializedIdentity)}

Access control is the channel's Readers policy evaluated over the signed
payload, exactly like service.go processQuery.
"""

from __future__ import annotations

import base64
import json
from dataclasses import asdict
from typing import Optional

from fabric_tpu.comm.server import GRPCServer, UNARY, channel_to
from fabric_tpu.discovery.service import DiscoveryError, DiscoveryService
from fabric_tpu.policy.manager import SignedData
from fabric_tpu.protos import discovery_pb2

SERVICE_NAME = "discovery.Discovery"


class DiscoveryServer:
    def __init__(self, service: DiscoveryService):
        self.service = service

    def process(self, request, context):
        out = discovery_pb2.QueryResponse()
        try:
            doc = json.loads(request.payload)
            client = SignedData(
                data=request.payload,
                identity=base64.b64decode(doc["identity"]),
                signature=request.signature,
            )
            channel = doc.get("channel", "")
            query = doc.get("query")
            if query == "peers":
                result = [
                    asdict(p) for p in self.service.peers(channel, client)
                ]
            elif query == "config":
                result = self.service.config(channel, client)
            elif query == "endorsers":
                desc = self.service.endorsers(
                    channel, doc.get("chaincode", ""), client
                )
                result = {
                    "chaincode": desc.chaincode,
                    "endorsers_by_groups": {
                        g: [asdict(p) for p in peers]
                        for g, peers in desc.endorsers_by_groups.items()
                    },
                    "layouts": desc.layouts,
                }
            else:
                raise DiscoveryError(f"unknown query {query!r}")
            out.status = 200
            out.result = json.dumps(result, sort_keys=True).encode()
        except (DiscoveryError, ValueError, KeyError) as exc:
            out.status = 500
            out.result = json.dumps({"error": str(exc)}).encode()
        return out

    def register(self, server: GRPCServer) -> None:
        server.register(
            SERVICE_NAME,
            {
                "Process": (
                    UNARY,
                    self.process,
                    discovery_pb2.SignedRequest.FromString,
                    discovery_pb2.QueryResponse.SerializeToString,
                )
            },
        )


def query(
    addr: str,
    signer,
    channel: str,
    what: str,
    chaincode: str = "",
    root_ca: Optional[bytes] = None,
):
    """Client half (discovery/client): sign + send one query, return the
    decoded JSON result (raises DiscoveryError on a service error)."""
    doc = {
        "channel": channel,
        "query": what,
        "chaincode": chaincode,
        "identity": base64.b64encode(signer.serialize()).decode(),
    }
    payload = json.dumps(doc, sort_keys=True).encode()
    req = discovery_pb2.SignedRequest()
    req.payload = payload
    req.signature = signer.sign(payload)
    conn = channel_to(addr, root_ca)
    try:
        resp = conn.unary_unary(
            f"/{SERVICE_NAME}/Process",
            request_serializer=discovery_pb2.SignedRequest.SerializeToString,
            response_deserializer=discovery_pb2.QueryResponse.FromString,
        )(req, timeout=10.0)
    finally:
        conn.close()
    body = json.loads(resp.result)
    if resp.status != 200:
        raise DiscoveryError(body.get("error", "discovery failed"))
    return body
