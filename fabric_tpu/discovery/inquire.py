"""Principal-set algebra over signature policies (reference
common/policies/inquire: SatisfiedBy/principalSets).

``satisfied_by(envelope)`` returns every minimal multiset of principals
that satisfies the policy — the input to endorsement-descriptor layout
computation (discovery/endorsement/endorsement.go:221-240). Combination
counts are capped like the reference's inquire (it bounds recursion via
combinationsUpperBound) so a pathological NOutOf cannot explode.
"""

from __future__ import annotations

from itertools import combinations
from typing import List, Tuple

from fabric_tpu.policy.ast import (
    MSPPrincipal,
    NOutOf,
    SignaturePolicyEnvelope,
    SignedBy,
)

COMBINATION_CAP = 10_000


class TooManyCombinationsError(Exception):
    pass


PrincipalSet = Tuple[MSPPrincipal, ...]  # a multiset, kept sorted


def _merge(a: PrincipalSet, b: PrincipalSet) -> PrincipalSet:
    return tuple(sorted(a + b, key=lambda p: (p.msp_id, p.role.value)))


def _sets_for(rule, identities) -> List[PrincipalSet]:
    if isinstance(rule, SignedBy):
        return [(identities[rule.index],)]
    assert isinstance(rule, NOutOf)
    child_sets = [_sets_for(r, identities) for r in rule.rules]
    out: List[PrincipalSet] = []
    for chosen in combinations(range(len(child_sets)), rule.n):
        partial: List[PrincipalSet] = [()]
        for idx in chosen:
            nxt = []
            for base in partial:
                for s in child_sets[idx]:
                    nxt.append(_merge(base, s))
                    if len(nxt) > COMBINATION_CAP:
                        raise TooManyCombinationsError(
                            "policy has too many satisfying combinations"
                        )
            partial = nxt
        out.extend(partial)
        if len(out) > COMBINATION_CAP:
            raise TooManyCombinationsError(
                "policy has too many satisfying combinations"
            )
    # dedupe while keeping deterministic order
    seen = set()
    uniq = []
    for s in out:
        if s not in seen:
            seen.add(s)
            uniq.append(s)
    return uniq


def satisfied_by(env: SignaturePolicyEnvelope) -> List[PrincipalSet]:
    return _sets_for(env.rule, env.identities)
