from fabric_tpu.discovery.inquire import satisfied_by  # noqa: F401
from fabric_tpu.discovery.service import (  # noqa: F401
    DiscoveryService,
    EndorsementDescriptor,
    PeerInfo,
)
