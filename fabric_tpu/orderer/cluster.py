"""Orderer-to-orderer cluster communication (reference orderer/common/
cluster/comm.go:117,127: per-channel DispatchSubmit / DispatchConsensus
behind the Step RPC).

Two paths, like the reference:

- Consensus: raft wire messages between cluster members, carried on a
  long-lived Step stream (fire-and-forget; raft handles loss by
  retransmission on the next tick/append).
- Submit: transaction forwarding from a follower to the raft leader, a
  unary call that returns the leader's Broadcast status (reference
  SubmitRequest/SubmitResponse on the Step stream).

The client keeps one sender thread + queue per remote node; broken
connections drop queued messages and reconnect lazily (raft tolerates
this: lost appends retransmit, lost votes retrigger elections).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Optional, Tuple

import grpc  # fablint: disable=module-import  # raft transport is grpc-only; comm.server below pulls grpc regardless

from fabric_tpu.comm.server import GRPCServer, STREAM_STREAM, UNARY, channel_to
from fabric_tpu.orderer.raft import Message, message_from_bytes, message_to_bytes
from fabric_tpu.protos import cluster_pb2, common_pb2

SERVICE_NAME = "orderer.Cluster"


class ClusterService:
    """Server side: dispatch Step payloads to the local registrar's chains
    (comm.go DispatchSubmit/DispatchConsensus)."""

    def __init__(self, registrar, broadcast_handler=None):
        self.registrar = registrar
        self.broadcast = broadcast_handler

    # Step: bidi stream of consensus messages (no responses)
    def step(self, request_iterator, context):
        for req in request_iterator:
            which = req.WhichOneof("payload")
            if which == "consensus_request":
                self._dispatch_consensus(req.consensus_request)
            elif which == "submit_request":
                status, info = self._dispatch_submit(req.submit_request)
                resp = cluster_pb2.ClusterStepResponse()
                resp.submit_res.channel = req.submit_request.channel
                resp.submit_res.status = status
                resp.submit_res.info = info
                yield resp

    def submit(self, request, context):
        status, info = self._dispatch_submit(request)
        resp = cluster_pb2.ClusterSubmitResponse()
        resp.channel = request.channel
        resp.status = status
        resp.info = info
        return resp

    def _dispatch_consensus(self, req) -> None:
        support = self.registrar.get_chain(req.channel)
        if support is None:
            return  # unknown channel: drop (reference logs + errors the stream)
        chain = support.chain
        if hasattr(chain, "step"):
            try:
                chain.step(message_from_bytes(req.payload))
            except Exception:
                # a malformed/stale message must not kill the stream
                pass

    def _dispatch_submit(self, req) -> Tuple[int, str]:
        if self.broadcast is None:
            return common_pb2.SERVICE_UNAVAILABLE, "no broadcast handler"
        # leader-side processing of a forwarded envelope: same msgprocessor
        # + order path as a direct Broadcast (broadcast.go), minus another
        # forwarding hop (forwarded=True breaks redirect loops).
        return self.broadcast.process_message(req.payload, forwarded=True)

    def register(self, server: GRPCServer) -> None:
        server.register(
            SERVICE_NAME,
            {
                "Step": (
                    STREAM_STREAM,
                    self.step,
                    cluster_pb2.ClusterStepRequest.FromString,
                    cluster_pb2.ClusterStepResponse.SerializeToString,
                ),
                "Submit": (
                    UNARY,
                    self.submit,
                    cluster_pb2.ClusterSubmitRequest.FromString,
                    cluster_pb2.ClusterSubmitResponse.SerializeToString,
                ),
            },
        )


class _Remote:
    """One peer orderer: lazy channel + a sender thread draining a queue
    into the Step stream (reference cluster.RemoteContext/Remote :168)."""

    def __init__(self, addr: str, root_ca: Optional[bytes] = None):
        self.addr = addr
        self.root_ca = root_ca
        self.q: "queue.Queue[Optional[cluster_pb2.ClusterStepRequest]]" = (
            queue.Queue(maxsize=4096)
        )
        self._channel: Optional[grpc.Channel] = None
        # the sender thread (reconnect path) and submit() callers both
        # create/reset _channel (fabdep unguarded-shared-write): without
        # the lock two channels can be created and one leaks unclosed
        self._ch_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name=f"cluster-send-{addr}", daemon=True
        )
        self._stopped = False
        self._thread.start()

    def channel(self) -> grpc.Channel:
        with self._ch_lock:
            if self._channel is None:
                self._channel = channel_to(self.addr, self.root_ca)
            return self._channel

    def _reset_channel(self) -> None:
        with self._ch_lock:
            ch, self._channel = self._channel, None
        if ch is not None:
            ch.close()

    def enqueue_consensus(self, channel_id: str, msg: Message) -> None:
        req = cluster_pb2.ClusterStepRequest()
        req.consensus_request.channel = channel_id
        req.consensus_request.payload = message_to_bytes(msg)
        req.consensus_request.from_node = msg.frm
        try:
            self.q.put_nowait(req)
        except queue.Full:
            pass  # backpressure: drop; raft retransmits

    def submit(
        self, channel_id: str, env: common_pb2.Envelope, timeout: float = 10.0
    ) -> Tuple[int, str]:
        req = cluster_pb2.ClusterSubmitRequest()
        req.channel = channel_id
        req.payload.CopyFrom(env)
        resp_bytes = self.channel().unary_unary(
            f"/{SERVICE_NAME}/Submit",
            request_serializer=cluster_pb2.ClusterSubmitRequest.SerializeToString,
            response_deserializer=cluster_pb2.ClusterSubmitResponse.FromString,
        )(req, timeout=timeout)
        return resp_bytes.status, resp_bytes.info

    def _run(self) -> None:
        while not self._stopped:
            first = self.q.get()
            if first is None:
                return

            def gen(head):
                yield head
                while True:
                    item = self.q.get()
                    if item is None:
                        return
                    yield item

            try:
                stream = self.channel().stream_stream(
                    f"/{SERVICE_NAME}/Step",
                    request_serializer=(
                        cluster_pb2.ClusterStepRequest.SerializeToString
                    ),
                    response_deserializer=(
                        cluster_pb2.ClusterStepResponse.FromString
                    ),
                )(gen(first))
                for _ in stream:  # drain (submit responses not used here)
                    pass
            except grpc.RpcError:
                # connection lost: reset the channel; messages queued in
                # the meantime go out on the next stream
                self._reset_channel()
                if self._stopped:
                    return
                threading.Event().wait(0.05)

    def stop(self) -> None:
        self._stopped = True
        self.q.put(None)
        self._reset_channel()
        # reap the sender: it exits on the None sentinel (or the 50ms
        # reconnect poll observing _stopped) — leaving it running leaks
        # one thread per remote across reconnect churn
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)


class ClusterClient:
    """Client side: the raft transport over real sockets. Endpoint maps
    are PER CHANNEL (each channel's consenter set comes from its own
    config block and channels may disagree about who node N is); remotes
    are shared per address. Gives the Registrar a transport factory and
    the broadcast path a leader-forwarding hook."""

    def __init__(
        self,
        node_id: int,
        endpoints: Optional[Dict[int, str]] = None,
        root_ca: Optional[bytes] = None,
    ):
        self.node_id = node_id
        self._default: Dict[int, str] = dict(endpoints or {})
        self._by_channel: Dict[str, Dict[int, str]] = {}
        self.root_ca = root_ca
        self._remotes: Dict[str, _Remote] = {}  # keyed by address
        self._lock = threading.Lock()

    def set_channel_endpoints(
        self, channel_id: str, endpoints: Dict[int, str]
    ) -> None:
        """Install/refresh one channel's consenter map (called on channel
        start and on every config block — consensus metadata is the
        source of truth, orderer main.go initializeClusterClientConfig)."""
        with self._lock:
            self._by_channel[channel_id] = dict(endpoints)

    def _addr(self, channel_id: str, to: int) -> Optional[str]:
        with self._lock:
            chan = self._by_channel.get(channel_id)
            if chan is not None and to in chan:
                return chan[to]
            return self._default.get(to)

    def _remote_for(self, addr: str) -> _Remote:
        with self._lock:
            r = self._remotes.get(addr)
            if r is None:
                r = _Remote(addr, self.root_ca)
                self._remotes[addr] = r
            return r

    def transport_factory(
        self, channel_id: str, node_id: int
    ) -> Callable[[int, Message], None]:
        def send(to: int, msg: Message) -> None:
            if to == self.node_id:
                return
            addr = self._addr(channel_id, to)
            if addr is not None:
                self._remote_for(addr).enqueue_consensus(channel_id, msg)

        return send

    def forward_submit(
        self, channel_id: str, env: common_pb2.Envelope, leader_id: int
    ) -> Tuple[int, str]:
        """Follower -> leader transaction forwarding (comm.go Submit)."""
        addr = self._addr(channel_id, leader_id)
        if addr is None:
            return (
                common_pb2.SERVICE_UNAVAILABLE,
                f"no endpoint for leader {leader_id}",
            )
        try:
            return self._remote_for(addr).submit(channel_id, env)
        except grpc.RpcError as e:
            return common_pb2.SERVICE_UNAVAILABLE, f"leader unreachable: {e.code()}"

    def stop(self) -> None:
        with self._lock:
            for r in self._remotes.values():
                r.stop()
            self._remotes.clear()
