"""Stable consenter -> raft-id tracking for the etcdraft consenter.

The reference keeps per-consenter raft IDs in the etcdraft BlockMetadata
stamped into every block's ORDERER metadata slot
(orderer/consensus/etcdraft/etcdraft.proto BlockMetadata;
chain.go writeBlock + util.go MembershipChanges): a consenter keeps its id
for the channel's lifetime, removed consenters retire their id forever, and
new consenters draw fresh ids from a monotonic counter.  Positional ids
(list index) break on any non-tail removal or reorder — the departing node
would keep consenting while an innocent one is evicted.

This module mirrors that design.  The mapping is keyed by the consenter's
host:port endpoint (our transport identity); the serialized form carries
the endpoints explicitly so a node joining mid-life reads the authoritative
mapping straight from any replicated block instead of re-deriving it
positionally from the config.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from fabric_tpu.protos import common_pb2, configtx_pb2, configuration_pb2, protoutil


def consenters_from_config_block(
    block: common_pb2.Block,
) -> Optional[List[str]]:
    """host:port consenter endpoints from a CONFIG block's etcdraft
    metadata; None for non-config blocks, non-raft channels, or parse
    failures (callers then leave the mapping untouched)."""
    from google.protobuf.message import DecodeError

    try:
        env = protoutil.get_envelope_from_block_data(block.data.data[0])
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        cenv = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
        og = cenv.config.channel_group.groups.get("Orderer")
        if og is None:
            return None
        ct_value = og.values.get("ConsensusType")
        if ct_value is None:
            return None
        ct = protoutil.unmarshal(
            configuration_pb2.ConsensusType, ct_value.value
        )
        if ct.type != "etcdraft":
            return None
        meta = protoutil.unmarshal(
            configuration_pb2.RaftConfigMetadata, ct.metadata
        )
    except (ValueError, IndexError, DecodeError):
        # get_envelope_from_block_data parses raw bytes and can raise the
        # protobuf DecodeError directly (a leader-flagged "config" entry
        # whose payload is not a valid Envelope must not kill the channel's
        # apply loop); the other steps wrap parse errors in ValueError.
        return None
    return [f"{c.host}:{c.port}" for c in meta.consenters]


class ConsenterIdTracker:
    """The (endpoint -> raft id, next id) state machine.

    Deterministic: every node that applies the same sequence of consenter
    sets reaches the same mapping, so each node stamping its own blocks
    (like the reference's per-node writeBlock) yields identical bytes.
    """

    def __init__(self, ids: Dict[str, int], next_id: int):
        self.ids = dict(ids)
        self.next_id = next_id

    @classmethod
    def bootstrap(cls, addresses: Sequence[str]) -> "ConsenterIdTracker":
        """Genesis rule: ids 1..n in config order (etcdraft chain start)."""
        ids = {a: i + 1 for i, a in enumerate(addresses)}
        return cls(ids, len(addresses) + 1)

    def apply(self, new_addresses: Sequence[str]) -> None:
        """Consenter-set change: removed endpoints retire their ids, added
        endpoints draw fresh ones (util.go MembershipChanges semantics)."""
        new_set = set(new_addresses)
        for addr in [a for a in self.ids if a not in new_set]:
            del self.ids[addr]
        for addr in new_addresses:
            if addr not in self.ids:
                self.ids[addr] = self.next_id
                self.next_id += 1

    def peer_ids(self) -> List[int]:
        return sorted(self.ids.values())

    def id_for(self, address: str) -> Optional[int]:
        return self.ids.get(address)

    def is_member(self, node_id: int) -> bool:
        return node_id in self.ids.values()

    # -- block metadata (ORDERER slot) --------------------------------------
    def to_bytes(self) -> bytes:
        meta = configuration_pb2.RaftBlockMetadata()
        for addr in sorted(self.ids, key=self.ids.__getitem__):
            meta.consenter_addresses.append(addr)
            meta.consenter_ids.append(self.ids[addr])
        meta.next_consenter_id = self.next_id
        return meta.SerializeToString()

    def stamp(self, block: common_pb2.Block) -> None:
        """Write the mapping into the block's ORDERER metadata slot (the
        reference stamps etcdraft BlockMetadata the same way)."""
        protoutil.init_block_metadata(block)
        block.metadata.metadata[common_pb2.ORDERER] = self.to_bytes()

    @classmethod
    def from_block(cls, block: Optional[common_pb2.Block]) -> Optional["ConsenterIdTracker"]:
        """Recover the mapping from a stored/replicated block; None when the
        block predates id tracking (then callers fall back to bootstrap)."""
        if block is None:
            return None
        metas = block.metadata.metadata
        if len(metas) <= common_pb2.ORDERER or not metas[common_pb2.ORDERER]:
            return None
        try:
            meta = protoutil.unmarshal(
                configuration_pb2.RaftBlockMetadata, metas[common_pb2.ORDERER]
            )
        except ValueError:
            return None
        if not meta.consenter_ids or len(meta.consenter_ids) != len(
            meta.consenter_addresses
        ):
            return None
        ids = dict(zip(meta.consenter_addresses, meta.consenter_ids))
        return cls(ids, meta.next_consenter_id or max(ids.values()) + 1)
