"""Follower chain: onboarding an orderer into a channel it does not yet
consent on (reference orderer/common/follower/follower_chain.go +
orderer/common/onboarding).

A follower runs when this node joins a channel where it is NOT in the
consenter set, or joins with a non-genesis join block (so the local
ledger must first be replicated from the cluster).  It:

- pulls blocks from the channel's consenters with the deliver-client
  failure discipline (backoff + endpoint failover), verifying hash-chain
  linkage as it appends;
- re-derives the channel bundle at every config block and watches the
  consenter set;
- once this node IS a consenter and the ledger has reached the join
  block, halts pulling and invokes the promotion callback so the
  registrar restarts the channel as a full raft member
  (follower_chain.go run -> checkMembership -> halt + chain re-create).

The block store path is the one RaftChain would use, so promotion is a
pure restart: the raft chain opens the same ledger at the same height.

Node identity: raft ids are STABLE per consenter (consenter_ids.py mirrors
the reference's etcdraft BlockMetadata) — a node's configured raft_node_id
must be the id the cluster assigned when its endpoint entered the
consenter set.  Membership checks read the mapping from replicated blocks'
ORDERER metadata; the positional convention (node_id == 1-based list
index) remains only as the fallback for ledgers written before id
tracking existed (there the two coincide, since ids start positional and
those ledgers never saw a non-tail removal).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence

from fabric_tpu.deliver.client import BlockDeliverer
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.orderer.consenter_ids import ConsenterIdTracker
from fabric_tpu.orderer.raft_chain import _is_config_block
from fabric_tpu.protos import common_pb2, configuration_pb2, protoutil

# status / consensus-relation strings mirror the channel-participation
# API (orderer/common/types/channel_info.go)
STATUS_ONBOARDING = "onboarding"
STATUS_ACTIVE = "active"
RELATION_FOLLOWER = "follower"
RELATION_CONSENTER = "consenter"


def consenter_addresses(bundle) -> List[str]:
    """host:port list from the bundle's etcdraft consensus metadata."""
    if bundle.orderer is None or bundle.orderer.consensus_type != "etcdraft":
        return []
    try:
        meta = protoutil.unmarshal(
            configuration_pb2.RaftConfigMetadata,
            bundle.orderer.consensus_metadata,
        )
    except ValueError:
        return []
    return [f"{c.host}:{c.port}" for c in meta.consenters]


def is_member(bundle, node_id: int) -> bool:
    return 1 <= node_id <= len(consenter_addresses(bundle))


class FollowerChain:
    def __init__(
        self,
        channel_id: str,
        join_block: common_pb2.Block,
        bundle,
        node_id: int,
        wal_dir: str,
        endpoint_factory: Callable[[Sequence[str]], List[Callable]],
        on_become_member: Callable[["FollowerChain"], None],
        provider=None,
    ):
        self.channel_id = channel_id
        self.join_block = join_block
        self.join_number = join_block.header.number
        self.bundle = bundle
        self.node_id = node_id
        self.provider = provider
        self._endpoint_factory = endpoint_factory
        self._on_become_member = on_become_member
        base = os.path.join(wal_dir, channel_id)
        os.makedirs(base, exist_ok=True)
        self.block_store = BlockStore(os.path.join(base, "chain.blocks"))
        if self.join_number == 0 and self.block_store.height == 0:
            self.block_store.add_block(join_block)
        # Stable raft-id mapping read from replicated blocks' ORDERER
        # metadata (consenter_ids.py); positional fallback for blocks
        # written before id tracking existed.  A restarted follower
        # prefers its LAST stored block — the join block's mapping goes
        # stale as soon as a replicated config block changes the set.
        last = (
            self.block_store.get_block_by_number(self.block_store.height - 1)
            if self.block_store.height
            else None
        )
        self.tracker = ConsenterIdTracker.from_block(
            last
        ) or ConsenterIdTracker.from_block(join_block)
        self._member = threading.Event()
        self._stop = threading.Event()
        self._deliverer: Optional[BlockDeliverer] = None
        self._thread: Optional[threading.Thread] = None

    # -- participation-API style introspection ---------------------------
    @property
    def height(self) -> int:
        return self.block_store.height

    def get_block(self, number: int) -> Optional[common_pb2.Block]:
        return self.block_store.get_block_by_number(number)

    @property
    def status(self) -> str:
        """onboarding until the ledger reaches the join block, then an
        active follower (channel_info.go Status)."""
        return (
            STATUS_ONBOARDING
            if self.height <= self.join_number
            else STATUS_ACTIVE
        )

    consensus_relation = RELATION_FOLLOWER

    # -- pull loop -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"follower-{self.channel_id}", daemon=True
        )
        self._thread.start()

    def _is_member(self) -> bool:
        """Membership by stable raft id when the mapping is known, else the
        positional convention (pre-tracking blocks)."""
        if self.tracker is not None:
            return self.tracker.is_member(self.node_id)
        return is_member(self.bundle, self.node_id)

    def _exclude_self(self, addrs: Sequence[str]) -> List[str]:
        if self.tracker is not None:
            return [
                a for a in addrs if self.tracker.id_for(a) != self.node_id
            ]
        out = list(addrs)
        if 1 <= self.node_id <= len(out):
            out.pop(self.node_id - 1)
        return out

    def _run(self) -> None:
        while not self._stop.is_set() and not self._member.is_set():
            endpoints = self._endpoint_factory(
                self._exclude_self(consenter_addresses(self.bundle))
            )
            self._deliverer = BlockDeliverer(
                self.channel_id,
                endpoints,
                on_block=self._append,
                next_block=lambda: self.block_store.height,
                max_total_delay=5.0,  # re-derive endpoints periodically
            )
            self._deliverer.run()
            if not self._member.is_set():
                self._stop.wait(0.1)
        if self._member.is_set() and not self._stop.is_set():
            self.block_store.close()
            self._on_become_member(self)

    def _append(self, block: common_pb2.Block) -> None:
        h = self.block_store.height
        if block.header.number != h:
            raise ConnectionError(
                f"follower expected block {h}, got {block.header.number}"
            )
        if h > 0:
            prev = self.block_store.last_block_hash
            if block.header.previous_hash != prev:
                raise ConnectionError(
                    f"block {h} breaks the hash chain"
                )
        if (
            protoutil.block_data_hash(block.data)
            != block.header.data_hash
        ):
            raise ConnectionError(f"block {h} DataHash mismatch")
        self.block_store.add_block(block)
        pulled = ConsenterIdTracker.from_block(block)
        if pulled is not None:
            self.tracker = pulled
        if _is_config_block(block):
            self._on_config_block(block)

    def _on_config_block(self, block: common_pb2.Block) -> None:
        from fabric_tpu.channelconfig.bundle import bundle_from_genesis_block

        try:
            self.bundle = bundle_from_genesis_block(block, self.provider)
        except Exception:  # noqa: BLE001 - keep following on a bad bundle
            return
        if self._is_member() and self.height > self.join_number:
            self._member.set()
            if self._deliverer is not None:
                self._deliverer.stop()

    def check_join_block_membership(self) -> None:
        """Joining with a non-genesis block where we're already a member:
        onboarding mode — replicate up to the join block, then promote
        (onboarding.go ReplicateChains)."""
        if self._is_member():
            # promotion happens when the pull reaches the join block; the
            # per-block hook below watches plain blocks too in this mode
            orig_append = self._append

            def append_and_check(block):
                orig_append(block)
                if (
                    not self._member.is_set()
                    and self.height > self.join_number
                ):
                    self._member.set()
                    if self._deliverer is not None:
                        self._deliverer.stop()

            self._append = append_and_check  # type: ignore[method-assign]

    def stop(self) -> None:
        self._stop.set()
        if self._deliverer is not None:
            self._deliverer.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if not self._member.is_set():
            self.block_store.close()
