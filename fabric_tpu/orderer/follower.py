"""Follower chain: onboarding an orderer into a channel it does not yet
consent on (reference orderer/common/follower/follower_chain.go +
orderer/common/onboarding).

A follower runs when this node joins a channel where it is NOT in the
consenter set, or joins with a non-genesis join block (so the local
ledger must first be replicated from the cluster).  It:

- pulls blocks from the channel's consenters with the deliver-client
  failure discipline (backoff + endpoint failover), verifying hash-chain
  linkage as it appends;
- re-derives the channel bundle at every config block and watches the
  consenter set;
- once this node IS a consenter and the ledger has reached the join
  block, halts pulling and invokes the promotion callback so the
  registrar restarts the channel as a full raft member
  (follower_chain.go run -> checkMembership -> halt + chain re-create).

The block store path is the one RaftChain would use, so promotion is a
pure restart: the raft chain opens the same ledger at the same height.

Node identity follows this codebase's convention: raft node id == the
1-based index into the consensus-metadata consenter list (see
nodes/orderer.py _refresh_cluster_endpoints); membership is therefore
node_id <= len(consenters).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence

from fabric_tpu.deliver.client import BlockDeliverer
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.orderer.raft_chain import _is_config_block
from fabric_tpu.protos import common_pb2, configuration_pb2, protoutil

# status / consensus-relation strings mirror the channel-participation
# API (orderer/common/types/channel_info.go)
STATUS_ONBOARDING = "onboarding"
STATUS_ACTIVE = "active"
RELATION_FOLLOWER = "follower"
RELATION_CONSENTER = "consenter"


def consenter_addresses(bundle) -> List[str]:
    """host:port list from the bundle's etcdraft consensus metadata."""
    if bundle.orderer is None or bundle.orderer.consensus_type != "etcdraft":
        return []
    try:
        meta = protoutil.unmarshal(
            configuration_pb2.RaftConfigMetadata,
            bundle.orderer.consensus_metadata,
        )
    except ValueError:
        return []
    return [f"{c.host}:{c.port}" for c in meta.consenters]


def is_member(bundle, node_id: int) -> bool:
    return 1 <= node_id <= len(consenter_addresses(bundle))


class FollowerChain:
    def __init__(
        self,
        channel_id: str,
        join_block: common_pb2.Block,
        bundle,
        node_id: int,
        wal_dir: str,
        endpoint_factory: Callable[[Sequence[str]], List[Callable]],
        on_become_member: Callable[["FollowerChain"], None],
        provider=None,
    ):
        self.channel_id = channel_id
        self.join_block = join_block
        self.join_number = join_block.header.number
        self.bundle = bundle
        self.node_id = node_id
        self.provider = provider
        self._endpoint_factory = endpoint_factory
        self._on_become_member = on_become_member
        base = os.path.join(wal_dir, channel_id)
        os.makedirs(base, exist_ok=True)
        self.block_store = BlockStore(os.path.join(base, "chain.blocks"))
        if self.join_number == 0 and self.block_store.height == 0:
            self.block_store.add_block(join_block)
        self._member = threading.Event()
        self._stop = threading.Event()
        self._deliverer: Optional[BlockDeliverer] = None
        self._thread: Optional[threading.Thread] = None

    # -- participation-API style introspection ---------------------------
    @property
    def height(self) -> int:
        return self.block_store.height

    def get_block(self, number: int) -> Optional[common_pb2.Block]:
        return self.block_store.get_block_by_number(number)

    @property
    def status(self) -> str:
        """onboarding until the ledger reaches the join block, then an
        active follower (channel_info.go Status)."""
        return (
            STATUS_ONBOARDING
            if self.height <= self.join_number
            else STATUS_ACTIVE
        )

    consensus_relation = RELATION_FOLLOWER

    # -- pull loop -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"follower-{self.channel_id}", daemon=True
        )
        self._thread.start()

    def _exclude_self(self, addrs: Sequence[str]) -> List[str]:
        out = list(addrs)
        if 1 <= self.node_id <= len(out):
            out.pop(self.node_id - 1)
        return out

    def _run(self) -> None:
        while not self._stop.is_set() and not self._member.is_set():
            endpoints = self._endpoint_factory(
                self._exclude_self(consenter_addresses(self.bundle))
            )
            self._deliverer = BlockDeliverer(
                self.channel_id,
                endpoints,
                on_block=self._append,
                next_block=lambda: self.block_store.height,
                max_total_delay=5.0,  # re-derive endpoints periodically
            )
            self._deliverer.run()
            if not self._member.is_set():
                self._stop.wait(0.1)
        if self._member.is_set() and not self._stop.is_set():
            self.block_store.close()
            self._on_become_member(self)

    def _append(self, block: common_pb2.Block) -> None:
        h = self.block_store.height
        if block.header.number != h:
            raise ConnectionError(
                f"follower expected block {h}, got {block.header.number}"
            )
        if h > 0:
            prev = self.block_store.last_block_hash
            if block.header.previous_hash != prev:
                raise ConnectionError(
                    f"block {h} breaks the hash chain"
                )
        if (
            protoutil.block_data_hash(block.data)
            != block.header.data_hash
        ):
            raise ConnectionError(f"block {h} DataHash mismatch")
        self.block_store.add_block(block)
        if _is_config_block(block):
            self._on_config_block(block)

    def _on_config_block(self, block: common_pb2.Block) -> None:
        from fabric_tpu.channelconfig.bundle import bundle_from_genesis_block

        try:
            self.bundle = bundle_from_genesis_block(block, self.provider)
        except Exception:  # noqa: BLE001 - keep following on a bad bundle
            return
        if is_member(self.bundle, self.node_id) and self.height > self.join_number:
            self._member.set()
            if self._deliverer is not None:
                self._deliverer.stop()

    def check_join_block_membership(self) -> None:
        """Joining with a non-genesis block where we're already a member:
        onboarding mode — replicate up to the join block, then promote
        (onboarding.go ReplicateChains)."""
        if is_member(self.bundle, self.node_id):
            # promotion happens when the pull reaches the join block; the
            # per-block hook below watches plain blocks too in this mode
            orig_append = self._append

            def append_and_check(block):
                orig_append(block)
                if (
                    not self._member.is_set()
                    and self.height > self.join_number
                ):
                    self._member.set()
                    if self._deliverer is not None:
                        self._deliverer.stop()

            self._append = append_and_check  # type: ignore[method-assign]

    def stop(self) -> None:
        self._stop.set()
        if self._deliverer is not None:
            self._deliverer.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if not self._member.is_set():
            self.block_store.close()
