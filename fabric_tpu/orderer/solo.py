"""Solo consenter + block writer (reference orderer/consensus/solo +
orderer/common/multichannel/blockwriter.go).

Single-node ordering for dev/test networks: envelopes go straight through
the blockcutter; each batch becomes a signed block chained by
previous_hash. Config messages cut their own block (msgprocessor
classification), matching the reference's isolation of config txs.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.protos import common_pb2, protoutil


class SoloChain:
    """One channel's chain: Order/Configure + block creation."""

    def __init__(
        self,
        channel_id: str,
        signer: Optional[SigningIdentity] = None,
        batch_config: Optional[BatchConfig] = None,
        deliver: Optional[Callable[[common_pb2.Block], None]] = None,
        genesis_block: Optional[common_pb2.Block] = None,
    ):
        self.channel_id = channel_id
        self.signer = signer
        self.cutter = BlockCutter(batch_config)
        self.deliver = deliver
        self.blocks: List[common_pb2.Block] = []
        self._last_hash = b""
        self._last_config_index = 0
        if genesis_block is not None:
            self._append(genesis_block)

    # -- consensus.Chain surface -------------------------------------------
    def order(self, env: common_pb2.Envelope) -> None:
        """Normal message path (broadcast -> ProcessNormalMsg -> Order)."""
        batches, _pending = self.cutter.ordered(env)
        for batch in batches:
            self._write_batch(batch)

    def configure(self, env: common_pb2.Envelope) -> None:
        """Config messages cut pending txs first, then go alone in a block."""
        pending = self.cutter.cut()
        if pending:
            self._write_batch(pending)
        self._write_batch([env], is_config=True)

    def flush(self) -> None:
        """Batch-timeout expiry analog: cut whatever is pending."""
        pending = self.cutter.cut()
        if pending:
            self._write_batch(pending)

    # -- block writer (multichannel/blockwriter.go) ------------------------
    @property
    def height(self) -> int:
        return len(self.blocks)

    def _write_batch(self, batch: List[common_pb2.Envelope], is_config: bool = False) -> None:
        block = protoutil.new_block(self.height, self._last_hash)
        for env in batch:
            block.data.data.append(env.SerializeToString())
        protoutil.seal_block(block)
        if is_config:
            self._last_config_index = block.header.number
        self._add_metadata(block)
        self._append(block)
        if self.deliver is not None:
            self.deliver(block)

    def _add_metadata(self, block: common_pb2.Block) -> None:
        protoutil.init_block_metadata(block)
        # LAST_CONFIG index rides inside the SIGNATURES metadata value
        # (blockwriter.go addBlockSignature: OrdererBlockMetadata).
        last_config = common_pb2.LastConfig()
        last_config.index = self._last_config_index
        meta = common_pb2.Metadata()
        meta.value = last_config.SerializeToString()
        if self.signer is not None:
            sig = meta.signatures.add()
            shdr = protoutil.make_signature_header(
                self.signer.serialize(), self.signer.new_nonce()
            )
            sig.signature_header = shdr.SerializeToString()
            # signed bytes: metadata value || signature header || block header
            signed = (
                meta.value
                + sig.signature_header
                + protoutil.block_header_bytes(block.header)
            )
            sig.signature = self.signer.sign(signed)
        block.metadata.metadata[common_pb2.SIGNATURES] = meta.SerializeToString()

    def _append(self, block: common_pb2.Block) -> None:
        self.blocks.append(block)
        self._last_hash = protoutil.block_header_hash(block.header)

    # -- deliver service surface -------------------------------------------
    def get_block(self, number: int) -> Optional[common_pb2.Block]:
        return self.blocks[number] if number < len(self.blocks) else None
