"""Solo consenter (reference orderer/consensus/solo/consensus.go).

Single-node ordering for dev/test networks: envelopes go straight through
the blockcutter; each batch becomes a signed block chained by
previous_hash via the shared BlockWriter. Config messages cut their own
block (msgprocessor classification), matching the reference's isolation
of config txs.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.protos import common_pb2


class SoloChain:
    """One channel's chain: Order/Configure + block creation."""

    def __init__(
        self,
        channel_id: str,
        signer: Optional[SigningIdentity] = None,
        batch_config: Optional[BatchConfig] = None,
        deliver: Optional[Callable[[common_pb2.Block], None]] = None,
        genesis_block: Optional[common_pb2.Block] = None,
        on_config_block: Optional[Callable[[common_pb2.Block], None]] = None,
    ):
        self.channel_id = channel_id
        self.cutter = BlockCutter(batch_config)
        self.deliver = deliver
        self.blocks: List[common_pb2.Block] = []
        self._on_config_block = on_config_block
        self.writer = BlockWriter(signer=signer, sink=self._store)
        if genesis_block is not None:
            self.writer.append_bootstrap(genesis_block)

    def _store(self, block: common_pb2.Block) -> None:
        self.blocks.append(block)
        if self.deliver is not None:
            self.deliver(block)

    # -- consensus.Chain surface -------------------------------------------
    def order(self, env: common_pb2.Envelope) -> None:
        """Normal message path (broadcast -> ProcessNormalMsg -> Order)."""
        batches, _pending = self.cutter.ordered(env)
        for batch in batches:
            self._write_batch(batch)

    def configure(self, env: common_pb2.Envelope) -> None:
        """Config messages cut pending txs first, then go alone in a block."""
        pending = self.cutter.cut()
        if pending:
            self._write_batch(pending)
        self._write_batch([env], is_config=True)

    def flush(self) -> None:
        """Batch-timeout expiry analog: cut whatever is pending."""
        pending = self.cutter.cut()
        if pending:
            self._write_batch(pending)

    def _write_batch(
        self, batch: List[common_pb2.Envelope], is_config: bool = False
    ) -> None:
        block = self.writer.create_next_block(batch)
        self.writer.write_block(block, is_config=is_config)
        if is_config and self._on_config_block is not None:
            self._on_config_block(block)

    # -- deliver service surface -------------------------------------------
    @property
    def height(self) -> int:
        return self.writer.height

    def get_block(self, number: int) -> Optional[common_pb2.Block]:
        return self.blocks[number] if number < len(self.blocks) else None
