"""Envelope batching (reference orderer/common/blockcutter/blockcutter.go).

Ordered() semantics replicated:
- a message larger than preferred_max_bytes is cut into its own batch
  (after first cutting any pending batch);
- appending a message that would overflow preferred_max_bytes cuts the
  pending batch first;
- reaching max_message_count cuts immediately;
- `pending` tells the caller whether a timer should be armed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from fabric_tpu.protos import common_pb2


@dataclass
class BatchConfig:
    max_message_count: int = 10
    absolute_max_bytes: int = 10 * 1024 * 1024
    preferred_max_bytes: int = 2 * 1024 * 1024


class BlockCutter:
    def __init__(self, config: Optional[BatchConfig] = None):
        self.config = config if config is not None else BatchConfig()
        self._pending: List[common_pb2.Envelope] = []
        self._pending_bytes = 0
        self._pending_since: Optional[float] = None

    def pending_age(self) -> Optional[float]:
        """Seconds since the oldest pending message arrived, or None for
        an empty batch — the reference's batch timer starts at the FIRST
        message of a batch (chain run loops: timer = time.After(...) when
        pending becomes non-empty), so BatchTimeout means 'oldest message
        waits at most this long', not a global flush cadence."""
        if not self._pending or self._pending_since is None:
            return None
        import time

        return time.monotonic() - self._pending_since

    @staticmethod
    def _size(env: common_pb2.Envelope) -> int:
        return len(env.SerializeToString())

    def ordered(self, env: common_pb2.Envelope) -> Tuple[List[List[common_pb2.Envelope]], bool]:
        """Returns (batches_to_cut, pending_remaining)."""
        batches: List[List[common_pb2.Envelope]] = []
        size = self._size(env)

        if size > self.config.preferred_max_bytes:
            # oversized message: flush pending, isolate this one
            if self._pending:
                batches.append(self._cut())
            batches.append([env])
            return batches, False

        if self._pending_bytes + size > self.config.preferred_max_bytes and self._pending:
            batches.append(self._cut())

        self._pending.append(env)
        self._pending_bytes += size
        if self._pending_since is None:
            # set AFTER the append: a concurrent timeout flush (solo
            # chains take no lock) may steal the batch between the two
            # statements, and a message must never sit with no timestamp
            # or the age-gated flush loop would skip it forever
            import time

            self._pending_since = time.monotonic()

        if len(self._pending) >= self.config.max_message_count:
            batches.append(self._cut())

        return batches, bool(self._pending)

    def cut(self) -> List[common_pb2.Envelope]:
        return self._cut() if self._pending else []

    def _cut(self) -> List[common_pb2.Envelope]:
        batch = self._pending
        self._pending = []
        self._pending_bytes = 0
        self._pending_since = None
        return batch
