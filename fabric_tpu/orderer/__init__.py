"""Ordering service (reference orderer/): blockcutter, block writer, solo."""

from fabric_tpu.orderer.blockcutter import BlockCutter
from fabric_tpu.orderer.solo import SoloChain

# BlockCutter dropped from __all__: consumed only inside the orderer
# package (fabdep dead-export); still importable as a module attribute
__all__ = ["SoloChain"]
