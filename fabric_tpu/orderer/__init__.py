"""Ordering service (reference orderer/): blockcutter, block writer, solo."""

from fabric_tpu.orderer.blockcutter import BlockCutter
from fabric_tpu.orderer.solo import SoloChain

__all__ = ["BlockCutter", "SoloChain"]
