"""Broadcast ingest handler (reference orderer/common/broadcast/
broadcast.go: classify -> msgprocessor -> WaitReady -> Order/Configure).

Returns a BroadcastResponse-style (status, info) pair per envelope instead
of streaming; the gRPC layer adapts this to the AtomicBroadcast service.
"""

from __future__ import annotations

from typing import Optional, Tuple

from fabric_tpu.orderer.msgprocessor import (
    MsgProcessorError,
    MsgTooLarge,
    PermissionDenied,
    classify,
)
from fabric_tpu.orderer.multichannel import Registrar, RegistrarError
from fabric_tpu.orderer.raft_chain import NotLeaderError
from fabric_tpu.protos import common_pb2, protoutil


class BroadcastHandler:
    def __init__(self, registrar: Registrar, signer=None, cluster_client=None):
        self.registrar = registrar
        self.signer = signer
        # follower -> leader Submit forwarding (orderer/common/cluster
        # comm.go Submit path); None on a solo/single orderer
        self.cluster_client = cluster_client

    def process_message(
        self, env: common_pb2.Envelope, forwarded: bool = False
    ) -> Tuple[int, str]:
        """One Broadcast message -> (common.Status, info). `forwarded`
        marks a Submit that already hopped orderer-to-orderer once: it
        must not be re-forwarded (redirect loop) even if leadership moved
        again."""
        try:
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            if not payload.header.channel_header:
                raise ValueError("missing channel header")
            chdr = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
        except ValueError as e:
            return common_pb2.BAD_REQUEST, str(e)

        kind = classify(chdr)
        support = self.registrar.get_chain(chdr.channel_id)

        try:
            if kind == "normal":
                if support is None:
                    return (
                        common_pb2.NOT_FOUND,
                        f"channel {chdr.channel_id} not found",
                    )
                support.processor.process_normal_msg(env)
                support.chain.order(env)
            elif kind == "config_update":
                if support is None:
                    # channel creation through the system channel
                    self.registrar.new_channel_from_update(env)
                    return common_pb2.SUCCESS, ""
                config_env, _seq = support.processor.process_config_update_msg(
                    env, signer=self.signer
                )
                support.chain.configure(config_env)
            else:  # a full CONFIG envelope resubmitted for re-validation
                if support is None:
                    return (
                        common_pb2.NOT_FOUND,
                        f"channel {chdr.channel_id} not found",
                    )
                config_env, _seq = support.processor.process_config_msg(
                    env, signer=self.signer
                )
                support.chain.configure(config_env)
        except MsgTooLarge as e:
            return common_pb2.REQUEST_ENTITY_TOO_LARGE, str(e)
        except PermissionDenied as e:
            return common_pb2.FORBIDDEN, str(e)
        except (MsgProcessorError, RegistrarError) as e:
            return common_pb2.BAD_REQUEST, str(e)
        except NotLeaderError as e:
            if (
                not forwarded
                and self.cluster_client is not None
                and e.leader_id
            ):
                return self.cluster_client.forward_submit(
                    chdr.channel_id, env, e.leader_id
                )
            return common_pb2.SERVICE_UNAVAILABLE, str(e)
        except ValueError as e:
            return common_pb2.BAD_REQUEST, str(e)
        return common_pb2.SUCCESS, ""
