"""Orderer-side message validation rules (reference
orderer/common/msgprocessor/*.go: classification, SigFilter, size filter,
expiration, StandardChannel/SystemChannel processors).

ProcessNormalMsg runs the filter chain (expiration -> size -> sig) and
returns the current config sequence; ProcessConfigUpdateMsg additionally
drives the configtx Validator to produce the CONFIG envelope the
consenter will order (reference standardchannel.go:147-201).
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Optional, Tuple

try:  # guarded: only the expiration filter needs X.509 parsing; its
    # except-Exception already treats parse failure as "cannot judge"
    from cryptography import x509
except ImportError:  # pragma: no cover - exercised in minimal envs
    x509 = None  # type: ignore

from fabric_tpu.channelconfig.bundle import Bundle
from fabric_tpu.channelconfig.configtx import Validator
from fabric_tpu.policy.manager import (
    CHANNEL_WRITERS,
    PolicyError,
    SignedData,
)
from fabric_tpu.protos import common_pb2, configtx_pb2, identities_pb2, protoutil


class MsgProcessorError(Exception):
    pass


class PermissionDenied(MsgProcessorError):
    pass


class MsgTooLarge(MsgProcessorError):
    pass


# -- classification (reference broadcast.go + msgprocessor interfaces) ------


def classify(chdr: common_pb2.ChannelHeader) -> str:
    """CONFIG_UPDATE messages take the config path; everything else is a
    normal message (reference standardchannel.go ClassifyMsg)."""
    if chdr.type in (common_pb2.CONFIG_UPDATE,):
        return "config_update"
    if chdr.type in (common_pb2.CONFIG, common_pb2.ORDERER_TRANSACTION):
        return "config"
    return "normal"


# -- filters ----------------------------------------------------------------


class SizeFilter:
    """Reject messages above absolute_max_bytes (sizefilter.go)."""

    def __init__(self, bundle: Bundle):
        self._max = (
            bundle.orderer.batch_size_absolute_max_bytes
            if bundle.orderer
            else 10 * 1024 * 1024
        )

    def apply(self, env: common_pb2.Envelope) -> None:
        size = len(env.SerializeToString())
        if size > self._max:
            raise MsgTooLarge(
                f"message payload is {size} bytes and exceeds maximum "
                f"allowed {self._max} bytes"
            )


class SigFilter:
    """Evaluate the channel Writers policy over the envelope signature
    (sigfilter.go:41-77). In maintenance mode the orderers policy is used
    instead ('/Channel/Orderer/Writers')."""

    def __init__(
        self,
        bundle: Bundle,
        normal_policy: str = CHANNEL_WRITERS,
        maintenance_policy: str = "/Channel/Orderer/Writers",
    ):
        self._bundle = bundle
        self._normal = normal_policy
        self._maintenance = maintenance_policy

    def apply(self, env: common_pb2.Envelope) -> None:
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        if not payload.header.signature_header:
            raise MsgProcessorError("missing signature header")
        shdr = protoutil.unmarshal(
            common_pb2.SignatureHeader, payload.header.signature_header
        )
        name = self._normal
        orderer = self._bundle.orderer
        if orderer is not None and orderer.consensus_state == 1:  # MAINTENANCE
            name = self._maintenance
        policy, ok = self._bundle.policy_manager.get_policy(name)
        if not ok:
            raise MsgProcessorError(f"could not find policy {name}")
        sd = SignedData(env.payload, shdr.creator, env.signature)
        try:
            policy.evaluate_signed_data([sd])
        except PolicyError as e:
            raise PermissionDenied(
                f"implicit policy evaluation failed: {e}"
            ) from e


class ExpirationFilter:
    """Reject envelopes whose signer cert is expired (expiration.go);
    gated on orderer V1_1 capabilities in the reference — always on here."""

    def apply(self, env: common_pb2.Envelope) -> None:
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        if not payload.header.signature_header:
            return
        shdr = protoutil.unmarshal(
            common_pb2.SignatureHeader, payload.header.signature_header
        )
        if not shdr.creator:
            return
        try:
            sid = protoutil.unmarshal(
                identities_pb2.SerializedIdentity, shdr.creator
            )
            cert = x509.load_pem_x509_certificate(sid.id_bytes)
        except Exception:
            return  # not an x509 identity; sig filter will judge it
        now = datetime.datetime.now(datetime.timezone.utc)  # fabdet: disable=wallclock-in-det  # identity-expiration admission filter (msgprocessor.go expiration discipline): semantically time-dependent gate on which envelopes are ADMITTED; block bytes are built from the admitted envelopes, not from the clock
        if cert.not_valid_after_utc < now:
            raise MsgProcessorError("identity expired")


class StandardChannelProcessor:
    """Per-channel msgprocessor (reference standardchannel.go)."""

    def __init__(self, channel_id: str, bundle: Bundle, validator: Validator):
        self.channel_id = channel_id
        self.validator = validator
        self.update_bundle(bundle)

    def update_bundle(self, bundle: Bundle) -> None:
        """Swap in the post-config-block bundle: filters AND the configtx
        validator's authorization tree must both follow the new config."""
        self.bundle = bundle
        self._filters = [ExpirationFilter(), SizeFilter(bundle), SigFilter(bundle)]
        self.validator.policy_manager = bundle.policy_manager

    def apply_filters(
        self, env: common_pb2.Envelope, include_sig: bool = True
    ) -> None:
        """Run the ingress filter chain alone. include_sig=False is the
        system channel's channel-creation path (systemchannel.go): the
        client envelope is authorized by the consortium's
        ChannelCreationPolicy, not the system channel's Writers — the
        SigFilter there sees the orderer-signed wrapper instead."""
        for f in self._filters:
            if not include_sig and isinstance(f, SigFilter):
                continue
            f.apply(env)

    def process_normal_msg(self, env: common_pb2.Envelope) -> int:
        """Returns the config sequence the message was validated against."""
        self.apply_filters(env)
        return self.validator.sequence

    def process_config_update_msg(
        self, env: common_pb2.Envelope, signer=None
    ) -> Tuple[common_pb2.Envelope, int]:
        """CONFIG_UPDATE -> (CONFIG envelope ready to order, sequence)
        (reference standardchannel.go ProcessConfigUpdateMsg)."""
        self.apply_filters(env)
        config_env = self.validator.propose_config_update(env)

        payload = common_pb2.Payload()
        chdr = protoutil.make_channel_header(common_pb2.CONFIG, self.channel_id)
        payload.header.channel_header = chdr.SerializeToString()
        if signer is not None:
            shdr = protoutil.make_signature_header(
                signer.serialize(), signer.new_nonce()
            )
            payload.header.signature_header = shdr.SerializeToString()
        else:
            payload.header.signature_header = (
                common_pb2.SignatureHeader().SerializeToString()
            )
        payload.data = config_env.SerializeToString()
        out = common_pb2.Envelope()
        out.payload = payload.SerializeToString()
        if signer is not None:
            out.signature = signer.sign(out.payload)
        return out, self.validator.sequence

    def process_config_msg(
        self, env: common_pb2.Envelope, signer=None
    ) -> Tuple[common_pb2.Envelope, int]:
        """Re-validate a CONFIG envelope by re-running its embedded update
        (reference standardchannel.go ProcessConfigMsg)."""
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        cenv = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
        if not cenv.HasField("last_update"):
            raise MsgProcessorError("config envelope has no last_update")
        return self.process_config_update_msg(cenv.last_update, signer=signer)
