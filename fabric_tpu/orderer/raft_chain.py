"""Raft consenter chain (reference orderer/consensus/etcdraft/chain.go):
ties the raft core to block cutting, block writing, WAL persistence and
snapshot-based catch-up for one channel.

Block creation happens only on the raft leader (chain.go run loop):
normal envelopes go through the blockcutter; each batch becomes a block
proposed as one raft entry (data = serialized block). Every node writes
committed blocks through its BlockWriter; stale blocks re-proposed by a
deposed leader are dropped by block-number dedup (chain.go writeBlock
checks block number == lastBlock+1).
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Callable, Dict, List, Optional, Sequence

from fabric_tpu.common.faults import fault_point
from fabric_tpu.orderer.blockcutter import BatchConfig, BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.orderer.consenter_ids import (
    ConsenterIdTracker,
    consenters_from_config_block,
)
from fabric_tpu.orderer.raft import (
    ENTRY_CONF,
    ENTRY_NORMAL,
    Entry,
    Message,
    RaftNode,
    SnapshotFile,
    WAL,
)
from fabric_tpu.protos import common_pb2, protoutil


def _is_config_block(block: common_pb2.Block) -> bool:
    if len(block.data.data) != 1:
        return False
    try:
        env = protoutil.get_envelope_from_block_data(block.data.data[0])
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        chdr = protoutil.unmarshal(
            common_pb2.ChannelHeader, payload.header.channel_header
        )
    except ValueError:
        return False
    return chdr.type == common_pb2.CONFIG


def _last_config_index(block: Optional[common_pb2.Block]) -> int:
    """Recover LastConfig.index from a stored block's SIGNATURES metadata
    (blockwriter.go lastConfigBlockNumber on restart)."""
    if block is None:
        return 0
    metas = block.metadata.metadata
    if len(metas) > common_pb2.SIGNATURES and metas[common_pb2.SIGNATURES]:
        try:
            meta = protoutil.unmarshal(
                common_pb2.Metadata, metas[common_pb2.SIGNATURES]
            )
            if meta.value:
                lc = protoutil.unmarshal(common_pb2.LastConfig, meta.value)
                return lc.index
        except ValueError:
            pass
    return block.header.number if _is_config_block(block) else 0


class NotLeaderError(Exception):
    """Submit must be forwarded to the raft leader (cluster Step RPC)."""

    def __init__(self, leader_id: int):
        super().__init__(f"not leader; current leader is {leader_id}")
        self.leader_id = leader_id


class RaftChain:
    def __init__(
        self,
        channel_id: str,
        node_id: int,
        peers: Sequence[int],
        wal_dir: str,
        signer=None,
        batch_config: Optional[BatchConfig] = None,
        sink: Optional[Callable[[common_pb2.Block], None]] = None,
        genesis_block: Optional[common_pb2.Block] = None,
        snapshot_interval: int = 100,
        transport: Optional[Callable[[int, Message], None]] = None,
        on_config_block: Optional[Callable[[common_pb2.Block], None]] = None,
        initial_consenters: Optional[Sequence[str]] = None,
    ):
        self.channel_id = channel_id
        # One lock serializes everything that mutates raft/cutter/writer
        # state: gRPC broadcast threads (order/configure), the cluster
        # Step dispatcher (step), and the node's tick loop all race here
        # once the transport is real sockets (the reference serializes the
        # same way through the etcdraft chain's single run() goroutine).
        self._lock = threading.RLock()
        self.cutter = BlockCutter(batch_config)
        self._sink = sink
        self._on_config_block = on_config_block
        self.snapshot_interval = snapshot_interval
        self.transport = transport or (lambda to, msg: None)
        self._applied_index = 0

        base = os.path.join(wal_dir, channel_id)
        # The block ledger is persistent (reference: etcdraft sits on the
        # multichannel blockledger); a restart must resume from the stored
        # height or a snapshotted node silently resets to height 0 and
        # re-mints already-used block numbers.
        from fabric_tpu.ledger.blockstore import BlockStore

        self.block_store = BlockStore(os.path.join(base, "chain.blocks"))
        last_block = (
            self.block_store.get_block_by_number(self.block_store.height - 1)
            if self.block_store.height
            else None
        )
        # Stable consenter->raft-id mapping (reference etcdraft
        # BlockMetadata): authoritative source is the last stored block's
        # ORDERER metadata (survives restarts AND mid-life joins, where a
        # replicated join block carries the cluster's mapping); a fresh
        # genesis falls back to the positional bootstrap rule.
        self.tracker = ConsenterIdTracker.from_block(
            last_block
        ) or ConsenterIdTracker.from_block(genesis_block)
        if self.tracker is None and initial_consenters:
            self.tracker = ConsenterIdTracker.bootstrap(initial_consenters)
        if self.tracker is not None and self.tracker.peer_ids():
            peers = self.tracker.peer_ids()
        self.node = RaftNode(node_id, peers)
        self.writer = BlockWriter(
            signer=signer,
            sink=self._store_block,
            last_block=last_block,
            last_config_index=_last_config_index(last_block),
        )
        self.wal = WAL(os.path.join(base, "wal.log"))
        self.snap = SnapshotFile(os.path.join(base, "snapshot"))
        self._persisted_snap_index = 0
        self._recover()
        self._persisted_snap_index = self.node.snap_index

        if genesis_block is not None and self.writer.height == 0:
            if (
                self.tracker is not None
                and ConsenterIdTracker.from_block(genesis_block) is None
            ):
                # stamp a COPY so followers joining later read the mapping
                # from block 0 — the caller's genesis object stays
                # byte-identical to the configtx artifact
                stamped = common_pb2.Block()
                stamped.CopyFrom(genesis_block)
                self.tracker.stamp(stamped)
                genesis_block = stamped
            self.writer.append_bootstrap(genesis_block)

    # -- persistence --------------------------------------------------------
    def _recover(self) -> None:
        """Replay snapshot + WAL into the raft core (storage.go:175-)."""
        snap = self.snap.load()
        if snap is not None:
            index, term, data = snap
            self.node.snap_index = index
            self.node.snap_term = term
            self.node.snap_data = data
            self.node.commit_index = index
            self._applied_index = index
        hard, entries = self.wal.replay()
        self.node.term, self.node.voted_for = max(
            (self.node.term, self.node.voted_for), hard
        )
        for e in entries:
            if e.index > self.node.snap_index:
                self.node.log.append(e)

    def _store_block(self, block: common_pb2.Block) -> None:
        self.block_store.add_block(block)
        if self._sink is not None:
            self._sink(block)

    @property
    def height(self) -> int:
        return self.writer.height

    def get_block(self, number: int) -> Optional[common_pb2.Block]:
        return self.block_store.get_block_by_number(number)

    # -- consensus.Chain surface -------------------------------------------
    def order(self, env: common_pb2.Envelope) -> None:
        with self._lock:
            if self.node.role != "leader":
                raise NotLeaderError(self.node.leader_id)
            batches, _ = self.cutter.ordered(env)
            for batch in batches:
                self._propose_batch(batch)
            self._pump()

    def configure(self, env: common_pb2.Envelope) -> None:
        with self._lock:
            if self.node.role != "leader":
                raise NotLeaderError(self.node.leader_id)
            pending = self.cutter.cut()
            if pending:
                self._propose_batch(pending)
            self._propose_batch([env], is_config=True)
            self._pump()

    def flush(self) -> None:
        """Batch timeout expiry."""
        with self._lock:
            if self.node.role != "leader":
                return
            pending = self.cutter.cut()
            if pending:
                self._propose_batch(pending)
                self._pump()

    def _propose_batch(
        self, batch: List[common_pb2.Envelope], is_config: bool = False
    ) -> None:
        block = self._next_proposed_block(batch)
        flag = b"\x01" if is_config else b"\x00"
        self.node.propose(flag + block.SerializeToString())

    _proposed_height: Optional[int] = None
    _proposed_term: int = -1

    def _next_proposed_block(self, batch) -> common_pb2.Block:
        """Leader-side block numbering: continues from the last *proposed*
        block this term, not the last committed one, so multiple in-flight
        proposals chain correctly. Resets on (re-)election so a deposed
        leader's uncommitted proposals don't poison its numbering."""
        if (
            self._proposed_term != self.node.term
            or self._proposed_height is None
            or self._proposed_height < self.writer.height
        ):
            self._proposed_term = self.node.term
            self._proposed_height = self.writer.height
            self._proposed_hash = self.block_store.last_block_hash
        block = protoutil.new_block(self._proposed_height, self._proposed_hash)
        for env in batch:
            block.data.data.append(env.SerializeToString())
        protoutil.seal_block(block)
        self._proposed_height += 1
        self._proposed_hash = protoutil.block_header_hash(block.header)
        return block

    # -- raft plumbing ------------------------------------------------------
    def tick(self) -> None:
        with self._lock:
            self.node.tick()
            self._pump()

    def step(self, msg: Message) -> None:
        # chaos seam: a 'drop' spec here is a lost consensus message —
        # raft's retransmission (leader append retries, election
        # timeouts) must absorb it without forking the committed chain.
        # UNKEYED on purpose: a heartbeat retransmits a byte-identical
        # append, so a content-keyed decision would drop the same
        # message forever (livelock); the per-site seeded stream
        # re-rolls per delivery — deterministic under fabchaos's
        # single-threaded pump, documented order-dependent otherwise.
        spec = fault_point("raft.step", interprets=("drop",))
        if spec is not None and spec.action == "drop":
            return
        with self._lock:
            self.node.step(msg)
            self._pump()

    def _pump(self) -> None:
        msgs, hard, new_entries = self.node.ready()
        self.wal.save(hard, new_entries)
        self._persist_received_snapshot()
        self._apply_committed()
        for m in msgs:
            self.transport(m.to, m)

    def _persist_received_snapshot(self) -> None:
        """A leader-installed snapshot (raft _on_snap) must hit disk like a
        self-taken one, or restart replays the WAL against snap_index=0
        with mis-based log offsets."""
        if (
            self.node.applied_snapshot is not None
            and self.node.snap_index > self._persisted_snap_index
        ):
            self.snap.save(
                self.node.snap_index, self.node.snap_term, self.node.snap_data
            )
            self._persisted_snap_index = self.node.snap_index
            self.wal.rotate((self.node.term, self.node.voted_for), self.node.log)

    def _apply_committed(self) -> None:
        while self._applied_index < self.node.commit_index:
            idx = self._applied_index + 1
            # idx <= snap_index covers idx == snap_index too: _term_at
            # answers with snap_term there, but the entry itself is NOT
            # in the log (log starts at snap_index+1) — indexing would
            # silently grab log[-1] (found by tests/test_raft_fuzz.py)
            if idx <= self.node.snap_index or self.node._term_at(idx) is None:
                # below our log start: state arrives via snapshot instead
                self._applied_index = self.node.snap_index
                continue
            off = idx - self.node.snap_index - 1
            entry = self.node.log[off]
            self._apply_entry(entry)
            self._applied_index = idx
            if (
                self.snapshot_interval
                and self._applied_index - self.node.snap_index
                >= self.snapshot_interval
            ):
                self._take_snapshot()

    def _apply_entry(self, entry: Entry) -> None:
        if entry.type == ENTRY_CONF:
            new_peers = [int(p) for p in entry.data.decode().split(",") if p]
            removed = self.node.peers - set(new_peers)
            if self.node.role == "leader":
                # final append so removed nodes see the committed conf entry
                # and self-evict (reference etcdraft/eviction.go suspicion)
                for p in removed - {self.node.id}:
                    self.node._send_append(p)
            self.node.apply_conf_change(new_peers)
            return
        if not entry.data:
            return  # leader noop
        is_config = entry.data[0:1] == b"\x01"
        block = common_pb2.Block()
        block.ParseFromString(entry.data[1:])
        if block.header.number != self.writer.height:
            return  # stale re-proposal from a deposed leader
        if self.tracker is not None:
            if is_config:
                # a consenter-set change takes effect in the mapping at the
                # config block that carries it (chain.go writeConfigBlock)
                addrs = consenters_from_config_block(block)
                if addrs is not None:
                    self.tracker.apply(addrs)
            self.tracker.stamp(block)
        self.writer.write_block(block, is_config=is_config)
        if is_config and self._on_config_block is not None:
            self._on_config_block(block)

    def _take_snapshot(self) -> None:
        data = struct.pack("<Q", self.writer.height)
        self.node.compact(self._applied_index, data)
        self.snap.save(self._applied_index, self.node.snap_term, data)
        self._persisted_snap_index = self._applied_index
        # rotate the WAL: replay only needs entries beyond the snapshot
        self.wal.rotate((self.node.term, self.node.voted_for), self.node.log)

    # -- membership ---------------------------------------------------------
    def propose_conf_change(self, new_peers: Sequence[int]) -> None:
        with self._lock:
            if self.node.role != "leader":
                raise NotLeaderError(self.node.leader_id)
            data = ",".join(str(p) for p in sorted(new_peers)).encode()
            self.node.propose(data, etype=ENTRY_CONF)
            self._pump()

    # -- catch-up (blockpuller.go analog) -----------------------------------
    def catch_up(self, blocks: Sequence[common_pb2.Block]) -> None:
        """Feed missing blocks pulled from another orderer after receiving
        a snapshot that outran our log. Config blocks are detected from the
        channel header so last-config tracking and the bundle stay fresh."""
        with self._lock:
            for b in sorted(blocks, key=lambda b: b.header.number):
                if b.header.number != self.writer.height:
                    continue
                is_config = _is_config_block(b)
                # replicated blocks carry the cluster's authoritative
                # consenter-id mapping; adopt it (else derive + stamp)
                pulled = ConsenterIdTracker.from_block(b)
                if pulled is not None:
                    self.tracker = pulled
                elif self.tracker is not None:
                    if is_config:
                        addrs = consenters_from_config_block(b)
                        if addrs is not None:
                            self.tracker.apply(addrs)
                    self.tracker.stamp(b)
                self.writer.write_block(b, is_config=is_config)
                if is_config and self._on_config_block is not None:
                    self._on_config_block(b)

    @property
    def needs_catch_up(self) -> Optional[int]:
        """If a received snapshot implies blocks we don't have, the height
        we must reach; else None."""
        if self.node.applied_snapshot is None:
            return None
        _, data = self.node.applied_snapshot
        if len(data) >= 8:
            (target,) = struct.unpack_from("<Q", data, 0)
            if target > self.writer.height:
                return target
        return None
