"""Raft CFT consensus core (reference orderer/consensus/etcdraft: one raft
group per channel, WAL + snapshots, leadership-aware block proposal).

Built tick-driven and message-passing like etcd/raft so tests can run a
whole cluster deterministically without wall-clock or sockets:

- RaftNode.tick() advances election/heartbeat timers;
- RaftNode.step(msg) consumes a peer message;
- both return nothing but queue outbound messages + ready state, drained
  via RaftNode.ready(): (messages, hard_state, committed_entries).

Persistence mirrors the reference's storage.go triple: a WAL of hard-state
changes and entries (CRC-framed, replayed on restart) and a snapshot file
that truncates the log prefix. The consenter layer (RaftChain) owns block
creation on the leader and block application everywhere (etcdraft/chain.go
writeBlock), including stale-leader deduplication by block number.
"""

from __future__ import annotations

import os
import random
import struct
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# -- log entries ------------------------------------------------------------

ENTRY_NORMAL = 0
ENTRY_CONF = 1  # data = comma-joined sorted node ids (membership change)


@dataclass(frozen=True)
class Entry:
    index: int
    term: int
    type: int
    data: bytes


@dataclass
class Message:
    kind: str  # vote_req | vote_resp | append | append_resp | snap
    term: int
    frm: int
    to: int
    # append
    prev_index: int = 0
    prev_term: int = 0
    entries: Tuple[Entry, ...] = ()
    commit: int = 0
    # vote_req
    last_index: int = 0
    last_term: int = 0
    # responses
    granted: bool = False
    success: bool = False
    match_index: int = 0
    # snap
    snap_index: int = 0
    snap_term: int = 0
    snap_data: bytes = b""


FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


# -- wire codec (cluster Step RPC payloads) ---------------------------------

_KINDS = ("vote_req", "vote_resp", "append", "append_resp", "snap")


def message_to_bytes(m: Message) -> bytes:
    """Frame a Message for the orderer-to-orderer Consensus stream
    (reference cluster ConsensusRequest.payload carries etcd raftpb bytes;
    here the same struct framing style as the WAL)."""
    head = struct.pack(
        "<BQQQQQQBBQQQQ",
        _KINDS.index(m.kind),
        m.term,
        m.frm,
        m.to,
        m.prev_index,
        m.prev_term,
        m.commit,
        1 if m.granted else 0,
        1 if m.success else 0,
        m.match_index,
        m.last_index,
        m.last_term,
        m.snap_index,
    )
    out = [head, struct.pack("<QI", m.snap_term, len(m.snap_data)), m.snap_data]
    out.append(struct.pack("<I", len(m.entries)))
    for e in m.entries:
        out.append(struct.pack("<QQBI", e.index, e.term, e.type, len(e.data)))
        out.append(e.data)
    return b"".join(out)


def message_from_bytes(raw: bytes) -> Message:
    head_fmt = "<BQQQQQQBBQQQQ"
    head_len = struct.calcsize(head_fmt)
    (
        kind_i,
        term,
        frm,
        to,
        prev_index,
        prev_term,
        commit,
        granted,
        success,
        match_index,
        last_index,
        last_term,
        snap_index,
    ) = struct.unpack_from(head_fmt, raw, 0)
    pos = head_len
    snap_term, snap_len = struct.unpack_from("<QI", raw, pos)
    pos += struct.calcsize("<QI")
    # Wire lengths are untrusted: a slice past the end of `raw` would
    # silently truncate (returning a short snapshot/entry as if it were
    # whole), so every decoded length is checked against the payload
    # before use and the frame is rejected loudly instead.
    if pos + snap_len > len(raw):
        raise ValueError(
            f"raft message snapshot length {snap_len} overruns the "
            f"{len(raw)}-byte payload"
        )
    snap_data = raw[pos : pos + snap_len]
    pos += snap_len
    (n_entries,) = struct.unpack_from("<I", raw, pos)
    pos += 4
    if n_entries > len(raw):
        raise ValueError(
            f"raft message entry count {n_entries} exceeds the "
            f"{len(raw)}-byte payload"
        )
    entries = []
    for _ in range(n_entries):
        index, eterm, etype, dlen = struct.unpack_from("<QQBI", raw, pos)
        pos += struct.calcsize("<QQBI")
        if pos + dlen > len(raw):
            raise ValueError(
                f"raft entry data length {dlen} overruns the "
                f"{len(raw)}-byte payload"
            )
        entries.append(Entry(index, eterm, etype, raw[pos : pos + dlen]))
        pos += dlen
    return Message(
        kind=_KINDS[kind_i],
        term=term,
        frm=frm,
        to=to,
        prev_index=prev_index,
        prev_term=prev_term,
        entries=tuple(entries),
        commit=commit,
        last_index=last_index,
        last_term=last_term,
        granted=bool(granted),
        success=bool(success),
        match_index=match_index,
        snap_index=snap_index,
        snap_term=snap_term,
        snap_data=snap_data,
    )


class RaftNode:
    """Single raft participant for one channel."""

    def __init__(
        self,
        node_id: int,
        peers: Sequence[int],
        election_tick: int = 10,
        heartbeat_tick: int = 1,
        rng: Optional[random.Random] = None,
    ):
        self.id = node_id
        self.peers = set(peers)
        assert node_id in self.peers
        self.term = 0
        self.voted_for = 0
        self.log: List[Entry] = []  # entries > snap_index
        self.snap_index = 0
        self.snap_term = 0
        self.snap_data = b""
        self.commit_index = 0
        self.role = FOLLOWER
        self.leader_id = 0
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self._rng = rng or random.Random(node_id * 7919)
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        self._votes: set = set()
        self._next: Dict[int, int] = {}
        self._match: Dict[int, int] = {}
        self._outbox: List[Message] = []
        self._hard_dirty = False
        self._new_entries: List[Entry] = []
        self.evicted = False
        self.applied_snapshot: Optional[Tuple[int, bytes]] = None

    # -- log helpers --------------------------------------------------------
    @property
    def last_index(self) -> int:
        return self.log[-1].index if self.log else self.snap_index

    def _term_at(self, index: int) -> Optional[int]:
        if index == 0:
            return 0
        if index == self.snap_index:
            return self.snap_term
        off = index - self.snap_index - 1
        if 0 <= off < len(self.log):
            return self.log[off].term
        return None

    def _entries_from(self, index: int) -> List[Entry]:
        off = index - self.snap_index - 1
        return list(self.log[max(off, 0):])

    # -- timers -------------------------------------------------------------
    def _rand_timeout(self) -> int:
        return self.election_tick + self._rng.randrange(self.election_tick)

    def tick(self) -> None:
        if self.evicted:
            return
        self._elapsed += 1
        if self.role == LEADER:
            if self._elapsed >= self.heartbeat_tick:
                self._elapsed = 0
                self._broadcast_append()
        elif self._elapsed >= self._timeout:
            self.campaign()

    def campaign(self) -> None:
        if len(self.peers) == 1:
            self._become_leader_if_single()
            return
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self._hard_dirty = True
        self._votes = {self.id}
        self._elapsed = 0
        self._timeout = self._rand_timeout()
        for p in self.peers - {self.id}:
            self._outbox.append(
                Message(
                    "vote_req",
                    self.term,
                    self.id,
                    p,
                    last_index=self.last_index,
                    last_term=self._term_at(self.last_index) or 0,
                )
            )

    def _become_leader_if_single(self) -> None:
        self.term += 1
        self.voted_for = self.id
        self._hard_dirty = True
        self._become_leader()

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_id = self.id
        self._elapsed = 0
        for p in self.peers:
            self._next[p] = self.last_index + 1
            self._match[p] = 0
        self._match[self.id] = self.last_index
        # noop entry to commit entries from prior terms (raft §5.4.2)
        self._append_local(ENTRY_NORMAL, b"")
        self._broadcast_append()

    def _become_follower(self, term: int, leader: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = 0
            self._hard_dirty = True
        self.role = FOLLOWER
        self.leader_id = leader
        self._elapsed = 0
        self._timeout = self._rand_timeout()

    # -- proposal -----------------------------------------------------------
    def propose(self, data: bytes, etype: int = ENTRY_NORMAL) -> bool:
        if self.role != LEADER or self.evicted:
            return False
        self._append_local(etype, data)
        self._broadcast_append()
        return True

    def _append_local(self, etype: int, data: bytes) -> None:
        e = Entry(self.last_index + 1, self.term, etype, data)
        self.log.append(e)
        self._new_entries.append(e)
        self._match[self.id] = self.last_index
        if len(self.peers) == 1:
            self._advance_commit()

    # -- replication --------------------------------------------------------
    def _broadcast_append(self) -> None:
        for p in self.peers - {self.id}:
            self._send_append(p)

    def _send_append(self, to: int) -> None:
        nxt = self._next.get(to, self.last_index + 1)
        if nxt <= self.snap_index:
            self._outbox.append(
                Message(
                    "snap",
                    self.term,
                    self.id,
                    to,
                    snap_index=self.snap_index,
                    snap_term=self.snap_term,
                    snap_data=self.snap_data,
                    commit=self.commit_index,
                )
            )
            return
        prev = nxt - 1
        prev_term = self._term_at(prev)
        entries = tuple(self._entries_from(nxt))
        self._outbox.append(
            Message(
                "append",
                self.term,
                self.id,
                to,
                prev_index=prev,
                prev_term=prev_term if prev_term is not None else 0,
                entries=entries,
                commit=self.commit_index,
            )
        )

    def step(self, m: Message) -> None:
        if self.evicted:
            return
        if m.term > self.term:
            self._become_follower(m.term, m.frm if m.kind == "append" else 0)
        if m.kind == "vote_req":
            self._on_vote_req(m)
        elif m.kind == "vote_resp":
            self._on_vote_resp(m)
        elif m.kind == "append":
            self._on_append(m)
        elif m.kind == "append_resp":
            self._on_append_resp(m)
        elif m.kind == "snap":
            self._on_snap(m)

    def _on_vote_req(self, m: Message) -> None:
        up_to_date = (m.last_term, m.last_index) >= (
            self._term_at(self.last_index) or 0,
            self.last_index,
        )
        grant = (
            m.term >= self.term
            and self.voted_for in (0, m.frm)
            and up_to_date
        )
        if grant:
            self.voted_for = m.frm
            self._hard_dirty = True
            self._elapsed = 0
        self._outbox.append(
            Message("vote_resp", self.term, self.id, m.frm, granted=grant)
        )

    def _on_vote_resp(self, m: Message) -> None:
        if self.role != CANDIDATE or m.term < self.term:
            return
        if m.granted:
            self._votes.add(m.frm)
            if len(self._votes) * 2 > len(self.peers):
                self._become_leader()

    def _on_append(self, m: Message) -> None:
        if m.term < self.term:
            self._outbox.append(
                Message("append_resp", self.term, self.id, m.frm, success=False)
            )
            return
        self._become_follower(m.term, m.frm)
        if m.prev_index < self.snap_index:
            # entries at/below our snapshot are already committed; the
            # leader's _next decayed past our compaction point. Tell it
            # where we really are instead of corrupting the log base.
            self._outbox.append(
                Message(
                    "append_resp",
                    self.term,
                    self.id,
                    m.frm,
                    success=False,
                    match_index=self.snap_index,
                )
            )
            return
        local_prev_term = self._term_at(m.prev_index)
        if local_prev_term is None or (
            m.prev_index > 0 and local_prev_term != m.prev_term
        ):
            self._outbox.append(
                Message(
                    "append_resp",
                    self.term,
                    self.id,
                    m.frm,
                    success=False,
                    match_index=min(self.last_index, m.prev_index - 1)
                    if m.prev_index > 0
                    else 0,
                )
            )
            return
        for e in m.entries:
            existing = self._term_at(e.index)
            if existing is None:
                self.log.append(e)
                self._new_entries.append(e)
            elif existing != e.term:
                # conflict: truncate from here, then append
                off = e.index - self.snap_index - 1
                del self.log[off:]
                self.log.append(e)
                self._new_entries.append(e)
        if m.commit > self.commit_index:
            self.commit_index = min(m.commit, self.last_index)
        self._outbox.append(
            Message(
                "append_resp",
                self.term,
                self.id,
                m.frm,
                success=True,
                match_index=self.last_index,
            )
        )

    def _on_append_resp(self, m: Message) -> None:
        if self.role != LEADER or m.term < self.term:
            return
        if m.success:
            self._match[m.frm] = max(self._match.get(m.frm, 0), m.match_index)
            self._next[m.frm] = self._match[m.frm] + 1
            self._advance_commit()
        else:
            hint = m.match_index
            self._next[m.frm] = max(1, hint + 1 if hint else self._next.get(m.frm, 2) - 1)
            self._send_append(m.frm)

    def _on_snap(self, m: Message) -> None:
        if m.term < self.term:
            return
        self._become_follower(m.term, m.frm)
        if m.snap_index <= self.commit_index:
            # already have this state; ack so the leader advances _next
            # instead of resending the snapshot forever
            self._outbox.append(
                Message(
                    "append_resp",
                    self.term,
                    self.id,
                    m.frm,
                    success=True,
                    match_index=self.commit_index,
                )
            )
            return
        self.snap_index = m.snap_index
        self.snap_term = m.snap_term
        self.snap_data = m.snap_data
        self.log = []
        self.commit_index = m.snap_index
        self.applied_snapshot = (m.snap_index, m.snap_data)
        self._outbox.append(
            Message(
                "append_resp",
                self.term,
                self.id,
                m.frm,
                success=True,
                match_index=m.snap_index,
            )
        )

    def _advance_commit(self) -> None:
        for idx in range(self.last_index, self.commit_index, -1):
            votes = sum(1 for p in self.peers if self._match.get(p, 0) >= idx)
            if votes * 2 > len(self.peers) and self._term_at(idx) == self.term:
                self.commit_index = idx
                break

    # -- membership ---------------------------------------------------------
    def apply_conf_change(self, new_peers: Sequence[int]) -> None:
        """Applied when an ENTRY_CONF commits; eviction detection
        (reference etcdraft/eviction.go): removed nodes halt."""
        self.peers = set(new_peers)
        if self.id not in self.peers:
            self.evicted = True
            self.role = FOLLOWER
        for p in list(self._next):
            if p not in self.peers:
                self._next.pop(p, None)
                self._match.pop(p, None)
        for p in self.peers:
            self._next.setdefault(p, self.last_index + 1)
            self._match.setdefault(p, 0)

    # -- compaction ---------------------------------------------------------
    def compact(self, index: int, data: bytes) -> None:
        """Truncate log entries <= index (applied state captured in data)."""
        if index <= self.snap_index:
            return
        term = self._term_at(index)
        assert term is not None, "cannot compact beyond the log"
        self.log = self._entries_from(index + 1)
        self.snap_index = index
        self.snap_term = term
        self.snap_data = data

    # -- ready --------------------------------------------------------------
    def ready(self) -> Tuple[List[Message], Optional[Tuple[int, int]], List[Entry]]:
        msgs, self._outbox = self._outbox, []
        hard = (self.term, self.voted_for) if self._hard_dirty else None
        self._hard_dirty = False
        entries, self._new_entries = self._new_entries, []
        return msgs, hard, entries


# -- WAL + snapshot persistence (reference etcdraft/storage.go) -------------

_REC_HARD = 1
_REC_ENTRY = 2


class WAL:
    """CRC-framed append-only log of hard-state changes + entries."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def _open(self):
        if self._f is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._f = open(self.path, "ab")
        return self._f

    def save(self, hard: Optional[Tuple[int, int]], entries: Sequence[Entry]) -> None:
        f = self._open()
        if hard is not None:
            body = struct.pack("<BQQ", _REC_HARD, hard[0], hard[1])
            f.write(struct.pack("<I", len(body)) + body + struct.pack("<I", zlib.crc32(body)))
        for e in entries:
            body = struct.pack("<BQQB", _REC_ENTRY, e.index, e.term, e.type) + e.data
            f.write(struct.pack("<I", len(body)) + body + struct.pack("<I", zlib.crc32(body)))
        f.flush()
        os.fsync(f.fileno())

    def replay(self) -> Tuple[Tuple[int, int], List[Entry]]:
        """Returns ((term, voted_for), entries) — truncated tails dropped."""
        hard = (0, 0)
        entries: List[Entry] = []
        if not os.path.exists(self.path):
            return hard, entries
        with open(self.path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 8 <= len(raw):
            (length,) = struct.unpack_from("<I", raw, pos)
            if pos + 4 + length + 4 > len(raw):
                break  # torn tail
            body = raw[pos + 4 : pos + 4 + length]
            (crc,) = struct.unpack_from("<I", raw, pos + 4 + length)
            if zlib.crc32(body) != crc:
                break
            pos += 8 + length
            kind = body[0]
            if kind == _REC_HARD:
                _, term, voted = struct.unpack("<BQQ", body)
                hard = (term, voted)
            elif kind == _REC_ENTRY:
                _, index, term, etype = struct.unpack_from("<BQQB", body)
                data = body[struct.calcsize("<BQQB"):]
                # conflicting rewrites: keep the latest copy of an index
                while entries and entries[-1].index >= index:
                    entries.pop()
                entries.append(Entry(index, term, etype, data))
        return hard, entries

    def rotate(self, hard: Tuple[int, int], entries: Sequence[Entry]) -> None:
        """Rewrite the WAL to just the current hard state + live entries
        (post-snapshot truncation; bounds file size and replay cost)."""
        self.close()
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        self._f = open(tmp, "ab")
        self.save(hard, entries)
        self.close()
        os.replace(tmp, self.path)

    def close(self):
        if self._f:
            self._f.close()
            self._f = None


class SnapshotFile:
    def __init__(self, path: str):
        self.path = path

    def save(self, index: int, term: int, data: bytes) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        body = struct.pack("<QQ", index, term) + data
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(struct.pack("<I", zlib.crc32(body)) + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[Tuple[int, int, bytes]]:
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            raw = f.read()
        if len(raw) < 20:
            return None
        (crc,) = struct.unpack_from("<I", raw, 0)
        body = raw[4:]
        if zlib.crc32(body) != crc:
            return None
        index, term = struct.unpack_from("<QQ", body, 0)
        return index, term, body[16:]
