"""Block creation + signing shared by all consenters (reference
orderer/common/multichannel/blockwriter.go).

The writer chains blocks by previous_hash, tracks the latest config block
index, signs the SIGNATURES metadata (value = OrdererBlockMetadata-style
LastConfig, signed bytes = value || signature_header || block_header DER),
and hands finished blocks to a sink (the channel's block store and any
deliver subscribers).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from fabric_tpu.protos import common_pb2, protoutil


class BlockWriter:
    def __init__(
        self,
        signer=None,
        sink: Optional[Callable[[common_pb2.Block], None]] = None,
        last_block: Optional[common_pb2.Block] = None,
        last_config_index: int = 0,
    ):
        self.signer = signer
        self.sink = sink
        self._last_config_index = last_config_index
        if last_block is not None:
            self.height = last_block.header.number + 1
            self._last_hash = protoutil.block_header_hash(last_block.header)
        else:
            self.height = 0
            self._last_hash = b""

    def create_next_block(
        self, envelopes: Sequence[common_pb2.Envelope]
    ) -> common_pb2.Block:
        block = protoutil.new_block(self.height, self._last_hash)
        for env in envelopes:
            block.data.data.append(env.SerializeToString())
        return protoutil.seal_block(block)

    def append_bootstrap(self, block: common_pb2.Block) -> None:
        """Adopt an externally-created block (genesis or latest config
        block on join) AS-IS: no re-signing, no mutation — the stored
        bytes must stay identical to the configtx artifact. Initializes
        the chain position from the block's own number."""
        self.height = block.header.number + 1
        self._last_hash = protoutil.block_header_hash(block.header)
        self._last_config_index = block.header.number
        if self.sink is not None:
            self.sink(block)

    def write_block(self, block: common_pb2.Block, is_config: bool = False) -> None:
        """Sign + advance the chain. Blocks must arrive in order."""
        if block.header.number != self.height:
            raise ValueError(
                f"wrote block {block.header.number}, expected {self.height}"
            )
        if is_config:
            self._last_config_index = block.header.number
        self._add_signature_metadata(block)
        self.height += 1
        self._last_hash = protoutil.block_header_hash(block.header)
        if self.sink is not None:
            self.sink(block)

    def _add_signature_metadata(self, block: common_pb2.Block) -> None:
        protoutil.init_block_metadata(block)
        last_config = common_pb2.LastConfig()
        last_config.index = self._last_config_index
        meta = common_pb2.Metadata()
        meta.value = last_config.SerializeToString()
        if self.signer is not None:
            sig = meta.signatures.add()
            shdr = protoutil.make_signature_header(
                self.signer.serialize(), self.signer.new_nonce()
            )
            sig.signature_header = shdr.SerializeToString()
            signed = (
                meta.value
                + sig.signature_header
                + protoutil.block_header_bytes(block.header)
            )
            sig.signature = self.signer.sign(signed)
        block.metadata.metadata[common_pb2.SIGNATURES] = meta.SerializeToString()

    @property
    def last_config_index(self) -> int:
        return self._last_config_index


def block_signature_verifier(bundle_getter, policy_name: str = "/Channel/Orderer/BlockValidation"):
    """Returns verify(block) -> bool for the peer's MCS.VerifyBlock
    (reference usable-inter-nal/peer/gossip/mcs.go:124): evaluate the
    BlockValidation policy over the SIGNATURES metadata signatures."""
    from fabric_tpu.policy.manager import SignedData

    def verify(block: common_pb2.Block) -> bool:
        bundle = bundle_getter()
        if bundle is None:
            return True
        if len(block.metadata.metadata) <= common_pb2.SIGNATURES:
            return False
        meta = protoutil.unmarshal(
            common_pb2.Metadata, block.metadata.metadata[common_pb2.SIGNATURES]
        )
        signed_data = []
        for sig in meta.signatures:
            shdr = protoutil.unmarshal(
                common_pb2.SignatureHeader, sig.signature_header
            )
            signed_data.append(
                SignedData(
                    meta.value
                    + sig.signature_header
                    + protoutil.block_header_bytes(block.header),
                    shdr.creator,
                    sig.signature,
                )
            )
        policy, ok = bundle.policy_manager.get_policy(policy_name)
        if not ok:
            return False
        try:
            policy.evaluate_signed_data(signed_data)
            return True
        except Exception:
            return False

    return verify
