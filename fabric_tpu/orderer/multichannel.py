"""Multichannel registrar (reference orderer/common/multichannel/
registrar.go): per-channel chain resources on the ordering side.

Each channel owns: a config Bundle + configtx Validator (hot-swapped on
config blocks), a msgprocessor, and a consenter chain (solo or raft).
Channel creation happens either through the system channel's Consortiums
group (a CONFIG_UPDATE for an unknown channel id) or by direct join with
a genesis/config block (channel participation API,
registrar.go JoinChannel).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from fabric_tpu.channelconfig.bundle import Bundle, bundle_from_genesis_block
from fabric_tpu.channelconfig.configtx import Validator
from fabric_tpu.channelconfig import encoder
from fabric_tpu.orderer.blockcutter import BatchConfig
from fabric_tpu.orderer.msgprocessor import (
    MsgProcessorError,
    StandardChannelProcessor,
    classify,
)
from fabric_tpu.orderer.raft_chain import RaftChain
from fabric_tpu.orderer.solo import SoloChain
from fabric_tpu.protos import common_pb2, configtx_pb2, protoutil


class RegistrarError(Exception):
    pass


@dataclass
class ChainSupport:
    channel_id: str
    bundle: Bundle
    validator: Validator
    processor: StandardChannelProcessor
    chain: object  # SoloChain | RaftChain

    @property
    def height(self) -> int:
        return self.chain.height

    def get_block(self, number: int):
        return self.chain.get_block(number)


class Registrar:
    def __init__(
        self,
        work_dir: str,
        signer=None,
        system_channel_id: Optional[str] = None,
        raft_node_id: int = 1,
        raft_transport_factory: Optional[Callable[[str, int], Callable]] = None,
        provider=None,
        follower_endpoint_factory: Optional[Callable] = None,
    ):
        self.work_dir = work_dir
        self.signer = signer
        self.provider = provider
        self.system_channel_id = system_channel_id
        self.raft_node_id = raft_node_id
        self.raft_transport_factory = raft_transport_factory or (
            lambda channel_id, node_id: (lambda to, msg: None)
        )
        # addresses -> deliver endpoints; enables follower/onboarding mode
        # (orderer/common/follower) for joins where this node is not (yet)
        # a consenter or joins from a non-genesis block
        self.follower_endpoint_factory = follower_endpoint_factory
        self.chains: Dict[str, ChainSupport] = {}
        self.followers: Dict[str, object] = {}  # channel -> FollowerChain
        # serializes chains/followers mutations: join_channel (gRPC
        # threads) races _promote_follower (the follower's pull thread)
        self._registry_lock = threading.RLock()
        self._block_listeners: List[Callable[[str, common_pb2.Block], None]] = []
        self._chain_listeners: List[Callable[[ChainSupport], None]] = []

    # -- wiring -------------------------------------------------------------
    def on_block(self, fn: Callable[[str, common_pb2.Block], None]) -> None:
        """Deliver-service hook: called for every block written anywhere."""
        self._block_listeners.append(fn)

    def on_chain(self, fn: Callable[[ChainSupport], None]) -> None:
        """Called when a chain starts AND after every config block it
        applies — the hook the node uses to keep cluster consenter
        endpoints current for channels created any way (join, system
        channel, config update)."""
        self._chain_listeners.append(fn)
        for support in self.chains.values():
            fn(support)

    def _sink_for(self, channel_id: str) -> Callable[[common_pb2.Block], None]:
        def sink(block: common_pb2.Block) -> None:
            for fn in self._block_listeners:
                fn(channel_id, block)

        return sink

    # -- channel lifecycle --------------------------------------------------
    def join_channel(self, genesis_block: common_pb2.Block):
        """Channel-participation join (registrar.go JoinChannel): bootstrap
        a chain from its genesis (or latest config) block.

        With a follower endpoint factory configured, a join where this
        node is not in the consenter set — or a join from a non-genesis
        config block — starts a FollowerChain that replicates the ledger
        from the cluster and promotes itself to a consenter when the
        config says so (orderer/common/follower + onboarding)."""
        bundle = bundle_from_genesis_block(genesis_block, self.provider)
        channel_id = bundle.channel_id
        with self._registry_lock:
            if channel_id in self.chains or channel_id in self.followers:
                raise RegistrarError(f"channel {channel_id} already exists")
            if (
                self.follower_endpoint_factory is not None
                and bundle.orderer is not None
                and bundle.orderer.consensus_type == "etcdraft"
            ):
                from fabric_tpu.orderer.consenter_ids import ConsenterIdTracker
                from fabric_tpu.orderer.follower import is_member

                # a join block carrying the cluster's id mapping decides
                # membership by stable id; genesis joins are positional
                tracker = ConsenterIdTracker.from_block(genesis_block)
                member = (
                    tracker.is_member(self.raft_node_id)
                    if tracker is not None
                    else is_member(bundle, self.raft_node_id)
                )
                if not member or genesis_block.header.number > 0:
                    return self._start_follower(
                        channel_id, bundle, genesis_block
                    )
            return self._start_chain(channel_id, bundle, genesis_block)

    def _start_follower(
        self,
        channel_id: str,
        bundle: Bundle,
        join_block: common_pb2.Block,
    ):
        from fabric_tpu.orderer.follower import FollowerChain

        follower = FollowerChain(
            channel_id,
            join_block,
            bundle,
            node_id=self.raft_node_id,
            wal_dir=os.path.join(self.work_dir, "etcdraft"),
            endpoint_factory=self.follower_endpoint_factory,
            on_become_member=self._promote_follower,
            provider=self.provider,
        )
        follower.check_join_block_membership()
        self.followers[channel_id] = follower
        follower.start()
        return follower

    def _promote_follower(self, follower) -> ChainSupport:
        """The follower reached a config where this node is a consenter:
        restart the channel as a raft member on the same ledger
        (follower_chain.go halt + registrar SwitchFollowerToChain)."""
        with self._registry_lock:
            # start the chain BEFORE dropping the follower entry so deliver
            # lookups never see the channel in neither map; _start_chain
            # inserting into chains also blocks a racing join_channel
            support = self._start_chain(
                follower.channel_id, follower.bundle, None
            )
            self.followers.pop(follower.channel_id, None)
            return support

    def channel_info(self, channel_id: str) -> Optional[Dict[str, object]]:
        """Channel-participation style status
        (orderer/common/types/channel_info.go)."""
        support = self.chains.get(channel_id)
        if support is not None:
            return {
                "name": channel_id,
                "height": support.height,
                "status": "active",
                "consensusRelation": "consenter"
                if hasattr(support.chain, "node")
                else "none",
            }
        follower = self.followers.get(channel_id)
        if follower is not None:
            return {
                "name": channel_id,
                "height": follower.height,
                "status": follower.status,
                "consensusRelation": follower.consensus_relation,
            }
        return None

    def _start_chain(
        self,
        channel_id: str,
        bundle: Bundle,
        genesis_block: Optional[common_pb2.Block],
    ) -> ChainSupport:
        validator = Validator(
            channel_id,
            _config_from_bundle(bundle),
            policy_manager=bundle.policy_manager,
        )
        processor = StandardChannelProcessor(channel_id, bundle, validator)
        batch_config = BatchConfig(
            max_message_count=bundle.orderer.batch_size_max_messages,
            absolute_max_bytes=bundle.orderer.batch_size_absolute_max_bytes,
            preferred_max_bytes=bundle.orderer.batch_size_preferred_max_bytes,
        ) if bundle.orderer else BatchConfig()

        support_holder: List[ChainSupport] = []

        def on_config_block(block: common_pb2.Block) -> None:
            self._apply_config_block(support_holder[0], block)

        consensus = bundle.orderer.consensus_type if bundle.orderer else "solo"
        if consensus == "etcdraft":
            from fabric_tpu.orderer.follower import consenter_addresses

            addresses = consenter_addresses(bundle)
            # positional fallback only; RaftChain prefers the stable id
            # mapping recovered from the ledger's ORDERER block metadata
            peer_ids = list(range(1, len(addresses) + 1)) or [1]
            chain = RaftChain(
                channel_id,
                self.raft_node_id,
                peer_ids,
                initial_consenters=addresses,
                wal_dir=os.path.join(self.work_dir, "etcdraft"),
                signer=self.signer,
                batch_config=batch_config,
                sink=self._sink_for(channel_id),
                genesis_block=genesis_block,
                transport=self.raft_transport_factory(
                    channel_id, self.raft_node_id
                ),
                on_config_block=on_config_block,
            )
        else:
            chain = SoloChain(
                channel_id,
                signer=self.signer,
                batch_config=batch_config,
                deliver=self._sink_for(channel_id),
                genesis_block=genesis_block,
                on_config_block=on_config_block,
            )
        support = ChainSupport(channel_id, bundle, validator, processor, chain)
        support_holder.append(support)
        self.chains[channel_id] = support
        for fn in self._chain_listeners:
            fn(support)
        return support

    def _apply_config_block(
        self, support: ChainSupport, block: common_pb2.Block
    ) -> None:
        """Hot-swap the bundle when a config block commits (reference
        bundlesource.go + registrar's config-block callback). A change
        to the etcdraft consenter set additionally bridges into a raft
        membership change (etcdraft chain.go detectConfChange →
        ProposeConfChange): the leader proposes the new peer set; the
        replicated ENTRY_CONF applies it on every member."""
        from fabric_tpu.orderer.follower import consenter_addresses

        env = protoutil.get_envelope_from_block_data(block.data.data[0])
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        cenv = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
        new_bundle = Bundle(support.channel_id, cenv.config, self.provider)
        support.bundle = new_bundle
        support.validator.config = cenv.config
        support.processor.update_bundle(new_bundle)
        new_consenters = len(consenter_addresses(new_bundle))
        chain = support.chain
        # Stable per-consenter raft ids come from the chain's tracker
        # (updated when the config block was written — raft_chain
        # _apply_entry), NOT from list positions: removing or reordering
        # a non-tail consenter must evict exactly the departed node.
        desired = (
            set(chain.tracker.peer_ids())
            if isinstance(chain, RaftChain) and chain.tracker is not None
            else set(range(1, new_consenters + 1))
        )
        if (
            new_consenters > 0
            and isinstance(chain, RaftChain)
            # compare against the chain's LIVE peer set, not the old
            # bundle: if a previous leader died between committing the
            # config block and committing its ENTRY_CONF, any later
            # config apply on the new leader re-proposes and repairs
            and desired != chain.node.peers
        ):
            from fabric_tpu.orderer.raft_chain import NotLeaderError

            # Called from inside the chain's own apply loop; the nested
            # propose->pump->apply re-entry is benign because
            # _apply_entry's writer-height guard skips the already
            # written block (raft_chain.py _apply_entry).
            try:
                chain.propose_conf_change(sorted(desired))
            except NotLeaderError:
                pass  # the leader's own apply proposes; replication covers us
        for fn in self._chain_listeners:
            fn(support)

    # -- lookup -------------------------------------------------------------
    def get_chain(self, channel_id: str) -> Optional[ChainSupport]:
        return self.chains.get(channel_id)

    def channel_list(self) -> List[str]:
        return sorted(set(self.chains) | set(self.followers))

    # -- system-channel channel creation ------------------------------------
    def new_channel_from_update(
        self, env: common_pb2.Envelope
    ) -> ChainSupport:
        """CONFIG_UPDATE addressed to a non-existent channel, arriving via
        the system channel (reference systemchannel.go
        NewChannelConfig): instantiate the channel from the consortium
        definition + the update's Application write set."""
        with self._registry_lock:
            return self._new_channel_from_update_locked(env)

    def _new_channel_from_update_locked(
        self, env: common_pb2.Envelope
    ) -> ChainSupport:
        # under _registry_lock: the exists-check and the _start_chain
        # insert must be atomic vs concurrent creations and promotions,
        # or two chains end up appending to one wal_dir ledger
        if self.system_channel_id is None:
            raise RegistrarError(
                "no system channel: create channels via join_channel"
            )
        sys_support = self.chains[self.system_channel_id]
        # Expiration + size filters apply to the client envelope; the
        # authorization check is the consortium's ChannelCreationPolicy
        # (below), matching systemchannel.go where the SigFilter only ever
        # sees the orderer-signed ORDERER_TRANSACTION wrapper.
        sys_support.processor.apply_filters(env, include_sig=False)
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        cue = protoutil.unmarshal(
            configtx_pb2.ConfigUpdateEnvelope, payload.data
        )
        update = protoutil.unmarshal(
            configtx_pb2.ConfigUpdate, cue.config_update
        )
        channel_id = update.channel_id
        if channel_id in self.chains or channel_id in self.followers:
            raise RegistrarError(f"channel {channel_id} already exists")

        cons_value = update.write_set.values.get("Consortium")
        if cons_value is None:
            raise RegistrarError("channel creation update names no consortium")
        from fabric_tpu.protos import configuration_pb2

        consortium = protoutil.unmarshal(
            configuration_pb2.Consortium, cons_value.value
        ).name
        sys_root = sys_support.validator.config.channel_group
        consortiums = sys_root.groups.get("Consortiums")
        if consortiums is None or consortium not in consortiums.groups:
            raise RegistrarError(f"unknown consortium {consortium}")

        # template: channel root from the system channel minus Consortiums,
        # with the Application group from the update's write set and org
        # definitions resolved from the consortium.
        template = configtx_pb2.ConfigGroup()
        template.CopyFrom(sys_root)
        del template.groups["Consortiums"]
        template.values["Consortium"].value = cons_value.value
        app = update.write_set.groups.get("Application")
        if app is None:
            raise RegistrarError("channel creation update has no Application group")
        new_app = template.groups["Application"]
        new_app.Clear()
        new_app.CopyFrom(app)
        new_app.version = 0
        cons_group = consortiums.groups[consortium]
        for org_name in list(new_app.groups):
            if org_name in cons_group.groups:
                new_app.groups[org_name].CopyFrom(cons_group.groups[org_name])
            elif not new_app.groups[org_name].values:
                raise RegistrarError(
                    f"org {org_name} not defined in consortium {consortium}"
                )

        cfg = configtx_pb2.Config()
        cfg.sequence = 0
        cfg.channel_group.CopyFrom(template)

        bundle = Bundle(channel_id, cfg, self.provider)
        self._check_creation_policy(cons_group, bundle, payload.data)

        cenv = configtx_pb2.ConfigEnvelope()
        cenv.config.CopyFrom(cfg)
        cenv.last_update.CopyFrom(env)
        genesis = _config_block(channel_id, cenv, 0, b"")
        return self._start_chain(channel_id, bundle, genesis)

    def _check_creation_policy(
        self,
        cons_group: configtx_pb2.ConfigGroup,
        new_bundle: Bundle,
        cue_bytes: bytes,
    ) -> None:
        """Enforce the consortium's ChannelCreationPolicy over the config
        update's signatures (reference systemchannel.go NewChannelConfig:
        the templator pins the Application group's mod_policy to the
        creation policy, evaluated with the NEW channel's org MSPs)."""
        from fabric_tpu.channelconfig.bundle import CHANNEL_CREATION_POLICY_KEY
        from fabric_tpu.channelconfig.configtx import _config_update_signed_data
        from fabric_tpu.policy.manager import (
            ImplicitMetaPolicy,
            PolicyError,
            SignaturePolicy,
            SignedData,
        )
        from fabric_tpu.policy import proto_convert
        from fabric_tpu.protos import policies_pb2

        cp_value = cons_group.values.get(CHANNEL_CREATION_POLICY_KEY)
        if cp_value is None:
            raise RegistrarError(
                "consortium has no ChannelCreationPolicy"
            )
        pol = protoutil.unmarshal(policies_pb2.Policy, cp_value.value)
        P = policies_pb2.Policy
        if pol.type == P.IMPLICIT_META:
            meta = policies_pb2.ImplicitMetaPolicy()
            meta.ParseFromString(pol.value)
            app_mgr = new_bundle.policy_manager.manager(["Application"])
            children = app_mgr.children if app_mgr is not None else {}
            subs = [
                child.get_policy(meta.sub_policy)[0]
                for child in children.values()
            ]
            policy = ImplicitMetaPolicy(meta.rule, meta.sub_policy, subs)
        elif pol.type == P.SIGNATURE:
            policy = SignaturePolicy(
                proto_convert.unmarshal_envelope(pol.value),
                new_bundle.msp_manager,
                self.provider,
            )
        else:
            raise RegistrarError(
                f"unsupported ChannelCreationPolicy type {pol.type}"
            )
        cue = protoutil.unmarshal(
            configtx_pb2.ConfigUpdateEnvelope, cue_bytes
        )
        # _config_update_signed_data returns (data, creator); SignedData is
        # (data, identity, signature).
        signed = []
        for s in cue.signatures:
            data, creator = _config_update_signed_data(cue, s)
            signed.append(SignedData(data, creator, s.signature))
        try:
            policy.evaluate_signed_data(signed)
        except PolicyError as e:
            raise RegistrarError(
                f"channel creation request failed authorization: {e}"
            ) from e


def _config_from_bundle(bundle: Bundle) -> configtx_pb2.Config:
    return bundle.config


def _config_block(
    channel_id: str,
    cenv: configtx_pb2.ConfigEnvelope,
    number: int,
    prev_hash: bytes,
) -> common_pb2.Block:
    payload = common_pb2.Payload()
    chdr = protoutil.make_channel_header(common_pb2.CONFIG, channel_id)
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = (
        common_pb2.SignatureHeader().SerializeToString()
    )
    payload.data = cenv.SerializeToString()
    env = common_pb2.Envelope()
    env.payload = payload.SerializeToString()
    block = protoutil.new_block(number, prev_hash)
    block.data.data.append(env.SerializeToString())
    return protoutil.seal_block(block)
