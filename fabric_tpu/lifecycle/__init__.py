"""Chaincode lifecycle (_lifecycle analog).

Reference: core/chaincode/lifecycle/lifecycle.go — install / approve /
commit chaincode definitions with per-org approvals, stored in the
`_lifecycle` namespace of channel state, serving validation info
(endorsement policy + validation plugin) to the commit-time dispatcher.
"""

from fabric_tpu.lifecycle.lifecycle import (
    ChaincodeDefinition,
    LifecycleError,
    LifecycleResources,
    NAMESPACE,
)

__all__ = [
    "ChaincodeDefinition",
    "LifecycleError",
    "LifecycleResources",
    "NAMESPACE",
]
