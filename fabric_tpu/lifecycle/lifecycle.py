"""Chaincode lifecycle: per-org approvals and committed definitions.

Reference mechanics (core/chaincode/lifecycle/lifecycle.go):

- a chaincode definition is a sequence-numbered tuple (version,
  endorsement plugin, validation plugin, validation parameter,
  collections, init-required);
- each org APPROVES a (sequence, definition[, package-id]) by writing it
  into its implicit private collection
  (ApproveChaincodeDefinitionForOrg, lifecycle.go:415);
- anyone may ask which orgs' approvals match a proposed definition
  (CheckCommitReadiness, lifecycle.go:320);
- COMMIT (CommitChaincodeDefinition, lifecycle.go:350) records the
  definition in public state at the next sequence, provided the
  approvals satisfy the channel's lifecycle endorsement policy
  (delegated here to an `approval_policy` callable);
- committed definitions serve validation info to the commit-time
  dispatcher (endorsement_info.go).

State layout mirrors the reference's serializer: in namespace
`_lifecycle`, `namespaces/metadata/<cc>` holds a StateMetadata and
`namespaces/fields/<cc>/<Field>` holds one StateData per field, so
state-level parity checks are possible. Org approvals live under
`chaincode-sources`-style keys in per-org maps here (the implicit
collection analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fabric_tpu.protos import lifecycle_pb2

NAMESPACE = "_lifecycle"

_NS_PREFIX = "namespaces"
_DATATYPE_DEFINITION = "ChaincodeDefinition"
_DATATYPE_PARAMETERS = "ChaincodeParameters"


class LifecycleError(Exception):
    pass


@dataclass(frozen=True)
class ChaincodeDefinition:
    """One sequence of a chaincode's governance parameters."""

    sequence: int
    version: str = "1.0"
    endorsement_plugin: str = "escc"
    validation_plugin: str = "vscc"
    validation_parameter: bytes = b""  # serialized ApplicationPolicy
    collections: bytes = b""  # serialized CollectionConfigPackage
    init_required: bool = False

    def parameters_equal(self, other: "ChaincodeDefinition") -> bool:
        return (
            self.version == other.version
            and self.endorsement_plugin == other.endorsement_plugin
            and self.validation_plugin == other.validation_plugin
            and self.validation_parameter == other.validation_parameter
            and self.collections == other.collections
            and self.init_required == other.init_required
        )


def _metadata_key(cc: str) -> str:
    return f"{_NS_PREFIX}/metadata/{cc}"


def _field_key(cc: str, fname: str) -> str:
    return f"{_NS_PREFIX}/fields/{cc}/{fname}"


_FIELDS = (
    "Sequence",
    "Version",
    "EndorsementPlugin",
    "ValidationPlugin",
    "ValidationParameter",
    "Collections",
    "InitRequired",
)


def _data_int(v: int) -> bytes:
    m = lifecycle_pb2.StateData()
    m.Int64 = v
    return m.SerializeToString()


def _data_str(v: str) -> bytes:
    m = lifecycle_pb2.StateData()
    m.String = v
    return m.SerializeToString()


def _data_bytes(v: bytes) -> bytes:
    m = lifecycle_pb2.StateData()
    m.Bytes = v
    return m.SerializeToString()


def _read_data(raw: Optional[bytes]):
    if raw is None:
        return None
    m = lifecycle_pb2.StateData()
    m.ParseFromString(raw)
    kind = m.WhichOneof("Type")
    if kind == "Int64":
        return m.Int64
    if kind == "Bytes":
        return m.Bytes
    if kind == "String":
        return m.String
    return None


class LifecycleResources:
    """The _lifecycle namespace over a pluggable state.

    `public_get`/`public_put` operate on (key) within the _lifecycle
    namespace of channel state. Org approvals are stored through
    `org_get`/`org_put(org, key)` — the implicit-collection analog.
    `approval_policy(approvals: {org: bool}) -> bool` stands in for the
    channel's LifecycleEndorsement policy (default: majority).
    """

    def __init__(
        self,
        public_get: Callable[[str], Optional[bytes]],
        public_put: Callable[[str, bytes], None],
        org_get: Callable[[str, str], Optional[bytes]],
        org_put: Callable[[str, str, bytes], None],
        org_names: Sequence[str],
        approval_policy: Optional[Callable[[Dict[str, bool]], bool]] = None,
    ):
        self.public_get = public_get
        self.public_put = public_put
        self.org_get = org_get
        self.org_put = org_put
        self.org_names = list(org_names)
        self.approval_policy = approval_policy or self._majority

    @staticmethod
    def _majority(approvals: Dict[str, bool]) -> bool:
        yes = sum(1 for ok in approvals.values() if ok)
        return yes > len(approvals) // 2

    # -- serialization ------------------------------------------------------

    def _write_definition(
        self,
        put: Callable[[str, bytes], None],
        cc: str,
        cd: ChaincodeDefinition,
        datatype: str,
    ) -> None:
        meta = lifecycle_pb2.StateMetadata()
        meta.datatype = datatype
        meta.fields.extend(_FIELDS)
        put(_metadata_key(cc), meta.SerializeToString())
        put(_field_key(cc, "Sequence"), _data_int(cd.sequence))
        put(_field_key(cc, "Version"), _data_str(cd.version))
        put(_field_key(cc, "EndorsementPlugin"), _data_str(cd.endorsement_plugin))
        put(_field_key(cc, "ValidationPlugin"), _data_str(cd.validation_plugin))
        put(
            _field_key(cc, "ValidationParameter"),
            _data_bytes(cd.validation_parameter),
        )
        put(_field_key(cc, "Collections"), _data_bytes(cd.collections))
        put(_field_key(cc, "InitRequired"), _data_int(int(cd.init_required)))

    def _read_definition(
        self, get: Callable[[str], Optional[bytes]], cc: str
    ) -> Optional[ChaincodeDefinition]:
        if get(_metadata_key(cc)) is None:
            return None
        seq = _read_data(get(_field_key(cc, "Sequence")))
        if seq is None:
            return None
        return ChaincodeDefinition(
            sequence=seq,
            version=_read_data(get(_field_key(cc, "Version"))) or "",
            endorsement_plugin=_read_data(get(_field_key(cc, "EndorsementPlugin"))) or "",
            validation_plugin=_read_data(get(_field_key(cc, "ValidationPlugin"))) or "",
            validation_parameter=_read_data(get(_field_key(cc, "ValidationParameter"))) or b"",
            collections=_read_data(get(_field_key(cc, "Collections"))) or b"",
            init_required=bool(_read_data(get(_field_key(cc, "InitRequired"))) or 0),
        )

    # -- external functions (lifecycle.go ExternalFunctions) ---------------

    def approve_chaincode_definition_for_org(
        self, org: str, cc: str, cd: ChaincodeDefinition, package_id: str = ""
    ) -> None:
        """ApproveChaincodeDefinitionForOrg (lifecycle.go:415): the
        requested sequence must be the current sequence or current+1."""
        current = self.current_sequence(cc)
        if cd.sequence not in (current, current + 1):
            raise LifecycleError(
                f"requested sequence is {cd.sequence}, but new definition "
                f"must be sequence {current + 1}"
            )
        if cd.sequence == current:
            committed = self.query_chaincode_definition(cc)
            if committed is not None and not committed.parameters_equal(cd):
                raise LifecycleError(
                    "attempted to redefine the current committed sequence "
                    f"({current}) with different parameters"
                )
        self._write_definition(
            lambda k, v: self.org_put(org, f"{cc}#{cd.sequence}/{k}", v),
            cc,
            cd,
            _DATATYPE_PARAMETERS,
        )
        if package_id:
            self.org_put(
                org,
                f"chaincode-sources/{cc}#{cd.sequence}",
                _data_str(package_id),
            )

    def _org_approved(self, org: str, cc: str, cd: ChaincodeDefinition) -> bool:
        stored = self._read_definition(
            lambda k: self.org_get(org, f"{cc}#{cd.sequence}/{k}"), cc
        )
        return stored is not None and stored.parameters_equal(cd) and stored.sequence == cd.sequence

    def check_commit_readiness(
        self, cc: str, cd: ChaincodeDefinition
    ) -> Dict[str, bool]:
        """CheckCommitReadiness (lifecycle.go:320): which orgs have
        approved exactly this definition at this sequence."""
        current = self.current_sequence(cc)
        if cd.sequence != current + 1:
            raise LifecycleError(
                f"requested sequence is {cd.sequence}, but new definition "
                f"must be sequence {current + 1}"
            )
        return {
            org: self._org_approved(org, cc, cd) for org in self.org_names
        }

    def commit_chaincode_definition(
        self, cc: str, cd: ChaincodeDefinition
    ) -> Dict[str, bool]:
        """CommitChaincodeDefinition (lifecycle.go:350)."""
        approvals = self.check_commit_readiness(cc, cd)
        if not self.approval_policy(approvals):
            raise LifecycleError(
                f"chaincode definition not agreed to by enough orgs: "
                f"{approvals}"
            )
        self._write_definition(self.public_put, cc, cd, _DATATYPE_DEFINITION)
        return approvals

    def current_sequence(self, cc: str) -> int:
        seq = _read_data(self.public_get(_field_key(cc, "Sequence")))
        return int(seq) if seq is not None else 0

    def query_chaincode_definition(self, cc: str) -> Optional[ChaincodeDefinition]:
        """QueryChaincodeDefinition (lifecycle.go:625)."""
        return self._read_definition(self.public_get, cc)

    # -- validation info for the dispatcher (endorsement_info.go) ----------

    def validation_info(self, cc: str) -> Optional[Tuple[str, bytes]]:
        """(validation_plugin, validation_parameter) for a committed
        chaincode, or None if undefined — what GetInfoForValidate needs
        (plugindispatcher/dispatcher.go:265)."""
        cd = self.query_chaincode_definition(cc)
        if cd is None:
            return None
        return cd.validation_plugin, cd.validation_parameter
