"""OpenSSL-backed P-256 ECDSA via the ``cryptography`` package.

This is the performance analog of the reference's SW BCCSP, whose Verify
rides Go's constant-time P-256 assembly (reference: bccsp/sw/ecdsa.go:41-57
-> Go crypto/ecdsa, ~10k verifies/s/core). The pure-Python module
``fabric_tpu.crypto.p256`` remains the *differential oracle*; this module is
the default host execution path (measured here: ~11k verifies/s, ~30k
signs/s on one core — ~2000x the oracle).

Semantics contract (kept bit-identical to the oracle):
- ``verify_digest`` implements Go crypto/ecdsa.Verify over (r, s) ints.  It
  does NOT apply the low-S rule; callers go through
  ``bccsp.parse_and_precheck`` first, exactly as with the oracle.
- ``sign_digest`` normalizes to low-S (bccsp/utils/ecdsa.go ToLowS).
- Out-of-range r/s and off-curve keys return False, never raise.

Key-object construction is cached: Fabric workloads verify thousands of
signatures from a small set of identities per block, so the
EllipticCurvePublicKey materialization (~10us) is paid once per (x, y).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# fastec IS the optional OpenSSL tier: the module must raise ImportError
# when `cryptography` is absent so the backend ladder (bccsp
# select_ec_backend) falls through to hostec — every importer guards it.
from cryptography.exceptions import InvalidSignature  # fablint: disable=module-import
from cryptography.hazmat.primitives import hashes  # fablint: disable=module-import
from cryptography.hazmat.primitives.asymmetric import ec  # fablint: disable=module-import
from cryptography.hazmat.primitives.asymmetric.utils import (  # fablint: disable=module-import
    Prehashed,
    decode_dss_signature,
    encode_dss_signature,
)

from fabric_tpu.common import p256

_CURVE = ec.SECP256R1()
_PREHASHED_SHA256 = ec.ECDSA(Prehashed(hashes.SHA256()))

# Bounded caches keyed by the integer key material.  Cleared wholesale when
# they exceed the cap — membership churn is tiny in practice (an org's worth
# of identities), the cap only guards pathological key-per-tx workloads.
_PUB_CACHE: Dict[Tuple[int, int], ec.EllipticCurvePublicKey] = {}
_PRIV_CACHE: Dict[int, ec.EllipticCurvePrivateKey] = {}
_CACHE_CAP = 8192


def _pub_key(x: int, y: int) -> Optional[ec.EllipticCurvePublicKey]:
    """Cached public-key object; None for an off-curve / out-of-range point."""
    key = _PUB_CACHE.get((x, y))
    if key is not None:
        return key
    try:
        key = ec.EllipticCurvePublicNumbers(x, y, _CURVE).public_key()
    except ValueError:
        return None
    if len(_PUB_CACHE) >= _CACHE_CAP:
        _PUB_CACHE.clear()
    _PUB_CACHE[(x, y)] = key
    return key


def _priv_key(d: int) -> ec.EllipticCurvePrivateKey:
    key = _PRIV_CACHE.get(d)
    if key is None:
        key = ec.derive_private_key(d, _CURVE)
        if len(_PRIV_CACHE) >= _CACHE_CAP:
            _PRIV_CACHE.clear()
        _PRIV_CACHE[d] = key
    return key


def verify_digest(pub: Tuple[int, int], digest: bytes, r: int, s: int) -> bool:
    """Go crypto/ecdsa.Verify semantics over a 32-byte SHA-256 digest.

    Differentially tested against the oracle ``p256.verify_digest``
    (tests/test_fastec.py).  Non-SHA-256-sized digests fall back to the
    oracle so the hashToInt truncation semantics stay exact.
    """
    if not (1 <= r < p256.N and 1 <= s < p256.N):
        return False
    if len(digest) != 32:
        return p256.verify_digest(pub, digest, r, s)
    key = _pub_key(pub[0], pub[1])
    if key is None:
        return False
    try:
        key.verify(encode_dss_signature(r, s), digest, _PREHASHED_SHA256)
        return True
    except InvalidSignature:
        return False


def sign_digest(priv: int, digest: bytes) -> Tuple[int, int]:
    """ECDSA sign, low-S normalized (reference signECDSA -> utils.ToLowS)."""
    if len(digest) != 32:
        return p256.sign_digest(priv, digest)
    sig = _priv_key(priv).sign(digest, _PREHASHED_SHA256)
    r, s = decode_dss_signature(sig)
    if s > p256.HALF_N:
        s = p256.N - s
    return r, s


def generate_keypair() -> p256.KeyPair:
    sk = ec.generate_private_key(_CURVE)
    nums = sk.private_numbers()
    pub = nums.public_numbers
    return p256.KeyPair(nums.private_value, (pub.x, pub.y))
