"""numpy limb-matrix batch FP256BN pairing engine (hostbn) — the Idemix
verify rung of the host ladder.

BENCH_r05 pins the pure-Python Idemix oracle (idemix/scheme.py
verify_signature) at ~1 s/signature — the generic-Fp12 Miller loop pays
an Fp12 inversion per line and the final exponentiation is a ~1020-bit
square-and-multiply of schoolbook Fp12 products.  This module ports the
PR 5 hostec_np playbook to the BN curve: the whole batch of signatures
rides ``(NPAIRS, k·lanes)`` uint64 pair-limb matrices (the SAME
radix-2^13 → paired-radix-2^26 compute form, Montgomery R = 2^286,
``common/limbparams`` constants, hostec_np's proven ``_mul_kernel`` /
``_sqr_kernel`` with the BN base-field modulus — the fabflow headroom
argument is per-limb-bound, not per-modulus, so the mechanized
2.8x-margin proof transfers unchanged; fabflow's limb tier covers this
file and holds it to the same contracts).

What makes the batch shape work:

- **Lane-shared Miller schedule**: the Idemix structure check
  ``Fexp(Ate(W, A') · Ate(g2, ABar)^-1).isunity`` fixes BOTH G2 points
  (the issuer key W, the generator) — only the G1 points vary per
  signature.  The entire G2 point chain therefore runs ON THE HOST once
  per issuer (host Fp12 ints, cached), emitting per-step line
  coefficient constants (A, B) with l(P) = A + B·px + py
  (common/fp256bn.line_coeffs, the same schedule ops/pairing_kernel
  ships to the device).  Every lane then executes the identical
  |6u+2|-bit doubling/addition sequence in lockstep: one Fp12
  squaring, one (or two) sparse line evaluations and Fp12 products per
  step, vectorized across lanes.
- **Fused tower ops**: an Fp12 value is a 12-row-stacked field batch —
  one bound-tracked ``_FE`` of width 12·lanes — and an Fp12 multiply is
  Karatsuba over Fp6 run as FROZEN linear maps (derived symbolically at
  import): one summed gather, ONE Montgomery kernel call of width
  54·lanes (18 Fp2 Karatsuba products), one summed-gather fold, one
  renormalizing multiply by one.  Squaring is the complex method over
  Fp6 (36 rows).  BOTH pairings of the check share one doubled-width
  batch (the loop schedule is a property of the curve), so each Miller
  step costs one squaring regardless of the pairing count.
- **Shared final exponentiation**: easy part via Frobenius + ONE Fp12
  norm-chain inverse whose single Fp inversion is a Blelloch tree
  batch inversion across lanes (hostec_np._invert_lanes — one Python
  ``pow`` per batch); hard part via the lane-shared fixed-exponent
  x-power chain: (p^4 - p^2 + 1)/r = λ0 + λ1·p + λ2·p^2 + p^3
  (Devegili–Scott–Dominguez, VERIFIED EXACTLY against the integer
  constants at import), needing three u-power chains (63 cyclotomic
  bits each) instead of the oracle's ~1020-bit ladder.  Conjugation
  inverts the unitary post-easy-part values, so negative λ terms are
  free.
- **Batched G1 MSM lanes**: the t1/t2/t3 commitment recomputations are
  per-signature multi-scalar multiplications over per-issuer bases.
  Jobs ride a (slots × jobs)-wide lane layout: lane-shared signed
  wNAF(5) windows against per-lane 16-entry tables (normalized with one
  tree inversion), Jacobian a=0 doubling (dbl-2007-bl) and hostec_np's
  mixed add, identity lanes as flags, adversarial P = ±Q collisions
  patched per lane through scalar host math, and the slot partial sums
  pairwise tree-reduced with the general Jacobian add.

Semantics are a bit-exactness contract with ``scheme.verify_signature``
(BASELINE config #3's mask discipline): the accept/reject set equals
the oracle's on every lane, including the adversarial flavors
(tampered scalars, wrong commitments, identity ABar, off-curve points
rejected at parse).  ``idemix/batch.py`` owns proto parsing, the
Fiat–Shamir transcript and the ladder routing; this module is pure
batched curve math.  numpy is optional: the module imports without it,
``bccsp.select_idemix_backend`` skips the rung with a logged warning,
and the ladder degrades to the scheme oracle.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.common import fp256bn as host
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.crypto import hostec_np as hnp
from fabric_tpu.crypto.hostec_np import (
    NPAIRS,
    PAIR_MASK,
    R_MONT,
    _FE,
    _Field,
    _ctx,
    _extract_windows,
    _invert_lanes,
    _signed_digits,
    ints_to_limbs13,
    limbs13_to_pairs,
    _pairs_to_int,
)

logger = must_get_logger("hostbn")

try:  # numpy is optional: the ladder skips this rung when it is absent
    import numpy as np

    HAVE_NUMPY = True
except ImportError:  # pragma: no cover - exercised via subprocess test
    np = None  # type: ignore[assignment]
    HAVE_NUMPY = False

P = host.P
R = host.R

G1Point = host.G1Point
G2Point = host.G2Point

# ---------------------------------------------------------------------------
# Final-exponentiation hard-part decomposition (checked, not trusted):
#   (p^4 - p^2 + 1)/r  ==  λ0 + λ1·p + λ2·p^2 + p^3          (exactly)
# with λ0 = -(36x^3 + 30x^2 + 18x + 2), λ1 = -(36x^3 + 18x^2 + 12x) + 1,
# λ2 = 6x^2 + 1 for the BN parameter x = u < 0
# (Devegili–Scott–Dominguez 2007).  The chain below only ever raises
# x-powers and conjugates (unitary inverse), so the computed VALUE is
# identical to the oracle's fp12_pow(s, _HARD_EXP) — same group element,
# canonical coordinates.
# ---------------------------------------------------------------------------

_X = host.U
_LAM0 = -36 * _X**3 - 30 * _X**2 - 18 * _X - 2
_LAM1 = -36 * _X**3 - 18 * _X**2 - 12 * _X + 1
_LAM2 = 6 * _X**2 + 1
if _LAM0 + _LAM1 * P + _LAM2 * P**2 + P**3 != host._HARD_EXP:
    raise ArithmeticError(
        "BN hard-part decomposition does not match (p^4-p^2+1)/r"
    )
_U_BITS = bin(abs(_X))[2:]
_SIX_U_TWO = 6 * host.U + 2
_N_BITS = bin(abs(_SIX_U_TWO))[3:]  # loop bits after the implicit MSB


# ---------------------------------------------------------------------------
# Row-stacked field batches: a _V is k logical Fp rows over `lanes`
# lanes, flattened to ONE bound-tracked _FE of width k·lanes so every
# tower op is a single fused Montgomery kernel call.
# ---------------------------------------------------------------------------


class _V:
    __slots__ = ("fe", "k", "lanes")

    def __init__(self, fe: _FE, k: int, lanes: int):
        self.fe = fe
        self.k = k
        self.lanes = lanes


def _vsplit3(v: _V) -> "np.ndarray":
    """(NPAIRS, k, lanes) view of the flattened limb matrix."""
    return v.fe.limbs.reshape(NPAIRS, v.k, v.lanes)


def _vgather(v: _V, idx) -> _V:
    out = np.ascontiguousarray(_vsplit3(v)[:, idx, :]).reshape(
        NPAIRS, len(idx) * v.lanes
    )
    return _V(_FE(out, v.fe.vb, v.fe.lb, v.fe.tb), len(idx), v.lanes)


def _vcat(*vs: _V) -> _V:
    lanes = vs[0].lanes
    mats = [_vsplit3(v) for v in vs]
    k = sum(v.k for v in vs)
    out = np.ascontiguousarray(np.concatenate(mats, axis=1)).reshape(
        NPAIRS, k * lanes
    )
    return _V(
        _FE(
            out,
            max(v.fe.vb for v in vs),
            max(v.fe.lb for v in vs),
            max(v.fe.tb for v in vs),
        ),
        k,
        lanes,
    )


def _vmul(field: _Field, x: _V, y: _V) -> _V:
    return _V(field.mul(x.fe, y.fe), x.k, x.lanes)


def _vadd(field: _Field, x: _V, y: _V) -> _V:
    return _V(field.add(x.fe, y.fe), x.k, x.lanes)


def _vsub(field: _Field, x: _V, y: _V) -> _V:
    return _V(field.sub(x.fe, y.fe), x.k, x.lanes)


def _vzero(lanes: int, k: int = 1) -> _V:
    return _V(
        _FE(np.zeros((NPAIRS, k * lanes), dtype=np.uint64), 1, 0), k, lanes
    )


def _vconst(field: _Field, values: Sequence[int], lanes: int) -> _V:
    """Host ints -> Montgomery-domain rows broadcast across lanes."""
    cols = np.concatenate(
        [field.ctx.to_limbs((v * R_MONT) % P) for v in values], axis=1
    )  # (NPAIRS, k)
    mat = np.ascontiguousarray(
        np.broadcast_to(cols[:, :, None], (NPAIRS, len(values), lanes))
    ).reshape(NPAIRS, len(values) * lanes)
    return _V(_FE(mat, 1, PAIR_MASK), len(values), lanes)


def _vselect_lanes(field: _Field, cond, x: _V, y: _V) -> _V:
    """Per-LANE select broadcast over the k rows (cond: (lanes,) bool)."""
    c = np.broadcast_to(cond, (x.k, x.lanes)).reshape(x.k * x.lanes)
    return _V(field.select(c, x.fe, y.fe), x.k, x.lanes)


# ---------------------------------------------------------------------------
# Fp12 tower on 12-row batches (row order [c0.re, c0.im, ..., c5.im],
# the ops/fp12.py layout; index tables copied from there)
# ---------------------------------------------------------------------------

if HAVE_NUMPY:
    _RE_IDX = np.arange(0, 12, 2)
    _IM_IDX = np.arange(1, 12, 2)
    _CONJ_NEG = np.array([2, 3, 6, 7, 10, 11], dtype=np.intp)
    # interleave separate (re..., im...) stacks back to [re0, im0, ...]
    _INTERLEAVE6 = np.array(
        [0, 6, 1, 7, 2, 8, 3, 9, 4, 10, 5, 11], dtype=np.intp
    )


def _fp12_one(field: _Field, lanes: int) -> _V:
    return _vconst(field, [1] + [0] * 11, lanes)


# --- static linear maps for the tower multiply/square -------------------
#
# An Fp12 product over the Fp6 Karatsuba tower (Fp12 = Fp6[w]/(w^2 − v),
# v = w^2, Fp6 = Fp2[v]/(v^3 − xi), Fp2 Karatsuba per product) is, end
# to end, ONE Montgomery kernel call between two operand stacks that
# are integer-linear in the input rows, followed by an integer-linear
# fold of the product rows.  The maps are derived SYMBOLICALLY below by
# running the textbook tower formulas over coefficient vectors — no
# hand-derived index tables to get wrong — then frozen into padded
# gather-and-sum index matrices (runtime: two summed gathers, one
# kernel, one summed-gather fold, one renormalizing multiply by one).


def _lin_add(a: dict, b: dict) -> dict:
    out = dict(a)
    for k, c in b.items():
        out[k] = out.get(k, 0) + c
        if out[k] == 0:
            del out[k]
    return out


def _lin_neg(a: dict) -> dict:
    return {k: -c for k, c in a.items()}


def _lin_sub(a: dict, b: dict) -> dict:
    return _lin_add(a, _lin_neg(b))


def _sym_rows(tag: str):
    """12 symbolic Fp rows as 6 Fp2 coefficient pairs."""
    return [
        ({(tag, 2 * j): 1}, {(tag, 2 * j + 1): 1}) for j in range(6)
    ]


def _sym_fp2_add(x, y):
    return (_lin_add(x[0], y[0]), _lin_add(x[1], y[1]))


def _sym_fp2_sub(x, y):
    return (_lin_sub(x[0], y[0]), _lin_sub(x[1], y[1]))


def _sym_fp2_xi(x):
    return (_lin_sub(x[0], x[1]), _lin_add(x[0], x[1]))


def _sym_fp6_add(p, q):
    return [_sym_fp2_add(a, b) for a, b in zip(p, q)]


def _sym_fp6_sub(p, q):
    return [_sym_fp2_sub(a, b) for a, b in zip(p, q)]


def _sym_mul_by_v(b):
    return [_sym_fp2_xi(b[2]), b[0], b[1]]


def _sym_ops6(p):
    return [
        p[0], p[1], p[2],
        _sym_fp2_add(p[0], p[1]),
        _sym_fp2_add(p[0], p[2]),
        _sym_fp2_add(p[1], p[2]),
    ]


def _sym_products(lhs_ops, rhs_ops):
    """Karatsuba product rows: per Fp2 pair t, rows (3t, 3t+1, 3t+2) =
    (re·re, im·im, (re+im)(re+im)); the Fp2 value folds back as
    re = p0 − p1, im = p2 − p0 − p1."""
    lrows, rrows, vals = [], [], []
    for t, (u, v) in enumerate(zip(lhs_ops, rhs_ops)):
        lrows += [u[0], u[1], _lin_add(u[0], u[1])]
        rrows += [v[0], v[1], _lin_add(v[0], v[1])]
        p0, p1, p2 = (
            {("p", 3 * t): 1},
            {("p", 3 * t + 1): 1},
            {("p", 3 * t + 2): 1},
        )
        vals.append(
            (_lin_sub(p0, p1), _lin_sub(p2, _lin_add(p0, p1)))
        )
    return lrows, rrows, vals


def _sym_fp6_fold(prods):
    """Karatsuba-3 combination of one Fp6 product's 6 Fp2 values
    [d0, d1, d2, m01, m02, m12]."""
    d0, d1, d2, m01, m02, m12 = prods
    r0 = _sym_fp2_add(
        d0,
        _sym_fp2_xi(_sym_fp2_sub(_sym_fp2_sub(m12, d1), d2)),
    )
    r1 = _sym_fp2_add(
        _sym_fp2_sub(_sym_fp2_sub(m01, d0), d1), _sym_fp2_xi(d2)
    )
    r2 = _sym_fp2_sub(_sym_fp2_add(m02, d1), _sym_fp2_add(d0, d2))
    return [r0, r1, r2]


def _sym_assemble(lo, hi):
    """(lo, hi) Fp6 halves -> 12 output row vectors [c0.re, c0.im, ...]
    with c0, c2, c4 = lo and c1, c3, c5 = hi."""
    out = []
    for j in range(3):
        out += [lo[j][0], lo[j][1], hi[j][0], hi[j][1]]
    # out currently [c0, c1, c2, c3, c4, c5] pairs in (lo0, hi0, ...)
    return out


def _freeze(rows, tag, zero_idx):
    """Row vectors over ('tag', i) symbols -> (n, T) padded gather
    index matrix; |coeff| c repeats the index c times; `zero_idx` is
    the implicit zero row appended by _gsum.  Returns
    (pos_idx, neg_idx_or_None, tpos, tneg)."""
    pos, neg = [], []
    for vec in rows:
        p, m = [], []
        for (t, i), c in sorted(vec.items()):
            if t != tag:
                raise AssertionError(f"foreign symbol {t} in {tag} map")
            (p if c > 0 else m).extend([i] * abs(c))
        pos.append(p)
        neg.append(m)
    tpos = max(len(p) for p in pos)
    tneg = max(len(m) for m in neg)

    def mat(lists, t):
        out = np.full((len(lists), t), zero_idx, dtype=np.intp)
        for r, l in enumerate(lists):
            out[r, : len(l)] = l
        return out

    return (
        mat(pos, max(tpos, 1)),
        mat(neg, tneg) if tneg else None,
        max(tpos, 1),
        tneg,
    )


def _build_tower_maps():
    x6 = _sym_rows("x")
    y6 = _sym_rows("y")
    xa, xb = [x6[0], x6[2], x6[4]], [x6[1], x6[3], x6[5]]
    ya, yb = [y6[0], y6[2], y6[4]], [y6[1], y6[3], y6[5]]

    # multiply: A = xa·ya, B = xb·yb, S = (xa+xb)(ya+yb);
    # lo = A + v·B, hi = S − A − B
    lhs = (
        _sym_ops6(xa) + _sym_ops6(xb) + _sym_ops6(_sym_fp6_add(xa, xb))
    )
    rhs = (
        _sym_ops6(ya) + _sym_ops6(yb) + _sym_ops6(_sym_fp6_add(ya, yb))
    )
    lrows, rrows, vals = _sym_products(lhs, rhs)
    fa = _sym_fp6_fold(vals[0:6])
    fb = _sym_fp6_fold(vals[6:12])
    fs = _sym_fp6_fold(vals[12:18])
    lo = _sym_fp6_add(fa, _sym_mul_by_v(fb))
    hi = _sym_fp6_sub(_sym_fp6_sub(fs, fa), fb)
    mul_maps = (
        _freeze(lrows, "x", 12),
        _freeze(rrows, "y", 12),
        _freeze(_sym_assemble(lo, hi), "p", 54),
        54,
    )

    # square: t = xa·xb, u = (xa+xb)(xa + v·xb);
    # lo = u − t − v·t, hi = 2t
    lhs = _sym_ops6(xa) + _sym_ops6(_sym_fp6_add(xa, xb))
    rhs = _sym_ops6(xb) + _sym_ops6(
        _sym_fp6_add(xa, _sym_mul_by_v(xb))
    )
    lrows, rrows, vals = _sym_products(lhs, rhs)
    ft = _sym_fp6_fold(vals[0:6])
    fu = _sym_fp6_fold(vals[6:12])
    lo = _sym_fp6_sub(_sym_fp6_sub(fu, ft), _sym_mul_by_v(ft))
    hi = _sym_fp6_add(ft, ft)
    sqr_maps = (
        _freeze(lrows, "x", 12),
        _freeze(rrows, "x", 12),
        _freeze(_sym_assemble(lo, hi), "p", 36),
        36,
    )
    return mul_maps, sqr_maps


if HAVE_NUMPY:
    _MUL_MAPS, _SQR_MAPS = _build_tower_maps()


def _gsum(field: _Field, v: _V, maps) -> _V:
    """Padded gather-and-sum evaluation of a frozen linear map: one
    fancy-index over (rows + implicit zero row), one axis sum, and at
    most one borrow-free subtract for the negative half.  Bounds scale
    by the term counts (inputs are canonical-or-shallow: sums of <= 8
    rows of lb <= ~2^30 stay far inside uint64; the kernels carry their
    operands back to the proven contracts)."""
    pos_idx, neg_idx, tpos, tneg = maps
    m = _vsplit3(v)
    z = np.zeros((NPAIRS, 1, v.lanes), dtype=np.uint64)
    me = np.concatenate([m, z], axis=1)
    out_k = pos_idx.shape[0]

    def summed(idx, t):
        s = me[:, idx, :].sum(axis=2)
        return _FE(
            np.ascontiguousarray(s).reshape(NPAIRS, out_k * v.lanes),
            v.fe.vb * t,
            v.fe.lb * t,
            v.fe.tb * t,
        )

    fe = summed(pos_idx, tpos)
    if neg_idx is not None:
        fe = field.sub(fe, summed(neg_idx, tneg))
    return _V(fe, out_k, v.lanes)


_ONE_CACHE: dict = {}


def _renorm12(field: _Field, v: _V) -> _V:
    """Value-bound renormalization (multiply by the domain's one, with
    the broadcast constant cached per width): the fold chain's
    borrow-free k·m bounds compound ~2x per level, and a second such
    value entering a multiply would breach the kernels' 2^30 input
    contract."""
    w = v.fe.limbs.shape[1]
    one = _ONE_CACHE.get(w)
    if one is None:
        one = _FE(
            np.ascontiguousarray(
                np.broadcast_to(
                    field.ctx.to_limbs(field.ctx.one_mont_int), (NPAIRS, w)
                )
            ),
            1,
            PAIR_MASK,
        )
        if len(_ONE_CACHE) > 32:
            _ONE_CACHE.clear()
        _ONE_CACHE[w] = one
    return _V(field.mul(v.fe, one), v.k, v.lanes)


def _fp12_mul(field: _Field, x: _V, y: _V) -> _V:
    """Karatsuba over Fp6: two summed gathers, ONE 54-row Montgomery
    kernel, one summed-gather fold, one renormalization."""
    l, r, o, _n = _MUL_MAPS
    p = _V(
        field.mul(_gsum(field, x, l).fe, _gsum(field, y, r).fe),
        54,
        x.lanes,
    )
    return _renorm12(field, _gsum(field, p, o))


def _fp12_sqr(field: _Field, x: _V) -> _V:
    """Complex squaring over Fp6 (t = xa·xb; lo = (xa+xb)(xa+v·xb) − t
    − v·t; hi = 2t): ONE 36-row kernel."""
    l, r, o, _n = _SQR_MAPS
    p = _V(
        field.mul(_gsum(field, x, l).fe, _gsum(field, x, r).fe),
        36,
        x.lanes,
    )
    return _renorm12(field, _gsum(field, p, o))


def _fp12_conj(field: _Field, x: _V) -> _V:
    neg = _vsub(field, _vzero(x.lanes, len(_CONJ_NEG)), _vgather(x, _CONJ_NEG))
    idx = np.arange(12)
    for pos, r in enumerate(_CONJ_NEG):
        idx[r] = 12 + pos
    return _vgather(_vcat(x, neg), idx)


def _fp2_mul_rows(field: _Field, x: _V, y: _V) -> _V:
    """K parallel Fp2 products on (2K)-row [re, im] batches."""
    k = x.k // 2
    re_x = _vgather(x, np.arange(0, x.k, 2))
    im_x = _vgather(x, np.arange(1, x.k, 2))
    re_y = _vgather(y, np.arange(0, y.k, 2))
    im_y = _vgather(y, np.arange(1, y.k, 2))
    p = _vmul(
        field,
        _vcat(re_x, im_x, re_x, im_x),
        _vcat(re_y, im_y, im_y, re_y),
    )
    a = _vgather(p, np.arange(0, k))
    b = _vgather(p, np.arange(k, 2 * k))
    c = _vgather(p, np.arange(2 * k, 3 * k))
    d = _vgather(p, np.arange(3 * k, 4 * k))
    out_re = _vsub(field, a, b)
    out_im = _vadd(field, c, d)
    inter = np.empty(2 * k, dtype=np.intp)
    inter[0::2] = np.arange(k)
    inter[1::2] = np.arange(k, 2 * k)
    return _vgather(_vcat(out_re, out_im), inter)


def _fp2_mul_xi(field: _Field, x: _V) -> _V:
    """K parallel multiplies by xi = 1 + i: (re − im, re + im)."""
    k = x.k // 2
    re = _vgather(x, np.arange(0, x.k, 2))
    im = _vgather(x, np.arange(1, x.k, 2))
    out_re = _vsub(field, re, im)
    out_im = _vadd(field, re, im)
    inter = np.empty(2 * k, dtype=np.intp)
    inter[0::2] = np.arange(k)
    inter[1::2] = np.arange(k, 2 * k)
    return _vgather(_vcat(out_re, out_im), inter)


def _fp12_inv(field: _Field, x: _V) -> _V:
    """conj(x)·(x·conj(x))^-1: norm chain down to ONE Fp inverse, run as
    a lane tree inversion (host fp12_inv / _fp6_inv mirrored row-wise).
    Zero inputs come back zero (the oracle's pow(0) behavior), so
    adversarial degenerate lanes keep bit-exact False verdicts."""
    xc = _fp12_conj(field, x)
    ac = _fp12_mul(field, x, xc)
    a0 = _vgather(ac, np.array([0, 1]))
    a1 = _vgather(ac, np.array([4, 5]))
    a2 = _vgather(ac, np.array([8, 9]))
    sq = _fp2_mul_rows(field, _vcat(a0, a2, a1), _vcat(a0, a2, a1))
    a0sq = _vgather(sq, np.array([0, 1]))
    a2sq = _vgather(sq, np.array([2, 3]))
    a1sq = _vgather(sq, np.array([4, 5]))
    cross = _fp2_mul_rows(field, _vcat(a1, a0, a0), _vcat(a2, a1, a2))
    a1a2 = _vgather(cross, np.array([0, 1]))
    a0a1 = _vgather(cross, np.array([2, 3]))
    a0a2 = _vgather(cross, np.array([4, 5]))
    c0 = _vsub(field, a0sq, _fp2_mul_xi(field, a1a2))
    c1 = _vsub(field, _fp2_mul_xi(field, a2sq), a0a1)
    c2 = _vsub(field, a1sq, a0a2)
    tc = _fp2_mul_rows(field, _vcat(a2, a1, a0), _vcat(c1, c2, c0))
    s = _vadd(
        field,
        _vgather(tc, np.array([0, 1])),
        _vgather(tc, np.array([2, 3])),
    )
    t = _vadd(field, _fp2_mul_xi(field, s), _vgather(tc, np.array([4, 5])))
    # Fp2 inverse of t: conj(t) / (re^2 + im^2); the Fp inversion is the
    # tree (zero lanes -> zero, matching pow(0, p-2) = 0)
    tsq = _vmul(field, t, t)
    norm = _vadd(
        field, _vgather(tsq, np.array([0])), _vgather(tsq, np.array([1]))
    )
    ninv = _V(_invert_lanes(field, norm.fe), 1, norm.lanes)
    t_re = _vgather(t, np.array([0]))
    t_im_neg = _vsub(field, _vzero(t.lanes, 1), _vgather(t, np.array([1])))
    ti = _vmul(field, _vcat(t_re, t_im_neg), _vcat(ninv, ninv))
    inv6 = _fp2_mul_rows(field, _vcat(c0, c1, c2), _vcat(ti, ti, ti))
    z2 = _vzero(x.lanes, 2)
    inv12 = _vcat(
        _vgather(inv6, np.array([0, 1])),
        z2,
        _vgather(inv6, np.array([2, 3])),
        z2,
        _vgather(inv6, np.array([4, 5])),
        z2,
    )
    return _fp12_mul(field, xc, inv12)


_GAMMA_CACHE: dict = {}


def _fp12_frob(field: _Field, x: _V, n: int) -> _V:
    """x -> x^(p^n): conjugate Fp2 coefficients n%2 times, multiply
    coefficient k by gamma_{n,k} (host fp12_frobenius mirrored)."""
    if n % 2 == 1:
        neg = _vsub(field, _vzero(x.lanes, 6), _vgather(x, _IM_IDX))
        idx = np.arange(12)
        for pos, r in enumerate(_IM_IDX):
            idx[r] = 12 + pos
        x = _vgather(_vcat(x, neg), idx)
    key = n % 12
    gvals = _GAMMA_CACHE.get(key)
    if gvals is None:
        gvals = []
        for k in range(6):
            g = host._FROB_GAMMA[key][k]
            gvals.extend([g[0], g[1]])
        _GAMMA_CACHE[key] = gvals
    g = _vconst(field, gvals, x.lanes)
    re = _vgather(x, _RE_IDX)
    im = _vgather(x, _IM_IDX)
    gre = _vgather(g, _RE_IDX)
    gim = _vgather(g, _IM_IDX)
    p = _vmul(field, _vcat(re, im, re, im), _vcat(gre, gim, gim, gre))
    a = _vgather(p, np.arange(0, 6))
    b = _vgather(p, np.arange(6, 12))
    c = _vgather(p, np.arange(12, 18))
    d = _vgather(p, np.arange(18, 24))
    return _vgather(
        _vcat(_vsub(field, a, b), _vadd(field, c, d)), _INTERLEAVE6
    )


def _fp12_is_one(field: _Field, x: _V) -> "np.ndarray":
    """Per-lane x == 1 (exact, mod p)."""
    d = _vsub(field, x, _fp12_one(field, x.lanes))
    z = field.is_zero_mod(d.fe)
    return z.reshape(12, x.lanes).all(axis=0)


# ---------------------------------------------------------------------------
# Per-issuer Miller schedules (host Fp12 ints, cached; the numpy pack
# happens once per schedule)
# ---------------------------------------------------------------------------


class _Schedule:
    """Line-coefficient schedule of ONE fixed G2 point: per |6u+2| bit a
    doubling line, plus an addition line on '1' bits, plus the two
    frobenius correction lines — host fp256bn ints."""

    def __init__(self, q: G2Point):
        qe = host._untwist(q)
        t = qe
        self.dbl: List[Tuple[host.Fp12, host.Fp12]] = []
        self.add: List[Optional[Tuple[host.Fp12, host.Fp12]]] = []
        for bit in _N_BITS:
            self.dbl.append(host.line_coeffs(t, t))
            t = host._e12_add(t, t)
            if bit == "1":
                self.add.append(host.line_coeffs(t, qe))
                t = host._e12_add(t, qe)
            else:
                self.add.append(None)
        # u < 0: conjugate then the two correction lines (host miller_loop)
        t = (t[0], host.fp12_neg(t[1]))
        q1 = (host.fp12_frobenius(qe[0], 1), host.fp12_frobenius(qe[1], 1))
        q2 = (
            host.fp12_frobenius(qe[0], 2),
            host.fp12_neg(host.fp12_frobenius(qe[1], 2)),
        )
        self.corr: List[Tuple[host.Fp12, host.Fp12]] = []
        self.corr.append(host.line_coeffs(t, q1))
        t = host._e12_add(t, q1)
        self.corr.append(host.line_coeffs(t, q2))


def _fp12_vals(v: host.Fp12) -> List[int]:
    out: List[int] = []
    for c in v:
        out.extend([c[0], c[1]])
    return out


class _PackedSchedule:
    """The fused two-pairing constants: per step, the (A, B) coefficient
    columns of the issuer-W half and the generator half side by side as
    (NPAIRS, 12, 2) Montgomery uint64 arrays."""

    def __init__(self, w: G2Point):
        sched_w = _Schedule(w)
        sched_g = _g_schedule()
        ctx = _ctx(P)

        def cols2(vw: host.Fp12, vg: host.Fp12) -> "np.ndarray":
            vals = _fp12_vals(vw) + _fp12_vals(vg)
            mat = np.concatenate(
                [ctx.to_limbs((v * R_MONT) % P) for v in vals], axis=1
            )  # (NPAIRS, 24): first 12 = W half, last 12 = G half
            return np.ascontiguousarray(
                mat.reshape(NPAIRS, 2, 12).transpose(0, 2, 1)
            )  # (NPAIRS, 12, 2)

        self.steps: List[Tuple["np.ndarray", "np.ndarray", Optional[Tuple]]] = []
        for (wa, wb), (ga, gb), add_w, add_g in zip(
            sched_w.dbl, sched_g.dbl, sched_w.add, sched_g.add
        ):
            add_cols = None
            if add_w is not None:
                add_cols = (cols2(add_w[0], add_g[0]), cols2(add_w[1], add_g[1]))
            self.steps.append((cols2(wa, ga), cols2(wb, gb), add_cols))
        self.corr = [
            (cols2(cw[0], cg[0]), cols2(cw[1], cg[1]))
            for cw, cg in zip(sched_w.corr, sched_g.corr)
        ]


_G_SCHEDULE: Optional[_Schedule] = None
# RLock: _PackedSchedule.__init__ (built under the lock in
# _schedule_for) itself calls _g_schedule()
_SCHED_LOCK = threading.RLock()
_SCHED_CACHE: dict = {}
_SCHED_CACHE_MAX = 8


def _g_schedule() -> _Schedule:
    global _G_SCHEDULE
    if _G_SCHEDULE is None:
        with _SCHED_LOCK:
            if _G_SCHEDULE is None:
                _G_SCHEDULE = _Schedule(host.G2_GEN)
    return _G_SCHEDULE


def _schedule_for(w: G2Point) -> _PackedSchedule:
    """Cached per-issuer packed schedule (~1s host Fp12 build each)."""
    key = host.g2_to_bytes(w)
    sched = _SCHED_CACHE.get(key)
    if sched is None:
        with _SCHED_LOCK:
            sched = _SCHED_CACHE.get(key)
            if sched is None:
                sched = _PackedSchedule(w)
                if len(_SCHED_CACHE) >= _SCHED_CACHE_MAX:
                    _SCHED_CACHE.pop(next(iter(_SCHED_CACHE)))
                _SCHED_CACHE[key] = sched
    return sched


def warm_schedules(w: Optional[G2Point] = None) -> None:
    """Build the generator (and optionally one issuer) schedule now."""
    _g_schedule()
    if w is not None:
        _schedule_for(w)


# ---------------------------------------------------------------------------
# Batched pairing structure check
# ---------------------------------------------------------------------------


def _line_eval(
    field: _Field,
    a_cols: "np.ndarray",
    b_cols: "np.ndarray",
    px: _V,
    py_rows: _V,
    lanes: int,
) -> _V:
    """A + B·px + py as a 12-row batch.  a_cols/b_cols are
    (NPAIRS, 12, 2) per-half constants; px is the per-lane G1 x tiled to
    12 rows; py_rows holds py at row 0 (the c0.re coefficient of the
    embedded G1 y) and zeros elsewhere."""
    half = lanes // 2

    def bcast(cols: "np.ndarray") -> _V:
        mat = np.ascontiguousarray(
            np.broadcast_to(
                cols[:, :, :, None], (NPAIRS, 12, 2, half)
            )
        ).reshape(NPAIRS, 12 * lanes)
        return _V(_FE(mat, 1, PAIR_MASK), 12, lanes)

    bp = _vmul(field, bcast(b_cols), px)
    return _vadd(field, _vadd(field, bcast(a_cols), bp), py_rows)


def _mont_lane_fe(field: _Field, vals: Sequence[int]) -> _FE:
    """Plain ints -> Montgomery-domain canonical (NPAIRS, n) _FE."""
    pairs = limbs13_to_pairs(ints_to_limbs13([v % P for v in vals]))
    r2 = field.fe(
        np.ascontiguousarray(
            np.broadcast_to(field.ctx.r2, (NPAIRS, len(vals)))
        ),
        1,
        PAIR_MASK,
    )
    return field.mul(_FE(pairs, 1, PAIR_MASK), r2)


def pairing_check_batch(
    w: G2Point,
    pairs: Sequence[Optional[Tuple[G1Point, Optional[G1Point]]]],
) -> List[bool]:
    """Per-lane Fexp(Ate(W, A')·Ate(g2, ABar)^-1) == 1 — the Idemix BBS+
    structure check (idemix/signature.go:288-296 semantics), both Miller
    loops fused into one doubled-width lane batch.  ``pairs[i]`` is
    (a_prime, a_bar) with a_bar possibly None (identity: that pairing
    is ONE, as the oracle's miller_loop returns for P = None); a None
    entry marks an already-invalid lane (False, dummy math)."""
    n = len(pairs)
    if n == 0:
        return []
    if not HAVE_NUMPY:
        raise RuntimeError("hostbn requires numpy")
    sched = _schedule_for(w)
    field = _Field(_ctx(P))
    gx, gy = host.G1_GEN
    ok = np.zeros(n, dtype=bool)
    abar_one = np.zeros(n, dtype=bool)
    p1 = [(gx, gy)] * n
    p2 = [(gx, gy)] * n
    for i, pair in enumerate(pairs):
        if pair is None or pair[0] is None:
            continue
        ok[i] = True
        p1[i] = pair[0]
        if pair[1] is None:
            abar_one[i] = True
        else:
            p2[i] = pair[1]

    lanes = 2 * n  # [A' half | ABar half]
    px = _mont_lane_fe(field, [p[0] for p in p1] + [p[0] for p in p2])
    py = _mont_lane_fe(field, [p[1] for p in p1] + [p[1] for p in p2])
    px12 = _V(
        _FE(
            np.ascontiguousarray(
                np.broadcast_to(
                    px.limbs[:, None, :], (NPAIRS, 12, lanes)
                )
            ).reshape(NPAIRS, 12 * lanes),
            px.vb,
            px.lb,
            px.tb,
        ),
        12,
        lanes,
    )
    py_mat = np.zeros((NPAIRS, 12, lanes), dtype=np.uint64)
    py_mat[:, 0, :] = py.limbs
    py_rows = _V(
        _FE(py_mat.reshape(NPAIRS, 12 * lanes), py.vb, py.lb, py.tb),
        12,
        lanes,
    )

    f = _fp12_one(field, lanes)
    for a_cols, b_cols, add_cols in sched.steps:
        f = _fp12_mul(
            field,
            _fp12_sqr(field, f),
            _line_eval(field, a_cols, b_cols, px12, py_rows, lanes),
        )
        if add_cols is not None:
            f = _fp12_mul(
                field,
                f,
                _line_eval(
                    field, add_cols[0], add_cols[1], px12, py_rows, lanes
                ),
            )
    f = _fp12_conj(field, f)  # u < 0
    for a_cols, b_cols in sched.corr:
        f = _fp12_mul(
            field, f, _line_eval(field, a_cols, b_cols, px12, py_rows, lanes)
        )

    # split halves: f1 = Miller(W, A'), f2 = Miller(g2, ABar)
    fm = _vsplit3(f).reshape(NPAIRS, 12, 2, n)
    f1 = _V(
        _FE(
            np.ascontiguousarray(fm[:, :, 0, :]).reshape(NPAIRS, 12 * n),
            f.fe.vb,
            f.fe.lb,
            f.fe.tb,
        ),
        12,
        n,
    )
    f2 = _V(
        _FE(
            np.ascontiguousarray(fm[:, :, 1, :]).reshape(NPAIRS, 12 * n),
            f.fe.vb,
            f.fe.lb,
            f.fe.tb,
        ),
        12,
        n,
    )
    f2 = _vselect_lanes(field, abar_one, _fp12_one(field, n), f2)

    m = _fp12_mul(field, f1, _fp12_inv(field, f2))
    return [
        bool(v) for v in (_final_exp_is_one(field, m) & ok)
    ]


def _pow_u(field: _Field, s: _V) -> _V:
    """s^|u| by the fixed 63-bit MSB chain (lane-shared)."""
    out = s
    for bit in _U_BITS[1:]:
        out = _fp12_sqr(field, out)
        if bit == "1":
            out = _fp12_mul(field, out, s)
    return out


def _final_exp_is_one(field: _Field, m: _V) -> "np.ndarray":
    """Per-lane Fexp(m) == 1: easy part op-for-op with the oracle, hard
    part via the verified λ x-power chain (same value as fp12_pow by the
    exact decomposition — conj inverts the unitary intermediates)."""
    s = _fp12_mul(field, _fp12_conj(field, m), _fp12_inv(field, m))
    s = _fp12_mul(field, _fp12_frob(field, s, 2), s)  # ^(p^2 + 1)
    # x-powers (x = u < 0: each |u|-power is conjugated)
    sx = _fp12_conj(field, _pow_u(field, s))
    sx2 = _fp12_conj(field, _pow_u(field, sx))
    sx3 = _fp12_conj(field, _pow_u(field, sx2))
    x2s = _fp12_sqr(field, sx)  # sx^2
    c3 = _fp12_mul(field, _fp12_sqr(field, sx2), sx2)  # sx2^3
    t = _fp12_sqr(field, sx3)
    s6 = _fp12_mul(field, _fp12_sqr(field, t), t)  # sx3^6
    a3 = _fp12_mul(field, _fp12_mul(field, s6, c3), x2s)
    t = _fp12_sqr(field, a3)
    big_a = _fp12_mul(field, _fp12_sqr(field, t), t)  # a3^6 = s^(36x^3+18x^2+12x)
    big_b = _fp12_mul(
        field,
        _fp12_mul(
            field,
            _fp12_sqr(field, _fp12_sqr(field, c3)),  # sx2^12
            _fp12_mul(field, _fp12_sqr(field, x2s), x2s),  # sx^6
        ),
        _fp12_sqr(field, s),  # s^2
    )  # s^(12x^2 + 6x + 2)
    y_l1 = _fp12_mul(field, _fp12_conj(field, big_a), s)
    y_l0 = _fp12_mul(
        field, _fp12_conj(field, big_a), _fp12_conj(field, big_b)
    )
    y_l2 = _fp12_mul(field, _fp12_sqr(field, c3), s)  # sx2^6 · s
    out = _fp12_mul(
        field,
        _fp12_mul(
            field,
            _fp12_mul(field, y_l0, _fp12_frob(field, y_l1, 1)),
            _fp12_frob(field, y_l2, 2),
        ),
        _fp12_frob(field, s, 3),
    )
    return _fp12_is_one(field, out)


# ---------------------------------------------------------------------------
# Batched G1 multi-scalar multiplication
# ---------------------------------------------------------------------------

Jac = Tuple[_FE, _FE, _FE]


def _fe_stack(*fes: _FE) -> _FE:
    """Side-by-side lane concat (ONE kernel call covers all parts)."""
    return _FE(
        np.concatenate([fe.limbs for fe in fes], axis=1),
        max(fe.vb for fe in fes),
        max(fe.lb for fe in fes),
        max(fe.tb for fe in fes),
    )


def _fe_split(fe: _FE, n: int) -> List[_FE]:
    w = fe.limbs.shape[1] // n
    return [
        _FE(
            np.ascontiguousarray(fe.limbs[:, i * w : (i + 1) * w]),
            fe.vb,
            fe.lb,
            fe.tb,
        )
        for i in range(n)
    ]


def _dbl_vec(field: _Field, X: _FE, Y: _FE, Z: _FE) -> Jac:
    """Jacobian doubling for a = 0 (dbl-2007-bl, 2M + 5S), squarings
    and multiplies stacked pairwise so the whole law is 4 kernel calls.
    Identity lanes (Z ≡ 0) stay identity: Z3 = 2·Y·Z ≡ 0."""
    A, B = _fe_split(field.sqr(_fe_stack(X, Y)), 2)
    C, t = _fe_split(field.sqr(_fe_stack(B, field.add(X, B))), 2)
    D = field.scale(field.sub(field.sub(t, A), C), 2)
    E = field.scale(A, 3)
    F = field.sqr(E)
    X3 = field.sub(F, field.scale(D, 2))
    ED, YZ = _fe_split(
        field.mul(_fe_stack(E, Y), _fe_stack(field.sub(D, X3), Z)), 2
    )
    Y3 = field.sub(ED, field.scale(C, 8))
    Z3 = field.scale(YZ, 2)
    return X3, Y3, Z3


def _madd_vec(
    field: _Field, X: _FE, Y: _FE, Z: _FE, x2: _FE, y2: _FE
) -> Tuple[_FE, _FE, _FE, "np.ndarray"]:
    """Mixed Jacobian+affine add (hostec_np._madd_vec's 8M + 3S
    formulas, restacked into 6 kernel calls).  `exceptional` marks
    Z3 ≡ 0 lanes (P = infinity, P = ±Q) for the caller's scalar patch."""
    ZZ = field.sqr(Z)
    U2, ZZZ = _fe_split(
        field.mul(_fe_stack(x2, Z), _fe_stack(ZZ, ZZ)), 2
    )
    S2 = field.mul(y2, ZZZ)
    H = field.carried(field.sub(U2, X))
    Rr = field.sub(S2, Y)
    HH, RR = _fe_split(field.sqr(_fe_stack(H, field.carried(Rr))), 2)
    HHH, V, Z3 = _fe_split(
        field.mul(_fe_stack(H, X, Z), _fe_stack(HH, HH, H)), 3
    )
    X3 = field.sub(field.sub(RR, HHH), field.add(V, V))
    RV, YH = _fe_split(
        field.mul(
            _fe_stack(Rr, Y), _fe_stack(field.sub(V, X3), HHH)
        ),
        2,
    )
    Y3 = field.sub(RV, YH)
    return X3, Y3, Z3, field.is_zero_mod(Z3)


_select_jac = hnp._select_jac


def _jac_to_affine_int(field: _Field, fes: Sequence[_FE], lane: int):
    """Decode one lane's (X, Y, Z) to an affine host point (None for
    infinity) — scalar patch paths only."""
    m = field.ctx.m
    rinv = field.ctx.rinv
    X, Y, Z = ((_pairs_to_int(fe.limbs[:, lane]) * rinv) % m for fe in fes)
    if Z == 0:
        return None
    zi = pow(Z, -1, m)
    zi2 = zi * zi % m
    return (X * zi2 % m, Y * zi2 * zi % m)


def _write_lane(fe: _FE, lane: int, value: int) -> None:
    fe.limbs[:, lane] = _ctx(P).to_limbs((value * R_MONT) % P)[:, 0]


def _patch_exc(
    field: _Field,
    flag: "np.ndarray",
    jac: Jac,
    X3: _FE,
    Y3: _FE,
    Z3: _FE,
    ax: _FE,
    ay: _FE,
    inf_out: Optional["np.ndarray"] = None,
) -> Jac:
    """Recompute flagged P = ±Q lanes through scalar host math
    (adversarially reachable, never hot) — the BN analog of
    hostec_np._patch_exceptional."""
    if not bool(flag.any()):
        return X3, Y3, Z3
    rinv = field.ctx.rinv
    jac_c = tuple(field.carried(v) for v in jac)
    axc, ayc = field.carried(ax), field.carried(ay)
    X3, Y3, Z3 = field.carried(X3), field.carried(Y3), field.carried(Z3)
    for j in np.nonzero(flag)[0]:
        lane = int(j)
        p1 = _jac_to_affine_int(field, jac_c, lane)
        q = (
            (_pairs_to_int(axc.limbs[:, lane]) * rinv) % P,
            (_pairs_to_int(ayc.limbs[:, lane]) * rinv) % P,
        )
        res = host.g1_add(p1, q)
        if res is None:
            if inf_out is not None:
                inf_out[lane] = True
            nx, ny, nz = 1, 1, 0
        else:
            nx, ny, nz = res[0], res[1], 1
        _write_lane(X3, lane, nx)
        _write_lane(Y3, lane, ny)
        _write_lane(Z3, lane, nz)
    return X3, Y3, Z3


def _add_vec(
    field: _Field, p1: Jac, p2: Jac
) -> Tuple[_FE, _FE, _FE, "np.ndarray"]:
    """General Jacobian + Jacobian add (add-2007-bl).  Returns
    (X3, Y3, Z3, exceptional): Z3 ≡ 0 flags every lane where either
    operand is the identity or P = ±Q — callers resolve via their
    infinity flags and the scalar patch."""
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = field.sqr(Z1)
    Z2Z2 = field.sqr(Z2)
    U1 = field.mul(X1, Z2Z2)
    U2 = field.mul(X2, Z1Z1)
    S1 = field.mul(Y1, field.mul(Z2, Z2Z2))
    S2 = field.mul(Y2, field.mul(Z1, Z1Z1))
    H = field.carried(field.sub(U2, U1))
    I = field.sqr(field.scale(H, 2))
    J = field.mul(H, I)
    Rr = field.scale(field.sub(S2, S1), 2)
    V = field.mul(U1, I)
    X3 = field.sub(field.sub(field.sqr(Rr), J), field.scale(V, 2))
    Y3 = field.sub(
        field.mul(Rr, field.sub(V, X3)),
        field.scale(field.mul(S1, J), 2),
    )
    Z3 = field.scale(field.mul(field.mul(Z1, Z2), H), 2)
    return X3, Y3, Z3, field.is_zero_mod(Z3)


# lane-shared signed wNAF(5) windows (the hostec_np recoding; scalars
# here are < r < 2^256, so the 52-window carry argument transfers)
_Q_WINDOW_BITS = hnp.Q_WINDOW_BITS
_NUM_WINDOWS = hnp.NUM_Q_WINDOWS
_TAB_ENTRIES = 16


def msm_batch(
    jobs: Sequence[Tuple[Sequence[G1Point], Sequence[int]]],
) -> List[G1Point]:
    """Per-job Σ_k e_k·B_k, batched.  Jobs are grouped by base count
    (the Idemix t1/t3 jobs carry 3 bases, t2 carries ~4+attrs — padding
    everything to the widest job would waste ~40% of every kernel) and
    each group runs as one lane batch.  Drop-in for
    ops/bn256_kernel.msm_host_batch, numpy instead of XLA."""
    if not HAVE_NUMPY:
        raise RuntimeError("hostbn requires numpy")
    if not jobs:
        return []
    by_k: dict = {}
    for i, (bases, _ss) in enumerate(jobs):
        by_k.setdefault(max(len(bases), 1), []).append(i)
    out: List[G1Point] = [None] * len(jobs)
    for _k, idxs in sorted(by_k.items()):
        for i, pt in zip(idxs, _msm_group([jobs[i] for i in idxs])):
            out[i] = pt
    return out


def _msm_group(
    jobs: Sequence[Tuple[Sequence[G1Point], Sequence[int]]],
) -> List[G1Point]:
    """One equal-base-count lane batch (slot-major layout: lane
    k·J + j is base slot k of job j)."""
    jcount = len(jobs)
    kmax = max(1, max(len(b) for b, _ in jobs))
    width = kmax * jcount
    gx, gy = host.G1_GEN
    bx = [gx] * width
    by = [gy] * width
    base_inf = np.zeros(width, dtype=bool)
    scalars = [0] * width
    for j, (bases, ss) in enumerate(jobs):
        for k in range(kmax):
            lane = k * jcount + j
            if k >= len(bases) or bases[k] is None:
                base_inf[lane] = True
                continue
            bx[lane], by[lane] = bases[k]
            scalars[lane] = ss[k] % host.R

    field = _Field(_ctx(P))
    digits = _signed_digits(
        _extract_windows(
            limbs13_to_pairs(ints_to_limbs13(scalars)),
            _Q_WINDOW_BITS,
            _NUM_WINDOWS,
        )
    )

    # ---- per-lane table 1..16 · B, affine Montgomery, one tree inversion
    Bx = _mont_lane_fe(field, bx)
    By = _mont_lane_fe(field, by)
    one_mont = field.const_int(1, width)
    tab_jac: List[Jac] = [(Bx, By, None)]  # None Z = affine
    d2 = _dbl_vec(field, Bx, By, one_mont)
    tab_jac.append(d2)
    for _d in range(3, _TAB_ENTRIES + 1):
        Xp, Yp, Zp = tab_jac[-1]
        X3, Y3, Z3, exc = _madd_vec(field, Xp, Yp, Zp, Bx, By)
        # d·B is never the identity for d <= 16 (prime order r) and the
        # dummy base is the generator — patch defensively anyway
        X3, Y3, Z3 = _patch_exc(
            field, exc & ~base_inf, (Xp, Yp, Zp), X3, Y3, Z3, Bx, By
        )
        tab_jac.append((X3, Y3, Z3))
    z_fes = [t[2] if t[2] is not None else one_mont for t in tab_jac[1:]]
    zs = np.concatenate([z.limbs for z in z_fes], axis=1)
    zinv = _invert_lanes(
        field,
        _FE(
            np.ascontiguousarray(zs),
            max(z.vb for z in z_fes),
            max(z.lb for z in z_fes),
            max(z.tb for z in z_fes),
        ),
    )
    tqx = np.empty((_TAB_ENTRIES, width, NPAIRS), dtype=np.uint64)
    tqy = np.empty((2 * _TAB_ENTRIES, width, NPAIRS), dtype=np.uint64)
    Bxc, Byc = field.carried(Bx), field.carried(By)
    tqx[0] = Bxc.limbs.T
    tqy[0] = Byc.limbs.T
    neg_col, neg_k, neg_max, neg_top = field.ctx.sub_k(PAIR_MASK, 0, 2)
    tqy[_TAB_ENTRIES] = (neg_col - Byc.limbs).T
    for d in range(1, _TAB_ENTRIES):
        zi = _FE(
            np.ascontiguousarray(zinv.limbs[:, (d - 1) * width : d * width]),
            2,
            PAIR_MASK,
        )
        zi2 = field.sqr(zi)
        ax = field.carried(field.mul(tab_jac[d][0], zi2))
        ay = field.carried(
            field.mul(tab_jac[d][1], field.mul(zi2, zi))
        )
        tqx[d] = ax.limbs.T
        tqy[d] = ay.limbs.T
        tqy[_TAB_ENTRIES + d] = (neg_col - ay.limbs).T

    # ---- Horner over the shared window schedule
    zero_lane = np.zeros((NPAIRS, width), dtype=np.uint64)
    RX = _FE(zero_lane.copy(), 1, PAIR_MASK)
    RY = field.const_int(1, width)
    RZ = _FE(zero_lane.copy(), 1, PAIR_MASK)
    acc_inf = np.ones(width, dtype=bool)
    lane_idx = np.arange(width)

    def add_affine(RX, RY, RZ, acc_inf, ax, ay, active):
        NX, NY, NZ, exc = _madd_vec(field, RX, RY, RZ, ax, ay)
        patched_inf = np.zeros_like(acc_inf)
        NX, NY, NZ = _patch_exc(
            field,
            exc & active & ~acc_inf,
            (RX, RY, RZ),
            NX,
            NY,
            NZ,
            ax,
            ay,
            inf_out=patched_inf,
        )
        fresh = acc_inf & active
        NX = field.select(fresh, ax, NX)
        NY = field.select(fresh, ay, NY)
        NZ = field.select(fresh, one_mont, NZ)
        RX, RY, RZ = _select_jac(field, active, (NX, NY, NZ), (RX, RY, RZ))
        new_inf = (acc_inf & ~active) | (active & patched_inf)
        return RX, RY, RZ, new_inf

    for j in range(_NUM_WINDOWS):
        if j:
            for _ in range(_Q_WINDOW_BITS):
                RX, RY, RZ = _dbl_vec(field, RX, RY, RZ)
        d = digits[_NUM_WINDOWS - 1 - j]
        xsel = np.clip(np.abs(d) - 1, 0, _TAB_ENTRIES - 1)
        ysel = xsel + np.where(d < 0, _TAB_ENTRIES, 0)
        ax = _FE(np.ascontiguousarray(tqx[xsel, lane_idx].T), 2, PAIR_MASK)
        ay = _FE(
            np.ascontiguousarray(tqy[ysel, lane_idx].T),
            neg_k,
            neg_max,
            neg_top,
        )
        RX, RY, RZ, acc_inf = add_affine(
            RX, RY, RZ, acc_inf, ax, ay, (d != 0) & ~base_inf
        )

    # ---- tree-reduce the slot partial sums down to one point per job
    cur = (RX, RY, RZ)
    cur_inf = acc_inf
    k = kmax
    while k > 1:
        half = k // 2

        def part(fe: _FE, sl) -> _FE:
            m = fe.limbs.reshape(NPAIRS, k, jcount)
            return _FE(
                np.ascontiguousarray(m[:, sl, :]).reshape(NPAIRS, -1),
                fe.vb,
                fe.lb,
                fe.tb,
            )

        infm = cur_inf.reshape(k, jcount)
        even = tuple(part(fe, slice(0, 2 * half, 2)) for fe in cur)
        odd = tuple(part(fe, slice(1, 2 * half, 2)) for fe in cur)
        inf1 = infm[0 : 2 * half : 2].reshape(-1)
        inf2 = infm[1 : 2 * half : 2].reshape(-1)
        X3, Y3, Z3, exc = _add_vec(field, even, odd)
        patched_inf = np.zeros_like(inf1)
        X3, Y3, Z3 = _patch_general(
            field, exc & ~inf1 & ~inf2, even, odd, X3, Y3, Z3, patched_inf
        )
        # identity operands resolve by select, not arithmetic
        X3 = field.select(inf1, odd[0], field.select(inf2, even[0], X3))
        Y3 = field.select(inf1, odd[1], field.select(inf2, even[1], Y3))
        Z3 = field.select(inf1, odd[2], field.select(inf2, even[2], Z3))
        new_inf = (inf1 & inf2) | (~inf1 & ~inf2 & patched_inf)
        if k % 2:
            tail = tuple(part(fe, slice(k - 1, k)) for fe in cur)
            X3 = _FE(
                np.concatenate(
                    [
                        X3.limbs.reshape(NPAIRS, half, jcount),
                        tail[0].limbs.reshape(NPAIRS, 1, jcount),
                    ],
                    axis=1,
                ).reshape(NPAIRS, -1),
                max(X3.vb, tail[0].vb),
                max(X3.lb, tail[0].lb),
                max(X3.tb, tail[0].tb),
            )
            Y3 = _FE(
                np.concatenate(
                    [
                        Y3.limbs.reshape(NPAIRS, half, jcount),
                        tail[1].limbs.reshape(NPAIRS, 1, jcount),
                    ],
                    axis=1,
                ).reshape(NPAIRS, -1),
                max(Y3.vb, tail[1].vb),
                max(Y3.lb, tail[1].lb),
                max(Y3.tb, tail[1].tb),
            )
            Z3 = _FE(
                np.concatenate(
                    [
                        Z3.limbs.reshape(NPAIRS, half, jcount),
                        tail[2].limbs.reshape(NPAIRS, 1, jcount),
                    ],
                    axis=1,
                ).reshape(NPAIRS, -1),
                max(Z3.vb, tail[2].vb),
                max(Z3.lb, tail[2].lb),
                max(Z3.tb, tail[2].tb),
            )
            new_inf = np.concatenate(
                [new_inf.reshape(half, jcount), infm[k - 1 : k]]
            ).reshape(-1)
            k = half + 1
        else:
            k = half
        cur = (X3, Y3, Z3)
        cur_inf = new_inf

    # ---- affine decode (one tree inversion across jobs)
    X, Y, Z = cur
    zinv = _invert_lanes(field, Z)
    zi2 = field.sqr(zinv)
    xs = field.to_ints(field.mul(field.carried(X), zi2))
    ys = field.to_ints(
        field.mul(field.carried(Y), field.mul(zi2, zinv))
    )
    return [
        None if cur_inf[j] else (xs[j], ys[j]) for j in range(jcount)
    ]


def _patch_general(
    field: _Field,
    flag: "np.ndarray",
    p1: Jac,
    p2: Jac,
    X3: _FE,
    Y3: _FE,
    Z3: _FE,
    inf_out: "np.ndarray",
) -> Jac:
    """Scalar host resolution of general-add P = ±Q lanes."""
    if not bool(flag.any()):
        return X3, Y3, Z3
    p1c = tuple(field.carried(v) for v in p1)
    p2c = tuple(field.carried(v) for v in p2)
    X3, Y3, Z3 = field.carried(X3), field.carried(Y3), field.carried(Z3)
    for j in np.nonzero(flag)[0]:
        lane = int(j)
        a = _jac_to_affine_int(field, p1c, lane)
        b = _jac_to_affine_int(field, p2c, lane)
        res = host.g1_add(a, b)
        if res is None:
            inf_out[lane] = True
            nx, ny, nz = 1, 1, 0
        else:
            nx, ny, nz = res[0], res[1], 1
        _write_lane(X3, lane, nx)
        _write_lane(Y3, lane, ny)
        _write_lane(Z3, lane, nz)
    return X3, Y3, Z3
