"""The TPU-backed BCCSP provider.

Occupies the same architectural slot as the reference's out-of-process
PKCS#11 HSM provider (reference bccsp/pkcs11, SURVEY.md §2.12: "the
bccsp/tpu-equivalent provider is the analog"): single-verify API preserved,
batches collected under the hood.

Host/device split (SURVEY.md §7 Stage 1): DER parsing, the low-S rule,
range checks and key deserialization are irregular byte-twiddling and stay
on host; the double-scalar multiplication runs as one fixed-shape XLA
program per batch-size bucket. Scalars are converted bytes->limbs with
vectorized numpy (np.unpackbits), not per-int Python loops, so the host
feed path keeps up with the device.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Sequence, Tuple

import numpy as np

from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common import fabobs, p256
from fabric_tpu.crypto.bccsp import (
    ECDSAPublicKey,
    Provider,
    VerifyError,
)
from fabric_tpu.ops import bignum as bn

logger = must_get_logger("tpu_provider")

_BUCKETS = [128, 256, 512, 1024, 2048, 4096, 8192, 16384]


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + _BUCKETS[-1] - 1) // _BUCKETS[-1]) * _BUCKETS[-1]


def be_bytes_to_limbs(rows: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 big-endian byte rows -> (20, B) uint32 13-bit limbs.

    Vectorized: unpack to bits, regroup in 13-bit windows.
    """
    b = rows.shape[0]
    # bit i (LSB-first) of the 256-bit integer
    bits = np.unpackbits(rows[:, ::-1], axis=1, bitorder="little")  # (B, 256)
    pad = np.zeros((b, bn.NLIMBS * bn.LIMB_BITS - 256), dtype=bits.dtype)
    bits = np.concatenate([bits, pad], axis=1).reshape(b, bn.NLIMBS, bn.LIMB_BITS)
    weights = (1 << np.arange(bn.LIMB_BITS, dtype=np.uint32)).astype(np.uint32)
    limbs = (bits.astype(np.uint32) * weights).sum(axis=2, dtype=np.uint32)
    return np.ascontiguousarray(limbs.T)


class TPUProvider(Provider):
    """Batched device verification with the reference's decision semantics."""

    def __init__(self):
        import jax

        from fabric_tpu.crypto.bccsp import SoftwareProvider
        from fabric_tpu.ops import p256_kernel as pk
        from fabric_tpu.utils.jaxcache import enable_compile_cache

        # every consumer of the device provider (peer/orderer processes
        # included) must hit the persistent XLA cache — a subprocess peer
        # without it recompiles the verify kernel for minutes
        enable_compile_cache()
        self._jax = jax
        self._pk = pk
        self._software = SoftwareProvider()
        self._key_limb_cache: Dict[
            bytes, Tuple[np.ndarray, np.ndarray, bool]
        ] = {}

    def _key_columns(self, distinct: Sequence[ECDSAPublicKey]):
        """(x limbs, y limbs, on_curve) per DISTINCT key, cached by SKI —
        mirrors the MSP identity cache the reference leans on (msp/cache,
        SURVEY.md §2.2). Cache misses convert in ONE vectorized
        be_bytes_to_limbs call per coordinate instead of a per-key
        int_to_limbs loop (PR 18, fabtrace transfer-in-loop). The
        on-curve gate matters: the complete-addition formulas are only
        defined for curve points, so off-curve keys must fail in the
        host mask, exactly as SoftwareProvider fails them."""
        skis = [key.ski() for key in distinct]
        missing = [
            i for i, ski in enumerate(skis)
            if ski not in self._key_limb_cache
        ]
        if missing:
            xb = np.frombuffer(
                b"".join(distinct[i].x.to_bytes(32, "big") for i in missing),
                dtype=np.uint8,
            ).reshape(len(missing), 32)
            yb = np.frombuffer(
                b"".join(distinct[i].y.to_bytes(32, "big") for i in missing),
                dtype=np.uint8,
            ).reshape(len(missing), 32)
            xl = be_bytes_to_limbs(xb)
            yl = be_bytes_to_limbs(yb)
            if len(self._key_limb_cache) > 65536:
                self._key_limb_cache.clear()
            for j, i in enumerate(missing):
                key = distinct[i]
                self._key_limb_cache[skis[i]] = (
                    np.ascontiguousarray(xl[:, j]),
                    np.ascontiguousarray(yl[:, j]),
                    p256.is_on_curve((key.x, key.y)),
                )
        return [self._key_limb_cache[ski] for ski in skis]

    # Below this count the device round-trip (and worse, a first-time XLA
    # compile) costs more than host verification; interactive paths (MSP
    # identity checks, orderer SigFilter, CLI clients) hit the single API
    # and must never wait on a kernel compile. The per-block validator
    # calls batch_verify with hundreds-to-thousands of lanes.
    MIN_DEVICE_BATCH = 32

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        # SoftwareProvider already does the DER parse + low-S precheck and
        # raises VerifyError with the reference's (bool, error) semantics.
        return self._software.verify(key, signature, digest)

    def describe_backend(self) -> str:
        """"tpu", or "tpu-degraded(<host tier>)" once any dispatch has been
        served by the software fallback — so a degraded run can never be
        mistaken for a device number downstream."""
        if type(self).degraded:
            return f"tpu-degraded({self._software.describe_backend()})"
        return "tpu"

    # distinct keys are padded to a fixed column bucket so the jitted
    # program's K dimension does not recompile per block (few orgs in
    # practice; overflow falls back to full limb matrices)
    KEY_BUCKET = 32

    def batch_verify(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        if len(signatures) < self.MIN_DEVICE_BATCH:
            out = []
            for key, sig, dig in zip(keys, signatures, digests):
                try:
                    out.append(self._software.verify(key, sig, dig))
                except VerifyError:
                    out.append(False)
            return out
        return self.batch_verify_async(keys, signatures, digests)()

    # flips to True the first time a device dispatch exhausts its
    # retries and the batch is served by the software path instead —
    # consumers (bench labeling, ops /healthz) read it to tell "device
    # result" from "degraded-but-alive result"
    degraded = False

    def _sw_verify_all(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        if not type(self).degraded:
            fabobs.obs_count("fabric_degrade_total", seam="tpu.dispatch")
            fabobs.obs_trigger("tpu.degraded")
        type(self).degraded = True
        out: List[bool] = []
        for key, sig, dig in zip(keys, signatures, digests):
            try:
                out.append(self._software.verify(key, sig, dig))
            except VerifyError:
                out.append(False)
        return out

    def batch_verify_async(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ):
        """Dispatch the device batch WITHOUT waiting: returns a resolver
        () -> List[bool]. Lets a pipelined caller (peer CommitPipeline,
        bench double-buffering) prep block N+1 on the single host core
        while the accelerator chews block N.

        Flake armor (round-4 postmortem: one UNAVAILABLE at dispatch
        killed the whole benchmark with rc=1): dispatch errors are
        retried with backoff — the tunnel's transient stalls recover in
        seconds — and a batch whose retries exhaust is verified by the
        OpenSSL software path instead of raising. Committers never stop
        committing because the accelerator went away."""
        n = len(signatures)
        t0 = time.perf_counter()
        prep, limbs = self.prep_bytes(keys, signatures, digests)
        attempts = max(int(os.environ.get("FABRIC_TPU_DISPATCH_RETRIES", "3")), 1)
        delay = 1.0
        out = None
        for attempt in range(attempts):
            try:
                if prep is None:  # key-bucket overflow: limb-matrix path
                    out = self._dispatch_limbs(limbs)
                else:
                    out = self._dispatch_bytes_or_fallback(prep)
                break
            except Exception as exc:  # noqa: BLE001 - backend init/dispatch flake
                if attempt == attempts - 1:
                    logger.warning(
                        "device dispatch failed %d time(s) (%s); "
                        "falling back to software verify", attempts, exc,
                    )
                    return lambda: self._sw_verify_all(keys, signatures, digests)
                time.sleep(delay)
                delay *= 3.0

        def resolve() -> List[bool]:
            try:
                verdicts = [bool(v) for v in np.asarray(out)[:n]]
            except Exception as exc:  # noqa: BLE001 - async error surfaces here
                logger.warning(
                    "async device result failed (%s); "
                    "falling back to software verify", exc,
                )
                return self._sw_verify_all(keys, signatures, digests)
            fabobs.obs_count("fabric_verify_lanes_total", n, rung="device")
            fabobs.obs_observe(
                "fabric_verify_seconds",
                time.perf_counter() - t0, rung="device",
            )
            return verdicts

        return resolve

    _bytes_path_broken = False

    def _dispatch_bytes_or_fallback(self, prep):
        """The bytes kernel is the fast path but its compile can be
        refused by the remote compile service; the limb-matrix kernel is
        the always-works fallback (its cache entry ships with the repo's
        .jax_cache). One hard failure disables the bytes path for the
        process."""
        bytes_failed = False
        if not self._bytes_path_broken:
            try:
                return self._dispatch_bytes(prep)
            except Exception as exc:  # noqa: BLE001 - compile/dispatch failure
                logger.warning(
                    "bytes kernel failed (%s); trying the limb-matrix "
                    "fallback", exc,
                )
                bytes_failed = True
        e_bytes, r_bytes, s_bytes, kx, ky, idx, ok = prep
        qx = np.ascontiguousarray(kx[:, idx])
        qy = np.ascontiguousarray(ky[:, idx])
        out = self._dispatch_limbs(
            (
                be_bytes_to_limbs(e_bytes),
                be_bytes_to_limbs(r_bytes),
                be_bytes_to_limbs(s_bytes),
                qx,
                qy,
                ok,
            )
        )
        if bytes_failed:
            # the limb program dispatched fine, so the failure was the
            # bytes program itself (e.g. remote compile refusal), not a
            # backend outage — only then is disabling it for the process
            # justified (a dead tunnel must not cost the fast path after
            # it recovers; the caller's retry loop handles outages)
            type(self)._bytes_path_broken = True
        return out

    def _dedup_key_columns(self, keys: Sequence[ECDSAPublicKey]):
        """One limb conversion + curve check per DISTINCT key object (the
        MSP cache reuses key objects for repeated identities), plus the
        per-lane column index. Shared by the bytes and limb paths."""
        columns: Dict[int, int] = {}
        distinct: List[ECDSAPublicKey] = []
        idx = np.zeros(len(keys), dtype=np.int32)
        for i, key in enumerate(keys):
            col = columns.get(id(key))
            if col is None:
                col = len(distinct)
                columns[id(key)] = col
                distinct.append(key)
            idx[i] = col
        cols = self._key_columns(distinct)
        kx_cols = [c[0] for c in cols]
        ky_cols = [c[1] for c in cols]
        on_curve = np.asarray([c[2] for c in cols], dtype=bool)
        return kx_cols, ky_cols, on_curve, idx

    def prep_bytes(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ):
        """Bytes-path host prep: DER parse + key-column dedup only; the
        byte->limb unpack and the per-lane key gather happen on device
        (p256_kernel.verify_batch_bytes_device). Returns None when the
        distinct-key count exceeds KEY_BUCKET (caller pivots to the
        limb-matrix path WITHOUT repeating this prep — see
        batch_verify_async)."""
        from fabric_tpu.utils import native

        n = len(signatures)
        r_bytes, s_bytes, ok_u8, low_s = native.batch_der_parse(signatures)
        ok = (ok_u8 & low_s).astype(bool)
        if any(len(d) != 32 for d in digests):
            raise VerifyError("digests must be 32-byte SHA-256 outputs")
        e_bytes = np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(
            n, 32
        )
        kx_cols, ky_cols, on_curve, idx = self._dedup_key_columns(keys)
        if kx_cols:
            ok &= on_curve[idx]
        if len(kx_cols) > self.KEY_BUCKET:
            # too many distinct keys for the fixed column bucket: hand the
            # already-built columns to the limb-matrix path
            qx = np.stack(kx_cols, axis=1)[:, idx]
            qy = np.stack(ky_cols, axis=1)[:, idx]
            return None, (
                be_bytes_to_limbs(e_bytes),
                be_bytes_to_limbs(r_bytes),
                be_bytes_to_limbs(s_bytes),
                qx,
                qy,
                ok,
            )
        k = self.KEY_BUCKET
        kx_mat = np.zeros((bn.NLIMBS, k), dtype=np.uint32)
        ky_mat = np.zeros((bn.NLIMBS, k), dtype=np.uint32)
        if kx_cols:
            kx_mat[:, : len(kx_cols)] = np.stack(kx_cols, axis=1)
            ky_mat[:, : len(ky_cols)] = np.stack(ky_cols, axis=1)
        return (e_bytes, r_bytes, s_bytes, kx_mat, ky_mat, idx, ok), None

    def _dispatch_bytes(self, prep):
        e_bytes, r_bytes, s_bytes, kx, ky, idx, ok = prep
        n = ok.shape[0]
        size = _bucket(n)
        pad = size - n

        def padded(a):
            if pad == 0:
                return a
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, widths)

        return self._pk.verify_batch_bytes_jit(
            padded(e_bytes),
            padded(r_bytes),
            padded(s_bytes),
            kx,
            ky,
            padded(idx),
            padded(ok.astype(bool)),
        )

    def _dispatch_limbs(self, limbs: Sequence[np.ndarray]):
        n = limbs[-1].shape[0]
        return self._pk.verify_batch_jit(*self.pad_limbs(limbs, _bucket(n)))

    def prep_limbs(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> Tuple[np.ndarray, ...]:
        """Vectorized host prep for the limb-matrix kernel (mesh and
        multi-channel paths): DER parse, byte->limb conversion and the
        deduped key-column gather, all on host. Returns the kernel-ready
        (e, r, s, qx, qy) (20, n) limb arrays + (n,) mask."""
        from fabric_tpu.utils import native

        n = len(signatures)
        r_bytes, s_bytes, ok_u8, low_s = native.batch_der_parse(signatures)
        # high-S rejected like utils.IsLowS (bccsp/sw/ecdsa.go:41-57)
        ok = (ok_u8 & low_s).astype(bool)

        if any(len(d) != 32 for d in digests):
            raise VerifyError("digests must be 32-byte SHA-256 outputs")
        e_bytes = np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(
            n, 32
        )
        kx_cols, ky_cols, on_curve, idx = self._dedup_key_columns(keys)
        if kx_cols:
            qx = np.stack(kx_cols, axis=1)[:, idx]
            qy = np.stack(ky_cols, axis=1)[:, idx]
            ok &= on_curve[idx]
        else:
            qx = np.zeros((bn.NLIMBS, n), dtype=np.uint32)
            qy = np.zeros((bn.NLIMBS, n), dtype=np.uint32)
        return (
            be_bytes_to_limbs(e_bytes),
            be_bytes_to_limbs(r_bytes),
            be_bytes_to_limbs(s_bytes),
            qx,
            qy,
            ok,
        )

    @staticmethod
    def pad_limbs(
        limbs: Sequence[np.ndarray], size: int
    ) -> Tuple[np.ndarray, ...]:
        """Pad (e, r, s, qx, qy, ok) from n lanes to `size` dead lanes."""
        *arrays, ok = limbs
        pad = size - ok.shape[0]
        if pad == 0:
            return (*arrays, ok.astype(bool))
        return tuple(
            np.pad(a, [(0, 0), (0, pad)]) for a in arrays
        ) + (np.pad(ok.astype(bool), (0, pad)),)

    def _run_kernel(self, limbs: Sequence[np.ndarray]) -> List[bool]:
        n = limbs[-1].shape[0]
        out = self._dispatch_limbs(limbs)
        return list(np.asarray(out)[:n])
