"""The TPU-backed BCCSP provider.

Occupies the same architectural slot as the reference's out-of-process
PKCS#11 HSM provider (reference bccsp/pkcs11, SURVEY.md §2.12: "the
bccsp/tpu-equivalent provider is the analog"): single-verify API preserved,
batches collected under the hood.

Host/device split (SURVEY.md §7 Stage 1): DER parsing, the low-S rule,
range checks and key deserialization are irregular byte-twiddling and stay
on host; the double-scalar multiplication runs as one fixed-shape XLA
program per batch-size bucket. Scalars are converted bytes->limbs with
vectorized numpy (np.unpackbits), not per-int Python loops, so the host
feed path keeps up with the device.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from fabric_tpu.crypto import p256
from fabric_tpu.crypto.bccsp import (
    ECDSAPublicKey,
    Provider,
    VerifyError,
)
from fabric_tpu.ops import bignum as bn

_BUCKETS = [128, 256, 512, 1024, 2048, 4096, 8192, 16384]


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + _BUCKETS[-1] - 1) // _BUCKETS[-1]) * _BUCKETS[-1]


def be_bytes_to_limbs(rows: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 big-endian byte rows -> (20, B) uint32 13-bit limbs.

    Vectorized: unpack to bits, regroup in 13-bit windows.
    """
    b = rows.shape[0]
    # bit i (LSB-first) of the 256-bit integer
    bits = np.unpackbits(rows[:, ::-1], axis=1, bitorder="little")  # (B, 256)
    pad = np.zeros((b, bn.NLIMBS * bn.LIMB_BITS - 256), dtype=bits.dtype)
    bits = np.concatenate([bits, pad], axis=1).reshape(b, bn.NLIMBS, bn.LIMB_BITS)
    weights = (1 << np.arange(bn.LIMB_BITS, dtype=np.uint32)).astype(np.uint32)
    limbs = (bits.astype(np.uint32) * weights).sum(axis=2, dtype=np.uint32)
    return np.ascontiguousarray(limbs.T)


class TPUProvider(Provider):
    """Batched device verification with the reference's decision semantics."""

    def __init__(self):
        import jax

        from fabric_tpu.crypto.bccsp import SoftwareProvider
        from fabric_tpu.ops import p256_kernel as pk

        self._jax = jax
        self._pk = pk
        self._software = SoftwareProvider()
        self._key_limb_cache: Dict[bytes, Tuple[np.ndarray, np.ndarray]] = {}

    def _key_limbs(self, key: ECDSAPublicKey) -> Tuple[np.ndarray, np.ndarray, bool]:
        """Per-key (x limbs, y limbs, on_curve) cached by SKI — mirrors the
        MSP identity cache the reference leans on (msp/cache, SURVEY.md
        §2.2). The on-curve gate matters: the complete-addition formulas
        are only defined for curve points, so off-curve keys must fail in
        the host mask, exactly as SoftwareProvider fails them."""
        ski = key.ski()
        hit = self._key_limb_cache.get(ski)
        if hit is None:
            on_curve = p256.is_on_curve((key.x, key.y))
            hit = (bn.int_to_limbs(key.x), bn.int_to_limbs(key.y), on_curve)
            if len(self._key_limb_cache) > 65536:
                self._key_limb_cache.clear()
            self._key_limb_cache[ski] = hit
        return hit

    # Below this count the device round-trip (and worse, a first-time XLA
    # compile) costs more than host verification; interactive paths (MSP
    # identity checks, orderer SigFilter, CLI clients) hit the single API
    # and must never wait on a kernel compile. The per-block validator
    # calls batch_verify with hundreds-to-thousands of lanes.
    MIN_DEVICE_BATCH = 32

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        # SoftwareProvider already does the DER parse + low-S precheck and
        # raises VerifyError with the reference's (bool, error) semantics.
        return self._software.verify(key, signature, digest)

    def batch_verify(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        if len(signatures) < self.MIN_DEVICE_BATCH:
            out = []
            for key, sig, dig in zip(keys, signatures, digests):
                try:
                    out.append(self._software.verify(key, sig, dig))
                except VerifyError:
                    out.append(False)
            return out
        return self._batch_verify_native(keys, signatures, digests)

    def _batch_verify_native(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        limbs = self.prep_limbs(keys, signatures, digests)
        return self._run_kernel(limbs)

    def prep_limbs(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> Tuple[np.ndarray, ...]:
        """Vectorized host prep shared by the single-chip and mesh paths:
        the C++ batched DER parser (falls back to Python transparently)
        emits fixed-width (r, s) words + validity masks; returns the
        kernel-ready (e, r, s, qx, qy) (20, n) limb arrays + (n,) mask."""
        from fabric_tpu.utils import native

        n = len(signatures)
        r_bytes, s_bytes, ok_u8, low_s = native.batch_der_parse(signatures)
        # high-S rejected like utils.IsLowS (bccsp/sw/ecdsa.go:41-57)
        ok = (ok_u8 & low_s).astype(bool)

        if any(len(d) != 32 for d in digests):
            raise VerifyError("digests must be 32-byte SHA-256 outputs")
        e_bytes = np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(
            n, 32
        )
        qx = np.zeros((bn.NLIMBS, n), dtype=np.uint32)
        qy = np.zeros((bn.NLIMBS, n), dtype=np.uint32)
        # keys repeat heavily per block (few orgs); dedupe limb conversion
        for i, key in enumerate(keys):
            if not ok[i]:
                continue
            kx, ky, on_curve = self._key_limbs(key)
            if not on_curve:
                ok[i] = False
                continue
            qx[:, i] = kx
            qy[:, i] = ky
        return (
            be_bytes_to_limbs(e_bytes),
            be_bytes_to_limbs(r_bytes),
            be_bytes_to_limbs(s_bytes),
            qx,
            qy,
            ok,
        )

    @staticmethod
    def pad_limbs(
        limbs: Sequence[np.ndarray], size: int
    ) -> Tuple[np.ndarray, ...]:
        """Pad (e, r, s, qx, qy, ok) from n lanes to `size` dead lanes."""
        *arrays, ok = limbs
        pad = size - ok.shape[0]
        if pad == 0:
            return (*arrays, ok.astype(bool))
        return tuple(
            np.pad(a, [(0, 0), (0, pad)]) for a in arrays
        ) + (np.pad(ok.astype(bool), (0, pad)),)

    def _run_kernel(self, limbs: Sequence[np.ndarray]) -> List[bool]:
        n = limbs[-1].shape[0]
        out = self._pk.verify_batch_jit(*self.pad_limbs(limbs, _bucket(n)))
        return list(np.asarray(out)[:n])
