"""Compatibility shim: der moved to ``fabric_tpu.common.der``.

The DER (de)serializers are needed below the crypto layer (utils/native
falls back to them when the native walker is absent), which created the
crypto<->utils import cycle the fabdep layering gate forbids; the
implementation now lives in the lowest shared layer.  This shim aliases
the real module, so ``fabric_tpu.crypto.der is fabric_tpu.common.der``
and every historical import keeps working.
"""

import sys as _sys

from fabric_tpu.common import der as _impl

_sys.modules[__name__] = _impl
