"""numpy limb-matrix batch ECDSA-P256 verification (hostec_np).

A rung of the host EC backend ladder between the OpenSSL tier and the
CPython list-comprehension tier: ``fastec -> hostec_np -> hostec ->
p256``.  Where hostec advances every lane through the window schedule
with one fused list comprehension of Python big-ints per field op, this
engine keeps the whole batch as limb MATRICES and lets numpy's C kernels
do the per-lane work — the same direction hardware-offload work takes
for Fabric's validation phase (arXiv:1907.08367, arXiv:2112.02229), on
commodity SIMD instead of an FPGA.

Representation (reusing the radix-2^13 machinery the fabflow gate
already proved overflow-free for the device kernels):

- **Batch interchange format**: a batch of field elements is a
  ``(lanes, NLIMBS)`` uint64 matrix of radix-2^13 limbs — the canonical
  LIMB_BITS/NLIMBS/LIMB_MASK constants from ``common/limbparams`` (the
  same single source of truth ops/bignum.py re-exports), so the CIOS
  headroom reasoning transfers and fabflow's const-drift rule applies
  unchanged.
- **Compute form**: inside the engine, adjacent limb pairs are condensed
  to radix-2^(2*LIMB_BITS) "pair limbs" held as ``(NPAIRS, lanes)``
  uint64 rows (limb-major: each pair-limb row is one contiguous vector
  numpy streams).  NPAIRS = NLIMBS//2 + 1: the spare eleventh pair-limb
  raises the Montgomery radix to R = 2^286, which buys enough value
  headroom (c1*c2 <= 2^30 instead of the device kernel's 16) that the
  group law never needs a conditional subtract — numpy pays ~5us of
  fixed cost per vector op, so the device kernel's reduce_canonical
  discipline (cheap inside a fused XLA program) would dominate a numpy
  profile.
- **Montgomery CIOS mul/sqr**: product MAC rows then a limb-serial REDC
  sweep, all in uint64 with lazy carries.  The mechanized worst-case
  accumulator (fabflow re-derives it over `_mul_kernel`) is
  NPAIRS * L32_BOUND * L4_BOUND + the q*m and carry terms
  < 2^62.5 < 2^64 — the pair-radix analog of the device kernel's
  2684174334 < 0.625 * 2^32 bound, with the same shape of proof.
- **Lazy bounds**: field values ride a small `_FE` wrapper tracking an
  exact value bound (multiple of the modulus) and an exact per-limb
  bound; additions and subtractions stay lazy (no carry chains), and
  `fe_mul`/`fe_sqr` carry an operand only when the tracked bound would
  exceed the kernel's proven input contract.  The bounds are Python
  ints computed once per batch op — a runtime mirror of the static
  proof that raises (never asserts) on a violated invariant.
- **Group law**: Jacobian dbl-2001-b (a = -3) and the standard mixed
  madd, identical formulas to hostec so the exceptional-case structure
  matches lane for lane.  Exceptional lanes (P = +-Q, P = infinity) are
  detected wholesale — Z3 < 2p comes back limb-canonical from the
  multiply, so Z3 ≡ 0 (mod p) is exactly "all limbs zero or equal to
  p's" — and patched per lane through hostec's scalar `_madd1`.
- **Scalars**: u2*Q uses lane-shared signed 5-bit windows (the regular
  wNAF(5) digit set: odd-free signed digits in [-15, 16], recoded
  vectorized across lanes) against a per-batch 16-entry table that is
  normalized to affine with ONE tree batch inversion; u1*G uses a
  precomputed 26-window x 1023-entry unsigned 10-bit comb of G
  multiples, normalized once at build with a Montgomery batch
  inversion and stored in the Montgomery domain.
- **Tree batch inversion**: Montgomery's trick serializes a prefix
  product across lanes, which CPython does cheaply but numpy cannot;
  the engine instead pairs lanes level by level (a Blelloch-style
  up/down sweep of Montgomery multiplies on halving widths), inverts
  the single root with one Python `pow`, and walks back down — O(log
  lanes) vector ops per inversion site instead of O(lanes) scalar ones.
- **Shared-memory sharding**: big batches are sharded across a process
  pool through ONE `multiprocessing.shared_memory` block — the parent
  packs prechecked lanes into limb matrices in shm, workers attach by
  name and write verdict bytes into their own slice of the result
  region, so nothing but (name, lo, hi) ever crosses the pickle
  boundary and reassembly is order-preserving by construction.

Semantics are bit-identical to hostec/the oracle (``verify_digest``
implements Go crypto/ecdsa.Verify: no low-S rule here, out-of-range r/s
and off-curve or identity keys return False and never raise).  Single
verifies and small batches delegate down-ladder to hostec — the matrix
engine's fixed cost only pays for itself from ~100 lanes up.  numpy
itself is an optional dependency: the module imports without it and
`bccsp.select_ec_backend` skips this rung with a warning (silently for
callers, loudly in the log) when it is absent.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from fabric_tpu.common import fabobs, p256
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.retry import CooldownGate
from fabric_tpu.common.limbparams import (
    LIMB_BITS,
    LIMB_MASK,
    NLIMBS,
    RADIX_BITS,
)
from fabric_tpu.common.p256 import GX, GY, N, P
from fabric_tpu.crypto import hostec

logger = must_get_logger("hostec_np")

try:  # numpy is optional: the ladder skips this rung when it is absent
    import numpy as np

    HAVE_NUMPY = True
except ImportError:  # pragma: no cover - exercised via monkeypatch
    np = None  # type: ignore[assignment]
    HAVE_NUMPY = False

KeyPair = p256.KeyPair
PubKey = Optional[Tuple[int, int]]

# -- pair-limb parameters, all derived from the canonical radix ----------
PAIR_BITS = 2 * LIMB_BITS  # 26
PAIR_MASK = (1 << PAIR_BITS) - 1
NPAIRS = NLIMBS // 2 + 1  # 11: one spare pair-limb of value headroom
MONT_BITS = PAIR_BITS * NPAIRS  # 286
R_MONT = 1 << MONT_BITS

# Proven input contracts of `_mul_kernel` (per-limb bounds); fe_mul
# carries an operand that exceeds them.  NPAIRS * L32 * L4 + the q*m
# rows stays < 2^63 — see the kernel comment for the exact bound.
L4_BOUND = 4 * (PAIR_MASK + 1) - 1  # ~2^28
L32_BOUND = 32 * (PAIR_MASK + 1) - 1  # ~2^31

# Engine thresholds (env-tunable, malformed values fall back silently).
# The matrix engine's fixed costs (three inversion trees, the per-batch
# Q table, digit recoding) amortize from roughly a thousand lanes up on
# a 2-core box — below FABRIC_TPU_HOSTEC_NP_MIN_LANES the sharded
# entrypoint delegates down-ladder to hostec's list engine instead.
NP_MIN_LANES = 1024
MIN_POOL_LANES = 2048  # below this a pool round-trip costs more
MIN_SHARD_LANES = 1024  # never split shards smaller than this


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            pass
    return default


# ---------------------------------------------------------------------------
# Montgomery context over pair limbs
# ---------------------------------------------------------------------------


def _int_to_pairs(x: int) -> List[int]:
    return [(x >> (PAIR_BITS * i)) & PAIR_MASK for i in range(NPAIRS)]


def _pairs_to_int(col) -> int:
    val = 0
    for i in range(NPAIRS - 1, -1, -1):
        val = (val << PAIR_BITS) + int(col[i])
    return val


class _NpMont:
    """Montgomery constants for an odd modulus m < 2^256 at R = 2^286,
    as (NPAIRS, 1) uint64 columns ready to broadcast across lanes."""

    def __init__(self, modulus: int):
        if modulus % 2 == 0:
            raise ValueError("modulus must be odd")
        self.m = modulus
        self.m_pairs = _int_to_pairs(modulus)
        self.m_col = np.array(self.m_pairs, dtype=np.uint64)[:, None]
        self.m0inv = int((-pow(modulus, -1, 1 << PAIR_BITS)) % (1 << PAIR_BITS))
        # contiguous nonzero pair-row runs of m: the REDC MAC skips zero
        # rows wholesale (P-256's p zeroes 4 of its 11 pairs)
        blocks = []
        i = 0
        while i < NPAIRS:
            if self.m_pairs[i]:
                j = i
                while j < NPAIRS and self.m_pairs[j]:
                    j += 1
                blocks.append((i, j))
                i = j
            else:
                i += 1
        self.mac_blocks = tuple(blocks)
        # P-256 fast path: validate the static shift decomposition and
        # build the complement-fold bias.  The REDC sweep adds
        # (PAIR_MASK - q) << 16 where the decomposition wants
        # -(q << 16): each iteration i thereby over-adds the constant
        # (PAIR_MASK << 16) * 2^(PAIR_BITS*(i+8)); the bias is
        # K*m - (that constant sum), chosen canonical (< m, plain
        # nonneg limbs), so the kernel never subtracts and the whole
        # sweep stays interval-provable with zero suppressions.
        self.p256_bias = None
        self.bias_rows = (0, 0)
        if self.m0inv == 1:
            recon = -1
            for coff, sh, sign in _P256_REDC_TERMS:
                recon += sign << (PAIR_BITS * coff + sh)
            if recon == modulus:
                over = 0
                for coff, sh, sign in _P256_REDC_TERMS:
                    if sign < 0:
                        for i in range(NPAIRS):
                            over += (PAIR_MASK << sh) << (
                                PAIR_BITS * (i + coff)
                            )
                kk = over // modulus + 1
                val = kk * modulus - over
                ncols = 2 * NPAIRS
                limbs = [
                    (val >> (PAIR_BITS * i)) & PAIR_MASK
                    for i in range(ncols)
                ]
                nz = [i for i, v in enumerate(limbs) if v] or [0]
                self.bias_rows = (min(nz), max(nz) + 1)
                self.p256_bias = np.array(limbs, dtype=np.uint64)[:, None]
        self.r2 = self.to_limbs((R_MONT * R_MONT) % modulus)
        self.one_mont_int = R_MONT % modulus
        self.rinv = pow(R_MONT, -1, modulus)
        # k*m in a redundant per-limb form with every limb >= `floor`,
        # for borrow-free lazy subtraction; built on demand per (k,
        # floor) and memoized.
        self._ksub: dict = {}

    def to_limbs(self, x: int) -> "np.ndarray":
        """Python int -> (NPAIRS, 1) uint64 column."""
        return np.array(_int_to_pairs(x), dtype=np.uint64)[:, None]

    def sub_k(
        self, floor: int, top_floor: int, vb: int
    ) -> Tuple["np.ndarray", int, int, int]:
        """The least power-of-two k (>= vb) such that k*m can be written
        with pair limbs 0..NPAIRS-2 all >= floor and the spare top limb
        >= top_floor — the borrow-free K of the lazy subtraction
        a + (K - b).  Values span only RADIX_BITS bits, so top_floor is
        tiny (the subtrahend's tracked top-limb spill from earlier
        K-chains).  Returns (column, k, maxlimb, toplimb)."""
        key = (floor, top_floor, vb)
        hit = self._ksub.get(key)
        if hit is not None:
            return hit
        need = sum(
            floor << (PAIR_BITS * i) for i in range(NPAIRS - 1)
        ) + (top_floor << (PAIR_BITS * (NPAIRS - 1)))
        k = 1
        while k < vb or k * self.m < need:
            k <<= 1
        if (k * self.m) >> MONT_BITS:
            raise ArithmeticError("k*m does not fit the pair radix")
        limbs = _int_to_pairs(k * self.m)
        # borrow from limb i+1 => +2^PAIR_BITS at limb i; intermediate
        # negatives resolve when their own turn borrows from above, so
        # feasibility is checked once, at the top
        for i in range(NPAIRS - 1):
            if limbs[i] < floor:
                borrow = (
                    floor - limbs[i] + (1 << PAIR_BITS) - 1
                ) >> PAIR_BITS
                limbs[i] += borrow << PAIR_BITS
                limbs[i + 1] -= borrow
        if limbs[NPAIRS - 1] < top_floor:
            raise ArithmeticError(
                f"cannot redistribute {k}*m with limb floor {floor}"
            )
        col = np.array(limbs, dtype=np.uint64)[:, None]
        out = (col, k, max(limbs), limbs[NPAIRS - 1])
        self._ksub[key] = out
        return out


_CTX_LOCK = threading.Lock()
_CTX: dict = {}


def _ctx(modulus: int) -> _NpMont:
    ctx = _CTX.get(modulus)
    if ctx is None:
        with _CTX_LOCK:
            ctx = _CTX.get(modulus)
            if ctx is None:
                ctx = _NpMont(modulus)
                _CTX[modulus] = ctx
    return ctx


# ---------------------------------------------------------------------------
# Core kernels (fabflow limb-tier coverage: the annotations below are
# the proven input contracts; callers enforce them via _FE bounds)
# ---------------------------------------------------------------------------


def _mul_kernel_ref(
    a: "PairMatL32",
    b: "PairMatL4",
    m_col: "PairMat",
    m0inv: int,
) -> "np.ndarray":
    """Reference Montgomery product — the exact recurrence of
    `_mul_kernel` in plain-operator form, which is what the fabflow
    limb-tier proof mechanizes: np.zeros starts every column at [0, 0],
    each MAC row adds at most (32*2^26)(4*2^26) = 2^59, the 11-row
    worst case is NPAIRS * 2^59 < 2^62.46, the dense q*m REDC rows add
    NPAIRS * 2^52 and each shifted carry < 2^36.5 — total < 2^62.5,
    2.8x under uint64.  tests/test_hostec_np.py pins this bit-exact
    against the workspace-optimized `_mul_kernel` (whose out=/buffer
    plumbing the abstract interpreter cannot track), so the proof
    transfers."""
    lanes = a.shape[1]
    t = np.zeros((2 * NPAIRS, lanes), dtype=np.uint64)
    for i in range(NPAIRS):
        t[i : i + NPAIRS] += a[i] * b
    for i in range(NPAIRS):
        q = ((t[i] & PAIR_MASK) * m0inv) & PAIR_MASK
        t[i : i + NPAIRS - 1] += q * m_col[0 : NPAIRS - 1]
        t[i + 1] += t[i] >> PAIR_BITS
    out = t[NPAIRS : 2 * NPAIRS].copy()
    for i in range(NPAIRS - 1):
        out[i + 1] += out[i] >> PAIR_BITS
        out[i] &= PAIR_MASK
    return out


def _mul_kernel_ref_p256(
    a: "PairMatL32",
    b: "PairMatL4",
    bias: "BiasMat",
) -> "np.ndarray":
    """Reference form of the P-256 shift-REDC fast path (see
    _P256_REDC_TERMS below): q*p collapses to four shifted ADDS per
    REDC iteration — the decomposition's one negative term rides the
    complement (q ^ PAIR_MASK) << 16 and the statically-known over-add
    is cancelled by the `bias` constant (K*p minus the over-add total,
    canonical limbs), keeping every column op non-negative and the
    whole sweep inside the interval domain with no suppression."""
    lanes = a.shape[1]
    t = np.zeros((2 * NPAIRS, lanes), dtype=np.uint64)
    for i in range(NPAIRS):
        t[i : i + NPAIRS] += a[i] * b
    t += bias
    for i in range(NPAIRS):
        q = t[i] & PAIR_MASK
        t[i + 1] += t[i] >> PAIR_BITS
        t[i + 3] += q << 18
        t[i + 7] += q << 10
        t[i + 8] += (q ^ PAIR_MASK) << 16  # -(q<<16) via complement+bias
        t[i + 9] += q << 22
    out = t[NPAIRS : 2 * NPAIRS].copy()
    for i in range(NPAIRS - 1):
        out[i + 1] += out[i] >> PAIR_BITS
        out[i] &= PAIR_MASK
    return out


class _WS:
    """Per-width kernel workspace (one per (field, lanes) pair, reused
    across every multiply of a batch pass — the kernels allocate
    nothing but their output row block)."""

    def __init__(self, lanes: int):
        self.t = np.empty((2 * NPAIRS, lanes), dtype=np.uint64)
        self.tmp = np.empty((NPAIRS, lanes), dtype=np.uint64)
        self.tmp2 = np.empty((NPAIRS, lanes), dtype=np.uint64)
        self.q = np.empty(lanes, dtype=np.uint64)
        self.c = np.empty(lanes, dtype=np.uint64)
        self.w = np.empty(lanes, dtype=np.uint64)


# p = 2^256 - 2^224 + 2^192 + 2^96 - 1: q*p decomposes into FIVE signed
# shifted copies of q instead of an 11-row MAC (the pair-radix global
# analog of the device kernel's per-limb qm_term shift decomposition).
# In 2^26 columns relative to the REDC row i:
#   -q           at col i+0   (absorbed: q IS t[i]'s low bits, and the
#                              carry (t[i] - q) >> 26 == t[i] >> 26)
#   +q << 18     at col i+3   (the +2^96 term;  96 == 3*26 + 18)
#   +q << 10     at col i+7   (the +2^192 term; 192 == 7*26 + 10)
#   -q << 16     at col i+8   (the -2^224 term; 224 == 8*26 + 16)
#   +q << 22     at col i+9   (the +2^256 term; 256 == 9*26 + 22)
# The one negative term is applied as the complement
# (PAIR_MASK - q) << 16 — an unconditional ADD — and the constant
# over-add that introduces is cancelled by a bias constant K*p - E
# (built in _NpMont, canonical limbs) pre-loaded into the accumulator:
# the net extra value is exactly K*p ≡ 0 (mod p), K*p/R < m * 2^-31,
# so the output bound stays < 2m and no column ever underflows.
# _P256_REDC_TERMS is validated against p at context build; the kernel
# below hardcodes it for the static proof.
_P256_REDC_TERMS = ((3, 18, 1), (7, 10, 1), (8, 16, -1), (9, 22, 1))


def _redc_rows_p256(t: "AccMat", q, c, w) -> None:
    """REDC sweep specialized to P-256's p (m0inv == 1, the shift
    decomposition above).  The -2^224 term rides the complement
    (PAIR_MASK - q) << 16 — a pure ADD — with the constant over-add
    folded into the kernel's bias, so every op stays non-negative.
    Each iteration adds at most q << 22 < 2^48 per column on top of
    the MAC bound — margin unchanged."""
    for i in range(NPAIRS):
        q = np.bitwise_and(t[i], PAIR_MASK, out=q)
        c = np.right_shift(t[i], PAIR_BITS, out=c)
        t[i + 1] += c
        w = np.left_shift(q, 18, out=w)
        t[i + 3] += w
        w = np.left_shift(q, 10, out=w)
        t[i + 7] += w
        w = np.bitwise_xor(q, PAIR_MASK, out=w)  # PAIR_MASK - q
        w = np.left_shift(w, 16, out=w)
        t[i + 8] += w
        w = np.left_shift(q, 22, out=w)
        t[i + 9] += w


def _redc_rows(t, m_col, m0inv, blocks, tmp, q, c):
    """The limb-serial REDC sweep shared by every kernel variant: for
    each of the NPAIRS iterations, derive the quotient digit from the
    (exact) low bits of t[i], MAC q*m onto the nonzero row blocks of
    the modulus, and shift the retired limb's carry up.  m0inv == 1
    (P-256's p ≡ -1 mod 2^26) makes the quotient digit free, the same
    specialization the device kernel's qm_term exploits."""
    for i in range(NPAIRS):
        if m0inv == 1:
            q = np.bitwise_and(t[i], PAIR_MASK, out=q)
        else:
            q = np.bitwise_and(t[i], PAIR_MASK, out=q)
            q = np.multiply(q, m0inv, out=q)
            q = np.bitwise_and(q, PAIR_MASK, out=q)
        for lo, hi in blocks:
            w = tmp[0 : hi - lo]
            w = np.multiply(q, m_col[lo:hi], out=w)
            t[i + lo : i + hi] += w
        c = np.right_shift(t[i], PAIR_BITS, out=c)
        t[i + 1] += c


def _finish(t, c) -> "np.ndarray":
    """Copy out the high half and carry-propagate to canonical limbs
    (the spare top pair-limb absorbs the spill: values < 2^30 * m)."""
    out = t[NPAIRS : 2 * NPAIRS].copy()
    for i in range(NPAIRS - 1):
        c = np.right_shift(out[i], PAIR_BITS, out=c)
        out[i + 1] += c
        out[i] &= PAIR_MASK
    return out


def _mul_kernel(
    a: "PairMatL32",
    b: "PairMatL4",
    m_col: "PairMat",
    m0inv: int,
    blocks=((0, NPAIRS - 1),),
    ws: Optional[_WS] = None,
    bias=None,
    bias_rows=(0, 0),
) -> "np.ndarray":
    """Montgomery product a*b*R^-1 mod m on pair-limb matrices.

    Static headroom proof (mechanized by tools/fabflow over this very
    loop): with a's limbs <= 32*2^26 and b's <= 4*2^26, each product
    row adds at most 2^31 * 2^28 = 2^59 per column; the 11-row MAC
    worst case is NPAIRS * 2^59 < 2^62.46, the REDC rows add
    NPAIRS * 2^26 * 2^26 = 2^55.46 more and each shifted-down carry at
    most 2^36.5 — total < 2^62.5, a 2.8x margin under the uint64
    accumulator.  Widening a's contract to match b's 2^31 (both lazy)
    would push the MAC term past 2^64: `fe_mul` carries the second
    operand first for exactly this reason.
    """
    if ws is None:
        ws = _WS(a.shape[1])
    t, tmp = ws.t, ws.tmp
    # first MAC row writes straight into t, so only the tail zeroes
    np.multiply(a[0], b, out=t[0:NPAIRS])
    t[NPAIRS : 2 * NPAIRS] = 0
    for i in range(1, NPAIRS):
        tmp = np.multiply(a[i], b, out=tmp)
        t[i : i + NPAIRS] += tmp
    if bias is not None:
        lo, hi = bias_rows
        t[lo:hi] += bias[lo:hi]
        _redc_rows_p256(t, ws.q, ws.c, ws.w)
    else:
        _redc_rows(t, m_col, m0inv, blocks, tmp, ws.q, ws.c)
    return _finish(t, ws.c)


def _sqr_kernel(
    a: "PairMatL4",
    m_col: "PairMat",
    m0inv: int,
    blocks=((0, NPAIRS - 1),),
    ws: Optional[_WS] = None,
    bias=None,
    bias_rows=(0, 0),
) -> "np.ndarray":
    """Montgomery square: the off-diagonal half of the product MAC is
    folded through a doubled operand (d = a + a <= 2^29 per limb), so
    the worst column is a[i]^2 + sum d[i]*a[j] <= 2^56 + 10 * 2^57
    < 2^60.4 — comfortably under the `_mul_kernel` bound."""
    if ws is None:
        ws = _WS(a.shape[1])
    t = ws.t
    d = np.add(a, a, out=ws.tmp)  # consumed row by row below
    t[:] = 0
    for i in range(NPAIRS):
        q = np.multiply(a[i], a[i], out=ws.q)
        t[2 * i] += q
        if i + 1 < NPAIRS:
            w = ws.tmp2[0 : NPAIRS - 1 - i]
            w = np.multiply(d[i], a[i + 1 :], out=w)
            t[2 * i + 1 : i + NPAIRS] += w
    if bias is not None:
        lo, hi = bias_rows
        t[lo:hi] += bias[lo:hi]
        _redc_rows_p256(t, ws.q, ws.c, ws.w)
    else:
        _redc_rows(t, m_col, m0inv, blocks, ws.tmp2, ws.q, ws.c)
    return _finish(t, ws.c)


def _carry_kernel(x: "PairMatL32") -> "np.ndarray":
    """In-place carry propagation to canonical (< 2^26) limbs.  The top
    pair-limb absorbs the spill: values here are < 2^30 * m < 2^286, so
    it stays <= PAIR_MASK."""
    for i in range(NPAIRS - 1):
        x[i + 1] += x[i] >> PAIR_BITS
        x[i] &= PAIR_MASK
    return x


def _cond_sub_kernel(x: "PairMat", m_col: "PairMat") -> "np.ndarray":
    """x - m where x >= m else x, on canonical limbs (device
    cond_sub_l's shape: int64 borrow chain, arithmetic shifts)."""
    d = x.astype(np.int64) - m_col.astype(np.int64)
    c = np.zeros(x.shape[1], dtype=np.int64)
    limbs = []
    for i in range(NPAIRS):
        v = d[i] + c
        c = v >> PAIR_BITS
        limbs.append(v & PAIR_MASK)
    keep = c < 0  # borrow out -> x < m
    out = np.empty_like(x)
    for i in range(NPAIRS):
        out[i] = np.where(keep, x[i], limbs[i].astype(np.uint64))
    return out


# ---------------------------------------------------------------------------
# Bound-tracked field elements
# ---------------------------------------------------------------------------


class _FE:
    """A batch of field values as a (NPAIRS, lanes) uint64 matrix with
    exact tracked bounds: value < vb * m, limbs 0..NPAIRS-2 <= lb, the
    spare top limb <= tb (nonzero only through K-chain spill).  The
    bounds are Python ints shared by all lanes (the schedule is
    lane-uniform), recomputed per abstract op — the runtime mirror of
    the fabflow proof."""

    __slots__ = ("limbs", "vb", "lb", "tb")

    def __init__(self, limbs, vb: int, lb: int, tb: int = 0):
        self.limbs = limbs
        self.vb = vb
        self.lb = lb
        self.tb = tb

    def copy(self) -> "_FE":
        return _FE(self.limbs.copy(), self.vb, self.lb, self.tb)


class _Field:
    """Field ops over a _NpMont context with automatic carry-on-demand.
    Instances are per-batch-pass (not shared across threads): they own
    the kernel workspaces."""

    def __init__(self, ctx: _NpMont):
        self.ctx = ctx
        self._ws: dict = {}

    def ws(self, lanes: int) -> _WS:
        w = self._ws.get(lanes)
        if w is None:
            w = _WS(lanes)
            self._ws[lanes] = w
        return w

    def kmul(self, a_limbs, b_limbs) -> "np.ndarray":
        """Raw kernel product on canonical-contract limb matrices."""
        return _mul_kernel(
            a_limbs,
            b_limbs,
            self.ctx.m_col,
            self.ctx.m0inv,
            self.ctx.mac_blocks,
            self.ws(a_limbs.shape[1]),
            self.ctx.p256_bias,
            self.ctx.bias_rows,
        )

    def fe(self, limbs, vb: int = 2, lb: int = PAIR_MASK) -> _FE:
        return _FE(limbs, vb, lb)

    def const_int(self, x: int, lanes: int, mont: bool = True) -> _FE:
        """A broadcast constant (optionally converted to the Montgomery
        domain via one multiply by R^2)."""
        if mont:
            x = (x * R_MONT) % self.ctx.m
        col = self.ctx.to_limbs(x)
        return _FE(
            np.broadcast_to(col, (NPAIRS, lanes)).copy(), 1, PAIR_MASK
        )

    def carried(self, x: _FE) -> _FE:
        if x.lb <= PAIR_MASK and x.tb <= PAIR_MASK:
            return x
        if x.vb >= 1 << 25:  # top pair-limb would spill (value >= 2^285)
            raise ArithmeticError(f"value bound {x.vb}m too lax to carry")
        return _FE(
            _carry_kernel(x.limbs.copy()),
            x.vb,
            PAIR_MASK,
            (x.vb * self.ctx.m) >> RADIX_BITS,
        )

    def mul(self, x: _FE, y: _FE) -> _FE:
        # laziest operand first; carry whatever exceeds the proven
        # kernel contract (never raises: carrying is always available)
        if max(x.lb, x.tb) < max(y.lb, y.tb):
            x, y = y, x
        if max(y.lb, y.tb) > L4_BOUND:
            y = self.carried(y)
        if max(x.lb, x.tb) > L32_BOUND:
            x = self.carried(x)
        if x.vb * y.vb >= 1 << 30:
            raise ArithmeticError(
                f"montgomery input bound exceeded: {x.vb}m * {y.vb}m"
            )
        return _FE(self.kmul(x.limbs, y.limbs), 2, PAIR_MASK)

    def sqr(self, x: _FE) -> _FE:
        if max(x.lb, x.tb) > L4_BOUND:
            x = self.carried(x)
        if x.vb * x.vb >= 1 << 30:
            raise ArithmeticError(f"montgomery input bound exceeded: {x.vb}m^2")
        out = _sqr_kernel(
            x.limbs,
            self.ctx.m_col,
            self.ctx.m0inv,
            self.ctx.mac_blocks,
            self.ws(x.limbs.shape[1]),
            self.ctx.p256_bias,
            self.ctx.bias_rows,
        )
        return _FE(out, 2, PAIR_MASK)

    def add(self, x: _FE, y: _FE) -> _FE:
        return _FE(
            x.limbs + y.limbs, x.vb + y.vb, x.lb + y.lb, x.tb + y.tb
        )

    def sub(self, x: _FE, y: _FE) -> _FE:
        """x - y + k*m with k the least power of two covering y's value
        bound AND the limb-floor redistribution, so the limbwise
        subtraction never borrows."""
        if y.lb > L4_BOUND or y.tb > L4_BOUND:
            y = self.carried(y)
        col, k, maxlimb, top = self.ctx.sub_k(y.lb, y.tb, y.vb)
        return _FE(
            x.limbs + (col - y.limbs),
            x.vb + k,
            x.lb + maxlimb,
            x.tb + top,
        )

    def scale(self, x: _FE, c: int) -> _FE:
        """c*x for small c via the uint64 product (c <= 16 keeps any
        canonical-or-lazy operand far inside the accumulator)."""
        if c * x.lb >= 1 << 62:
            x = self.carried(x)
        return _FE(x.limbs * np.uint64(c), x.vb * c, x.lb * c, x.tb * c)

    def select(self, cond, x: _FE, y: _FE) -> _FE:
        """Lanewise cond ? x : y (cond is a (lanes,) bool array)."""
        return _FE(
            np.where(cond, x.limbs, y.limbs),
            max(x.vb, y.vb),
            max(x.lb, y.lb),
            max(x.tb, y.tb),
        )

    def renorm2(self, x: _FE) -> _FE:
        """Bring the value bound back under 2m (Montgomery-multiply by
        the domain's one: yR * R * R^-1 = yR, value preserved)."""
        if x.vb <= 2:
            return x
        lanes = x.limbs.shape[1]
        one = _FE(
            np.broadcast_to(
                self.ctx.to_limbs(self.ctx.one_mont_int), (NPAIRS, lanes)
            ).copy(),
            1,
            PAIR_MASK,
        )
        return self.mul(x, one)

    def is_zero_mod(self, x: _FE):
        """Lanes where x ≡ 0 (mod m): after renormalizing to < 2m and
        carrying, exactly the lanes whose limbs are all zero or all
        equal m's."""
        x = self.carried(self.renorm2(x))
        z = (x.limbs == 0).all(axis=0)
        e = (x.limbs == self.ctx.m_col).all(axis=0)
        return z | e

    def to_ints(self, x: _FE, from_mont: bool = True) -> List[int]:
        """Exact per-lane Python ints (mod m)."""
        x = self.carried(x)
        m = self.ctx.m
        rinv = self.ctx.rinv if from_mont else 1
        arr = x.limbs
        return [
            (_pairs_to_int(arr[:, j]) * rinv) % m
            for j in range(arr.shape[1])
        ]


# ---------------------------------------------------------------------------
# Tree batch inversion (Montgomery's trick with lane pairing)
# ---------------------------------------------------------------------------


def _invert_lanes(field: _Field, x: _FE) -> _FE:
    """Per-lane modular inverse of a Montgomery-domain batch in O(log
    lanes) vector multiplies: pair lanes level by level, invert the
    single root with one Python pow, walk back down.  Zero lanes come
    back zero (callers mask them), without poisoning the tree."""
    ctx = field.ctx
    x = field.carried(field.renorm2(x))
    lanes = x.limbs.shape[1]
    zero = field.is_zero_mod(x)
    one = ctx.to_limbs(ctx.one_mont_int)
    vals = np.where(zero, one, x.limbs)

    levels = []  # (even, odd, tail_or_None)
    cur = vals
    while cur.shape[1] > 1:
        w = cur.shape[1]
        even = cur[:, 0 : w - 1 : 2]
        odd = cur[:, 1:w:2]
        tail = cur[:, w - 1 : w] if w % 2 else None
        nxt = field.kmul(
            np.ascontiguousarray(even), np.ascontiguousarray(odd)
        )
        if tail is not None:
            nxt = np.concatenate([nxt, tail], axis=1)
        levels.append((even, odd, tail))
        cur = nxt

    root = _pairs_to_int(cur[:, 0])
    root_val = (root * ctx.rinv) % ctx.m
    inv_mont = (pow(root_val, ctx.m - 2, ctx.m) * R_MONT) % ctx.m
    inv = ctx.to_limbs(inv_mont)

    for even, odd, tail in reversed(levels):
        pair_inv = inv if tail is None else inv[:, :-1]
        inv_even = field.kmul(
            np.ascontiguousarray(pair_inv), np.ascontiguousarray(odd)
        )
        inv_odd = field.kmul(
            np.ascontiguousarray(pair_inv), np.ascontiguousarray(even)
        )
        w = even.shape[1] + odd.shape[1] + (0 if tail is None else 1)
        nxt = np.empty((NPAIRS, w), dtype=np.uint64)
        nxt[:, 0 : w - 1 if tail is not None else w : 2] = inv_even
        nxt[:, 1 : w : 2] = inv_odd
        if tail is not None:
            nxt[:, w - 1] = inv[:, -1]
        inv = nxt

    out = np.where(zero, np.zeros((NPAIRS, 1), dtype=np.uint64), inv)
    return _FE(np.ascontiguousarray(out), 2, PAIR_MASK)


# ---------------------------------------------------------------------------
# Packing: Python ints <-> radix-2^13 interchange <-> pair rows
# ---------------------------------------------------------------------------


def ints_to_limbs13(xs: Sequence[int]) -> "np.ndarray":
    """Batch of ints -> the (lanes, NLIMBS) uint64 radix-2^13 batch
    interchange matrix, via one bytes pass (no per-limb Python loop
    over lanes)."""
    lanes = len(xs)
    raw = b"".join(x.to_bytes((RADIX_BITS + 7) // 8, "little") for x in xs)
    nbytes = (RADIX_BITS + 7) // 8
    u8 = np.frombuffer(raw, dtype=np.uint8).reshape(lanes, nbytes)
    out = np.empty((lanes, NLIMBS), dtype=np.uint64)
    for j in range(NLIMBS):
        bit = j * LIMB_BITS
        k, off = bit // 8, bit % 8
        word = u8[:, k].astype(np.uint64) | (
            u8[:, k + 1].astype(np.uint64) << np.uint64(8)
        )
        if k + 2 < nbytes:
            word |= u8[:, k + 2].astype(np.uint64) << np.uint64(16)
        out[:, j] = (word >> np.uint64(off)) & np.uint64(LIMB_MASK)
    return out


def limbs13_to_pairs(limbs: "np.ndarray") -> "np.ndarray":
    """(lanes, NLIMBS) radix-2^13 interchange -> (NPAIRS, lanes) compute
    rows (adjacent limbs condensed; spare top pair-limb zero)."""
    lanes = limbs.shape[0]
    out = np.zeros((NPAIRS, lanes), dtype=np.uint64)
    for i in range(NLIMBS // 2):
        out[i] = limbs[:, 2 * i] | (
            limbs[:, 2 * i + 1] << np.uint64(LIMB_BITS)
        )
    return out


def pairs_to_limbs13(pairs: "np.ndarray") -> "np.ndarray":
    """Canonical (NPAIRS, lanes) pair rows -> (lanes, NLIMBS) radix-2^13
    interchange (values must fit RADIX_BITS, i.e. be fully reduced)."""
    lanes = pairs.shape[1]
    out = np.empty((lanes, NLIMBS), dtype=np.uint64)
    for i in range(NLIMBS // 2):
        out[:, 2 * i] = pairs[i] & np.uint64(LIMB_MASK)
        out[:, 2 * i + 1] = pairs[i] >> np.uint64(LIMB_BITS)
    return out


# ---------------------------------------------------------------------------
# Jacobian group law (hostec's formulas, bound-tracked)
# ---------------------------------------------------------------------------

Jac = Tuple[_FE, _FE, _FE]


def _dbl_vec(field: _Field, X: _FE, Y: _FE, Z: _FE) -> Jac:
    """dbl-2001-b (a = -3): 3M + 5S, matching hostec's _dbl_vec."""
    delta = field.sqr(Z)
    gamma = field.sqr(Y)
    beta = field.mul(X, gamma)
    t1 = field.sub(X, delta)
    t2 = field.add(X, delta)
    mm = field.mul(t1, t2)
    alpha = field.add(field.add(mm, mm), mm)
    X3 = field.sub(field.sqr(alpha), field.scale(beta, 8))
    Z3 = field.sub(
        field.sub(field.sqr(field.add(Y, Z)), gamma), delta
    )
    Y3 = field.sub(
        field.mul(alpha, field.sub(field.scale(beta, 4), X3)),
        field.scale(field.sqr(gamma), 8),
    )
    return X3, Y3, Z3


def _madd_vec(
    field: _Field, X: _FE, Y: _FE, Z: _FE, x2: _FE, y2: _FE
) -> Tuple[_FE, _FE, _FE, "np.ndarray"]:
    """Mixed Jacobian+affine add (8M + 3S), hostec's _madd_vec formulas.
    Returns (X3, Y3, Z3, exceptional) where `exceptional` marks lanes
    with Z3 ≡ 0 mod p (P = infinity, P = +-Q) that the caller must
    patch scalar-wise."""
    ZZ = field.sqr(Z)
    U2 = field.mul(x2, ZZ)
    S2 = field.mul(y2, field.mul(Z, ZZ))
    H = field.sub(U2, X)
    Rr = field.sub(S2, Y)
    H = field.carried(H)
    HH = field.sqr(H)
    HHH = field.mul(H, HH)
    V = field.mul(X, HH)
    X3 = field.sub(
        field.sub(field.sqr(Rr), HHH), field.add(V, V)
    )
    Y3 = field.sub(
        field.mul(Rr, field.sub(V, X3)), field.mul(Y, HHH)
    )
    Z3 = field.mul(Z, H)
    return X3, Y3, Z3, field.is_zero_mod(Z3)


def _patch_exceptional(
    field: _Field,
    flag: "np.ndarray",
    jac: Jac,
    X3: _FE,
    Y3: _FE,
    Z3: _FE,
    ax: _FE,
    ay: _FE,
    inf_out: Optional["np.ndarray"] = None,
) -> Jac:
    """Recompute flagged lanes through hostec's scalar _madd1 in plain
    ints (adversarially reachable, never hot), writing the results back
    into the vector state.  A patched lane whose result is the identity
    (P = -Q) is recorded in `inf_out` when given."""
    if not bool(flag.any()):
        return X3, Y3, Z3
    m = field.ctx.m
    rinv = field.ctx.rinv
    X, Y, Z = (field.carried(v) for v in jac)
    axc, ayc = field.carried(ax), field.carried(ay)
    X3 = field.carried(X3)
    Y3 = field.carried(Y3)
    Z3 = field.carried(Z3)
    for j in np.nonzero(flag)[0]:
        lane = int(j)

        def unm(fe: _FE) -> int:
            return (_pairs_to_int(fe.limbs[:, lane]) * rinv) % m

        nx, ny, nz = hostec._madd1(
            unm(X), unm(Y), unm(Z), unm(axc), unm(ayc)
        )
        if inf_out is not None and nz % m == 0:
            inf_out[lane] = True
        for fe, v in ((X3, nx), (Y3, ny), (Z3, nz)):
            fe.limbs[:, lane] = _ctx(m).to_limbs((v * R_MONT) % m)[:, 0]
    return X3, Y3, Z3


def _select_jac(
    field: _Field, cond: "np.ndarray", new: Jac, old: Jac
) -> Jac:
    return (
        field.select(cond, new[0], old[0]),
        field.select(cond, new[1], old[1]),
        field.select(cond, new[2], old[2]),
    )


# ---------------------------------------------------------------------------
# Scalar digit schedules (lane-shared wNAF(5) for Q, w10 comb for G)
# ---------------------------------------------------------------------------

Q_WINDOW_BITS = 5
# scalars are < 2n < 2^257: ceil(257 / 5) = 52 windows cover every bit
NUM_Q_WINDOWS = (257 + Q_WINDOW_BITS - 1) // Q_WINDOW_BITS
G_WINDOW_BITS = 2 * Q_WINDOW_BITS  # 10: one G window per two rounds
NUM_G_WINDOWS = 26


def _extract_windows(
    pairs: "np.ndarray", width: int, count: int
) -> List["np.ndarray"]:
    """Unsigned `width`-bit windows of a canonical pair-limb batch,
    little-endian window order, each an int64 (lanes,) array."""
    mask = np.int64((1 << width) - 1)
    out = []
    for w in range(count):
        bit = w * width
        i, off = bit // PAIR_BITS, bit % PAIR_BITS
        word = pairs[i] >> np.uint64(off)
        if off + width > PAIR_BITS and i + 1 < NPAIRS:
            word = word | (pairs[i + 1] << np.uint64(PAIR_BITS - off))
        out.append(word.astype(np.int64) & mask)
    return out


def _signed_digits(windows: List["np.ndarray"]) -> List["np.ndarray"]:
    """Unsigned base-32 digits -> signed digits in [-15, 16] (the
    lane-shared regular wNAF(5) recoding): d > 16 becomes d - 32 with a
    carry into the next window.  The top window of a < 2^257 scalar is
    <= 4, so the final carry never overflows."""
    out = []
    carry = np.zeros_like(windows[0])
    for d in windows:
        d = d + carry
        neg = d > 16
        carry = neg.astype(np.int64)
        out.append(d - (carry << np.int64(Q_WINDOW_BITS)))
    if int(out[-1].min()) < 0 or int(out[-1].max()) > 16:
        raise ArithmeticError("wNAF top-window carry overflowed")
    return out


# ---------------------------------------------------------------------------
# Fixed-base G comb (lazy global tables, Montgomery domain)
# ---------------------------------------------------------------------------

_G_COMB_NP = None
_G_TABLE_LOCK = threading.Lock()

G_TABLE_ENTRIES = (1 << G_WINDOW_BITS) - 1  # 1023


def _build_g_comb():
    """(G_TABLE_ENTRIES, NPAIRS) uint64 per coordinate: affine d * G in
    the Montgomery domain, d in 1..1023 (index d - 1).  The window
    depth 2^(10w) rides the shared doubling chain — the comb table
    itself is depth-free, exactly like hostec's Horner table, just
    wider.  Built once in plain Python ints via hostec's scalar helpers
    plus one Montgomery batch inversion, then packed."""
    jac: List[Tuple[int, int, int]] = [(GX, GY, 1)]
    for _d in range(G_TABLE_ENTRIES - 1):
        Xr, Yr, Zr = jac[-1]
        jac.append(hostec._madd1(Xr, Yr, Zr, GX, GY))
    aff = hostec._normalize_jacobians(jac)
    xs = ints_to_limbs13([(x * R_MONT) % P for x, _ in aff])
    ys = ints_to_limbs13([(y * R_MONT) % P for _, y in aff])
    gx = np.ascontiguousarray(limbs13_to_pairs(xs).T)
    gy = np.ascontiguousarray(limbs13_to_pairs(ys).T)
    return gx, gy, G_TABLE_ENTRIES


def _g_comb():
    global _G_COMB_NP
    if _G_COMB_NP is None:
        with _G_TABLE_LOCK:
            if _G_COMB_NP is None:
                _G_COMB_NP = _build_g_comb()
    return _G_COMB_NP


def warm_tables() -> None:
    """Build the fixed-base comb now (e.g. before forking pool workers)."""
    if HAVE_NUMPY:
        _g_comb()
    hostec.warm_tables()


# ---------------------------------------------------------------------------
# Core batch verification
# ---------------------------------------------------------------------------


# test/debug seam: when set, called after every Horner add with
# (kind, round, RX, RY, RZ, acc_inf); tests use it to pin per-round
# accumulator state against the scalar oracle
_DEBUG_HOOK = None


# ONE precheck for the whole ladder: the tiers' accept/reject sets are
# a bit-exactness contract, so the per-lane precheck lives in hostec
# and is shared, never mirrored.
_precheck_lanes = hostec._precheck_lanes


def _verify_packed(
    valid: "np.ndarray",
    rr13: "np.ndarray",
    ss13: "np.ndarray",
    qx13: "np.ndarray",
    qy13: "np.ndarray",
    ee13: "np.ndarray",
) -> "np.ndarray":
    """The matrix engine proper: (lanes, NLIMBS) radix-2^13 interchange
    matrices in, verdict uint8 lanes out.  This is the function shard
    workers run against shared memory."""
    lanes = rr13.shape[0]
    fp = _Field(_ctx(P))
    fn = _Field(_ctx(N))

    # ---- u1 = e/s, u2 = r/s (mod n): one tree inversion for every s
    s_m = fn.mul(_FE(limbs13_to_pairs(ss13), 1, PAIR_MASK), fn.fe(
        np.broadcast_to(fn.ctx.r2, (NPAIRS, lanes)).copy(), 1, PAIR_MASK
    ))
    w = _invert_lanes(fn, s_m)
    e_m = fn.mul(_FE(limbs13_to_pairs(ee13), 1, PAIR_MASK), fn.fe(
        np.broadcast_to(fn.ctx.r2, (NPAIRS, lanes)).copy(), 1, PAIR_MASK
    ))
    r_pairs = limbs13_to_pairs(rr13)
    r_m = fn.mul(_FE(r_pairs.copy(), 1, PAIR_MASK), fn.fe(
        np.broadcast_to(fn.ctx.r2, (NPAIRS, lanes)).copy(), 1, PAIR_MASK
    ))
    # from_mont via a multiply by 1 (the u digits only need the value
    # mod n up to one extra n: (u + n) * Q = u * Q)
    one_col = fn.ctx.to_limbs(1)
    one_b = _FE(np.broadcast_to(one_col, (NPAIRS, lanes)).copy(), 1, PAIR_MASK)
    u1 = fn.carried(fn.mul(fn.mul(e_m, w), one_b))
    u2 = fn.carried(fn.mul(fn.mul(r_m, w), one_b))

    q_digits = _signed_digits(
        _extract_windows(u2.limbs, Q_WINDOW_BITS, NUM_Q_WINDOWS)
    )
    g_digits = _extract_windows(u1.limbs, G_WINDOW_BITS, NUM_G_WINDOWS)

    # ---- per-lane Q table: 1..16 times Q, affine Montgomery, one tree
    # ---- inversion across (16 * lanes)
    r2_b = fp.fe(np.broadcast_to(fp.ctx.r2, (NPAIRS, lanes)).copy(), 1, PAIR_MASK)
    Qx = fp.mul(_FE(limbs13_to_pairs(qx13), 1, PAIR_MASK), r2_b)
    Qy = fp.mul(_FE(limbs13_to_pairs(qy13), 1, PAIR_MASK), r2_b)
    tab_jac: List[Jac] = [(Qx, Qy, None)]  # None Z = affine (Z = 1)
    one_mont = fp.const_int(1, lanes)
    d2 = _dbl_vec(fp, Qx, Qy, one_mont)
    tab_jac.append(d2)
    for _d in range(3, 17):
        Xp, Yp, Zp = tab_jac[-1]
        X3, Y3, Z3, exc = _madd_vec(fp, Xp, Yp, Zp, Qx, Qy)
        # d*Q is never the identity for d <= 16 (prime group order), and
        # P = +-Q cannot occur between d*Q and Q for d >= 2 — but a
        # malicious "point" that slipped the curve check cannot reach
        # here (precheck), so exc must be empty; patch defensively.
        X3, Y3, Z3 = _patch_exceptional(
            fp, exc, (Xp, Yp, Zp), X3, Y3, Z3, Qx, Qy
        )
        tab_jac.append((X3, Y3, Z3))

    z_fes = [
        (t[2] if t[2] is not None else one_mont) for t in tab_jac[1:]
    ]
    zs = np.concatenate([z.limbs for z in z_fes], axis=1)
    # the stacked FE carries the entries' TRUE tracked bounds (the 2Q
    # entry is a lazy _dbl_vec output): _invert_lanes then renormalizes
    # and carries before its kernels, keeping the L4/L32 contracts real
    zinv = _invert_lanes(
        fp,
        _FE(
            np.ascontiguousarray(zs),
            max(z.vb for z in z_fes),
            max(z.lb for z in z_fes),
            max(z.tb for z in z_fes),
        ),
    )
    tqx = np.empty((16, lanes, NPAIRS), dtype=np.uint64)
    tqy = np.empty((32, lanes, NPAIRS), dtype=np.uint64)
    Qxc, Qyc = fp.carried(Qx), fp.carried(Qy)
    tqx[0] = Qxc.limbs.T
    tqy[0] = Qyc.limbs.T
    neg_col, neg_k, neg_max, neg_top = fp.ctx.sub_k(PAIR_MASK, 0, 2)
    tqy[16] = (neg_col - Qyc.limbs).T  # -Q: (x, k*p - y), lazy limbs ok
    for t in range(1, 16):
        zi = _FE(
            np.ascontiguousarray(zinv.limbs[:, (t - 1) * lanes : t * lanes]),
            2,
            PAIR_MASK,
        )
        zi2 = fp.sqr(zi)
        ax = fp.carried(fp.mul(tab_jac[t][0], zi2))
        ay = fp.carried(fp.mul(tab_jac[t][1], fp.mul(zi2, zi)))
        tqx[t] = ax.limbs.T
        tqy[t] = ay.limbs.T
        tqy[16 + t] = (neg_col - ay.limbs).T

    gx_tab, gy_tab, _n = _g_comb()

    # ---- joint Horner: 5 doublings per round; Q digit every round, G
    # ---- digit every second round (w10 comb) — every lane walks the
    # ---- same schedule, digit-0 lanes compute and discard via select
    zero_lane = np.zeros((NPAIRS, lanes), dtype=np.uint64)
    RX = _FE(zero_lane.copy(), 1, PAIR_MASK)
    RY = fp.const_int(1, lanes)
    RZ = _FE(zero_lane.copy(), 1, PAIR_MASK)
    one_mont_fe = fp.const_int(1, lanes)
    # acc = infinity (Z ≡ 0) is the COMMON exceptional case — every lane
    # starts there — so it rides a vectorized select; only genuine
    # P = +-Q collisions (adversarially reachable, never hot) take the
    # scalar patch path.
    acc_inf = np.ones(lanes, dtype=bool)

    def add_affine(RX, RY, RZ, acc_inf, ax, ay, active):
        NX, NY, NZ, exc = _madd_vec(fp, RX, RY, RZ, ax, ay)
        patched_inf = np.zeros_like(acc_inf)
        NX, NY, NZ = _patch_exceptional(
            fp,
            exc & active & ~acc_inf,
            (RX, RY, RZ),
            NX,
            NY,
            NZ,
            ax,
            ay,
            inf_out=patched_inf,
        )
        fresh = acc_inf & active  # infinity + P = (ax, ay, 1)
        NX = fp.select(fresh, ax, NX)
        NY = fp.select(fresh, ay, NY)
        NZ = fp.select(fresh, one_mont_fe, NZ)
        RX, RY, RZ = _select_jac(fp, active, (NX, NY, NZ), (RX, RY, RZ))
        # infinity propagates as a flag (doubling preserves it; an
        # active add clears it unless the scalar patch produced P=-Q)
        new_inf = (acc_inf & ~active) | (active & patched_inf)
        return RX, RY, RZ, new_inf

    lane_idx = np.arange(lanes)
    for j in range(NUM_Q_WINDOWS):
        if j:
            for _ in range(Q_WINDOW_BITS):
                RX, RY, RZ = _dbl_vec(fp, RX, RY, RZ)
        d = q_digits[NUM_Q_WINDOWS - 1 - j]
        xsel = np.clip(np.abs(d) - 1, 0, 15)
        ysel = xsel + np.where(d < 0, 16, 0)
        ax = _FE(
            np.ascontiguousarray(tqx[xsel, lane_idx].T), 2, PAIR_MASK
        )
        ay = _FE(
            np.ascontiguousarray(tqy[ysel, lane_idx].T),
            neg_k,  # positive entries are < 2p; negated ones < neg_k*p
            neg_max,
            neg_top,
        )
        RX, RY, RZ, acc_inf = add_affine(
            RX, RY, RZ, acc_inf, ax, ay, d != 0
        )
        if _DEBUG_HOOK is not None:
            _DEBUG_HOOK("q", j, RX, RY, RZ, acc_inf)
        if j & 1:
            gw = (NUM_Q_WINDOWS - 1 - j) >> 1
            gd = g_digits[gw]
            gi = np.clip(gd - 1, 0, G_TABLE_ENTRIES - 1)
            ax = _FE(
                np.ascontiguousarray(gx_tab[gi].T), 2, PAIR_MASK
            )
            ay = _FE(
                np.ascontiguousarray(gy_tab[gi].T), 2, PAIR_MASK
            )
            RX, RY, RZ, acc_inf = add_affine(
                RX, RY, RZ, acc_inf, ax, ay, gd != 0
            )
            if _DEBUG_HOOK is not None:
                _DEBUG_HOOK("g", j, RX, RY, RZ, acc_inf)

    # ---- affine x(R) via one tree inversion; compare x mod n == r
    infinity = acc_inf
    zinv = _invert_lanes(fp, RZ)
    zi2 = fp.sqr(zinv)
    x_mont = fp.mul(fp.carried(RX), zi2)
    x_aff = fp.mul(x_mont, one_b)  # from Montgomery, < 2p canonical
    x_can = _cond_sub_kernel(fp.carried(x_aff).limbs, fp.ctx.m_col)
    # x mod n: x < p < 2n, so at most one subtract of n
    x_modn = _cond_sub_kernel(x_can, fn.ctx.m_col)
    ok = (x_modn == r_pairs).all(axis=0)
    return (ok & valid.astype(bool) & ~infinity).astype(np.uint8)


def verify_parsed_batch(
    lanes: Sequence[Tuple[PubKey, bytes, int, int]],
) -> List[bool]:
    """One matrix-engine pass over (pub, digest, r, s) lanes, all in
    THIS process.  Bit-exact with hostec.verify_parsed_batch / the
    oracle; the low-S rule is NOT applied here (same contract)."""
    if not HAVE_NUMPY:  # pragma: no cover - ladder skips this rung
        return hostec.verify_parsed_batch(lanes)
    nlanes = len(lanes)
    if nlanes == 0:
        return []
    valid, rr, ss, qx, qy, ee = _precheck_lanes(lanes)
    out = _verify_packed(
        np.array(valid, dtype=np.uint8),
        ints_to_limbs13(rr),
        ints_to_limbs13(ss),
        ints_to_limbs13(qx),
        ints_to_limbs13(qy),
        ints_to_limbs13(ee),
    )
    return [bool(v) for v in out]


# ---------------------------------------------------------------------------
# Shared-memory process-pool sharding
# ---------------------------------------------------------------------------

_POOL = None
_POOL_PROCS = 1
_POOL_LOCK = threading.Lock()
# rebuild cooldown after breakage (see hostec._POOL_GATE); mutated only
# under _POOL_LOCK
_POOL_GATE = CooldownGate()

_SHM_FIELDS = 5  # r, s, qx, qy, e limb matrices


def pool_procs() -> int:
    """Worker count (1 = pool disabled); FABRIC_TPU_HOSTEC_NP_PROCS
    overrides, falling back to hostec's FABRIC_TPU_HOSTEC_PROCS
    discipline (malformed values degrade to the default, never raise)."""
    procs = os.environ.get("FABRIC_TPU_HOSTEC_NP_PROCS", "")
    if procs:
        try:
            return max(int(procs), 1)
        except ValueError:
            pass
    return hostec.pool_procs()


def _pool():
    """Lazy shared ProcessPoolExecutor (forkserver/spawn preferred: the
    parent is multithreaded by the time big batches arrive).  Broken or
    unavailable pools degrade to inline compute, never die."""
    global _POOL, _POOL_PROCS
    with _POOL_LOCK:
        if _POOL is None:
            if not _POOL_GATE.ready():
                # recently broken: stay inline for the cooldown
                return None
            procs = pool_procs()
            _POOL_PROCS = procs
            if procs <= 1:
                _POOL = False
                return None
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            methods = multiprocessing.get_all_start_methods()
            start = os.environ.get("FABRIC_TPU_HOSTEC_START", "")
            if start not in methods:
                for start in ("forkserver", "spawn", "fork"):
                    if start in methods:
                        break
            try:
                _POOL = ProcessPoolExecutor(
                    max_workers=procs,
                    mp_context=multiprocessing.get_context(start),
                )
                fabobs.obs_count(
                    "fabric_pool_rebuilds_total", pool="hostec_np"
                )
            except Exception as exc:  # pragma: no cover - sandboxes
                logger.warning(
                    "process pool unavailable (%s); verifying inline", exc
                )
                _POOL = False
    return _POOL or None


def shutdown_pool(broken: bool = False) -> None:
    """Tear the pool down; ``broken=True`` arms the rebuild cooldown
    (degrade paths only — clean teardowns leave the gate closed)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL:
            _POOL.shutdown(wait=False, cancel_futures=True)
        _POOL = None
        if broken:
            _POOL_GATE.record_failure()
    if broken:
        fabobs.obs_count("fabric_pool_cooldowns_total", pool="hostec_np")
        fabobs.obs_count("fabric_degrade_total", seam="hostec_np.pool")
        fabobs.obs_trigger("hostec_np.pool_broken")


def _shard_worker(shm_name: str, nlanes: int, lo: int, hi: int) -> bool:
    """Runs in a pool worker: attach to the parent's shared-memory
    block, verify lanes [lo, hi), write verdict bytes into the result
    region.  Only (name, counts) crossed the pickle boundary."""
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        mat = np.ndarray(
            (_SHM_FIELDS, nlanes, NLIMBS), dtype=np.uint64, buffer=shm.buf
        )
        flags_off = _SHM_FIELDS * nlanes * NLIMBS * 8
        valid = np.ndarray(
            (nlanes,), dtype=np.uint8, buffer=shm.buf, offset=flags_off
        )
        verdict = np.ndarray(
            (nlanes,),
            dtype=np.uint8,
            buffer=shm.buf,
            offset=flags_off + nlanes,
        )
        sl = slice(lo, hi)
        verdict[sl] = _verify_packed(
            valid[sl].copy(),
            mat[0, sl].copy(),
            mat[1, sl].copy(),
            mat[2, sl].copy(),
            mat[3, sl].copy(),
            mat[4, sl].copy(),
        )
        return True
    finally:
        shm.close()


def verify_parsed_batch_sharded(
    lanes: Sequence[Tuple[PubKey, bytes, int, int]],
) -> Callable[[], List[bool]]:
    """Shard a parsed batch across the process pool through one
    shared-memory block; returns a resolver (call it for the verdicts)
    so callers can overlap host prep with shard execution.  Shards are
    slices of one verdict array: results are order-preserving by
    construction.

    Small batches delegate down-ladder to hostec (the matrix engine's
    fixed cost only pays off from ~NP_MIN_LANES up); mid-size batches
    run inline; a broken pool or shm failure degrades to inline compute
    — degrade, never die."""
    lanes = list(lanes)
    nlanes = len(lanes)
    if not HAVE_NUMPY or nlanes < _env_int(
        "FABRIC_TPU_HOSTEC_NP_MIN_LANES", NP_MIN_LANES
    ):
        return hostec.verify_parsed_batch_sharded(lanes)
    pool = _pool() if nlanes >= MIN_POOL_LANES else None
    if pool is None:
        out = verify_parsed_batch(lanes)
        return lambda: out

    valid, rr, ss, qx, qy, ee = _precheck_lanes(lanes)
    try:
        from multiprocessing import shared_memory

        size = _SHM_FIELDS * nlanes * NLIMBS * 8 + 2 * nlanes
        shm = shared_memory.SharedMemory(create=True, size=size)
    except Exception as exc:  # pragma: no cover - /dev/shm-less sandboxes
        logger.warning("shared memory unavailable (%s); inline verify", exc)
        out = verify_parsed_batch(lanes)
        return lambda: out

    mat = np.ndarray(
        (_SHM_FIELDS, nlanes, NLIMBS), dtype=np.uint64, buffer=shm.buf
    )
    for k, xs in enumerate((rr, ss, qx, qy, ee)):
        mat[k] = ints_to_limbs13(xs)
    flags_off = _SHM_FIELDS * nlanes * NLIMBS * 8
    valid_arr = np.ndarray(
        (nlanes,), dtype=np.uint8, buffer=shm.buf, offset=flags_off
    )
    valid_arr[:] = np.array(valid, dtype=np.uint8)
    verdict = np.ndarray(
        (nlanes,), dtype=np.uint8, buffer=shm.buf, offset=flags_off + nlanes
    )
    verdict[:] = 0

    nshards = min(_POOL_PROCS, max(nlanes // MIN_SHARD_LANES, 1))
    step = (nlanes + nshards - 1) // nshards
    try:
        fault_point("hostec_np.pool.submit")
        futures = [
            pool.submit(
                _shard_worker, shm.name, nlanes, off, min(off + step, nlanes)
            )
            for off in range(0, nlanes, step)
        ]
    except Exception as exc:  # BrokenProcessPool / shutdown race
        logger.warning("pool submit failed (%s); recomputing inline", exc)
        shutdown_pool(broken=True)
        shm.close()
        shm.unlink()
        out = verify_parsed_batch(lanes)
        return lambda: out

    memo: dict = {}

    def resolve() -> List[bool]:
        # memoized: the verdict array is a view over the shm buffer,
        # which the first call unmaps — a second resolve must return
        # the cached verdicts, never re-read the dead mapping
        if "out" in memo:
            return memo["out"]
        try:
            fault_point("hostec_np.pool.resolve")
            for f in futures:
                f.result()
            out = [bool(v) for v in verdict]
            # a batch that made it THROUGH the pool resets the rebuild
            # cooldown ramp (construction alone proves nothing)
            with _POOL_LOCK:
                _POOL_GATE.record_success()
        except Exception as exc:  # worker died mid-run: inline fallback
            logger.warning(
                "pool worker died mid-batch (%s); recomputing inline", exc
            )
            shutdown_pool(broken=True)
            out = verify_parsed_batch(lanes)
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:  # pragma: no cover - racing unlink
                pass
        memo["out"] = out
        return out

    return resolve


# ---------------------------------------------------------------------------
# Scalar API — drop-in parity with the other ladder tiers.  Single
# verifies and signing gain nothing from matrix lanes; they ride
# hostec's scalar paths (bit-identical semantics).
# ---------------------------------------------------------------------------


def verify_digest(pub: Tuple[int, int], digest: bytes, r: int, s: int) -> bool:
    """Go crypto/ecdsa.Verify semantics (no low-S rule), single lane —
    delegated to hostec: one lane cannot amortize a matrix pass."""
    return hostec.verify_digest(pub, digest, r, s)


def scalar_base_mult(k: int) -> p256.AffinePoint:
    return hostec.scalar_base_mult(k)


def sign_digest(priv: int, digest: bytes) -> Tuple[int, int]:
    """ECDSA sign, low-S normalized (hostec's comb-based signer)."""
    return hostec.sign_digest(priv, digest)


def generate_keypair() -> KeyPair:
    return hostec.generate_keypair()
