"""Compatibility shim: p256 moved to ``fabric_tpu.common.p256``.

The P-256 host oracle is imported by both the crypto providers and the
ops/ device kernels; keeping it under crypto/ created the crypto<->ops
import cycle the fabdep layering gate forbids, so the implementation now
lives in the lowest shared layer.  This shim aliases the real module, so
``fabric_tpu.crypto.p256 is fabric_tpu.common.p256`` and every
historical import keeps working.
"""

import sys as _sys

from fabric_tpu.common import p256 as _impl

_sys.modules[__name__] = _impl
