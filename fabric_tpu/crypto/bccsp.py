"""BCCSP-style pluggable crypto provider SPI.

Shaped after the reference provider interface (bccsp/bccsp.go:90-130:
KeyGen / KeyImport / Hash / Sign / Verify) with one TPU-native extension:
``batch_verify`` — the single-verify API is kept for drop-in compatibility
while batches are what the device kernels actually consume (SURVEY.md §7
Stage 1: the sidecar collects per-block batches under the hood).

Providers:
- SoftwareProvider: host-only, mirrors bccsp/sw (verifyECDSA:
  DER unmarshal -> low-S check -> ecdsa.Verify, bccsp/sw/ecdsa.go:41-57).
- TPUProvider (fabric_tpu.crypto.tpu_provider): same decision function,
  ECDSA math executed as a batched JAX kernel.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from fabric_tpu.crypto import der, p256

try:  # OpenSSL-backed fast path (reference SW BCCSP speed class); the
    # pure-Python module stays as the differential oracle.
    from fabric_tpu.crypto import fastec as _ec
except ImportError:  # pragma: no cover - cryptography missing
    _ec = p256  # type: ignore[assignment]


def ec_backend():
    """The active scalar-EC module: ``fastec`` (OpenSSL) normally, the
    ``p256`` oracle only when the cryptography package is absent.  Exposed
    so callers (msp.signer, bench) share one seam and can report which
    backend actually ran."""
    return _ec


@dataclass(frozen=True)
class ECDSAPublicKey:
    """An imported P-256 public key (reference bccsp/sw/ecdsakey.go analog)."""

    x: int
    y: int

    @property
    def point(self) -> Tuple[int, int]:
        return (self.x, self.y)

    def ski(self) -> bytes:
        """Subject Key Identifier: SHA-256 of the uncompressed point, as the
        reference computes it (bccsp/sw/ecdsakey.go SKI)."""
        return hashlib.sha256(p256.pubkey_to_bytes(self.point)).digest()


@dataclass(frozen=True)
class ECDSAPrivateKey:
    d: int
    public: ECDSAPublicKey


class VerifyError(Exception):
    """Verification *errors* (vs. clean False) — mirrors the reference's
    (bool, error) split: malformed DER and high-S return an error, a failed
    curve equation check returns (false, nil)."""


class Provider:
    """SPI. Verify semantics contract (bccsp/sw/ecdsa.go verifyECDSA):

    - signature fails DER unmarshal or has non-positive R/S -> VerifyError
    - S > N/2 (not low-S)                                   -> VerifyError
    - otherwise                                             -> bool
    """

    def hash(self, msg: bytes) -> bytes:
        return hashlib.sha256(msg).digest()

    def batch_hash(self, msgs: Sequence[bytes]) -> List[bytes]:
        """One digest per message; implementations may batch (the native
        C++ SHA-256 below). Must equal [self.hash(m) for m in msgs]."""
        from fabric_tpu.utils.native import batch_sha256

        return [bytes(d) for d in batch_sha256(msgs)]

    def key_import(self, raw: bytes) -> ECDSAPublicKey:
        x, y = p256.pubkey_from_bytes(raw)
        return ECDSAPublicKey(x, y)

    def key_gen(self) -> ECDSAPrivateKey:
        kp = _ec.generate_keypair()
        return ECDSAPrivateKey(kp.priv, ECDSAPublicKey(*kp.pub))

    def sign(self, key: ECDSAPrivateKey, digest: bytes) -> bytes:
        r, s = _ec.sign_digest(key.d, digest)
        return der.marshal_signature(r, s)

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        raise NotImplementedError

    def batch_verify(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        """Batched verification; the host parse/low-S failures map to False
        (batch callers care about the boolean mask, not error strings)."""
        out = []
        for k, sig, d in zip(keys, signatures, digests, strict=True):
            try:
                out.append(self.verify(k, sig, d))
            except VerifyError:
                out.append(False)
        return out


def parse_and_precheck(signature: bytes) -> Tuple[int, int]:
    """Host-side DER unmarshal + low-S gate shared by all providers.

    Raises VerifyError exactly where the reference returns an error.
    """
    try:
        r, s = der.unmarshal_signature(signature)
    except der.DerError as e:
        raise VerifyError(f"failed unmarshalling signature [{e}]") from e
    if not p256.is_low_s(s):
        raise VerifyError("invalid S, must be smaller than half the order")
    return r, s


class SoftwareProvider(Provider):
    """Host provider at the reference SW BCCSP's speed class: DER parse +
    low-S gate in Python, the curve math on OpenSSL (~11k verifies/s/core,
    the same ballpark as Go's P-256 assembly the reference rides)."""

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        r, s = parse_and_precheck(signature)
        return _ec.verify_digest(key.point, digest, r, s)


class PurePythonProvider(SoftwareProvider):
    """The clarity-first big-int oracle (~5 verifies/s).  Differential tests
    ONLY — never a benchmark baseline or a default path."""

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        r, s = parse_and_precheck(signature)
        return p256.verify_digest(key.point, digest, r, s)

    def sign(self, key: ECDSAPrivateKey, digest: bytes) -> bytes:
        r, s = p256.sign_digest(key.d, digest)
        return der.marshal_signature(r, s)

    def key_gen(self) -> ECDSAPrivateKey:
        kp = p256.generate_keypair()
        return ECDSAPrivateKey(kp.priv, ECDSAPublicKey(*kp.pub))


_default: Optional[Provider] = None


def default_provider() -> Provider:
    """Factory (reference bccsp/factory analog): the TPU provider if an
    actual accelerator device is present, else the software provider.
    (A CPU-only jax install must NOT route single verifies through the
    XLA kernel — its compile cost alone is minutes.)"""
    global _default
    if _default is None:
        try:
            # BOUNDED probe: a dead accelerator tunnel makes the naive
            # jax.devices() call hang forever (observed round 4) — a
            # node start must degrade to the software provider instead
            from fabric_tpu.utils.deviceprobe import accelerator_present

            if accelerator_present():
                from fabric_tpu.crypto.tpu_provider import TPUProvider

                _default = TPUProvider()
            else:
                _default = SoftwareProvider()
        except Exception:
            _default = SoftwareProvider()
    return _default
