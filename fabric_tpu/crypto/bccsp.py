"""BCCSP-style pluggable crypto provider SPI.

Shaped after the reference provider interface (bccsp/bccsp.go:90-130:
KeyGen / KeyImport / Hash / Sign / Verify) with one TPU-native extension:
``batch_verify`` — the single-verify API is kept for drop-in compatibility
while batches are what the device kernels actually consume (SURVEY.md §7
Stage 1: the sidecar collects per-block batches under the hood).

Providers:
- SoftwareProvider: host-only, mirrors bccsp/sw (verifyECDSA:
  DER unmarshal -> low-S check -> ecdsa.Verify, bccsp/sw/ecdsa.go:41-57).
  Its curve math rides a four-tier backend ladder: fastec (OpenSSL via
  the cryptography package) -> hostec_np (numpy limb-matrix lanes with
  shared-memory shards) -> hostec (dependency-free vectorized pure
  Python, batches sharded across CPU cores) -> p256 (the clarity-first
  oracle; explicit selection only, never an automatic fallback).
  Select with BCCSP.SW.ECBackend config / FABRIC_TPU_EC_BACKEND /
  select_ec_backend(); introspect with ec_backend_name() and each
  provider's describe_backend().
- TPUProvider (fabric_tpu.crypto.tpu_provider): same decision function,
  ECDSA math executed as a batched JAX kernel.
"""

from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import os

from fabric_tpu.common import fabobs
from fabric_tpu.common.faults import corrupt_verdicts, fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common import der, p256
from fabric_tpu.crypto import hostec

logger = must_get_logger("bccsp")

# ---------------------------------------------------------------------------
# Host EC backend ladder: fastec (OpenSSL) -> hostec_np (numpy
# limb-matrix lanes) -> hostec (vectorized pure Python) -> p256
# (clarity-first oracle).  All tiers share one semantics contract (Go
# crypto/ecdsa.Verify decision, low-S pre-checked by callers via
# parse_and_precheck) and are differentially tested against each other.
# The oracle is never auto-selected — it exists for tests and explicit
# opt-in only.
# ---------------------------------------------------------------------------

EC_TIERS = ("fastec", "hostec_np", "hostec", "p256")


def _load_ec_backend(name: str):
    """Backend module by tier name; raises ImportError/ValueError."""
    if name == "fastec":
        from fabric_tpu.crypto import fastec

        return fastec
    if name == "hostec_np":
        from fabric_tpu.crypto import hostec_np

        if not hostec_np.HAVE_NUMPY:
            # the module itself imports fine without numpy (guarded
            # import), but the TIER is unavailable; callers decide what
            # that means (the auto walk logs the skip, an explicit pin
            # propagates this as a hard error)
            raise ImportError("hostec_np requires numpy")
        return hostec_np
    if name == "hostec":
        return hostec
    if name == "p256":
        return p256
    raise ValueError(
        f"unknown EC backend {name!r} (expected one of {EC_TIERS})"
    )


def available_ec_backends():
    """Tier name -> importable right now. hostec and p256 are pure Python
    and always available; fastec needs the ``cryptography`` package and
    hostec_np needs numpy."""
    out = {}
    for name in EC_TIERS:
        try:
            _load_ec_backend(name)
            out[name] = True
        except ImportError:
            out[name] = False
    return out


def select_ec_backend(name: str = "auto"):
    """Select the process-wide scalar/batch EC backend and return it.

    ``auto`` honors FABRIC_TPU_EC_BACKEND when it names a usable tier,
    else warns and walks the ladder fastec -> hostec_np -> hostec (the
    oracle is never an auto choice) — asking for ``auto`` NEVER raises,
    so a malformed env var cannot poison imports or a valid config.  An
    explicitly named unavailable tier raises ImportError so a configured
    expectation is never silently downgraded."""
    global _ec
    name = str(name or "auto").lower()
    if name != "auto":
        _ec = _load_ec_backend(name)
        return _ec
    env = os.environ.get("FABRIC_TPU_EC_BACKEND", "").lower()
    if env and env != "auto":
        try:
            _ec = _load_ec_backend(env)
            return _ec
        except (ImportError, ValueError) as exc:
            import warnings

            warnings.warn(
                f"FABRIC_TPU_EC_BACKEND: {exc}; using the "
                "fastec->hostec_np->hostec auto ladder",
                RuntimeWarning,
                stacklevel=2,
            )
    for tier in ("fastec", "hostec_np"):
        try:
            _ec = _load_ec_backend(tier)
            return _ec
        except ImportError:
            if tier == "hostec_np":
                # loudly-in-the-log, silently-for-callers: the numpy
                # rung is skipped only here, on the auto walk
                logger.warning(
                    "hostec_np tier skipped (numpy not installed); "
                    "walking down to hostec"
                )
            continue
    _ec = hostec
    return _ec


def ec_backend():
    """The active scalar-EC module: ``fastec`` (OpenSSL) when available,
    else the numpy ``hostec_np`` tier, else the vectorized pure-Python
    ``hostec`` tier; the ``p256`` oracle only on explicit selection.
    Exposed so callers (msp.signer, bench, the validator) share one
    seam and can report which backend actually ran."""
    return _ec


def ec_backend_name() -> str:
    """Short tier name of the active backend
    (``fastec``/``hostec_np``/``hostec``/``p256``)."""
    return _ec.__name__.rsplit(".", 1)[-1]


def ec_pool_ready() -> bool:
    """Health view of the active EC tier's process pool: False while a
    broken pool's rebuild cooldown is open (verifies still serve, but
    inline — degraded throughput an operator should see on /healthz).
    Tiers without a pool gate are trivially ready."""
    gate = getattr(_ec, "_POOL_GATE", None)
    if gate is None:
        return True
    try:
        return bool(gate.ready())
    except Exception as exc:  # noqa: BLE001 - health probe must not raise
        logger.debug("ec pool gate probe failed (%s); reporting ready", exc)
        return True


# Import-time init: select_ec_backend("auto") never raises (see above),
# so a bad env var can't fail every `import bccsp` and re-poison test
# collection wholesale.
_ec = select_ec_backend("auto")


# ---------------------------------------------------------------------------
# Idemix verify backend ladder: hostbn (numpy limb-matrix FP256BN
# pairing lanes, crypto/hostbn.py) -> scheme (the per-signature
# idemix/scheme.py oracle).  Same contract discipline as EC_TIERS: one
# accept/reject set across rungs (differentially tested), pins honored
# hard, the auto walk warns-never-raises.  The "scheme" rung is a
# SENTINEL (None): idemix/batch.py owns the oracle loop — the scheme
# module lives a layer above crypto and is never imported from here.
# ---------------------------------------------------------------------------

IDEMIX_TIERS = ("hostbn", "scheme")


def _load_idemix_backend(name: str):
    """Backend module by tier name (None for the scheme-oracle rung);
    raises ImportError/ValueError like _load_ec_backend."""
    if name == "hostbn":
        from fabric_tpu.crypto import hostbn

        if not hostbn.HAVE_NUMPY:
            raise ImportError("hostbn requires numpy")
        return hostbn
    if name == "scheme":
        return None
    raise ValueError(
        f"unknown idemix backend {name!r} (expected one of {IDEMIX_TIERS})"
    )


def available_idemix_backends():
    """Tier name -> usable right now (hostbn needs numpy; the scheme
    oracle is always available)."""
    out = {}
    for name in IDEMIX_TIERS:
        try:
            _load_idemix_backend(name)
            out[name] = True
        except ImportError:
            out[name] = False
    return out


def select_idemix_backend(name: str = "auto"):
    """Select the process-wide Idemix batch-verify rung and return its
    module (None = the scheme oracle).  ``auto`` honors
    FABRIC_TPU_IDEMIX_BACKEND when it names a usable tier, else warns
    and walks hostbn -> scheme — asking for ``auto`` NEVER raises.  An
    explicitly named unavailable tier raises ImportError so a
    configured expectation is never silently downgraded."""
    global _idemix, _idemix_name
    name = str(name or "auto").lower()
    if name != "auto":
        _idemix = _load_idemix_backend(name)
        _idemix_name = name
        return _idemix
    env = os.environ.get("FABRIC_TPU_IDEMIX_BACKEND", "").lower()
    if env and env != "auto":
        try:
            _idemix = _load_idemix_backend(env)
            _idemix_name = env
            return _idemix
        except (ImportError, ValueError) as exc:
            import warnings

            warnings.warn(
                f"FABRIC_TPU_IDEMIX_BACKEND: {exc}; using the "
                "hostbn->scheme auto ladder",
                RuntimeWarning,
                stacklevel=2,
            )
    try:
        _idemix = _load_idemix_backend("hostbn")
        _idemix_name = "hostbn"
    except ImportError:
        # loudly-in-the-log, silently-for-callers (EC ladder discipline)
        logger.warning(
            "hostbn idemix tier skipped (numpy not installed); "
            "falling back to the scheme oracle rung"
        )
        _idemix = None
        _idemix_name = "scheme"
    return _idemix


def idemix_backend():
    """The active Idemix batch rung module (crypto/hostbn), or None
    when the scheme-oracle rung is active."""
    return _idemix


def idemix_backend_name() -> str:
    """Short tier name of the active Idemix rung (``hostbn``/``scheme``)."""
    return _idemix_name


_idemix = None
_idemix_name = "scheme"
_idemix = select_idemix_backend("auto")


@dataclass(frozen=True)
class ECDSAPublicKey:
    """An imported P-256 public key (reference bccsp/sw/ecdsakey.go analog)."""

    x: int
    y: int

    @property
    def point(self) -> Tuple[int, int]:
        return (self.x, self.y)

    def ski(self) -> bytes:
        """Subject Key Identifier: SHA-256 of the uncompressed point, as the
        reference computes it (bccsp/sw/ecdsakey.go SKI)."""
        return hashlib.sha256(p256.pubkey_to_bytes(self.point)).digest()


@dataclass(frozen=True)
class ECDSAPrivateKey:
    d: int
    public: ECDSAPublicKey


class VerifyError(Exception):
    """Verification *errors* (vs. clean False) — mirrors the reference's
    (bool, error) split: malformed DER and high-S return an error, a failed
    curve equation check returns (false, nil)."""


class Provider:
    """SPI. Verify semantics contract (bccsp/sw/ecdsa.go verifyECDSA):

    - signature fails DER unmarshal or has non-positive R/S -> VerifyError
    - S > N/2 (not low-S)                                   -> VerifyError
    - otherwise                                             -> bool
    """

    def hash(self, msg: bytes) -> bytes:
        return hashlib.sha256(msg).digest()

    def batch_hash(self, msgs: Sequence[bytes]) -> List[bytes]:
        """One digest per message; implementations may batch (the native
        C++ SHA-256 below). Must equal [self.hash(m) for m in msgs]."""
        from fabric_tpu.utils.native import batch_sha256

        return [bytes(d) for d in batch_sha256(msgs)]

    def key_import(self, raw: bytes) -> ECDSAPublicKey:
        x, y = p256.pubkey_from_bytes(raw)
        return ECDSAPublicKey(x, y)

    def key_gen(self) -> ECDSAPrivateKey:
        kp = _ec.generate_keypair()
        return ECDSAPrivateKey(kp.priv, ECDSAPublicKey(*kp.pub))

    def sign(self, key: ECDSAPrivateKey, digest: bytes) -> bytes:
        r, s = _ec.sign_digest(key.d, digest)
        return der.marshal_signature(r, s)

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        raise NotImplementedError

    def batch_verify(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        """Batched verification; the host parse/low-S failures map to False
        (batch callers care about the boolean mask, not error strings)."""
        out = []
        for k, sig, d in zip(keys, signatures, digests, strict=True):
            try:
                out.append(self.verify(k, sig, d))
            except VerifyError:
                out.append(False)
        return out

    def describe_backend(self) -> str:
        """Short runtime label of the execution path batches actually take
        (surfaced by the validator and bench so an oracle-tier fallback can
        never masquerade as a fast-tier number)."""
        return type(self).__name__


def parse_and_precheck(signature: bytes) -> Tuple[int, int]:
    """Host-side DER unmarshal + low-S gate shared by all providers.

    Raises VerifyError exactly where the reference returns an error.
    """
    try:
        r, s = der.unmarshal_signature(signature)
    except der.DerError as e:
        raise VerifyError(f"failed unmarshalling signature [{e}]") from e
    if not p256.is_low_s(s):
        raise VerifyError("invalid S, must be smaller than half the order")
    return r, s


class SoftwareProvider(Provider):
    """Host provider riding the active EC backend tier: DER parse + low-S
    gate in Python, then the curve math on OpenSSL (fastec, ~11k
    verifies/s/core) or the vectorized pure-Python hostec engine
    (~50-100x the oracle, batches sharded across CPU cores)."""

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        r, s = parse_and_precheck(signature)
        return _ec.verify_digest(key.point, digest, r, s)

    def describe_backend(self) -> str:
        return f"sw:{ec_backend_name()}"

    def _parse_lanes(self, keys, signatures, digests):
        """(pub, digest, r, s) lanes for hostec's vectorized engine; parse
        and low-S failures become r = s = 0 (an always-False lane)."""
        lanes = []
        for k, sig, d in zip(keys, signatures, digests, strict=True):
            try:
                r, s = parse_and_precheck(sig)
            except VerifyError:
                r, s = 0, 0
            lanes.append((k.point if k is not None else None, d, r, s))
        return lanes

    @staticmethod
    def _chaos_verdicts(out: List[bool]) -> List[bool]:
        """``bccsp.verdict`` corrupt seam: only an installed fault plan
        can reach the flip — it exists so the fabchaos oracle gate can
        prove its bit-exact mask assertion CATCHES a corrupted mask
        (corrupt_detect scenario), the empirical twin of the fabflow
        fail-closed proof."""
        spec = fault_point("bccsp.verdict", interprets=("corrupt",))
        if spec is not None and spec.action == "corrupt":
            return corrupt_verdicts(out, spec)
        return out

    def batch_verify(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        # unkeyed: batch sizes are static in steady state, so a content
        # key would turn a probabilistic plan into all-or-nothing
        fault_point("bccsp.dispatch")
        rung = ec_backend_name()
        t0 = time.perf_counter()
        with fabobs.span("bccsp.batch_verify", rung=rung, lanes=len(keys)):
            sharded = getattr(_ec, "verify_parsed_batch_sharded", None)
            if sharded is None:
                out = super().batch_verify(keys, signatures, digests)
            else:
                out = sharded(self._parse_lanes(keys, signatures, digests))()
        fabobs.obs_count("fabric_verify_lanes_total", len(keys), rung=rung)
        fabobs.obs_observe(
            "fabric_verify_seconds", time.perf_counter() - t0, rung=rung
        )
        return self._chaos_verdicts(list(out))

    def batch_verify_async(self, keys, signatures, digests):
        """Resolver-style dispatch (the VerifyBatcher/validator seam): on
        the hostec/hostec_np tiers the batch is sharded across the
        process pool and the resolver joins the shards
        (order-preserving), overlapping any host work the caller does
        before resolving.  Other tiers compute synchronously and hand
        back a trivial resolver."""
        fault_point("bccsp.dispatch")
        rung = ec_backend_name()
        t0 = time.perf_counter()
        sharded = getattr(_ec, "verify_parsed_batch_sharded", None)
        if sharded is None:
            out = Provider.batch_verify(self, keys, signatures, digests)
            inner = lambda v=out: v  # noqa: E731
        else:
            inner = sharded(self._parse_lanes(keys, signatures, digests))
        n = len(keys)

        def resolve() -> List[bool]:
            # latency spans dispatch -> resolve: the window a caller
            # actually waits on this rung, pool shards included
            verdicts = self._chaos_verdicts(list(inner()))
            fabobs.obs_count("fabric_verify_lanes_total", n, rung=rung)
            fabobs.obs_observe(
                "fabric_verify_seconds", time.perf_counter() - t0, rung=rung
            )
            return verdicts

        return resolve


class PurePythonProvider(SoftwareProvider):
    """The clarity-first big-int oracle (~5 verifies/s).  Differential tests
    ONLY — never a benchmark baseline or a default path.  Pins the p256
    module regardless of the active backend tier (it IS the oracle the
    other tiers are tested against)."""

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        r, s = parse_and_precheck(signature)
        return p256.verify_digest(key.point, digest, r, s)

    def describe_backend(self) -> str:
        return "sw:p256"

    def batch_verify(self, keys, signatures, digests) -> List[bool]:
        return Provider.batch_verify(self, keys, signatures, digests)

    def batch_verify_async(self, keys, signatures, digests):
        out = Provider.batch_verify(self, keys, signatures, digests)
        return lambda: out

    def sign(self, key: ECDSAPrivateKey, digest: bytes) -> bytes:
        r, s = p256.sign_digest(key.d, digest)
        return der.marshal_signature(r, s)

    def key_gen(self) -> ECDSAPrivateKey:
        kp = p256.generate_keypair()
        return ECDSAPrivateKey(kp.priv, ECDSAPublicKey(*kp.pub))


_default: Optional[Provider] = None
# two channels starting concurrently (one Channel.__init__ per deliver
# thread) must not both construct a provider: a TPUProvider holds the
# device executor, and the loser's instance would keep compiling kernels
# nothing ever reads
_default_lock = threading.Lock()


def default_provider() -> Provider:
    """Factory (reference bccsp/factory analog): the TPU provider if an
    actual accelerator device is present, else the software provider.
    (A CPU-only jax install must NOT route single verifies through the
    XLA kernel — its compile cost alone is minutes.)"""
    with _default_lock:
        return _default_provider_locked()


def _default_provider_locked() -> Provider:
    global _default
    if _default is None:
        # fleet routing first: several sidecars behind the peer-side
        # failover router beat one (FABRIC_TPU_SERVE_ENDPOINTS wins
        # over FABRIC_TPU_SERVE_ADDR when both are set — the single
        # address is the degenerate one-endpoint fleet)
        endpoints = os.environ.get("FABRIC_TPU_SERVE_ENDPOINTS", "")
        addr = os.environ.get("FABRIC_TPU_SERVE_ADDR", "")
        if endpoints or addr:
            # resident-sidecar routing (fabric_tpu.serve): every default
            # consumer (peer channels, the chaos harness) transparently
            # sends its batches to the warm sidecar.  The rung builds
            # WITHOUT contacting the sidecar (a peer may start before
            # its sidecar; batch_verify re-dials behind a failure
            # cooldown, so a late-arriving sidecar is picked up) and
            # degrades through
            # probe_provider() — an accelerator node with a stale env
            # var keeps its device, never silently pins the SW rung
            try:
                from fabric_tpu.crypto.factory import provider_from_config

                serve_cfg: dict = {"Address": addr}
                if endpoints:
                    serve_cfg["Endpoints"] = [
                        a.strip() for a in endpoints.split(",") if a.strip()
                    ]
                _default = provider_from_config(
                    {"Default": "SERVE", "SERVE": serve_cfg}
                )
                return _default
            except Exception as exc:  # noqa: BLE001 - env routing best-effort
                logger.warning(
                    "serve routing (%s) unusable (%s); using the "
                    "in-process provider ladder", endpoints or addr, exc,
                )
        _default = probe_provider()
    return _default


def probe_provider() -> Provider:
    """The device-probe ladder, independent of any sidecar routing: the
    TPU provider if an accelerator answers the bounded probe, else the
    software provider.  Also the sidecar client's degrade target, so an
    accelerator-attached node that loses its sidecar falls back to the
    device, not to a hardcoded SW rung."""
    try:
        # BOUNDED probe: a dead accelerator tunnel makes the naive
        # jax.devices() call hang forever (observed round 4) — a
        # node start must degrade to the software provider instead
        from fabric_tpu.utils.deviceprobe import accelerator_present

        if accelerator_present():
            from fabric_tpu.crypto.tpu_provider import TPUProvider

            return TPUProvider()
        return SoftwareProvider()
    except Exception as exc:  # noqa: BLE001 - probe flake: SW serves
        logger.warning(
            "device probe failed (%s); using the software provider", exc
        )
        fabobs.obs_count("fabric_degrade_total", seam="bccsp.probe")
        return SoftwareProvider()
