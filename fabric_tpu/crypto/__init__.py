"""Crypto layer: curve math oracle, DER codecs, BCCSP-style provider SPI."""

from fabric_tpu.crypto import der, p256
from fabric_tpu.crypto.bccsp import SoftwareProvider

__all__ = ["der", "p256", "SoftwareProvider"]
