"""Dependency-free vectorized host ECDSA-P256 batch verification (hostec).

The middle tier of the host EC backend ladder (``fastec`` -> ``hostec`` ->
``p256``): everywhere the ``cryptography`` package is absent the software
provider used to fall back to the affine pure-Python oracle
(crypto/p256.py, one modular inversion per point add, ~8 verifies/s) —
three orders of magnitude below the OpenSSL tier and useless against the
north-star batch-verify throughput target. This module is the portable
replacement: pure Python ints, no third-party imports, ~50-100x the
oracle on commodity CPUs.

Design (the same shape as the device kernel in ops/p256_kernel.py, but
tuned for CPython instead of XLA):

- **Lane-vectorized field ops.** A batch is a list of Python ints per
  coordinate; every field operation is one fused list comprehension over
  all lanes (one interpreter pass, one ``%`` per lane per op). All lanes
  advance through the *same* window schedule, so the work is array-shaped
  — there is no per-signature control flow in the hot loop.
- **Jacobian coordinates** (no inversions in the group law): doubling is
  dbl-2001-b for a = -3 (8 big mults), mixed add is the standard
  Jacobian+affine madd (11 big mults). Exceptional lanes (P = +-Q,
  P = infinity) are detected wholesale via ``0 in Z3`` and patched with a
  scalar fallback — they are adversarially reachable, never hot.
- **Shamir's trick, joint Horner loop**: u1*G + u2*Q shares one doubling
  chain. Q uses 4-bit windows (a per-lane 15-entry table, normalized to
  affine with ONE Montgomery batch inversion across table x lanes); G
  rides the same doublings with 8-bit windows into a precomputed global
  255-entry affine table, so the fixed base costs 32 adds, not 256
  doublings.
- **Montgomery batch inversion** everywhere an inverse is needed per lane
  (s^-1 mod n, table normalization, the final affine x comparison):
  3 mults per element plus a single Fermat ``pow`` per batch instead of
  one ~170us ``pow`` per lane.
- **Process-pool sharding**: batches >= ``MIN_POOL_LANES`` lanes split
  evenly across CPU cores (``FABRIC_TPU_HOSTEC_PROCS``, default all).
  Shards are concatenated in submission order, so results are
  order-preserving. The pool is created lazily and shared process-wide;
  ``parallel.batcher.VerifyBatcher`` rides it through the software
  provider's ``batch_verify_async`` seam.

Semantics are bit-identical to the oracle (tests/test_hostec.py fuzzes
the valid/invalid mask differentially): ``verify_digest`` implements Go
crypto/ecdsa.Verify — no low-S rule here (callers pre-check via
``bccsp.parse_and_precheck``), out-of-range r/s and off-curve or identity
public keys return False and never raise. ``sign_digest`` normalizes to
low-S exactly like fastec/p256.
"""

from __future__ import annotations

import os
import secrets
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common import fabobs
from fabric_tpu.common.retry import CooldownGate
from fabric_tpu.common import p256
from fabric_tpu.common.p256 import A, B, GX, GY, HALF_N, N, P, hash_to_int

logger = must_get_logger("hostec")

KeyPair = p256.KeyPair

# Public keys as affine (x, y) tuples; None marks an unusable lane (the
# identity / a parse failure) which verifies False.
PubKey = Optional[Tuple[int, int]]

WINDOW_BITS = 4
NUM_WINDOWS = 64  # 256 / 4
G_WINDOW_BITS = 8  # fixed-base digits ride every 2nd doubling round

# Below this lane count a pool round-trip costs more than it saves.
MIN_POOL_LANES = 256


# ---------------------------------------------------------------------------
# Scalar Jacobian helpers (table precompute + exceptional-lane patches)
# ---------------------------------------------------------------------------


def _dbl1(X: int, Y: int, Z: int) -> Tuple[int, int, int]:
    """dbl-2001-b (a = -3). Complete for this curve: Z=0 stays Z=0 and
    P-256 has no 2-torsion, so Y=0 never occurs on-curve."""
    delta = Z * Z % P
    gamma = Y * Y % P
    beta = X * gamma % P
    alpha = 3 * (X - delta) * (X + delta) % P
    X3 = (alpha * alpha - 8 * beta) % P
    Z3 = ((Y + Z) * (Y + Z) - gamma - delta) % P
    Y3 = (alpha * (4 * beta - X3) - 8 * gamma * gamma) % P
    return X3, Y3, Z3


def _madd1(X: int, Y: int, Z: int, x2: int, y2: int) -> Tuple[int, int, int]:
    """Mixed Jacobian + affine add with the exceptional cases handled."""
    if Z == 0:
        return x2, y2, 1
    ZZ = Z * Z % P
    U2 = x2 * ZZ % P
    S2 = y2 * Z * ZZ % P
    H = (U2 - X) % P
    R = (S2 - Y) % P
    if H == 0:
        if R == 0:
            return _dbl1(x2, y2, 1)  # P == Q
        return 1, 1, 0  # P == -Q
    HH = H * H % P
    HHH = H * HH % P
    V = X * HH % P
    X3 = (R * R - HHH - 2 * V) % P
    Y3 = (R * (V - X3) - Y * HHH) % P
    Z3 = Z * H % P
    return X3, Y3, Z3


# ---------------------------------------------------------------------------
# Lane-vectorized group law (lists of ints; fused list comprehensions)
# ---------------------------------------------------------------------------

Lanes = List[int]


def _dbl_vec(X: Lanes, Y: Lanes, Z: Lanes) -> Tuple[Lanes, Lanes, Lanes]:
    delta = [z * z % P for z in Z]
    gamma = [y * y % P for y in Y]
    beta = [x * g % P for x, g in zip(X, gamma)]
    alpha = [3 * (x - d) * (x + d) % P for x, d in zip(X, delta)]
    X3 = [(a * a - 8 * b) % P for a, b in zip(alpha, beta)]
    Z3 = [
        ((y + z) * (y + z) - g - d) % P
        for y, z, g, d in zip(Y, Z, gamma, delta)
    ]
    Y3 = [
        (a * (4 * b - x3) - 8 * g * g) % P
        for a, b, x3, g in zip(alpha, beta, X3, gamma)
    ]
    return X3, Y3, Z3


def _madd_vec(
    X: Lanes, Y: Lanes, Z: Lanes, x2: Lanes, y2: Lanes
) -> Tuple[Lanes, Lanes, Lanes]:
    """Vector mixed add. Z3 = Z*H is 0 exactly on the exceptional lanes
    (P = infinity, P = +-Q), which are then recomputed scalar-wise — the
    check itself is one C-level ``in`` scan per add."""
    ZZ = [z * z % P for z in Z]
    U2 = [a * b % P for a, b in zip(x2, ZZ)]
    S2 = [y * z * zz % P for y, z, zz in zip(y2, Z, ZZ)]
    H = [(u - x) % P for u, x in zip(U2, X)]
    R = [(s - y) % P for s, y in zip(S2, Y)]
    HH = [h * h % P for h in H]
    HHH = [h * hh % P for h, hh in zip(H, HH)]
    V = [x * hh % P for x, hh in zip(X, HH)]
    X3 = [(r * r - hhh - 2 * v) % P for r, hhh, v in zip(R, HHH, V)]
    Y3 = [
        (r * (v - x3) - y * hhh) % P
        for r, v, x3, y, hhh in zip(R, V, X3, Y, HHH)
    ]
    Z3 = [z * h % P for z, h in zip(Z, H)]
    if 0 in Z3:
        for i, z3 in enumerate(Z3):
            if z3 == 0:
                X3[i], Y3[i], Z3[i] = _madd1(X[i], Y[i], Z[i], x2[i], y2[i])
    return X3, Y3, Z3


def _batch_inv(vals: Sequence[int], m: int) -> List[int]:
    """Montgomery batch inversion mod a prime m: 3 mults per element plus
    ONE Fermat pow for the whole batch. Zero entries yield 0 (callers mask
    those lanes) without poisoning the product chain."""
    n = len(vals)
    pre = [1] * (n + 1)
    acc = 1
    for i, v in enumerate(vals):
        if v:
            acc = acc * v % m
        pre[i + 1] = acc
    inv_acc = pow(acc, m - 2, m)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        v = vals[i]
        if v:
            out[i] = inv_acc * pre[i] % m
            inv_acc = inv_acc * v % m
    return out


# ---------------------------------------------------------------------------
# Precomputed fixed-base tables (lazy; module-level caches)
# ---------------------------------------------------------------------------

_G_HORNER: Optional[Tuple[List[int], List[int]]] = None  # d*G, d in 1..255
_G_COMB: Optional[List[List[Tuple[int, int]]]] = None  # [w][d-1] = d*16^w*G
# lazy-build guard: the verify path runs on the TPU dispatch thread, the
# commit thread, AND inline fallbacks concurrently — an unlocked first
# build is merely idempotent-but-wasted work (hundreds of field
# inversions per extra builder), but fabdep rightly flags the write
_TABLE_LOCK = threading.Lock()


def _normalize_jacobians(
    pts: Sequence[Tuple[int, int, int]],
) -> List[Tuple[int, int]]:
    zinv = _batch_inv([p[2] for p in pts], P)
    out = []
    for (X, Y, _Z), zi in zip(pts, zinv):
        zi2 = zi * zi % P
        out.append((X * zi2 % P, Y * zi2 * zi % P))
    return out


def _g_horner_table() -> Tuple[List[int], List[int]]:
    """Affine d*G for d in 1..255 (index d-1), one batch inversion total."""
    global _G_HORNER
    if _G_HORNER is None:
        with _TABLE_LOCK:
            if _G_HORNER is None:
                jac = [(GX, GY, 1)]
                for _ in range(254):
                    X, Y, Z = jac[-1]
                    jac.append(_madd1(X, Y, Z, GX, GY))
                aff = _normalize_jacobians(jac)
                _G_HORNER = ([x for x, _ in aff], [y for _, y in aff])
    return _G_HORNER


def _g_comb_table() -> List[List[Tuple[int, int]]]:
    """Affine d * 16^w * G for w in 0..63, d in 1..15 — the fixed-base comb
    for signing/keygen: a base mult is 64 mixed adds, zero doublings."""
    global _G_COMB
    if _G_COMB is None:
        with _TABLE_LOCK:
            if _G_COMB is None:
                rows_jac: List[List[Tuple[int, int, int]]] = []
                base = (GX, GY, 1)
                for _w in range(NUM_WINDOWS):
                    bz = pow(base[2], P - 2, P)
                    bz2 = bz * bz % P
                    bx, by = base[0] * bz2 % P, base[1] * bz2 * bz % P
                    row = [(bx, by, 1)]
                    for _d in range(14):
                        X, Y, Z = row[-1]
                        row.append(_madd1(X, Y, Z, bx, by))
                    rows_jac.append(row)
                    base = (bx, by, 1)
                    for _ in range(WINDOW_BITS):
                        base = _dbl1(*base)
                flat = _normalize_jacobians(
                    [p for row in rows_jac for p in row]
                )
                _G_COMB = [
                    flat[w * 15 : (w + 1) * 15] for w in range(NUM_WINDOWS)
                ]
    return _G_COMB


def warm_tables() -> None:
    """Build both fixed-base tables now (e.g. before forking pool workers)."""
    _g_horner_table()
    _g_comb_table()


# ---------------------------------------------------------------------------
# Core batch verification
# ---------------------------------------------------------------------------


def _precheck_lanes(lanes):
    """Per-lane prechecks mirroring the oracle exactly: r/s range, key
    present, coordinates in range, curve equation.  Bad lanes get
    benign substitutes (r = s = 1, Q = G, e = 0) so vector math stays
    defined, and must be forced False at the end.  Shared by this
    engine and crypto/hostec_np — the tiers' accept/reject sets are a
    load-bearing bit-exactness contract, so there is exactly ONE copy
    of it."""
    nlanes = len(lanes)
    valid = [True] * nlanes
    rr = [1] * nlanes
    ss = [1] * nlanes
    qx = [GX] * nlanes
    qy = [GY] * nlanes
    ee = [0] * nlanes
    for i, (pub, digest, r, s) in enumerate(lanes):
        if not (1 <= r < N and 1 <= s < N) or pub is None:
            valid[i] = False
            continue
        x, y = pub
        if not (0 <= x < P and 0 <= y < P) or (
            y * y - (x * x * x + A * x + B)
        ) % P != 0:
            valid[i] = False
            continue
        rr[i], ss[i] = r, s
        qx[i], qy[i] = x, y
        ee[i] = hash_to_int(digest)
    return valid, rr, ss, qx, qy, ee


def verify_parsed_batch(
    lanes: Sequence[Tuple[PubKey, bytes, int, int]],
) -> List[bool]:
    """One vectorized pass over (pub, digest, r, s) lanes, all in THIS
    process. Bit-exact with ``p256.verify_digest`` per lane; the low-S rule
    is NOT applied here (same contract as the oracle and fastec)."""
    nlanes = len(lanes)
    if nlanes == 0:
        return []

    valid, rr, ss, qx, qy, ee = _precheck_lanes(lanes)

    # u1 = e/s, u2 = r/s mod n — one batch inversion for every lane's s.
    w = _batch_inv(ss, N)
    u1 = [e * wi % N for e, wi in zip(ee, w)]
    u2 = [r * wi % N for r, wi in zip(rr, w)]

    # Per-lane 4-bit window table d*Q, d in 1..15 (index d-1), built
    # vectorized then normalized to affine with one batch inversion so the
    # hot loop uses 11-mult mixed adds. d*Q is never the identity for
    # d <= 15 (prime group order), so no exceptional lanes here.
    ones = [1] * nlanes
    tab_jac = [(qx, qy, ones)]
    d2x, d2y, d2z = _dbl_vec(qx, qy, ones)
    tab_jac.append((d2x, d2y, d2z))
    for _d in range(3, 16):
        X, Y, Z = tab_jac[-1]
        tab_jac.append(_madd_vec(X, Y, Z, qx, qy))
    flat_z = [z for _X, _Y, Z in tab_jac for z in Z]
    zinv = _batch_inv(flat_z, P)
    tqx: List[Lanes] = []
    tqy: List[Lanes] = []
    for t, (X, Y, _Z) in enumerate(tab_jac):
        zi = zinv[t * nlanes : (t + 1) * nlanes]
        zi2 = [a * a % P for a in zi]
        tqx.append([x * a % P for x, a in zip(X, zi2)])
        tqy.append([y * a * b % P for y, a, b in zip(Y, zi2, zi)])

    gx_tab, gy_tab = _g_horner_table()

    # Joint Horner: R = 16*R + d2_k*Q every round (k = 63-j), plus
    # d1_i*G every odd round (i = (63-j)/2, 8-bit digits). Every lane
    # walks this same schedule; digit-0 lanes compute the add too and a
    # select keeps their old point.
    RX, RY, RZ = [1] * nlanes, [1] * nlanes, [0] * nlanes
    for j in range(NUM_WINDOWS):
        if j:
            for _ in range(WINDOW_BITS):
                RX, RY, RZ = _dbl_vec(RX, RY, RZ)
        sh = 4 * (NUM_WINDOWS - 1 - j)
        ds = [(u >> sh) & 15 for u in u2]
        ax = [tqx[d - 1][i] if d else GX for i, d in enumerate(ds)]
        ay = [tqy[d - 1][i] if d else GY for i, d in enumerate(ds)]
        NX, NY, NZ = _madd_vec(RX, RY, RZ, ax, ay)
        RX = [n if d else o for n, o, d in zip(NX, RX, ds)]
        RY = [n if d else o for n, o, d in zip(NY, RY, ds)]
        RZ = [n if d else o for n, o, d in zip(NZ, RZ, ds)]
        if j & 1:
            gsh = 8 * ((NUM_WINDOWS - 1 - j) >> 1)
            ds = [(u >> gsh) & 255 for u in u1]
            ax = [gx_tab[d - 1] if d else GX for d in ds]
            ay = [gy_tab[d - 1] if d else GY for d in ds]
            NX, NY, NZ = _madd_vec(RX, RY, RZ, ax, ay)
            RX = [n if d else o for n, o, d in zip(NX, RX, ds)]
            RY = [n if d else o for n, o, d in zip(NY, RY, ds)]
            RZ = [n if d else o for n, o, d in zip(NZ, RZ, ds)]

    # Affine comparison x(R) mod n == r via one final batch inversion.
    zinv = _batch_inv(RZ, P)
    out = []
    for i in range(nlanes):
        if not valid[i] or RZ[i] == 0:
            out.append(False)
            continue
        zi = zinv[i]
        x_aff = RX[i] * zi * zi % P
        out.append(x_aff % N == rr[i])
    return out


# ---------------------------------------------------------------------------
# Process-pool sharding
# ---------------------------------------------------------------------------

_POOL = None
_POOL_PROCS = 1
_POOL_LOCK = threading.Lock()
# a pool that just broke must not be rebuilt in a hot loop: each
# breakage opens an exponentially longer cooldown during which big
# batches stay inline (mutated only under _POOL_LOCK)
_POOL_GATE = CooldownGate()


def pool_procs() -> int:
    """Worker count the pool will use (1 = pool disabled).  A malformed
    FABRIC_TPU_HOSTEC_PROCS must degrade to the default, never raise out
    of the verify path.  The default clamps at 8: spawn-method workers
    re-import the parent's __main__ (jax and all, for bench/node
    entrypoints), so an uncapped cpu_count on a big host would turn the
    first large batch into a multi-second worker-boot stall."""
    procs = os.environ.get("FABRIC_TPU_HOSTEC_PROCS", "")
    if procs:
        try:
            return max(int(procs), 1)
        except ValueError:
            pass
    return min(os.cpu_count() or 1, 8)


def _pool():
    """Lazy shared ProcessPoolExecutor.  By the time the first big batch
    arrives the parent is multithreaded (JAX runtime, gRPC servers), so
    plain fork risks workers wedged on a lock some other thread held
    mid-fork — prefer forkserver/spawn and let each worker rebuild the
    fixed-base tables (a few ms, once).  Note spawn-method workers also
    re-import the parent's __main__ module, which can be heavy (bench.py
    imports jax) — hence the pool_procs() clamp."""
    global _POOL, _POOL_PROCS
    with _POOL_LOCK:
        if _POOL is None:
            if not _POOL_GATE.ready():
                # recently broken: stay inline for the cooldown instead
                # of paying a worker-boot stall per batch in a hot loop
                return None
            procs = pool_procs()
            _POOL_PROCS = procs
            if procs <= 1:
                _POOL = False
                return None
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            methods = multiprocessing.get_all_start_methods()
            start = os.environ.get("FABRIC_TPU_HOSTEC_START", "")
            if start not in methods:
                for start in ("forkserver", "spawn", "fork"):
                    if start in methods:
                        break
            if start == "fork":
                warm_tables()  # children inherit, never rebuild
            try:
                _POOL = ProcessPoolExecutor(
                    max_workers=procs,
                    mp_context=multiprocessing.get_context(start),
                )
                fabobs.obs_count("fabric_pool_rebuilds_total", pool="hostec")
            except Exception as exc:  # pragma: no cover - restricted sandboxes
                logger.warning(
                    "process pool unavailable (%s); verifying inline", exc
                )
                _POOL = False
    return _POOL or None


def shutdown_pool(broken: bool = False) -> None:
    """Tear the pool down.  ``broken=True`` (the degrade paths) also
    arms the rebuild cooldown so a flapping pool can't thrash; a clean
    shutdown (tests, bench teardown) leaves the gate closed."""
    global _POOL
    with _POOL_LOCK:
        if _POOL:
            _POOL.shutdown(wait=False, cancel_futures=True)
        _POOL = None
        if broken:
            _POOL_GATE.record_failure()
    if broken:
        fabobs.obs_count("fabric_pool_cooldowns_total", pool="hostec")
        fabobs.obs_count("fabric_degrade_total", seam="hostec.pool")
        fabobs.obs_trigger("hostec.pool_broken")


def verify_parsed_batch_sharded(
    lanes: Sequence[Tuple[PubKey, bytes, int, int]],
) -> Callable[[], List[bool]]:
    """Shard a parsed batch across the process pool; returns a resolver
    (call it for the verdicts) so callers — the VerifyBatcher dispatcher
    in particular — can overlap host prep with shard execution. Shards
    are reassembled in submission order: results are order-preserving.

    Small batches (or a disabled/unavailable pool) run inline.  A pool
    that breaks (worker OOM-killed, interpreter torn down) is discarded
    and the batch recomputed inline — degrade, never die: the next big
    batch lazily builds a fresh pool."""
    lanes = list(lanes)
    pool = _pool() if len(lanes) >= MIN_POOL_LANES else None
    if pool is None:
        out = verify_parsed_batch(lanes)
        return lambda: out
    nshards = min(_POOL_PROCS, max(len(lanes) // (MIN_POOL_LANES // 2), 1))
    step = (len(lanes) + nshards - 1) // nshards
    try:
        fault_point("hostec.pool.submit")
        futures = [
            pool.submit(verify_parsed_batch, lanes[off : off + step])
            for off in range(0, len(lanes), step)
        ]
    except Exception as exc:  # BrokenProcessPool / shutdown race
        logger.warning("pool submit failed (%s); recomputing inline", exc)
        shutdown_pool(broken=True)
        out = verify_parsed_batch(lanes)
        return lambda: out

    def resolve() -> List[bool]:
        out: List[bool] = []
        try:
            fault_point("hostec.pool.resolve")
            for f in futures:
                out.extend(f.result())
        except Exception as exc:  # worker died mid-run: inline fallback
            logger.warning(
                "pool worker died mid-batch (%s); recomputing inline", exc
            )
            shutdown_pool(broken=True)
            return verify_parsed_batch(lanes)
        # only a batch that made it THROUGH the pool resets the rebuild
        # cooldown ramp — construction succeeding proves nothing about a
        # persistently worker-killing environment
        with _POOL_LOCK:
            _POOL_GATE.record_success()
        return out

    return resolve


# ---------------------------------------------------------------------------
# Scalar API — drop-in parity with crypto.fastec / crypto.p256
# ---------------------------------------------------------------------------


def verify_digest(pub: Tuple[int, int], digest: bytes, r: int, s: int) -> bool:
    """Go crypto/ecdsa.Verify semantics (no low-S rule), single lane."""
    return verify_parsed_batch([(pub, digest, r, s)])[0]


def scalar_base_mult(k: int) -> p256.AffinePoint:
    """k*G via the fixed-base comb: 64 mixed adds, zero doublings."""
    k %= N
    if k == 0:
        return None
    comb = _g_comb_table()
    X, Y, Z = 1, 1, 0
    for w in range(NUM_WINDOWS):
        d = (k >> (4 * w)) & 15
        if d:
            X, Y, Z = _madd1(X, Y, Z, *comb[w][d - 1])
    if Z == 0:
        return None
    zi = pow(Z, P - 2, P)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


def sign_digest(priv: int, digest: bytes) -> Tuple[int, int]:
    """ECDSA sign, low-S normalized (reference signECDSA -> ToLowS)."""
    e = hash_to_int(digest)
    while True:
        k = secrets.randbelow(N - 1) + 1
        pt = scalar_base_mult(k)
        if pt is None:
            raise ArithmeticError("k*G is infinity for k in [1, N-1]")
        r = pt[0] % N
        if r == 0:
            continue
        s = pow(k, N - 2, N) * (e + r * priv) % N
        if s == 0:
            continue
        if s > HALF_N:
            s = N - s
        return r, s


def generate_keypair() -> KeyPair:
    d = secrets.randbelow(N - 1) + 1
    q = scalar_base_mult(d)
    if q is None:
        raise ArithmeticError("d*G is infinity for d in [1, N-1]")
    return KeyPair(d, q)
