"""PKCS#11 (HSM) BCCSP provider (reference bccsp/pkcs11/pkcs11.go).

The reference's HSM story: private keys live on a Cryptoki token; the
host hashes, the token runs the ECDSA scalar ops (C_Sign / C_Verify on
CKM_ECDSA over the 32-byte digest), and the provider enforces the same
low-S normalization as the software path so signatures verify
identically everywhere. Public-key material is located by SKI
(CKA_ID), mirroring pkcs11.go's getECKey.

This module binds a standard Cryptoki shared object via ctypes
(`Cryptoki`), and `PKCS11Provider` implements the BCCSP surface on top
of a minimal session abstraction. The provider logic (SKI lookup,
DER wrap/unwrap, low-S, verify semantics) is unit-tested against a
faked token; the ctypes layer follows the PKCS#11 v2.40 ABI and
activates only when a `Library` path is configured — this image ships
no HSM, so a missing/unloadable library raises `PKCS11Error` with a
clear message instead of probing anything (factory.go's pkcs11factory
errors the same way when the library is absent).
"""

from __future__ import annotations

import ctypes
import hashlib
import threading
from typing import Dict, List, Optional, Sequence

from fabric_tpu.common import der, p256
from fabric_tpu.crypto.bccsp import (
    ECDSAPublicKey,
    Provider,
    SoftwareProvider,
    VerifyError,
)


class PKCS11Error(Exception):
    pass


# -- Cryptoki ABI subset (PKCS#11 v2.40) ------------------------------------

CKR_OK = 0
CKF_SERIAL_SESSION = 0x4
CKF_RW_SESSION = 0x2
CKU_USER = 1
CKM_ECDSA = 0x1041
CKO_PRIVATE_KEY = 0x3
CKO_PUBLIC_KEY = 0x2
CKA_CLASS = 0x0
CKA_ID = 0x102
CKA_EC_POINT = 0x181


class _CK_ATTRIBUTE(ctypes.Structure):
    _fields_ = [
        ("type", ctypes.c_ulong),
        ("pValue", ctypes.c_void_p),
        ("ulValueLen", ctypes.c_ulong),
    ]


class _CK_MECHANISM(ctypes.Structure):
    _fields_ = [
        ("mechanism", ctypes.c_ulong),
        ("pParameter", ctypes.c_void_p),
        ("ulParameterLen", ctypes.c_ulong),
    ]


def _attr(atype: int, value: bytes) -> _CK_ATTRIBUTE:
    buf = ctypes.create_string_buffer(value, len(value))
    return _CK_ATTRIBUTE(
        atype, ctypes.cast(buf, ctypes.c_void_p), len(value)
    )


class Cryptoki:
    """Thin ctypes session over one Cryptoki library + token slot.
    Methods mirror the C_* calls pkcs11.go uses; any non-CKR_OK return
    raises PKCS11Error(rv)."""

    def __init__(self, library: str, pin: str, slot: Optional[int] = None):
        try:
            self._lib = ctypes.CDLL(library)
        except OSError as exc:
            raise PKCS11Error(
                f"cannot load PKCS#11 library {library!r}: {exc}"
            ) from exc
        self._check(self._lib.C_Initialize(None), "C_Initialize")
        if slot is None:
            count = ctypes.c_ulong(0)
            self._check(
                self._lib.C_GetSlotList(1, None, ctypes.byref(count)),
                "C_GetSlotList",
            )
            if count.value == 0:
                raise PKCS11Error("no PKCS#11 token slots present")
            slots = (ctypes.c_ulong * count.value)()
            self._check(
                self._lib.C_GetSlotList(1, slots, ctypes.byref(count)),
                "C_GetSlotList",
            )
            slot = slots[0]
        self._session = ctypes.c_ulong(0)
        self._check(
            self._lib.C_OpenSession(
                slot,
                CKF_SERIAL_SESSION | CKF_RW_SESSION,
                None,
                None,
                ctypes.byref(self._session),
            ),
            "C_OpenSession",
        )
        if pin:
            pin_b = pin.encode()
            self._check(
                self._lib.C_Login(self._session, CKU_USER, pin_b, len(pin_b)),
                "C_Login",
            )
        self._lock = threading.Lock()

    @staticmethod
    def _check(rv: int, call: str) -> None:
        if rv != CKR_OK:
            raise PKCS11Error(f"{call} failed: CKR=0x{rv:x}")

    def find_key(self, ski: bytes, private: bool) -> int:
        """Object handle for the key with CKA_ID == ski (getECKey)."""
        with self._lock:
            cls = CKO_PRIVATE_KEY if private else CKO_PUBLIC_KEY
            template = (_CK_ATTRIBUTE * 2)(
                _attr(CKA_CLASS, cls.to_bytes(8, "little")),
                _attr(CKA_ID, ski),
            )
            self._check(
                self._lib.C_FindObjectsInit(self._session, template, 2),
                "C_FindObjectsInit",
            )
            handle = ctypes.c_ulong(0)
            count = ctypes.c_ulong(0)
            try:
                self._check(
                    self._lib.C_FindObjects(
                        self._session,
                        ctypes.byref(handle),
                        1,
                        ctypes.byref(count),
                    ),
                    "C_FindObjects",
                )
            finally:
                self._lib.C_FindObjectsFinal(self._session)
            if count.value == 0:
                raise PKCS11Error(f"no key with SKI {ski.hex()} on token")
            return handle.value

    def sign_raw(self, key_handle: int, digest: bytes) -> bytes:
        """CKM_ECDSA C_Sign: 64-byte r||s over the digest."""
        with self._lock:
            mech = _CK_MECHANISM(CKM_ECDSA, None, 0)
            self._check(
                self._lib.C_SignInit(
                    self._session, ctypes.byref(mech), key_handle
                ),
                "C_SignInit",
            )
            out_len = ctypes.c_ulong(128)
            out = ctypes.create_string_buffer(128)
            self._check(
                self._lib.C_Sign(
                    self._session,
                    digest,
                    len(digest),
                    out,
                    ctypes.byref(out_len),
                ),
                "C_Sign",
            )
            return out.raw[: out_len.value]

    def verify_raw(self, key_handle: int, digest: bytes, rs: bytes) -> bool:
        """CKM_ECDSA C_Verify over r||s; CKR_SIGNATURE_INVALID -> False."""
        with self._lock:
            mech = _CK_MECHANISM(CKM_ECDSA, None, 0)
            self._check(
                self._lib.C_VerifyInit(
                    self._session, ctypes.byref(mech), key_handle
                ),
                "C_VerifyInit",
            )
            rv = self._lib.C_Verify(
                self._session, digest, len(digest), rs, len(rs)
            )
            if rv == CKR_OK:
                return True
            if rv in (0xC0, 0xC1):  # CKR_SIGNATURE_INVALID / _LEN_RANGE
                return False
            raise PKCS11Error(f"C_Verify failed: CKR=0x{rv:x}")


class PKCS11Provider(Provider):
    """BCCSP provider over a Cryptoki token. Token signatures are
    normalized to low-S and DER-wrapped so they are indistinguishable
    from software-path signatures (pkcs11.go signECDSA + utils.IsLowS);
    verification of PUBLIC keys runs on host (the token only holds OUR
    keys — same split as the reference, whose Verify with a plain
    public key goes through the software curve math)."""

    def __init__(self, token: Cryptoki):
        self._token = token
        self._sw = SoftwareProvider()
        self._handles: Dict[bytes, int] = {}

    # -- BCCSP surface -----------------------------------------------------
    def _priv_handle(self, ski: bytes) -> int:
        h = self._handles.get(ski)
        if h is None:
            h = self._token.find_key(ski, private=True)
            self._handles[ski] = h
        return h

    def sign_by_ski(self, ski: bytes, digest: bytes) -> bytes:
        """Sign with the token key identified by SKI; DER(low-S)."""
        rs = self._token.sign_raw(self._priv_handle(ski), digest)
        if len(rs) != 64:
            raise PKCS11Error(f"token returned {len(rs)}-byte signature")
        r = int.from_bytes(rs[:32], "big")
        s = int.from_bytes(rs[32:], "big")
        if not p256.is_low_s(s):
            s = p256.N - s  # toLowS, pkcs11.go:486
        return der.marshal_signature(r, s)

    def verify(self, key: ECDSAPublicKey, signature: bytes, digest: bytes) -> bool:
        # plain public keys verify on host exactly like SW (the token
        # adds nothing for keys it does not hold)
        return self._sw.verify(key, signature, digest)

    def batch_verify(
        self,
        keys: Sequence[ECDSAPublicKey],
        signatures: Sequence[bytes],
        digests: Sequence[bytes],
    ) -> List[bool]:
        out = []
        for key, sig, dig in zip(keys, signatures, digests):
            try:
                out.append(self.verify(key, sig, dig))
            except VerifyError:
                out.append(False)
        return out
