"""Compatibility shim: fp256bn moved to ``fabric_tpu.common.fp256bn``.

The BN-256 host field/curve oracle is imported by both the crypto
providers and the ops/ device kernels; keeping it under crypto/ created
the crypto<->ops import cycle the fabdep layering gate forbids, so the
implementation now lives in the lowest shared layer.  This shim aliases
the real module, so ``fabric_tpu.crypto.fp256bn is
fabric_tpu.common.fp256bn`` and every historical import keeps working.
"""

import sys as _sys

from fabric_tpu.common import fp256bn as _impl

_sys.modules[__name__] = _impl
