"""BCCSP factory: config-driven provider selection (reference
bccsp/factory/factory.go:64 GetBCCSPFromOpts + swfactory/pkcs11factory;
sampleconfig/core.yaml:295-319 BCCSP section).

Config shape (the core.yaml BCCSP block):

  BCCSP:
    Default: TPU          # TPU | SW | PKCS11 | SERVE
                          #  TPU is the accelerator provider (SURVEY
                          #  §2.12: architecturally the out-of-process
                          #  crypto-module slot); PKCS11 is a REAL
                          #  Cryptoki HSM binding (crypto/pkcs11.py)
    SW:
      Hash: SHA2
      Security: 256
      # optional tier pins (absent keys leave earlier pins alone):
      # ECBackend: fastec | hostec_np | hostec | p256
      # IdemixBackend: hostbn | scheme
    TPU:
      MinDeviceBatch: 32  # below this, verification stays on host
    PKCS11:
      Library: /usr/lib/softhsm/libsofthsm2.so
      Pin: "98765432"
      Slot: 0             # optional; first token slot when omitted
    SERVE:
      Address: /tmp/fabserve.sock   # resident sidecar socket
                          #  (fabric_tpu.serve: batch verifies route to
                          #  the warm sidecar; degrade-to-in-process on
                          #  sidecar death, fail-closed masks)

TPU degrades to SW when no device answers; PKCS11 errors HARD on a
missing library (an operator who configured an HSM must not silently
run on software keys), like the reference's pkcs11factory.  SERVE
builds the sidecar client rung — registered by fabric_tpu.serve.client
via register_provider_factory (dependency inversion: serve sits above
crypto in the layer map, so the factory never imports it statically).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from fabric_tpu.common import flogging
from fabric_tpu.crypto.bccsp import Provider, SoftwareProvider

logger = flogging.must_get_logger("bccsp.factory")


class FactoryError(Exception):
    pass


# -- pluggable provider rungs (dependency inversion) ------------------------
# Higher-layer packages (the serve sidecar lives above crypto in
# tools/layers.toml) register their provider builders here instead of
# being imported upward.  _LAZY_PROVIDER_MODULES maps a config Default
# to the module whose import performs that registration — resolved via
# importlib at runtime, so the layer map stays a static DAG.

_PROVIDER_FACTORIES: Dict[str, Callable[[dict], Provider]] = {}
_LAZY_PROVIDER_MODULES = {"SERVE": "fabric_tpu.serve.client"}


def register_provider_factory(
    name: str, builder: Callable[[dict], Provider]
) -> None:
    """Register a config ``Default:`` name -> provider builder (the
    builder receives the full BCCSP config dict)."""
    _PROVIDER_FACTORIES[name.upper()] = builder


def _resolve_provider_factory(name: str) -> Optional[Callable]:
    builder = _PROVIDER_FACTORIES.get(name)
    if builder is not None:
        return builder
    module = _LAZY_PROVIDER_MODULES.get(name)
    if module is None:
        return None
    import importlib

    try:
        importlib.import_module(module)  # import side effect: registers
    except ImportError as exc:
        raise FactoryError(
            f"BCCSP default {name!r} needs {module} which failed to "
            f"import: {exc}"
        ) from exc
    builder = _PROVIDER_FACTORIES.get(name)
    if builder is None:
        raise FactoryError(
            f"{module} imported but did not register a {name!r} provider"
        )
    return builder


def provider_from_config(cfg: Optional[dict]) -> Provider:
    """BCCSP config dict -> Provider instance."""
    cfg = cfg or {}
    default = str(cfg.get("Default", "TPU")).upper()

    sw_cfg = cfg.get("SW") or {}
    hash_family = str(sw_cfg.get("Hash", "SHA2")).upper()
    security = int(sw_cfg.get("Security", 256))
    if hash_family != "SHA2" or security != 256:
        # the reference factory rejects unsupported suites outright
        raise FactoryError(
            f"unsupported BCCSP suite {hash_family}-{security} "
            "(only SHA2-256 is implemented)"
        )

    # Host EC tier (fastec -> hostec_np -> hostec -> p256 ladder,
    # crypto/bccsp.py): process-wide, since every provider's host path
    # shares the seam.  A KNOWN tier that can't load is a hard error —
    # an operator who pinned the OpenSSL tier must not silently run the
    # slower ladder, mirroring the PKCS11 discipline below.  An UNKNOWN
    # value warns and leaves the current selection alone (a config
    # written for a newer ladder must not brick an older node), exactly
    # like the FABRIC_TPU_EC_BACKEND env-var semantics from PR 1.  An
    # ABSENT key also leaves the selection alone, so building a provider
    # from a plain config cannot reset an earlier explicit pin.
    if "ECBackend" in sw_cfg:
        ec_backend = str(sw_cfg["ECBackend"]).lower()
        from fabric_tpu.crypto.bccsp import (
            ec_backend_name,
            select_ec_backend,
        )

        try:
            select_ec_backend(ec_backend)
        except ValueError:
            # error-level: this may be a typo'd pin running a slower
            # tier than the operator intended — but per the ladder's
            # forward-compat contract an unknown NAME never bricks an
            # older node (a KNOWN-but-unavailable tier still raises)
            logger.error(
                "BCCSP.SW.ECBackend %r is not a known tier "
                "(fastec/hostec_np/hostec/p256); keeping the current "
                "%s backend",
                ec_backend,
                ec_backend_name(),
            )
        except ImportError as exc:
            raise FactoryError(
                f"BCCSP.SW.ECBackend {ec_backend!r} unavailable: {exc}"
            ) from exc
        logger.info("host EC backend: %s", ec_backend_name())

    # Idemix batch-verify rung (hostbn -> scheme ladder, crypto/bccsp.py
    # IDEMIX_TIERS): same contract as ECBackend — a KNOWN tier that
    # cannot load is a hard error, an UNKNOWN name warns and keeps the
    # current selection, an ABSENT key leaves an earlier pin alone.
    if "IdemixBackend" in sw_cfg:
        idemix_backend = str(sw_cfg["IdemixBackend"]).lower()
        from fabric_tpu.crypto.bccsp import (
            idemix_backend_name,
            select_idemix_backend,
        )

        try:
            select_idemix_backend(idemix_backend)
        except ValueError:
            logger.error(
                "BCCSP.SW.IdemixBackend %r is not a known tier "
                "(hostbn/scheme); keeping the current %s backend",
                idemix_backend,
                idemix_backend_name(),
            )
        except ImportError as exc:
            raise FactoryError(
                f"BCCSP.SW.IdemixBackend {idemix_backend!r} "
                f"unavailable: {exc}"
            ) from exc
        logger.info("idemix batch backend: %s", idemix_backend_name())

    # Registered rungs first (SERVE and future out-of-process providers):
    # the tier pins above already applied, so a rung's in-process
    # fallback rides the operator's chosen ladder.
    registered = _resolve_provider_factory(default)
    if registered is not None:
        try:
            return registered(cfg)
        except FactoryError:
            raise
        except Exception as exc:
            raise FactoryError(
                f"BCCSP default {default!r} provider failed to build: {exc}"
            ) from exc

    if default == "SW":
        return SoftwareProvider()
    if default == "PKCS11":
        # HSM slot (bccsp/factory/pkcs11factory.go): a missing or
        # unloadable library is a hard error, exactly like the
        # reference — an operator who configured an HSM must not be
        # silently downgraded to software keys
        from fabric_tpu.crypto.pkcs11 import Cryptoki, PKCS11Provider

        p11 = cfg.get("PKCS11") or {}
        library = p11.get("Library")
        if not library:
            raise FactoryError("BCCSP.PKCS11.Library is required")
        token = Cryptoki(
            library, str(p11.get("Pin", "")), p11.get("Slot")
        )
        return PKCS11Provider(token)
    if default == "TPU":
        try:
            from fabric_tpu.crypto.tpu_provider import TPUProvider

            provider = TPUProvider()
            tpu_cfg = cfg.get("TPU") or {}
            if "MinDeviceBatch" in tpu_cfg:
                provider.MIN_DEVICE_BATCH = int(tpu_cfg["MinDeviceBatch"])
            return provider
        except Exception as exc:  # noqa: BLE001 - no device: degrade to SW
            logger.warning(
                "TPU BCCSP unavailable (%s); falling back to SW", exc
            )
            return SoftwareProvider()
    raise FactoryError(f"unknown BCCSP default {default!r}")
