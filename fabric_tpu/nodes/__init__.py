from fabric_tpu.nodes.orderer import OrdererNode  # noqa: F401
from fabric_tpu.nodes.peer import PeerNode  # noqa: F401
