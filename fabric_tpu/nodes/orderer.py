"""Orderer node composition root (reference orderer/common/server/
main.go): multichannel registrar + broadcast handler + deliver engine
behind one gRPC server serving orderer.AtomicBroadcast.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from fabric_tpu.comm.server import GRPCServer
from fabric_tpu.comm.services import register_atomic_broadcast
from fabric_tpu.deliver.server import BlockSource, DeliverHandler
from fabric_tpu.operations import Options as OpsOptions, System
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos import common_pb2, protoutil


def parse_duration(text: str, default: float) -> float:
    """"2s" / "500ms" / "1m" -> seconds (orderer.yaml BatchTimeout)."""
    if not text:
        return default
    text = text.strip().lower()
    try:
        if text.endswith("ms"):
            return float(text[:-2]) / 1000.0
        if text.endswith("s"):
            return float(text[:-1])
        if text.endswith("m"):
            return float(text[:-1]) * 60.0
        return float(text)
    except ValueError:
        return default


class OrdererNode:
    def __init__(
        self,
        work_dir: str,
        signer=None,
        listen_address: str = "127.0.0.1:0",
        system_channel_id: Optional[str] = None,
        ops_address: Optional[str] = None,
        provider=None,
        raft_node_id: int = 1,
        raft_tick_seconds: float = 0.1,
        # grpc.ServerCredentials (comm.server.CertReloader.credentials()
        # for hot rotation) + per-service concurrent-RPC caps, matching
        # the peer node's surface (General.TLS / General.Limits)
        tls_credentials=None,
        rpc_limits=None,
        # root CA PEM for OUTBOUND intra-cluster dials (Step/raft + the
        # follower block puller): when this node serves TLS, its peers
        # do too, so the cluster client must dial TLS as well
        cluster_root_ca: bytes = b"",
    ):
        from fabric_tpu.orderer.cluster import ClusterClient, ClusterService

        # cluster comm (orderer/common/cluster): raft messages between
        # orderers ride the Step stream on THIS node's gRPC listener;
        # consenter endpoints come from each channel's consensus metadata
        # at join time (main.go initializeClusterClientConfig).
        self.raft_node_id = raft_node_id
        self.raft_tick_seconds = raft_tick_seconds
        # ticker threads (created by start(); stop() joins them, and
        # must stay a safe no-op before start)
        self._flusher: Optional[threading.Thread] = None
        self._raft_ticker: Optional[threading.Thread] = None
        self._cluster_root_ca = cluster_root_ca or None
        self.cluster_client = ClusterClient(
            raft_node_id, {}, root_ca=self._cluster_root_ca
        )
        self.registrar = Registrar(
            work_dir,
            signer=signer,
            system_channel_id=system_channel_id,
            provider=provider,
            raft_node_id=raft_node_id,
            raft_transport_factory=self.cluster_client.transport_factory,
            follower_endpoint_factory=self._follower_endpoints,
        )
        self.broadcast = BroadcastHandler(
            self.registrar, signer=signer, cluster_client=self.cluster_client
        )
        self._block_events: dict[str, threading.Condition] = {}
        self.registrar.on_block(self._notify_block)
        # keep consenter endpoints current for channels created ANY way
        # (join, system-channel creation, consenter-set config updates)
        self.registrar.on_chain(self._refresh_cluster_endpoints)

        self.deliver = DeliverHandler(self._block_source)

        self.ops: Optional[System] = None
        interceptors = []
        if ops_address is not None:
            # same provider discipline as the peer shell: the fabobs
            # data-plane registry IS the /metrics surface
            from fabric_tpu.common import fabobs

            obs = fabobs.ensure_enabled()
            self.ops = System(
                OpsOptions(listen_address=ops_address, provider=obs.provider)
            )
            self.ops.register_checker("registrar", lambda: None)
            from fabric_tpu.comm.interceptors import (
                LoggingInterceptor,
                MetricsInterceptor,
            )

            interceptors = [
                LoggingInterceptor(),
                MetricsInterceptor(self.ops.provider),
            ]

        if rpc_limits:
            from fabric_tpu.comm.server import ConcurrencyLimiter

            interceptors = [ConcurrencyLimiter(dict(rpc_limits))] + list(
                interceptors
            )
        self.server = GRPCServer(
            listen_address,
            credentials=tls_credentials,
            interceptors=interceptors,
        )
        register_atomic_broadcast(self.server, self.broadcast, self.deliver)
        ClusterService(self.registrar, self.broadcast).register(self.server)

    # -- block availability signaling (deliver BLOCK_UNTIL_READY) --------
    def _cond(self, channel_id: str) -> threading.Condition:
        return self._block_events.setdefault(channel_id, threading.Condition())

    def _notify_block(self, channel_id: str, _block) -> None:
        cond = self._cond(channel_id)
        with cond:
            cond.notify_all()

    def _follower_endpoints(self, addresses):
        """addresses -> deliver-endpoint callables for FollowerChain block
        pulling (cluster.BlockPuller analog over fellow orderers'
        AtomicBroadcast/Deliver)."""
        from fabric_tpu.comm.server import channel_to
        from fabric_tpu.comm.services import deliver_stream

        import grpc

        def make(addr):
            def endpoint(env):
                conn = channel_to(addr, self._cluster_root_ca)
                try:
                    yield from deliver_stream(conn, env)
                except grpc.RpcError as e:
                    # surface as the deliver client's retryable error
                    # class so backoff/failover applies (and a server
                    # shutdown doesn't kill the follower thread)
                    raise ConnectionError(f"deliver rpc failed: {e.code()}")
                finally:
                    conn.close()

            return endpoint

        return [make(a) for a in addresses]

    def _block_source(self, channel_id: str) -> Optional[BlockSource]:
        support = self.registrar.get_chain(channel_id)
        if support is None:
            # followers serve deliver too (participation-API semantics):
            # readers can tail a replicating channel
            follower = self.registrar.followers.get(channel_id)
            if follower is None:
                return None

            def wait_poll(number: int, timeout: float) -> bool:
                # poll the replicating ledger for the FULL timeout (the
                # deliver engine calls this once and errors on False)
                budget = (
                    threading.TIMEOUT_MAX if timeout is None else timeout
                )
                deadline = time.monotonic() + budget
                while True:
                    if follower.height > number:
                        return True
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    threading.Event().wait(min(remaining, 0.1))

            return BlockSource(
                follower.get_block, lambda: follower.height, wait_poll
            )
        cond = self._cond(channel_id)

        def wait_for(number: int, timeout: float) -> bool:
            deadline = threading.TIMEOUT_MAX if timeout is None else timeout
            with cond:
                if support.height > number:
                    return True
                cond.wait(timeout=deadline)
            return support.height > number

        return BlockSource(support.get_block, lambda: support.height, wait_for)

    # -- lifecycle -------------------------------------------------------
    def join_channel(self, genesis_block: common_pb2.Block):
        return self.registrar.join_channel(genesis_block)

    def _refresh_cluster_endpoints(self, support) -> None:
        """Per-channel consenter endpoints from the channel's consensus
        metadata (reference: cluster endpoints come from the config
        block; refreshed on chain start and every config block)."""
        bundle = support.bundle
        if bundle.orderer is None or bundle.orderer.consensus_type != "etcdraft":
            return
        from fabric_tpu.protos import configuration_pb2

        try:
            meta = protoutil.unmarshal(
                configuration_pb2.RaftConfigMetadata,
                bundle.orderer.consensus_metadata,
            )
        except ValueError:
            return
        # raft ids are STABLE per consenter (orderer/consenter_ids.py) —
        # route by the chain's tracker, never by list position: after a
        # non-tail removal the positions shift but the ids must not
        tracker = getattr(support.chain, "tracker", None)
        if tracker is not None:
            endpoints = {
                node_id: addr for addr, node_id in tracker.ids.items()
            }
        else:
            endpoints = {
                i + 1: f"{c.host}:{c.port}"
                for i, c in enumerate(meta.consenters)
            }
        self.cluster_client.set_channel_endpoints(
            support.channel_id, endpoints
        )

    def _raft_tick_loop(self) -> None:
        """Wall-clock ticker driving raft election/heartbeat timers for
        every raft channel (etcdraft chain.go's clock)."""
        while not self._stopped.wait(self.raft_tick_seconds):
            for support in list(self.registrar.chains.values()):
                chain = support.chain
                if hasattr(chain, "tick") and hasattr(chain, "node"):
                    try:
                        chain.tick()
                    except Exception:  # noqa: BLE001 - chain-local failure
                        pass

    def _flush_loop(self) -> None:
        """Batch-timeout ticker (reference blockcutter timer in the
        consenter run loops): a channel's pending batch is cut only once
        its OLDEST message has waited the channel's BatchTimeout — a
        fixed global cadence would force-cut partial blocks and make any
        BatchTimeout above the cadence meaningless."""
        while not self._stopped.wait(self._next_flush_interval()):
            for support in list(self.registrar.chains.values()):
                timeout = (
                    parse_duration(support.bundle.orderer.batch_timeout, 0.5)
                    if support.bundle.orderer is not None
                    else 0.5
                )
                cutter = getattr(support.chain, "cutter", None)
                age = cutter.pending_age() if cutter is not None else None
                if age is None or age < timeout:
                    continue
                try:
                    support.chain.flush()
                except Exception:  # noqa: BLE001 - chain-local failure
                    pass

    def _next_flush_interval(self) -> float:
        """Poll at a fraction of the smallest BatchTimeout so expiry is
        detected promptly without busy-spinning."""
        intervals = [0.5]
        for support in self.registrar.chains.values():
            if support.bundle.orderer is not None:
                intervals.append(
                    parse_duration(support.bundle.orderer.batch_timeout, 0.5)
                )
        return min(0.5, max(0.02, min(intervals) / 4.0))

    def start(self) -> str:
        if self.ops is not None:
            self.ops.start()
        self._stopped = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, name="blockcutter-timeout", daemon=True
        )
        self._flusher.start()
        self._raft_ticker = threading.Thread(
            target=self._raft_tick_loop, name="raft-ticker", daemon=True
        )
        self._raft_ticker.start()
        return self.server.start()

    def stop(self) -> None:
        if getattr(self, "_stopped", None) is not None:
            self._stopped.set()
        # reap the cutter/raft loops: both poll _stopped, so the joins
        # settle within one tick — an unjoined ticker surviving stop()
        # keeps firing raft ticks into a torn-down registrar
        for t in (self._flusher, self._raft_ticker):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=2.0)
        for follower in list(self.registrar.followers.values()):
            follower.stop()
        self.cluster_client.stop()
        self.server.stop()
        if self.ops is not None:
            self.ops.stop()

    @property
    def addr(self) -> str:
        return self.server.addr
