"""Peer node composition root (reference usable-inter-nal/peer/node/
start.go serve()): channels + endorser + chaincode support + system
chaincodes + deliver services behind one gRPC server, plus a
deliver-client loop pulling blocks from the orderer into the commit
pipeline (core/deliverservice).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional

from fabric_tpu.chaincode.support import ChaincodeSupport
from fabric_tpu.comm.server import GRPCServer, channel_to
from fabric_tpu.comm.services import (
    deliver_stream,
    register_endorser,
    register_peer_deliver,
)
from fabric_tpu.deliver.client import seek_envelope
from fabric_tpu.deliver.server import BlockSource, DeliverHandler
from fabric_tpu.endorser.endorser import Endorser
from fabric_tpu.gossip.coordinator import TransientStore
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.msp.signer import SigningIdentity
from fabric_tpu.operations import Options as OpsOptions, System
from fabric_tpu.peer.channel import Channel
from fabric_tpu.protos import ab_pb2, common_pb2
from fabric_tpu.scc import CSCC, LSCC, QSCC
from fabric_tpu.validation.validator import ChaincodeRegistry


class PeerNode:
    def __init__(
        self,
        work_dir: str,
        msp_manager: MSPManager,
        signer: SigningIdentity,
        registry_factory: Callable[[str], ChaincodeRegistry],
        listen_address: str = "127.0.0.1:0",
        ops_address: Optional[str] = None,
        provider=None,
        external_builders=None,
        device_mvcc: bool = False,
        # DEFAULT-ON (SURVEY P7): every channel validator funnels its
        # device batches through one coalescing launch queue; pass False
        # to route batch_verify straight at the provider
        shared_verify_batcher: bool = True,
        # dispatcher.PluginRegistry with custom validation plugins loaded
        # from node config (reference core/handlers/library registry)
        plugin_registry=None,
        # grpc.ServerCredentials (e.g. comm.server.CertReloader
        # .credentials() for hot-rotating TLS) — None = plaintext
        tls_credentials=None,
        # per-service concurrent-RPC caps, e.g. {"protos.Endorser": 50}
        # (reference usable-inter-nal/peer/node/grpc_limiters.go)
        rpc_limits=None,
        # channel_id -> statecouch.CouchStateAdapter (public-state
        # operational mirror; reference statecouchdb's deployment role)
        state_mirror_factory=None,
        # root CA PEM for the deliver client's orderer dials (the
        # reference's peer.tls.rootcert for deliveryclient connections)
        orderer_root_ca: bytes = b"",
    ):
        self.work_dir = work_dir
        self.msp_manager = msp_manager
        self.signer = signer
        self.provider = provider
        if shared_verify_batcher:
            # one device-launch queue for every channel validator on the
            # node (SURVEY P7): small per-channel batches coalesce into
            # large fixed-shape launches with bounded backpressure
            from fabric_tpu.crypto.bccsp import default_provider
            from fabric_tpu.parallel.batcher import BatchingProvider

            self.provider = BatchingProvider(provider or default_provider())
        self.device_mvcc = device_mvcc
        self.plugin_registry = plugin_registry
        self._state_mirror_factory = state_mirror_factory
        self._orderer_root_ca = orderer_root_ca or None
        self._registry_factory = registry_factory
        self.channels: Dict[str, Channel] = {}
        self.transient = TransientStore()
        self._commit_conds: Dict[str, threading.Condition] = {}
        self._stop = threading.Event()
        self._pull_threads: list[threading.Thread] = []
        # last deliver-loop failure per channel (blocksprovider logging)
        self.deliver_errors: Dict[str, str] = {}
        self._commit_listeners: list[Callable] = []
        self.snapshot_managers: Dict[str, object] = {}
        self.gossip_nodes: Dict[str, object] = {}
        self._pipelines: Dict[str, object] = {}

        # out-of-process chaincode runtime (reference core/container
        # externalbuilder + core/chaincode/persistence): installed
        # packages on disk, a launcher for subprocesses, and the shim
        # stream listener on this peer's gRPC server.
        from fabric_tpu.chaincode.extbuilder import ExternalBuilder, Launcher
        from fabric_tpu.chaincode.extserver import ChaincodeListener
        from fabric_tpu.chaincode.package import PackageStore

        self.package_store = PackageStore(
            os.path.join(work_dir, "lifecycle", "chaincodes")
        )
        self.launcher = Launcher(
            os.path.join(work_dir, "ccbuild"),
            builders=[
                ExternalBuilder(p) for p in (external_builders or [])
            ],
        )
        self.cc_listener = ChaincodeListener()
        self._cc_sources: Dict[tuple, str] = self._load_cc_sources()

        self.support = ChaincodeSupport(
            state_getter=lambda cid: (
                self.channels[cid].ledger.state_db
                if cid in self.channels
                else None
            ),
            listener=self.cc_listener,
            launcher=self.launcher,
            package_store=self.package_store,
            source_resolver=lambda cid, name: self._cc_sources.get(
                (cid, name)
            )
            or self._cc_sources.get(("", name)),
            chaincode_address=lambda: self.addr,
        )
        self.support.register(
            "qscc",
            QSCC(lambda cid: self._ledger(cid)),
            system=True,
        )
        self.support.register(
            "cscc",
            CSCC(
                join_chain=self.join_channel,
                channel_list=lambda: sorted(self.channels),
                get_config_block=self._config_block,
                join_by_snapshot=self.join_channel_by_snapshot,
            ),
            system=True,
        )
        self.support.register(
            "lscc",
            LSCC(self._list_chaincodes, v20_active=self._v20_active),
            system=True,
        )
        from fabric_tpu.scc.lifecycle_scc import LifecycleSCC

        self.support.register(
            "_lifecycle",
            LifecycleSCC(
                install=self.install_chaincode,
                list_installed=self.package_store.list_installed,
                approve=self.approve_chaincode,
                load_package=self.package_store.load,
            ),
            system=True,
        )

        self.endorser = Endorser(
            signer,
            msp_manager,
            self.support,
            get_ledger=lambda cid: self._ledger(cid),
            on_pvt_results=self._distribute_pvt,
        )
        self.deliver = DeliverHandler(self._block_source)

        self.ops: Optional[System] = None
        self.committer_metrics = None
        interceptors = []
        if ops_address is not None:
            # the data plane (batcher, ladder rungs, pipeline stages,
            # retries, fault fires) reports onto the SAME provider the
            # ops server scrapes: first enabler wins process-wide, and
            # this node's System serves whichever registry is live
            from fabric_tpu.common import fabobs

            obs = fabobs.ensure_enabled()
            self.ops = System(
                OpsOptions(listen_address=ops_address, provider=obs.provider)
            )
            from fabric_tpu.comm.interceptors import (
                LoggingInterceptor,
                MetricsInterceptor,
            )
            from fabric_tpu.ledger.ledgermetrics import CommitterMetrics

            # committer metrics (kvledger/metrics.go) surface on /metrics;
            # RPC logs + counters (grpclogging/grpcmetrics) wrap the server
            self.committer_metrics = CommitterMetrics(self.ops.provider)
            interceptors = [
                LoggingInterceptor(),
                MetricsInterceptor(self.ops.provider),
            ]

            def _device_check():
                # surfaces TPUProvider's degraded flag on /healthz: the
                # node KEEPS committing through the software fallback,
                # but operators see the accelerator outage
                if getattr(self.provider, "degraded", False):
                    raise RuntimeError(
                        "accelerator dispatch degraded to software path"
                    )

            self.ops.register_checker("bccsp-device", _device_check)

        if rpc_limits:
            from fabric_tpu.comm.server import ConcurrencyLimiter

            interceptors = [ConcurrencyLimiter(dict(rpc_limits))] + list(
                interceptors
            )
        self.server = GRPCServer(
            listen_address,
            credentials=tls_credentials,
            interceptors=interceptors,
        )
        register_endorser(self.server, self.endorser)
        register_peer_deliver(
            self.server,
            self.deliver,
            pvt_entries=self._pvt_entries_for,
            # private-collection cleartext leaves the peer only for
            # clients satisfying the channel Readers policy (the event
            # ACL the reference checks on this stream)
            pvt_policy_checker=lambda cid, sd: self._channel_policy_check(
                cid, "/Channel/Application/Readers", sd
            ),
        )
        from fabric_tpu.comm.services import register_snapshot_service

        register_snapshot_service(
            self.server,
            lambda cid: self.snapshot_managers.get(cid),
            # snapshot admin ops need channel admins (reference
            # snapshot/* ACL defaults)
            policy_checker=lambda cid, sd: self._channel_policy_check(
                cid, "/Channel/Application/Admins", sd
            ),
        )
        self.cc_listener.register(self.server)

        # discovery service (discovery/service.go) on the same listener
        from fabric_tpu.discovery.server import DiscoveryServer
        from fabric_tpu.discovery.service import DiscoveryService

        self.discovery = DiscoveryService(
            peers_provider=self._discovery_peers,
            bundle_provider=self._discovery_bundle,
            policy_provider=self._discovery_policy,
        )
        DiscoveryServer(self.discovery).register(self.server)
        self._bundle_cache: Dict[str, tuple] = {}

    # -- chaincode lifecycle (install/approve, the org-local half) --------
    def _sources_path(self) -> str:
        return os.path.join(self.work_dir, "lifecycle", "local_sources.json")

    def _load_cc_sources(self) -> Dict[tuple, str]:
        import json

        try:
            with open(self._sources_path()) as f:
                raw = json.load(f)
            return {tuple(k.split("\x00", 1)): v for k, v in raw.items()}
        except (OSError, ValueError):
            return {}

    def install_chaincode(self, package_bytes: bytes) -> str:
        """`peer lifecycle chaincode install` (lifecycle.go InstallChaincode):
        persist the package, return its package-id."""
        return self.package_store.install(package_bytes).package_id

    def approve_chaincode(
        self, channel_id: str, name: str, package_id: str
    ) -> None:
        """The org-local half of ApproveChaincodeDefinitionForOrg
        (lifecycle.go:415): bind this org's installed package-id to the
        chaincode name — the reference stores this in the org's implicit
        collection, i.e. per-peer state, which is exactly what this is."""
        import json

        self._cc_sources[(channel_id, name)] = package_id
        os.makedirs(os.path.dirname(self._sources_path()), exist_ok=True)
        with open(self._sources_path(), "w") as f:
            json.dump(
                {"\x00".join(k): v for k, v in self._cc_sources.items()}, f,
                sort_keys=True,
            )

    # -- private data distribution (endorser.go distributePrivateData) ----
    def _distribute_pvt(self, channel_id: str, tx_id: str, pvt_writes) -> None:
        """Endorsement-time private data: local transient store first,
        then a gossip push to the channel's members so their transient
        stores are warm before the block commits (gossip/privdata
        pull.go DistributePrivateData)."""
        for ns, coll, raw in pvt_writes:
            self.transient.persist(tx_id, ns, coll, raw)
        node = self.gossip_nodes.get(channel_id)
        if node is not None:
            node.disseminate_pvt(tx_id, pvt_writes)

    # -- discovery providers (discovery/support analog) -------------------
    def _discovery_peers(self, channel_id: str):
        from fabric_tpu.discovery.service import PeerInfo

        ch = self.channels.get(channel_id)
        if ch is None:
            return []
        chaincodes = tuple(ch.validator.registry.names())
        peers = [
            PeerInfo(
                msp_id=self.signer.msp_id,
                endpoint=self.addr,
                ledger_height=ch.ledger.height,
                chaincodes=chaincodes,
            )
        ]
        node = self.gossip_nodes.get(channel_id)
        if node is not None:
            # gossip peer ids are "MSPID:host:port" (see
            # enable_gossip_for_channel)
            for member in node.membership.alive_peers():
                msp_id, _, endpoint = str(member).partition(":")
                if endpoint and endpoint != self.addr:
                    peers.append(
                        PeerInfo(
                            msp_id=msp_id,
                            endpoint=endpoint,
                            chaincodes=chaincodes,
                        )
                    )
        return peers

    def _discovery_bundle(self, channel_id: str):
        block = self._config_block(channel_id)
        if block is None:
            return None
        cached = self._bundle_cache.get(channel_id)
        if cached is not None and cached[0] == block.header.number:
            return cached[1]
        from fabric_tpu.channelconfig.bundle import bundle_from_genesis_block

        bundle = bundle_from_genesis_block(block, self.provider)
        self._bundle_cache[channel_id] = (block.header.number, bundle)
        return bundle

    def _discovery_policy(self, chaincode: str, channel_id: str):
        ch = self.channels.get(channel_id)
        if ch is None:
            return None
        definition = ch.validator.registry.get(chaincode)
        return definition.endorsement_policy if definition else None

    # -- helpers ---------------------------------------------------------
    def _ledger(self, channel_id: str):
        ch = self.channels.get(channel_id)
        return ch.ledger if ch else None

    def _config_block(self, channel_id: str):
        """Latest config block via the last block's LAST_CONFIG pointer
        (reference cscc getConfigBlock -> blockledger lastConfig)."""
        ch = self.channels.get(channel_id)
        if ch is None:
            return None
        store = ch.ledger.block_store
        last = store.get_block_by_number(store.height - 1)
        if last is None:
            return None
        metas = last.metadata.metadata
        if len(metas) > common_pb2.SIGNATURES and metas[common_pb2.SIGNATURES]:
            from fabric_tpu.protos import protoutil

            try:
                meta = protoutil.unmarshal(
                    common_pb2.Metadata, metas[common_pb2.SIGNATURES]
                )
                if meta.value:
                    lc = protoutil.unmarshal(common_pb2.LastConfig, meta.value)
                    pointed = store.get_block_by_number(lc.index)
                    if pointed is not None:
                        return pointed
            except ValueError:
                pass
        return store.get_block_by_number(store.base_height)

    def _list_chaincodes(self):
        out = []
        for cid, ch in self.channels.items():
            for name in ch.validator.registry.names():
                out.append((name, "1.0"))
        return sorted(set(out))

    def _block_source(self, channel_id: str) -> Optional[BlockSource]:
        ch = self.channels.get(channel_id)
        if ch is None:
            return None
        cond = self._commit_conds.setdefault(channel_id, threading.Condition())  # fabdep: disable=unguarded-shared-write  # dict.setdefault is atomic under the GIL; one Condition per channel wins

        def wait_for(number: int, timeout: float) -> bool:
            with cond:
                if ch.ledger.height > number:
                    return True
                cond.wait(timeout=timeout)
            return ch.ledger.height > number

        return BlockSource(
            ch.ledger.block_store.get_block_by_number,
            lambda: ch.ledger.height,
            wait_for,
        )

    def _v20_active(self, channel_id: str) -> bool:
        """ONE definition of 'this channel runs the v2.0 lifecycle' shared
        by lscc (deploy refusal) and the validator's write-set routing —
        a missing bundle/capabilities section counts as V2_0, so the two
        can never disagree about which regime governs the channel."""
        caps = self._app_capabilities(channel_id)
        return caps is None or caps.v20_validation

    def _app_capabilities(self, channel_id: str):
        bundle = self._discovery_bundle(channel_id)
        app = bundle.application if bundle is not None else None
        return app.capabilities if app is not None else None

    def _legacy_writeset_check(self, channel_id, rwset, invoked_ns):
        """Capability-routed legacy write-set guards (txvalidator v14
        router analog): V2_0 channels use the lifecycle rules only;
        V1_2+ channels get the v13 guards incl. collection validation
        against the committed LSCC record; older channels get v12."""
        from fabric_tpu.validation.legacy import (
            check_v12_writeset,
            check_v13_writeset,
            collection_key,
        )

        if self._v20_active(channel_id):
            return None  # _lifecycle governs deploys on V2_0 channels
        caps = self._app_capabilities(channel_id)
        ch = self.channels.get(channel_id)

        def committed_collections(cc: str):
            if ch is None:
                return None
            vv = ch.ledger.state_db.get_state("lscc", collection_key(cc))
            return vv.value if vv is not None else None

        if caps.v12_validation:
            return check_v13_writeset(rwset, invoked_ns, committed_collections)
        return check_v12_writeset(rwset, invoked_ns)

    def _collection_access(self, channel_id: str, ns: str, coll: str):
        """CollectionAccess for a committed chaincode's collection
        (reference core/common/privdata/store.go: _lifecycle definitions
        first, then the legacy LSCC '<cc>~collection' record — legacy
        channels deployed their collections through LSCC and must keep
        reconciling).  None when undefined."""
        from fabric_tpu.ledger.collections import (
            CollectionStore,
            NoSuchCollectionError,
        )
        from fabric_tpu.lifecycle import NAMESPACE as LIFECYCLE_NS
        from fabric_tpu.lifecycle.lifecycle import LifecycleResources
        from fabric_tpu.validation.legacy import collection_key

        ch = self.channels.get(channel_id)
        if ch is None:
            return None

        def state_get(state_ns: str, key: str):
            vv = ch.ledger.state_db.get_state(state_ns, key)
            return vv.value if vv is not None else None

        def collections_bytes(cc: str) -> bytes:
            resources = LifecycleResources(
                public_get=lambda key: state_get(LIFECYCLE_NS, key),
                public_put=lambda *a: None,
                org_get=lambda org, key: None,
                org_put=lambda *a: None,
                org_names=[],
            )
            cd = resources.query_chaincode_definition(cc)
            if cd is not None and cd.collections:
                return cd.collections
            return state_get("lscc", collection_key(cc)) or b""

        try:
            return CollectionStore(collections_bytes).collection(ns, coll)
        except NoSuchCollectionError:
            return None

    def _channel_policy_check(self, channel_id: str, path: str, sd) -> None:
        """Evaluate one SignedData against a channel policy path (raises
        on failure; signature verification happens inside the policy
        evaluation, policies/policy.go SignatureSetToValidIdentities)."""
        bundle = self._discovery_bundle(channel_id)
        if bundle is None:
            raise ValueError(f"channel {channel_id} not found")
        policy, ok = bundle.policy_manager.get_policy(path)
        if not ok:
            raise ValueError(f"policy {path} not found on {channel_id}")
        policy.evaluate_signed_data([sd])

    def _pvt_entries_for(self, channel_id: str, block_num: int):
        """DeliverWithPrivateData source: this peer's stored cleartext
        private rwsets for one block (deliverevents.go:270)."""
        ch = self.channels.get(channel_id)
        if ch is None:
            return []
        return ch.ledger.pvt_store.get_pvt_data_by_block(block_num)

    # -- channel lifecycle ----------------------------------------------
    def join_channel(self, genesis_block: common_pb2.Block) -> Channel:
        """cscc JoinChain: bootstrap the channel from its genesis block
        (core/peer/peer.go createChannel)."""
        from fabric_tpu.protos import protoutil

        env = protoutil.get_envelope_from_block_data(genesis_block.data.data[0])
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        chdr = protoutil.unmarshal(
            common_pb2.ChannelHeader, payload.header.channel_header
        )
        channel_id = chdr.channel_id
        if channel_id in self.channels:
            raise ValueError(f"channel {channel_id} already joined")
        if os.path.exists(os.path.join(self.work_dir, channel_id, "PAUSED")):
            # peer node pause marker (reference kvledger pause_resume.go:
            # a paused channel's ledger is not opened and no deliver runs)
            raise ValueError(f"channel {channel_id} is paused")
        ch = Channel(
            channel_id,
            os.path.join(self.work_dir, channel_id),
            self.msp_manager,
            self._registry_factory(channel_id),
            self.provider,
            transient_store=self.transient,
            metrics=self.committer_metrics,
            device_mvcc=self.device_mvcc,
            writeset_check=lambda rwset, ns, cid=channel_id: (
                self._legacy_writeset_check(cid, rwset, ns)
            ),
            plugin_registry=self.plugin_registry,
            state_mirror=(
                self._state_mirror_factory(channel_id)
                if self._state_mirror_factory is not None
                else None
            ),
        )
        if ch.ledger.height == 0:
            ch.ledger.commit(genesis_block)
        self.channels[channel_id] = ch
        # snapshot request bookkeeping (snapshot_mgr.go) + commit hook
        from fabric_tpu.ledger.snapshot import SnapshotRequestManager

        self.snapshot_managers[channel_id] = SnapshotRequestManager(
            ch.ledger, os.path.join(self.work_dir, "snapshots")
        )
        return ch

    def join_channel_by_snapshot(self, snap_dir: str) -> str:
        """cscc JoinChainBySnapshot (reference core/peer
        CreateChannelFromSnapshot): build the channel's ledger from an
        exported snapshot (ledger/snapshot.py create_from_snapshot), then
        wire the Channel around it.  The ledger starts at the snapshot
        height with no block prefix; deliver loops resume from there."""
        from fabric_tpu.ledger.snapshot import SnapshotRequestManager, verify_snapshot

        meta = verify_snapshot(snap_dir)
        channel_id = meta["channel_name"]
        if channel_id in self.channels:
            raise ValueError(f"channel {channel_id} already joined")
        ledger_dir = os.path.join(self.work_dir, channel_id)
        from fabric_tpu.ledger.snapshot import create_from_snapshot

        # build the persistent stores, then let the Channel reopen them
        create_from_snapshot(snap_dir, ledger_dir).close()
        ch = Channel(
            channel_id,
            ledger_dir,
            self.msp_manager,
            self._registry_factory(channel_id),
            self.provider,
            transient_store=self.transient,
            metrics=self.committer_metrics,
            device_mvcc=self.device_mvcc,
            writeset_check=lambda rwset, ns, cid=channel_id: (
                self._legacy_writeset_check(cid, rwset, ns)
            ),
            plugin_registry=self.plugin_registry,
            state_mirror=(
                self._state_mirror_factory(channel_id)
                if self._state_mirror_factory is not None
                else None
            ),
        )
        self.channels[channel_id] = ch
        self.snapshot_managers[channel_id] = SnapshotRequestManager(
            ch.ledger, os.path.join(self.work_dir, "snapshots")
        )
        return channel_id

    def commit_block(self, channel_id: str, block: common_pb2.Block):
        ch = self.channels[channel_id]
        flags = ch.store_block(block)
        self._after_commit(channel_id, block)
        return flags

    def _after_commit(self, channel_id: str, block: common_pb2.Block) -> None:
        cond = self._commit_conds.setdefault(channel_id, threading.Condition())  # fabdep: disable=unguarded-shared-write  # dict.setdefault is atomic under the GIL; one Condition per channel wins
        with cond:
            cond.notify_all()
        mgr = self.snapshot_managers.get(channel_id)
        if mgr is not None:
            mgr.on_block_committed()
        for fn in self._commit_listeners:
            fn(channel_id, block)

    def commit_pipeline(self, channel_id: str):
        """Per-channel two-stage commit pipeline (SURVEY §2.13 P4): the
        deliver loop prepares block N (parse + device sig batch) while
        the committer thread finishes block N-1."""
        from fabric_tpu.peer.pipeline import CommitPipeline

        pipe = self._pipelines.get(channel_id)
        if pipe is None:
            ch = self.channels[channel_id]
            pipe = CommitPipeline(
                ch,
                on_commit=lambda block, _flags: self._after_commit(
                    channel_id, block
                ),
                on_error=lambda block, exc: self.deliver_errors.__setitem__(
                    channel_id, f"pipeline commit failed: {exc}"
                ),
            )
            self._pipelines[channel_id] = pipe
        return pipe

    def on_commit(self, fn: Callable[[str, common_pb2.Block], None]) -> None:
        self._commit_listeners.append(fn)

    # -- gossip (gossip/service gossip_service.go InitializeChannel) -----
    def enable_gossip_for_channel(
        self,
        channel_id: str,
        bootstrap: Sequence[str] = (),
        orderer_addr: Optional[str] = None,
        gossip_listen: str = "127.0.0.1:0",
        # mTLS + TLS-bound ConnEstablish handshake (gossip/comm):
        # {"server_creds": grpc.ServerCredentials,
        #  "client": (root_ca_pem, (key_pem, cert_pem)),
        #  "self_cert_der": bytes, "require_handshake": bool}
        tls: Optional[dict] = None,
    ):
        """Start a gossip node for the channel. With an orderer address,
        the elected leader runs the deliver client and pushes blocks to
        followers; followers self-heal via anti-entropy (state.go)."""
        from fabric_tpu.gossip.comm import GossipNode
        from fabric_tpu.gossip.state import StateProvider

        ch = self.channels[channel_id]
        state = StateProvider(
            channel_id,
            lambda b: self.commit_block(channel_id, b),
            lambda: ch.ledger.height,
        )
        def pvt_reader(block_num, tx_num, ns, coll):
            for e in ch.ledger.pvt_store.get_pvt_data(block_num, tx_num):
                if e.namespace == ns and e.collection == coll:
                    return e.rwset
            return None

        def verify_identity(pki_id: bytes, identity: bytes) -> bool:
            """Certstore adoption gate (reference certstore: identity must
            hash to the claimed pki_id): the claimed MSP id must match the
            serialized identity's, and the identity must deserialize +
            validate (cert chain, CRL) under this channel's MSPs."""
            try:
                msp_id = pki_id.decode().split(":", 1)[0]
                ident, msp = self.msp_manager.deserialize_identity(identity)
                if ident.msp_id != msp_id:
                    return False
                msp.validate(ident)
                return True
            except Exception:  # noqa: BLE001 - any failure = reject
                return False

        def verify_member_sig(identity: bytes, data: bytes, sig: bytes) -> bool:
            try:
                ident, msp = self.msp_manager.deserialize_identity(identity)
                msp.validate(ident)
                ident.verify(data, sig)
                return True
            except Exception:  # noqa: BLE001 - any failure = reject
                return False

        def requester_eligible(ns: str, coll: str, identity: bytes) -> bool:
            """pull.go:614,662: serve a digest only when the REQUESTER's
            identity satisfies that collection's member-orgs policy (read
            from the channel's committed lifecycle definition)."""
            try:
                access = self._collection_access(channel_id, ns, coll)
                if access is None:
                    return False
                ident, msp = self.msp_manager.deserialize_identity(identity)
                return access.is_member(ident, msp)
            except Exception:  # noqa: BLE001 - any failure = ineligible
                return False

        node = GossipNode(
            f"{self.signer.msp_id}:{self.addr}",
            channel_id,
            state,
            ch.ledger.block_store.get_block_by_number,
            lambda: ch.ledger.height,
            listen_address=gossip_listen,
            identity_bytes=self.signer.serialize(),
            verify_identity=verify_identity,
            transient_store=self.transient,
            pvt_reader=pvt_reader,
            pvt_serve_policy=ch.is_eligible,
            pvt_verify_member_sig=verify_member_sig,
            pvt_requester_eligible=requester_eligible,
            pvt_sign_request=self.signer.sign,
            sign_message=self.signer.sign,
            require_signed_alive=True,
            tls_server_creds=(tls or {}).get("server_creds"),
            tls_client=(tls or {}).get("client"),
            self_tls_cert_der=(tls or {}).get("self_cert_der", b""),
            require_handshake=bool((tls or {}).get("require_handshake")),
        )
        # reconciler loop (reconcile.go:104-126): patch missing pvt data
        # recorded at commit from peers, hash-checked on arrival
        node.enable_reconciliation(
            ch.ledger.pvt_store.get_missing_pvt_data,
            ch.ledger.commit_reconciled_pvt,
        )
        self.gossip_nodes[channel_id] = node

        if orderer_addr is not None:
            deliver_state = {"thread": None}

            def on_leadership(am_leader: bool) -> None:
                # one gated thread: it pulls while leader, idles when
                # demoted, resumes on re-election (deliveryclient yield)
                if am_leader and deliver_state["thread"] is None:
                    deliver_state["thread"] = self.start_deliver_for_channel(
                        channel_id,
                        orderer_addr,
                        should_run=lambda: node.is_leader,
                    )

            node.election.on_leadership_change = on_leadership
            self.on_commit(
                lambda cid, block: (
                    node.broadcast_block(block)
                    if cid == channel_id and node.is_leader
                    else None
                )
            )
        node.start()
        for endpoint in bootstrap:
            node.connect(endpoint)
        return node

    # -- deliver client (core/deliverservice) ----------------------------
    def start_deliver_for_channel(
        self,
        channel_id: str,
        orderer_addr: str,
        should_run: Optional[Callable[[], bool]] = None,
        pipelined: bool = True,
    ) -> threading.Thread:
        """Pull blocks from the orderer and feed the commit pipeline
        (blocksprovider.DeliverBlocks). Reconnects with backoff until
        stop() — each reconnect re-seeks from the current height.
        ``should_run`` gates the loop (gossip leadership: a demoted
        leader must stop pulling, reference deliveryclient leadership
        yield). ``pipelined`` (DEFAULT-ON, SURVEY §2.13 P4) overlaps
        block N's parse + device sig batch with block N-1's commit —
        sustained multi-block streams hide the host parse under device
        time; pass False for strictly sequential commits."""

        def run():
            backoff = 0.05
            pipe = self.commit_pipeline(channel_id) if pipelined else None
            while not self._stop.is_set():
                if should_run is not None and not should_run():
                    self._stop.wait(0.2)
                    continue
                try:
                    ch = self.channels[channel_id]
                    if pipe is not None:
                        pipe.drain()  # reseek only from a settled height
                    env = seek_envelope(
                        channel_id,
                        start=ch.ledger.height,
                        signer=self.signer,
                    )
                    conn = channel_to(orderer_addr, self._orderer_root_ca)
                    try:
                        for resp in deliver_stream(conn, env):
                            if self._stop.is_set():
                                return
                            if should_run is not None and not should_run():
                                break  # demoted: idle in the outer loop
                            kind = resp.WhichOneof("Type")
                            if kind == "block":
                                if pipe is not None:
                                    pipe.submit(resp.block)
                                else:
                                    self.commit_block(channel_id, resp.block)
                                backoff = 0.05
                            elif kind == "status":
                                break
                    finally:
                        conn.close()
                except Exception as exc:  # noqa: BLE001 - retried with backoff
                    import traceback

                    self.deliver_errors[channel_id] = (
                        f"{exc}\n{traceback.format_exc()}"
                    )
                self._stop.wait(backoff)
                backoff = min(backoff * 1.2, 2.0)  # reference base 1.2

        t = threading.Thread(
            target=run, name=f"deliver-{channel_id}", daemon=True
        )
        t.start()
        self._pull_threads.append(t)
        return t

    # -- lifecycle -------------------------------------------------------
    def start(self) -> str:
        if self.ops is not None:
            self.ops.start()
        return self.server.start()

    def stop(self) -> None:
        self._stop.set()
        for node in self.gossip_nodes.values():
            node.stop()
        from fabric_tpu.parallel.batcher import BatchingProvider

        if isinstance(self.provider, BatchingProvider):
            self.provider.stop()
        self.launcher.stop()
        self.server.stop()
        if self.ops is not None:
            self.ops.stop()

    @property
    def addr(self) -> str:
        return self.server.addr
