"""Operations server (reference core/operations/system.go).

Serves the node admin plane over HTTP on a local port:

* ``GET  /metrics``  — Prometheus text format  (system.go:134)
* ``GET  /healthz``  — runs registered health checkers; 200 {"status":"OK"}
                       or 503 with the failed checks (system.go:154)
* ``GET  /logspec``  — active flogging spec     (system.go:149)
* ``PUT  /logspec``  — activate a new spec from {"spec": "..."}
* ``GET  /version``  — version payload          (system.go:157-163)

The reference gates mutating endpoints behind TLS client auth; here the
server binds loopback by default and exposes the same surface. Providers:
``prometheus`` | ``statsd`` | ``disabled`` (system.go initializeMetrics).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from fabric_tpu.common import flogging
from fabric_tpu.common.metrics import (
    DisabledProvider,
    PrometheusProvider,
    Provider,
    StatsdProvider,
)

VERSION = "0.1.0"


@dataclass
class Options:
    listen_address: str = "127.0.0.1:0"
    metrics_provider: str = "prometheus"  # prometheus | statsd | disabled
    # an already-live provider to serve instead of constructing one —
    # how the serve sidecar and the node shells hand the fabobs
    # data-plane registry to /metrics (overrides metrics_provider)
    provider: Optional[Provider] = None
    statsd_sink: Optional[Callable[[str], None]] = None
    statsd_prefix: str = ""
    version: str = VERSION
    # TLS + optional mutual-TLS client auth (reference core/operations/
    # system.go TLS.Enabled / ClientCertRequired)
    tls_cert_file: Optional[str] = None
    tls_key_file: Optional[str] = None
    client_ca_file: Optional[str] = None  # set -> client certs REQUIRED
    # profiling endpoints (reference General.Profile.Enabled pprof
    # service, orderer/common/server/main.go:458; gated off by default)
    profile_enabled: bool = False


class System:
    """Owns the metrics provider, the health checker registry and the
    admin HTTP server for one node process."""

    def __init__(self, options: Optional[Options] = None):
        self.options = options or Options()
        self._checkers: Dict[str, Callable[[], None]] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

        kind = self.options.metrics_provider
        if self.options.provider is not None:
            self.provider = self.options.provider
        elif kind == "prometheus":
            self.provider: Provider = PrometheusProvider()
        elif kind == "statsd":
            self.provider = StatsdProvider(
                self.options.statsd_sink or (lambda line: None),
                prefix=self.options.statsd_prefix,
            )
        elif kind == "disabled":
            self.provider = DisabledProvider()
        else:
            raise ValueError(f"unknown metrics provider: {kind}")

    # -- health checker registry (healthz.HealthHandler analog) --
    def register_checker(self, component: str, check: Callable[[], None]) -> None:
        """check() raises to signal failure (healthz lib contract)."""
        with self._lock:
            if component in self._checkers:
                raise ValueError(f"duplicate health checker: {component}")
            self._checkers[component] = check

    def deregister_checker(self, component: str) -> None:
        with self._lock:
            self._checkers.pop(component, None)

    def run_checks(self) -> Dict[str, str]:
        """component -> failure reason for every failing checker."""
        with self._lock:
            checkers = dict(self._checkers)
        failures = {}
        for name, check in checkers.items():
            try:
                check()
            except Exception as exc:  # noqa: BLE001 - report any failure
                failures[name] = str(exc)
        return failures

    # -- HTTP server --
    @property
    def addr(self) -> str:
        assert self._server is not None, "system not started"
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> str:
        host, _, port = self.options.listen_address.rpartition(":")
        system = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    if isinstance(system.provider, PrometheusProvider):
                        self._reply(
                            200,
                            system.provider.gather().encode(),
                            "text/plain; version=0.0.4",
                        )
                    else:
                        self._reply(404, b"metrics provider is not prometheus",
                                    "text/plain")
                elif self.path == "/healthz":
                    failures = system.run_checks()
                    if failures:
                        body = json.dumps(
                            {
                                "status": "Service Unavailable",
                                "failed_checks": [
                                    {"component": c, "reason": r}
                                    for c, r in sorted(failures.items())
                                ],
                            }
                        ).encode()
                        self._reply(503, body, "application/json")
                    else:
                        self._reply(
                            200, b'{"status":"OK"}', "application/json"
                        )
                elif self.path == "/logspec":
                    body = json.dumps({"spec": flogging.spec()}).encode()
                    self._reply(200, body, "application/json")
                elif self.path == "/version":
                    body = json.dumps(
                        {"Version": system.options.version}
                    ).encode()
                    self._reply(200, body, "application/json")
                elif self.path == "/trace":
                    # fabobs flight recorder on demand: the bounded span
                    # ring as Chrome trace-event JSON (404 when the obs
                    # registry is disabled in this process)
                    from fabric_tpu.common import fabobs

                    reg = fabobs.active()
                    if reg is None:
                        self._reply(
                            404, b"observability is not enabled",
                            "text/plain",
                        )
                    else:
                        self._reply(
                            200, reg.dump().encode(), "application/json"
                        )
                elif self.path.startswith("/debug/pprof"):
                    self._pprof()
                else:
                    self._reply(404, b"not found", "text/plain")

            def _pprof(self):
                """Go-pprof analog endpoints (main.go:458 Profile service):
                profile (sampled CPU), goroutine (thread dump), heap."""
                if not system.options.profile_enabled:
                    self._reply(
                        404, b"profiling is not enabled", "text/plain"
                    )
                    return
                from urllib.parse import parse_qs, urlparse

                from fabric_tpu.operations import pprof

                parsed = urlparse(self.path)
                name = parsed.path[len("/debug/pprof") :].strip("/")
                if name == "profile":
                    q = parse_qs(parsed.query)
                    try:
                        seconds = float(q.get("seconds", ["2"])[0])
                    except ValueError:
                        self._reply(
                            400, b"seconds must be a number", "text/plain"
                        )
                        return
                    self._reply(
                        200, pprof.cpu_profile(seconds).encode(), "text/plain"
                    )
                elif name in ("goroutine", "threads"):
                    self._reply(200, pprof.thread_dump().encode(), "text/plain")
                elif name == "heap":
                    self._reply(200, pprof.heap_profile().encode(), "text/plain")
                elif name == "":
                    self._reply(
                        200,
                        b"profiles: profile?seconds=N goroutine heap\n",
                        "text/plain",
                    )
                else:
                    self._reply(404, b"unknown profile", "text/plain")

            def do_PUT(self):
                if self.path != "/logspec":
                    self._reply(404, b"not found", "text/plain")
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    spec = payload.get("spec", "") if isinstance(
                        payload, dict
                    ) else None
                    if not isinstance(spec, str):
                        # {"spec": ["not","a","string"]} used to escape
                        # as an AttributeError out of activate_spec —
                        # a malformed body must 400 and leave the
                        # active spec untouched
                        raise ValueError("logspec body must be {\"spec\": str}")
                    flogging.activate_spec(spec)
                except (ValueError, flogging.InvalidSpecError) as exc:
                    body = json.dumps({"error": str(exc)}).encode()
                    self._reply(400, body, "application/json")
                    return
                self._reply(204, b"", "application/json")

            do_POST = do_PUT

        self._server = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port or 0)), Handler
        )
        tls_bits = (
            self.options.tls_cert_file,
            self.options.tls_key_file,
        )
        if any(tls_bits) or self.options.client_ca_file:
            if not all(tls_bits):
                # never degrade to cleartext on a partial TLS config
                raise ValueError(
                    "operations TLS requires both tls_cert_file and "
                    "tls_key_file"
                )
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(
                self.options.tls_cert_file, self.options.tls_key_file
            )
            if self.options.client_ca_file:
                ctx.load_verify_locations(self.options.client_ca_file)
                ctx.verify_mode = ssl.CERT_REQUIRED
            # defer the handshake to the per-request handler thread — on
            # the listening socket it would run in the accept loop, where
            # one stalled client starves every other ops request
            self._server.socket = ctx.wrap_socket(
                self._server.socket,
                server_side=True,
                do_handshake_on_connect=False,
            )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="operations", daemon=True
        )
        self._thread.start()
        return self.addr

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        # shutdown() blocks until serve_forever returns; the join makes
        # the reap explicit (and covers the not-yet-serving window)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
            self._thread = None
