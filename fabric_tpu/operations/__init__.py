from fabric_tpu.operations.system import System, Options  # noqa: F401
