"""Process profiling for the operations server — the Python analog of
the reference's Go pprof service (orderer/common/server/main.go:458,
peer `node start` profile listener).

Go pprof's value is (a) sampled CPU profiles and (b) goroutine dumps.
The analogs here:

- cpu_profile(seconds): statistical sampler over sys._current_frames()
  across ALL threads, reported as collapsed stacks ("frame;frame;... N")
  — the flamegraph input format, aggregated by identical stack.
- thread_dump(): every live thread's current stack (goroutine profile).
- heap_profile(): tracemalloc top allocation sites; tracing starts on
  first request (like pprof heap profiling being opt-in) so the first
  call returns a short note and subsequent calls return data.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


def thread_dump() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(frames.items()):
        out.append(f"thread {ident} [{names.get(ident, '?')}]:")
        out.extend(
            line.rstrip()
            for line in traceback.format_stack(frame)
        )
        out.append("")
    return "\n".join(out)


def _collapse(frame) -> str:
    parts = []
    stack = traceback.extract_stack(frame)
    for fs in stack:
        parts.append(f"{fs.name}@{fs.filename.rsplit('/', 1)[-1]}:{fs.lineno}")
    return ";".join(parts)


def cpu_profile(seconds: float = 2.0, hz: int = 100) -> str:
    """Sample all threads for `seconds`, emit collapsed-stack lines
    sorted by sample count (flamegraph.pl / speedscope compatible)."""
    seconds = max(0.1, min(seconds, 30.0))
    interval = 1.0 / max(1, min(hz, 1000))
    me = threading.get_ident()
    counts: Counter = Counter()
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            counts[_collapse(frame)] += 1
        samples += 1
        time.sleep(interval)
    lines = [
        f"# cpu profile: {samples} sampling passes over {seconds:.1f}s "
        f"({len(counts)} distinct stacks)"
    ]
    for stack, n in counts.most_common():
        lines.append(f"{stack} {n}")
    return "\n".join(lines) + "\n"


def heap_profile(top: int = 40) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return (
            "# tracemalloc started; allocations are now being traced — "
            "re-request this endpoint to see a snapshot\n"
        )
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    total = sum(s.size for s in snap.statistics("filename"))
    lines = [f"# heap: {total / 1024:.1f} KiB traced, top {len(stats)} sites"]
    for s in stats:
        lines.append(f"{s.traceback} size={s.size} count={s.count}")
    return "\n".join(lines) + "\n"
