"""Fabric-wire-compatible protobuf messages.

Field numbers and package names mirror the public fabric-protos schemas
(the reference consumes them via the fabric-protos-go module, go.mod:42),
so envelopes/blocks produced here parse in stock Fabric tooling and vice
versa. Sources in src/, generated modules committed; regenerate with
gen.sh.

protoc's generated modules import each other by bare module name, so this
package directory is appended to sys.path before loading them.
"""

import os
import sys

_here = os.path.dirname(__file__)
if _here not in sys.path:
    sys.path.append(_here)

from fabric_tpu.protos import common_pb2  # noqa: E402
from fabric_tpu.protos import identities_pb2  # noqa: E402
from fabric_tpu.protos import kv_rwset_pb2  # noqa: E402
from fabric_tpu.protos import msp_principal_pb2  # noqa: E402
from fabric_tpu.protos import peer_pb2  # noqa: E402
from fabric_tpu.protos import policies_pb2  # noqa: E402
from fabric_tpu.protos import rwset_pb2  # noqa: E402
from fabric_tpu.protos import txmgr_updates_pb2  # noqa: E402

__all__ = [
    "common_pb2",
    "identities_pb2",
    "kv_rwset_pb2",
    "msp_principal_pb2",
    "peer_pb2",
    "policies_pb2",
    "rwset_pb2",
    "txmgr_updates_pb2",
]
