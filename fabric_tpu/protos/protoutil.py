"""Proto helpers mirroring the reference's protoutil package.

Byte-exact parity surfaces (reference protoutil/):
- TxID = hex(SHA-256(nonce || creator))                  (proputils.go:357)
- BlockHeaderHash = SHA-256(ASN.1-DER(SEQUENCE{number INTEGER,
  previous_hash OCTET STRING, data_hash OCTET STRING})) (blockutils.go:60)
- BlockDataHash = SHA-256(concat(data...))               (blockutils.go:65)
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Optional, Sequence

from fabric_tpu.protos import common_pb2, identities_pb2, peer_pb2


def compute_tx_id(nonce: bytes, creator: bytes) -> str:
    return hashlib.sha256(nonce + creator).hexdigest()


def check_tx_id(tx_id: str, nonce: bytes, creator: bytes) -> bool:
    """reference protoutil.CheckTxID (proputils.go:368)."""
    return tx_id == compute_tx_id(nonce, creator)


# --- minimal DER encoder for the block-header triple -----------------------


def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _der_integer(v: int) -> bytes:
    # two's-complement minimal encoding, matching Go asn1.Marshal of *big.Int
    if v == 0:
        content = b"\x00"
    elif v > 0:
        content = v.to_bytes((v.bit_length() + 8) // 8, "big")
        if len(content) > 1 and content[0] == 0 and content[1] & 0x80 == 0:
            content = content[1:]
    else:
        raise ValueError("negative block numbers do not occur")
    return b"\x02" + _der_len(len(content)) + content


def _der_octet_string(b: bytes) -> bytes:
    return b"\x04" + _der_len(len(b)) + b


def block_header_bytes(header: common_pb2.BlockHeader) -> bytes:
    body = (
        _der_integer(header.number)
        + _der_octet_string(header.previous_hash)
        + _der_octet_string(header.data_hash)
    )
    return b"\x30" + _der_len(len(body)) + body


def block_header_hash(header: common_pb2.BlockHeader) -> bytes:
    return hashlib.sha256(block_header_bytes(header)).digest()


def block_data_hash(data: common_pb2.BlockData) -> bytes:
    return hashlib.sha256(b"".join(data.data)).digest()


# --- block assembly --------------------------------------------------------


def new_block(number: int, previous_hash: bytes) -> common_pb2.Block:
    block = common_pb2.Block()
    block.header.number = number
    block.header.previous_hash = previous_hash
    block.data.SetInParent()
    init_block_metadata(block)
    return block


def init_block_metadata(block: common_pb2.Block) -> None:
    """Ensure the metadata array covers all BlockMetadataIndex slots
    (reference protoutil.InitBlockMetadata)."""
    want = len(common_pb2.BlockMetadataIndex.keys())
    while len(block.metadata.metadata) < want:
        block.metadata.metadata.append(b"")


def seal_block(block: common_pb2.Block) -> common_pb2.Block:
    block.header.data_hash = block_data_hash(block.data)
    return block


# --- envelope/tx plumbing --------------------------------------------------


def make_signature_header(creator: bytes, nonce: bytes) -> common_pb2.SignatureHeader:
    sh = common_pb2.SignatureHeader()
    sh.creator = creator
    sh.nonce = nonce
    return sh


def make_channel_header(
    header_type: int,
    channel_id: str,
    tx_id: str = "",
    epoch: int = 0,
    extension: bytes = b"",
    version: int = 0,
) -> common_pb2.ChannelHeader:
    ch = common_pb2.ChannelHeader()
    ch.type = header_type
    ch.version = version
    ch.channel_id = channel_id
    ch.tx_id = tx_id
    ch.epoch = epoch
    if extension:
        ch.extension = extension
    return ch


def serialize_identity(mspid: str, cert_pem: bytes) -> bytes:
    sid = identities_pb2.SerializedIdentity()
    sid.mspid = mspid
    sid.id_bytes = cert_pem
    return sid.SerializeToString()


def get_envelope_from_block_data(data: bytes) -> common_pb2.Envelope:
    env = common_pb2.Envelope()
    env.ParseFromString(data)
    return env


def unmarshal(msg_cls, raw: bytes):
    """Parse or raise ValueError (Go-style unmarshal-with-error wrapper)."""
    msg = msg_cls()
    try:
        msg.ParseFromString(raw)
    except Exception as e:
        raise ValueError(f"error unmarshalling {msg_cls.__name__}: {e}") from e
    return msg
