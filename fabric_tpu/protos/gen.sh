#!/bin/sh
# Regenerate Python protobuf modules from src/*.proto.
# Generated *_pb2.py files are committed; rerun after editing any .proto.
set -e
cd "$(dirname "$0")"
protoc --proto_path=src --python_out=. src/*.proto
echo "generated:" *_pb2.py
