"""ACL management (reference core/aclmgmt: resources.go, aclmgmtimpl.go,
defaultaclprovider.go).

Maps resource names ("qscc/GetChainInfo", "peer/Propose", ...) to channel
policy references and evaluates the caller's SignedData against them.
Channel config may override any mapping via the Application group's ACLs
value (peer/configure.go, channelconfig ApplicationConfig.acls); otherwise
the defaults below apply (defaultaclprovider.go:43-112).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

from fabric_tpu.policy.manager import (
    CHANNEL_APPLICATION_ADMINS,
    CHANNEL_APPLICATION_READERS,
    CHANNEL_APPLICATION_WRITERS,
    Manager,
    PolicyError,
    SignedData,
)

# Resource names (reference core/aclmgmt/resources/resources.go)
LSCC_GET_CHAINCODES = "lscc/GetInstantiatedChaincodes"
LSCC_GET_CC_DATA = "lscc/ChaincodeData"
QSCC_GET_CHAIN_INFO = "qscc/GetChainInfo"
QSCC_GET_BLOCK_BY_NUMBER = "qscc/GetBlockByNumber"
QSCC_GET_BLOCK_BY_HASH = "qscc/GetBlockByHash"
QSCC_GET_TX_BY_ID = "qscc/GetTransactionByID"
QSCC_GET_BLOCK_BY_TX_ID = "qscc/GetBlockByTxID"
CSCC_JOIN_CHAIN = "cscc/JoinChain"
CSCC_GET_CHANNELS = "cscc/GetChannels"
CSCC_GET_CONFIG_BLOCK = "cscc/GetConfigBlock"
PEER_PROPOSE = "peer/Propose"
PEER_CHAINCODE_TO_CHAINCODE = "peer/ChaincodeToChaincode"
EVENT_BLOCK = "event/Block"
EVENT_FILTERED_BLOCK = "event/FilteredBlock"
LIFECYCLE_INSTALL = "_lifecycle/InstallChaincode"
LIFECYCLE_QUERY_INSTALLED = "_lifecycle/QueryInstalledChaincodes"
LIFECYCLE_APPROVE = "_lifecycle/ApproveChaincodeDefinitionForMyOrg"
LIFECYCLE_COMMIT = "_lifecycle/CommitChaincodeDefinition"
LIFECYCLE_CHECK_READINESS = "_lifecycle/CheckCommitReadiness"
LIFECYCLE_QUERY_DEFINITION = "_lifecycle/QueryChaincodeDefinition"

# "local" MSP policies for channel-less resources (defaultaclprovider.go
# pResourcePolicyMap): evaluated against the local MSP, not a channel.
LOCAL_ADMINS = "Admins"
LOCAL_MEMBERS = "Members"

DEFAULT_ACLS: Dict[str, str] = {
    LSCC_GET_CHAINCODES: CHANNEL_APPLICATION_READERS,
    LSCC_GET_CC_DATA: CHANNEL_APPLICATION_READERS,
    QSCC_GET_CHAIN_INFO: CHANNEL_APPLICATION_READERS,
    QSCC_GET_BLOCK_BY_NUMBER: CHANNEL_APPLICATION_READERS,
    QSCC_GET_BLOCK_BY_HASH: CHANNEL_APPLICATION_READERS,
    QSCC_GET_TX_BY_ID: CHANNEL_APPLICATION_READERS,
    QSCC_GET_BLOCK_BY_TX_ID: CHANNEL_APPLICATION_READERS,
    CSCC_GET_CONFIG_BLOCK: CHANNEL_APPLICATION_READERS,
    CSCC_GET_CHANNELS: LOCAL_MEMBERS,
    CSCC_JOIN_CHAIN: LOCAL_ADMINS,
    PEER_PROPOSE: CHANNEL_APPLICATION_WRITERS,
    PEER_CHAINCODE_TO_CHAINCODE: CHANNEL_APPLICATION_WRITERS,
    EVENT_BLOCK: CHANNEL_APPLICATION_READERS,
    EVENT_FILTERED_BLOCK: CHANNEL_APPLICATION_READERS,
    LIFECYCLE_INSTALL: LOCAL_ADMINS,
    LIFECYCLE_QUERY_INSTALLED: LOCAL_ADMINS,
    LIFECYCLE_APPROVE: CHANNEL_APPLICATION_ADMINS,
    LIFECYCLE_COMMIT: CHANNEL_APPLICATION_WRITERS,
    LIFECYCLE_CHECK_READINESS: CHANNEL_APPLICATION_WRITERS,
    LIFECYCLE_QUERY_DEFINITION: CHANNEL_APPLICATION_WRITERS,
}


class ACLError(Exception):
    pass


class ACLProvider:
    """resource -> policy evaluation (aclmgmtimpl.go CheckACL).

    ``get_policy_manager(channel_id)`` resolves the channel's root policy
    manager; ``acl_overrides(channel_id)`` the Application ACLs map from
    channel config (may be empty). ``local_check(policy, signed_data)``
    handles the channel-less local-MSP policies.
    """

    def __init__(
        self,
        get_policy_manager: Callable[[str], Optional[Manager]],
        acl_overrides: Optional[Callable[[str], Dict[str, str]]] = None,
        local_check: Optional[
            Callable[[str, Sequence[SignedData]], None]
        ] = None,
    ):
        self._get_pm = get_policy_manager
        self._overrides = acl_overrides or (lambda cid: {})
        self._local_check = local_check

    def policy_for(self, resource: str, channel_id: str) -> Optional[str]:
        override = self._overrides(channel_id).get(resource)
        if override:
            # config ACLs name Application-relative refs like
            # "/Channel/Application/Readers" or bare sub-policy names
            if not override.startswith("/"):
                override = f"/Channel/Application/{override}"
            return override
        return DEFAULT_ACLS.get(resource)

    def check_acl(
        self,
        resource: str,
        channel_id: str,
        signed_data: Sequence[SignedData],
    ) -> None:
        """Raise ACLError unless signed_data satisfies the resource's
        policy on the channel."""
        policy_name = self.policy_for(resource, channel_id)
        if policy_name is None:
            raise ACLError(f"no policy mapping for resource {resource}")
        if not policy_name.startswith("/"):
            # local MSP policy (channel-less resource)
            if self._local_check is None:
                raise ACLError(
                    f"resource {resource} needs a local MSP check"
                )
            try:
                self._local_check(policy_name, signed_data)
            except Exception as e:
                raise ACLError(
                    f"access denied for {resource}: {e}"
                ) from e
            return
        pm = self._get_pm(channel_id)
        if pm is None:
            raise ACLError(f"channel {channel_id} not found")
        policy, ok = pm.get_policy(policy_name)
        if not ok:
            raise ACLError(
                f"policy {policy_name} not found on channel {channel_id}"
            )
        try:
            policy.evaluate_signed_data(signed_data)
        except PolicyError as e:
            raise ACLError(
                f"access denied for {resource} on {channel_id}: {e}"
            ) from e
