"""Two-stage commit pipeline (SURVEY.md §2.13 P4: deliver -> payload
buffer -> validate -> commit stages overlap across blocks; reference
gossip/state.go:542 + kv_ledger.go:596 run block N's delivery while
block N-1 commits).

Stage A (prepare): orderer-sig check + host parse + the DEVICE signature
batch for block N — runs while stage B finishes block N-1.
Stage B (commit): policy circuits, MVCC, stores — inherently sequential
per channel, one worker, in order.

The bounded queue between the stages is the backpressure discipline of
SURVEY §2.13 P7 (orderer WaitReady analog): a slow commit stage stalls
`submit`, which stalls the deliver client, which stops pulling."""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Optional

from fabric_tpu.common import fabobs
from fabric_tpu.common.fabobs import STAGE_BUCKETS
from fabric_tpu.common.faults import fault_point
from fabric_tpu.common.flogging import must_get_logger
from fabric_tpu.common.metrics import (
    new_histogram_state,
    observe_into,
    summary_from_histogram_state,
)
from fabric_tpu.protos import common_pb2


class PipelineError(Exception):
    pass


class CommitPipeline:
    def __init__(
        self,
        channel,  # peer.channel.Channel
        on_commit: Optional[Callable[[common_pb2.Block, object], None]] = None,
        on_error: Optional[Callable[[common_pb2.Block, Exception], None]] = None,
        depth: int = 2,
    ):
        self.channel = channel
        self.on_commit = on_commit
        self.on_error = on_error
        self._prepared: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stopped = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._pending = 0
        self._pending_lock = threading.Lock()
        # terminal triage for soak runs: drain() returning False means
        # "not yet idle" — last_error (most recent commit exception,
        # guarded by _pending_lock) and dead (committer thread gone
        # without stop()) distinguish slow from dead
        self.last_error: Optional[BaseException] = None
        self._crashed = False
        # per-stage latency as metrics-SPI histogram state (PR 10: the
        # raw-sample reservoirs became bucket accumulators — one
        # definition shared with /metrics, constant memory for the
        # process lifetime, summarized by summary_from_histogram_state)
        self._stage_hist = {
            "prepare": new_histogram_state(STAGE_BUCKETS),
            "commit": new_histogram_state(STAGE_BUCKETS),
        }
        self._committer = threading.Thread(
            target=self._commit_loop,
            name=f"commit-{channel.channel_id}",
            daemon=True,
        )
        self._committer.start()

    # -- producer side (the deliver loop) ----------------------------------
    def submit(self, block: common_pb2.Block) -> None:
        """Prepare block and hand it to the committer. Runs stage A on
        the CALLING thread (the deliver loop), so while the committer
        drains block N-1 this thread already parses + device-verifies
        block N. Blocks when the queue is full (P7 backpressure)."""
        if self._stopped.is_set():
            raise PipelineError("pipeline stopped")
        with self._pending_lock:
            self._pending += 1
            self._idle.clear()
        try:
            t0 = time.perf_counter()
            with fabobs.span(
                "pipeline.prepare",
                block=int(getattr(block.header, "number", 0)),
            ):
                prepared = self.channel.prepare_block(block)
            self._observe_stage("prepare", time.perf_counter() - t0)
            # bounded put that watches _stopped: a plain blocking put on
            # a full queue after stop() would wait forever — the
            # committer has exited and will never drain it (pipeline
            # audit, PR 3)
            while True:
                if self._stopped.is_set():
                    raise PipelineError("pipeline stopped")
                try:
                    self._prepared.put((block, prepared), timeout=0.2)
                except queue.Full:
                    continue
                if self._stopped.is_set() and not self._committer.is_alive():
                    # stop() landed between our check and the put: the
                    # committer will never consume this item. Reclaim it
                    # (one submitter per pipeline, so the reclaimed item
                    # is ours) so _pending/_idle stay balanced.
                    try:
                        self._prepared.get_nowait()
                    except queue.Empty:
                        return  # consumed before the committer exited
                    raise PipelineError("pipeline stopped")
                return
        except Exception:
            with self._pending_lock:
                self._pending -= 1
                if self._pending == 0:
                    self._idle.set()
            raise

    # -- consumer side -----------------------------------------------------
    def _commit_loop(self) -> None:
        try:
            self._commit_loop_inner()
        except BaseException as exc:
            # the loop only exits this way on a non-Exception escape
            # (interpreter teardown, injected BaseException): latch the
            # crash so dead stays True even after a cleanup stop()
            with self._pending_lock:
                self.last_error = exc
            self._crashed = True
            raise

    def _commit_loop_inner(self) -> None:
        while not self._stopped.is_set():
            try:
                item = self._prepared.get(timeout=0.2)
            except queue.Empty:
                continue
            block, prepared = item
            try:
                # chaos seam: keyed by block number, so a seeded plan
                # fails a deterministic subset of commits
                fault_point(
                    "pipeline.commit",
                    key=int(getattr(block.header, "number", 0)),
                )
                t0 = time.perf_counter()
                with fabobs.span(
                    "pipeline.commit",
                    block=int(getattr(block.header, "number", 0)),
                ):
                    flags = self.channel.store_block(block, prepared=prepared)
                self._observe_stage("commit", time.perf_counter() - t0)
                if self.on_commit is not None:
                    self.on_commit(block, flags)
            except Exception as exc:  # noqa: BLE001 - surfaced to the owner
                fabobs.obs_count("fabric_pipeline_commit_failures_total")
                with self._pending_lock:
                    self.last_error = exc
                if self.on_error is not None:
                    self.on_error(block, exc)
                else:
                    # no owner callback installed: a silently dropped
                    # block would stall the channel with no trace
                    # (fabflow mask-fail-open audit) — log loudly
                    must_get_logger("pipeline").error(
                        "commit of block %s failed with no on_error "
                        "handler installed: %s",
                        getattr(block.header, "number", "?"), exc,
                    )
            finally:
                with self._pending_lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()

    def _observe_stage(self, stage: str, seconds: float) -> None:
        with self._pending_lock:
            observe_into(self._stage_hist[stage], STAGE_BUCKETS, seconds)
        fabobs.obs_observe(
            "fabric_pipeline_stage_seconds", seconds, stage=stage
        )

    def stage_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-stage latency summary over the accumulated histogram
        state: {"prepare": {n, p50_ms, p99_ms, mean_ms}, "commit":
        {...}} — what 1907.08367's reordered-stage analysis wants
        measured, served from the live pipeline instead of a one-off
        bench probe.  Quantiles are bucket upper bounds (STAGE_BUCKETS),
        the same series a /metrics scrape sees."""
        with self._pending_lock:
            states = {
                k: summary_from_histogram_state(v, STAGE_BUCKETS)
                for k, v in self._stage_hist.items()
            }
        return states

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until every submitted block has committed.  Returns
        False on timeout — check ``last_error`` (the loop's most recent
        commit exception) and ``dead`` to tell a slow pipeline from a
        wedged or crashed one."""
        return self._idle.wait(timeout)

    @property
    def dead(self) -> bool:
        """True when the committer thread crashed or exited without
        stop() — the pipeline will never drain (vs. merely slow).  The
        crashed state is latched, so a cleanup stop() after the fact
        does not mask it."""
        return self._crashed or (
            not self._committer.is_alive() and not self._stopped.is_set()
        )

    def stop(self) -> None:
        self._stopped.set()
        self._committer.join(timeout=5)
        # release the pending counts of any items the committer never
        # consumed, so a post-stop drain() returns instead of hanging
        while True:
            try:
                self._prepared.get_nowait()
            except queue.Empty:
                break
            with self._pending_lock:
                self._pending -= 1
                if self._pending == 0:
                    self._idle.set()
