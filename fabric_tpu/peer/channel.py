"""Per-channel peer pipeline (reference core/peer/peer.go createChannel
wiring + gossip/privdata/coordinator.go StoreBlock + the MCS block checks).

Block intake order matches the reference (SURVEY.md §3.1):
1. MCS.VerifyBlock: recompute DataHash, check the header chain, verify the
   orderer block signature when a verifier is configured
   (usable-inter-nal/peer/gossip/mcs.go:124);
2. txvalidator.Validate -> TRANSACTIONS_FILTER (signatures + policies,
   TPU-batched);
3. kvledger.commit -> MVCC merge + block store + state/history commit.
"""

from __future__ import annotations

from typing import Callable, Optional

from fabric_tpu.crypto.bccsp import Provider, default_provider
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.protos import common_pb2, protoutil
from fabric_tpu.validation.msgvalidation import parse_transaction
from fabric_tpu.validation.txflags import ValidationFlags
from fabric_tpu.validation.validator import BlockValidator, ChaincodeRegistry


class BlockVerificationError(Exception):
    pass


class Channel:
    def __init__(
        self,
        channel_id: str,
        ledger_dir: str,
        msp_manager: MSPManager,
        registry: ChaincodeRegistry,
        provider: Optional[Provider] = None,
        verify_orderer_sig: Optional[Callable[[common_pb2.Block], bool]] = None,
        apply_config: Optional[Callable[[bytes], None]] = None,
    ):
        self.channel_id = channel_id
        self.provider = provider or default_provider()
        self.ledger = KVLedger(ledger_dir, channel_id)
        self.verify_orderer_sig = verify_orderer_sig

        def get_state_metadata(ns: str, coll: str, key) -> Optional[bytes]:
            if coll:
                return self.ledger.state_db.get_hashed_metadata(ns, coll, key)
            return self.ledger.state_db.get_state_metadata(ns, key)

        self.validator = BlockValidator(
            channel_id,
            msp_manager,
            self.provider,
            registry,
            tx_exists=self.ledger.tx_exists,
            apply_config=apply_config,
            get_state_metadata=get_state_metadata,
        )

    def store_block(self, block: common_pb2.Block) -> ValidationFlags:
        """The full commit pipeline for one delivered block. Envelopes are
        parsed once and the result shared between validation and commit."""
        self._verify_block(block)
        parsed = [
            parse_transaction(i, d) for i, d in enumerate(block.data.data)
        ]
        self.validator.validate(block, parsed=parsed)
        return self.ledger.commit(block, rwsets=[p.rwset for p in parsed])

    def _verify_block(self, block: common_pb2.Block) -> None:
        if block.header.number != self.ledger.height:
            raise BlockVerificationError(
                f"expected block {self.ledger.height}, got {block.header.number}"
            )
        if protoutil.block_data_hash(block.data) != block.header.data_hash:
            raise BlockVerificationError(
                "Header.DataHash is different from Hash(block.Data)"
            )
        if (
            self.ledger.height > 0
            and block.header.previous_hash != self.ledger.block_store.last_block_hash
        ):
            raise BlockVerificationError("previous-hash mismatch")
        if self.verify_orderer_sig is not None and not self.verify_orderer_sig(block):
            raise BlockVerificationError("orderer block signature invalid")

    @property
    def height(self) -> int:
        return self.ledger.height
