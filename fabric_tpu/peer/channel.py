"""Per-channel peer pipeline (reference core/peer/peer.go createChannel
wiring + gossip/privdata/coordinator.go StoreBlock + the MCS block checks).

Block intake order matches the reference (SURVEY.md §3.1):
1. MCS.VerifyBlock: recompute DataHash, check the header chain, verify the
   orderer block signature when a verifier is configured
   (usable-inter-nal/peer/gossip/mcs.go:124);
2. txvalidator.Validate -> TRANSACTIONS_FILTER (signatures + policies,
   TPU-batched);
3. kvledger.commit -> MVCC merge + block store + state/history commit.
"""

from __future__ import annotations

from typing import Callable, Optional

from fabric_tpu.common import flogging
from fabric_tpu.crypto.bccsp import Provider, default_provider
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.msp.identity import MSPManager
from fabric_tpu.protos import common_pb2, protoutil
from fabric_tpu.validation.blockparse import parse_block
from fabric_tpu.common.txflags import TxValidationCode, ValidationFlags
from fabric_tpu.validation.validator import BlockValidator, ChaincodeRegistry

logger = flogging.must_get_logger("committer")


class BlockVerificationError(Exception):
    pass


class Channel:
    def __init__(
        self,
        channel_id: str,
        ledger_dir: str,
        msp_manager: MSPManager,
        registry: ChaincodeRegistry,
        provider: Optional[Provider] = None,
        verify_orderer_sig: Optional[Callable[[common_pb2.Block], bool]] = None,
        apply_config: Optional[Callable[[bytes], None]] = None,
        transient_store=None,  # gossip.coordinator.TransientStore
        fetch_pvt: Optional[Callable] = None,  # (blk, tx, txid, ns, coll) -> bytes|None
        is_eligible: Optional[Callable[[str, str], bool]] = None,
        btl_policy: Optional[Callable[[str, str], int]] = None,
        metrics=None,  # ledger.ledgermetrics.CommitterMetrics
        device_mvcc: bool = False,  # SURVEY P5 device fixpoint resolver
        writeset_check=None,  # legacy v12/v13 write-set guards
        plugin_registry=None,  # dispatcher.PluginRegistry (custom plugins)
        state_mirror=None,  # statecouch.CouchStateAdapter (public mirror)
    ):
        self.metrics = metrics
        self.channel_id = channel_id
        base_provider = provider or default_provider()
        # serve-plane QoS dispatch: a sidecar-routed provider binds this
        # channel's admission class (FABRIC_TPU_SERVE_QOS map) so the
        # shared sidecar sheds priority-aware — a spam channel's batches
        # carry its class, never the paying channel's.  Non-serve
        # providers have no for_channel and pass through unchanged.
        bind = getattr(base_provider, "for_channel", None)
        self.provider = bind(channel_id) if callable(bind) else base_provider
        self.ledger = KVLedger(
            ledger_dir, channel_id, btl_policy=btl_policy,
            device_mvcc=device_mvcc, state_mirror=state_mirror,
        )
        self.verify_orderer_sig = verify_orderer_sig
        self.transient_store = transient_store
        self.fetch_pvt = fetch_pvt
        self.is_eligible = is_eligible

        def get_state_metadata(ns: str, coll: str, key) -> Optional[bytes]:
            if coll:
                return self.ledger.state_db.get_hashed_metadata(ns, coll, key)
            return self.ledger.state_db.get_state_metadata(ns, key)

        self.validator = BlockValidator(
            channel_id,
            msp_manager,
            self.provider,
            registry,
            tx_exists=self.ledger.tx_exists,
            apply_config=apply_config,
            get_state_metadata=get_state_metadata,
            writeset_check=writeset_check,
            plugin_registry=plugin_registry,
        )

    def prepare_block(self, block: common_pb2.Block):
        """Stage A of the commit pipeline (SURVEY.md §2.13 P4): orderer
        signature check, host parse, and the DEVICE signature batch —
        everything that may overlap the previous block's sequential
        MVCC/commit epilogue. Returns the opaque tuple store_block takes
        as `prepared`."""
        self._verify_block_content(block)
        parsed = parse_block(list(block.data.data))
        jobs, job_identity, keys, sigs, digests = (
            self.validator.collect_sig_jobs(parsed)
        )
        # dispatch WITHOUT waiting when the provider has an async seam
        # (device kernels, pool shards, the serve sidecar): the returned
        # resolver rides the prepared tuple and store_block collects the
        # verdicts at stage B — block N's signature math overlaps block
        # N-1's sequential commit epilogue across the full dispatch
        # ladder, not just inside one provider
        dispatch = getattr(self.provider, "batch_verify_async", None)
        if dispatch is None:
            ok_list = self.provider.batch_verify(keys, sigs, digests)
        else:
            ok_list = dispatch(keys, sigs, digests)
        return parsed, jobs, job_identity, ok_list

    def store_block(
        self, block: common_pb2.Block, prepared=None
    ) -> ValidationFlags:
        """The full commit pipeline for one delivered block. Envelopes are
        parsed once and the result shared between validation and commit;
        a pipelined deliver loop passes `prepared` from prepare_block run
        on another thread (P4 overlap).

        Private data is assembled coordinator-style (gossip/privdata/
        coordinator.go:149-209): transient store first, then the peer
        fetcher, with anything still missing recorded for the reconciler."""
        import time as _time

        t0 = _time.perf_counter()
        self._verify_block_position(block)
        if prepared is None:
            prepared = self.prepare_block(block)
        parsed, jobs, job_identity, ok_list = prepared
        if callable(ok_list):
            # async-prepared tuple: resolve the verify dispatch now.  A
            # resolver failure raises here and surfaces through the
            # commit error path (the block is NOT committed — fail
            # closed), same as a synchronous batch_verify failure would.
            ok_list = ok_list()
        sig_results = self.validator.finish_sig_results(
            jobs, job_identity, ok_list
        )
        flags = self.validator.validate(
            block, parsed=parsed, sig_results=sig_results
        )
        t_validate = _time.perf_counter() - t0
        rwsets = [p.rwset for p in parsed]
        # materializing rwsets may demote txs the native walker accepted
        # but the Python parser rejects (ParsedTx.rwset divergence guard);
        # fold that into the filter BEFORE it is persisted so native and
        # pure-Python peers commit the same TRANSACTIONS_FILTER
        refilter = False
        for p in parsed:
            if p.code == TxValidationCode.BAD_RWSET and (
                flags.flag(p.index) == TxValidationCode.VALID
            ):
                flags.set_flag(p.index, TxValidationCode.BAD_RWSET)
                rwsets[p.index] = None
                refilter = True
        if refilter:
            block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = (
                flags.tobytes()
            )
        pvt_data, missing = self._assemble_pvt_data(block, parsed, flags)
        result = self.ledger.commit(
            block, rwsets=rwsets, pvt_data=pvt_data, missing_pvt=missing
        )
        if self.transient_store is not None:
            self.transient_store.purge_by_txids(
                [p.tx_id for p in parsed if p.tx_id]
            )
        timings = getattr(self.ledger, "last_commit_timings", {})
        logger.debug(
            "[%s] committed block [%d] in %dms (state_validation=%dms "
            "block_and_pvtdata_commit=%dms state_commit=%dms)",
            self.channel_id,
            block.header.number,
            int((t_validate + sum(timings.values())) * 1000),
            int(timings.get("state_validation", 0) * 1000),
            int(timings.get("block_and_pvtdata_commit", 0) * 1000),
            int(timings.get("state_commit", 0) * 1000),
        )
        if self.metrics is not None:
            self.metrics.observe_commit(
                self.channel_id,
                result,
                self.ledger.height,
                t_validate + timings.get("state_validation", 0.0),
                timings.get("block_and_pvtdata_commit", 0.0),
                timings.get("state_commit", 0.0),
            )
        return result

    def _assemble_pvt_data(self, block, parsed, flags):
        """(tx_num, ns, coll) -> cleartext KVRWSet bytes for every valid tx
        whose hashed rwset references a collection this peer is eligible
        for; plus MissingEntry records for what could not be found."""
        from fabric_tpu.ledger.pvtdatastore import MissingEntry

        pvt_data = {}
        missing = []
        wanted = []  # (tx_num, tx_id, ns, coll)
        arr = flags.asarray() if flags is not None else None
        for p in parsed:
            if arr is not None and arr[p.index] != 0:  # not VALID
                continue
            if p.rwset is None:
                continue
            for ns_rw in p.rwset.ns_rw_sets:
                for coll in ns_rw.coll_hashed:
                    if not coll.hashed_writes:
                        continue
                    if self.is_eligible is not None and not self.is_eligible(
                        ns_rw.namespace, coll.collection_name
                    ):
                        continue
                    wanted.append(
                        (p.index, p.tx_id, ns_rw.namespace, coll.collection_name)
                    )
        from fabric_tpu.ledger.kvledger import pvt_data_matches_hashes

        by_index = {p.index: p for p in parsed}
        for tx_num, tx_id, ns, coll in wanted:
            rwset = by_index[tx_num].rwset
            data = None
            if self.transient_store is not None and tx_id:
                data = self.transient_store.get(tx_id, ns, coll)
                if data is not None and not pvt_data_matches_hashes(
                    rwset, ns, coll, data
                ):
                    data = None
            if data is None and self.fetch_pvt is not None:
                data = self.fetch_pvt(block.header.number, tx_num, tx_id, ns, coll)
                # fetched from untrusted peers: a hash mismatch is treated
                # as missing, never an error (coordinator.go fetch path)
                if data is not None and not pvt_data_matches_hashes(
                    rwset, ns, coll, data
                ):
                    data = None
            if data is not None:
                pvt_data[(tx_num, ns, coll)] = data
            else:
                missing.append(MissingEntry(tx_num, ns, coll))
        return pvt_data, missing

    def _verify_block_content(self, block: common_pb2.Block) -> None:
        """Position-independent checks (MCS VerifyBlock: DataHash +
        orderer signature) — safe in pipeline stage A, before the
        preceding block committed."""
        if protoutil.block_data_hash(block.data) != block.header.data_hash:
            raise BlockVerificationError(
                "Header.DataHash is different from Hash(block.Data)"
            )
        if self.verify_orderer_sig is not None and not self.verify_orderer_sig(block):
            raise BlockVerificationError("orderer block signature invalid")

    def _verify_block_position(self, block: common_pb2.Block) -> None:
        """Chain-position checks — must run in commit order (stage B)."""
        if block.header.number != self.ledger.height:
            raise BlockVerificationError(
                f"expected block {self.ledger.height}, got {block.header.number}"
            )
        if (
            self.ledger.height > 0
            and block.header.previous_hash != self.ledger.block_store.last_block_hash
        ):
            raise BlockVerificationError("previous-hash mismatch")

    def _verify_block(self, block: common_pb2.Block) -> None:
        self._verify_block_position(block)
        self._verify_block_content(block)

    @property
    def height(self) -> int:
        return self.ledger.height
