"""Peer runtime: per-channel wiring of validator + ledger."""

from fabric_tpu.peer.channel import Channel

__all__ = ["Channel"]
