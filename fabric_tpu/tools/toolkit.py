"""toolkit — the shared scaffold under the fabric-tpu static analyzers.

fablint (per-file invariants), fabdep (whole-program layering +
concurrency), fabflow (value-range abstract interpretation) and fabreg
(declarative-contract drift) are four different analyses with one
identical chassis: walk the repo skipping generated artifacts, parse
per-line ``# <tool>: disable=rule  # reason`` suppressions, report
``Finding`` records, and drive it all from a ``--json`` /
``--list-rules`` / ``--rules`` CLI with the shared exit-code convention
(0 = clean, 1 = findings, 2 = usage/IO error).  Before this module each
tool re-implemented that chassis; now they share it, so a fifth
analyzer costs only its rules.

Everything here is dependency-free stdlib (``ast`` isn't even needed —
the tools own their parsing); nothing imports analyzed code, so the
tools keep running in minimal environments without cryptography/jax/
numpy.

Suppression grammar (shared by every tool; ``<tool>`` is the tool
name)::

    # <tool>: disable=rule-id[,rule-id...]  # <reason>

``disable=all`` silences every rule for that line.  The trailing
comment is the justification; :func:`parse_suppressions` returns it so
tools (fabflow's numeric-bound discipline, fabreg's suppression-stale
rule) can hold suppressions to their stated reasons.
"""

from __future__ import annotations

import argparse
import fnmatch
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__version__ = "1.0"

#: Generated / non-source artifacts no analyzer ever parses.
DEFAULT_EXCLUDES = (
    "*_pb2.py",
    "*/__pycache__/*",
    "*/native/*",
    "*/protos/src/*",
    "*/.git/*",
)

@dataclass(frozen=True)
class AnalyzerSpec:
    """One registered analyzer — the single source of truth fabreg's
    suppression-stale rule iterates, so a new analyzer is picked up by
    adding a row HERE (plus implementing the staleness protocol in its
    module) without editing fabreg.

    ``module``: dotted import path.  For post-toolkit analyzers the
    module must expose ``live_suppression_keys(sources, rules) ->
    {(normalized_path, line, rule), ...}`` — the set of suppression
    comments that still absorb a finding.  The three pre-toolkit tools
    (fablint/fabdep/fabflow) predate the protocol; fabreg carries
    legacy adapters for exactly those names and resolves everything
    else through this registry.

    ``pkg_scope_only``: True when the tool's CI gate analyzes only the
    package tree — its suppression comments outside it are inert and
    never judged stale.  Tools whose gates also scan tests/ and
    bench.py (fabreg, fablife) set False."""

    name: str
    module: str
    pkg_scope_only: bool = True


#: The analyzer registry (fabreg's suppression-stale rule scans every
#: row's suppression comments; all share the grammar above).
ANALYZER_SPECS: Tuple["AnalyzerSpec", ...] = (
    AnalyzerSpec("fablint", "fabric_tpu.tools.fablint"),
    AnalyzerSpec("fabdep", "fabric_tpu.tools.fabdep"),
    AnalyzerSpec("fabflow", "fabric_tpu.tools.fabflow"),
    AnalyzerSpec("fabreg", "fabric_tpu.tools.fabreg", pkg_scope_only=False),
    AnalyzerSpec("fablife", "fabric_tpu.tools.fablife", pkg_scope_only=False),
    AnalyzerSpec("fabwire", "fabric_tpu.tools.fabwire"),
    AnalyzerSpec("fabtrace", "fabric_tpu.tools.fabtrace"),
    AnalyzerSpec("fabdet", "fabric_tpu.tools.fabdet"),
)

#: Historical shape: the tool-name tuple (derived from the registry).
ANALYZER_TOOLS = tuple(spec.name for spec in ANALYZER_SPECS)

#: The pre-toolkit tools fabreg adapts by hand; everything else must
#: implement the ``live_suppression_keys`` protocol.
LEGACY_ANALYZER_TOOLS = ("fablint", "fabdep", "fabflow", "fabreg")


def analyzer_spec(name: str) -> Optional["AnalyzerSpec"]:
    for spec in ANALYZER_SPECS:
        if spec.name == name:
            return spec
    return None


def normalize_path(path: str) -> str:
    """The ONE path normalization the suppression-staleness protocol
    keys on: fabreg compares ``live_suppression_keys`` results against
    comment locations, and both sides must normalize identically or
    every suppression silently reads stale."""
    try:
        return Path(path).resolve().as_posix()
    except OSError:
        return Path(path).as_posix()


@dataclass
class Finding:
    """One analyzer finding.  ``key()`` is the canonical sort/dedup
    order shared by every tool's output."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


class FileContext:
    """Per-file info shared by rules: posix path + path predicates."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.posix = Path(path).as_posix()

    def matches(self, patterns: Iterable[str]) -> bool:
        return any(fnmatch.fnmatch(self.posix, pat) for pat in patterns)


_DISABLE_RES: Dict[str, "re.Pattern[str]"] = {}


def disable_re(tool: str) -> "re.Pattern[str]":
    """The compiled suppression regex for one tool's comments."""
    pat = _DISABLE_RES.get(tool)
    if pat is None:
        pat = _DISABLE_RES[tool] = re.compile(
            r"#\s*" + re.escape(tool)
            + r":\s*disable=([A-Za-z0-9_\-, ]+)(?:#\s*(.*))?"
        )
    return pat


def parse_suppressions(
    source: str, tool: str
) -> Dict[int, Tuple[Set[str], str]]:
    """1-based line number -> (disabled rule ids, reason text)."""
    pat = disable_re(tool)
    out: Dict[int, Tuple[Set[str], str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = pat.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out[lineno] = (rules, (m.group(2) or "").strip())
    return out


def suppressed_rules(
    source: str, tool: str
) -> Dict[int, Set[str]]:
    """:func:`parse_suppressions` without the reasons (fablint/fabdep's
    historical shape)."""
    return {
        line: rules
        for line, (rules, _reason) in parse_suppressions(source, tool).items()
    }


def apply_suppressions(
    findings: Iterable[Finding],
    suppressions: Dict[int, Set[str]],
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (kept, suppressed) against one file's
    per-line suppression map."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        disabled = suppressions.get(f.line, set())
        if f.rule in disabled or "all" in disabled:
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


def iter_py_files(paths: Sequence[str], excludes: Sequence[str]) -> List[str]:
    """Expand files/directories to the sorted ``*.py`` set minus the
    exclusion globs (the shared repo walk)."""
    out: List[str] = []
    for raw in paths:
        p = Path(raw)
        candidates = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in candidates:
            posix = f.as_posix()
            if any(fnmatch.fnmatch(posix, pat) for pat in excludes):
                continue
            out.append(str(f))
    return out


def read_sources(
    files: Sequence[str],
) -> Tuple[Dict[str, str], List[Finding]]:
    """Read every file; unreadable ones become ``io-error`` findings
    instead of exceptions (the gate must report, not crash)."""
    sources: Dict[str, str] = {}
    io_findings: List[Finding] = []
    for f in files:
        try:
            sources[f] = Path(f).read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as exc:
            io_findings.append(Finding("io-error", f, 1, 0, str(exc)))
    return sources, io_findings


# --------------------------------------------------------------------------
# CLI plumbing
# --------------------------------------------------------------------------


def build_parser(
    prog: str, description: str, paths_help: str = "files or directories"
) -> argparse.ArgumentParser:
    """The shared argument set: paths + --json/--list-rules/--rules/
    --exclude.  Tools add their extras on top."""
    parser = argparse.ArgumentParser(prog=prog, description=description)
    parser.add_argument("paths", nargs="*", help=paths_help)
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit"
    )
    parser.add_argument(
        "--rules",
        metavar="ID[,ID...]",
        help="run only these rule ids (default: all)",
    )
    parser.add_argument(
        "--exclude",
        action="append",
        default=[],
        metavar="GLOB",
        help="extra exclusion globs (added to the built-in generated-code "
        "list)",
    )
    return parser


def print_rule_list(docs: Dict[str, str], width: int) -> None:
    for rid in sorted(docs):
        print(f"{rid:{width}s} {docs[rid]}")


def parse_rule_arg(
    raw: Optional[str], known: Iterable[str], prog: str
) -> Tuple[Optional[List[str]], int]:
    """``--rules a,b`` -> (ids, 0), or (None, 2) after printing the
    shared unknown-rule usage error."""
    if not raw:
        return None, 0
    import sys

    rule_ids = [r.strip() for r in raw.split(",") if r.strip()]
    known_set = set(known)
    unknown = [r for r in rule_ids if r not in known_set]
    if unknown:
        print(
            f"{prog}: error: unknown rule(s): {', '.join(unknown)}",
            file=sys.stderr,
        )
        return None, 2
    return rule_ids, 0


def check_paths_exist(
    paths: Sequence[str], prog: str, parser: argparse.ArgumentParser
) -> int:
    """The shared no-paths / missing-path usage errors (exit code 2)."""
    import sys

    if not paths:
        parser.print_usage(sys.stderr)
        print(f"{prog}: error: no paths given", file=sys.stderr)
        return 2
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(
            f"{prog}: error: no such file or directory: "
            f"{', '.join(missing)}",
            file=sys.stderr,
        )
        return 2
    return 0


def print_findings(findings: Iterable[Finding]) -> None:
    for f in findings:
        print(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}")
