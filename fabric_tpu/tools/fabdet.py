"""fabdet — whole-program byte-determinism taint analyzer for fabric-tpu.

Byte-determinism is this repo's verification currency: chaos_gate
byte-diffs same-seed scorecards, crash_matrix byte-diffs crashchild
digests, the snapshot plane sha256-seals its files, and the two big
ROADMAP items (verify-once verdict certificates, snapshot-streaming
bootstrap) are only *sound* if two peers compute byte-identical
artifacts.  fabdet statically guards that property: it is an
interprocedural, flow-sensitive taint analysis that tracks
nondeterminism *sources* (wall clocks, unseeded randomness, process
environment, hash/fs iteration order, unsorted JSON encoding) into
declared det *surfaces* (the functions that emit persisted,
cross-peer-compared, or replay-diffed bytes).

The surface table is declarative — ``tools/det.toml`` — so the
verdict-certificate and snapshot-bootstrap builders extend the gate by
adding ``[[surface]]`` rows, never by editing the analyzer (the
fabreg/fabwire discipline).  Each row declares::

    [[surface]]
    name = "snapshot-files"                  # unique id for messages
    module = "fabric_tpu/ledger/snapshot.py" # path, pinned on disk
    functions = ["_w", "generate_snapshot"]  # fnmatch over qualnames
    tier = "cross-peer"        # persisted | cross-peer | replay
    doc = "why these bytes must be deterministic"
    # optional:
    # mode = "det-dict"        # fabchaos scorecard mode (see below)
    # decorator = "scenario"   # det-dict: analyze decorated functions
    # sinks = ["execute"]      # extra call leaves whose args are sinks

Tier semantics: ``persisted`` bytes are re-read/byte-diffed across
process restarts on ONE node (store frames, AOT artifacts, metadata
files); ``cross-peer`` bytes are compared between peers (wire bodies,
rwset hashes, snapshot files, block content); ``replay`` bytes are
byte-diffed between same-seed runs (chaos scorecards, crash digests).
All three demand the same discipline — the tier names which contract a
finding breaks, and which regression test a fix needs.

Two surface modes:

* ``outputs`` (default): the function's *emissions* are the sink —
  returned/yielded values, arguments of ``.write()``/``json.dump``
  calls inside it, arguments it passes to other declared surfaces, and
  any extra per-row ``sinks`` leaves.  A tainted branch condition that
  gates a ``raise``/``return``/``break`` inside the function is also
  reported (a delivery stream that cuts off on wall-clock is not
  byte-deterministic for a replaying twin).
* ``det-dict`` (the fabreg ``det-hazard`` semantics, promoted here and
  retired there): the sink is the scenario's deterministic scorecard —
  writes into the ``det`` dict (or whatever name the decorated
  function returns as its tuple's first element).  The observed
  section stays free: ``time.perf_counter()`` flowing only into
  ``obs`` is fine, and ``random.Random(seed)`` draws are exempt.

Whole-program half: EVERY function in the scanned tree is walked once,
so a helper that forwards its argument into ``pack_frame`` propagates
"reaches a det surface" to its own callers (memoized per-function
summaries: taint of the return value under clean arguments, which
parameters flow to the return, and which parameters reach a surface
sink).  Calls are resolved through the per-module import table, so the
analysis crosses module boundaries without ever importing analyzed
code — pure ``ast`` on the toolkit chassis, dependency-free, runs
identically without numpy/jax/cryptography.

Rules (``--list-rules``): wallclock-in-det, unseeded-random-in-det,
env-in-det, hash-order-hazard, fs-order-hazard, unsorted-serialize.
``json.dump`` to a file handle is treated as a persisted surface *by
construction* wherever it appears (the bytes land on disk); bare
``json.dumps`` only fires when its result actually flows into a
declared surface, so transient in-process encodings stay silent.  A
``[[surface]]`` row whose declared function is absent from its scanned
module is reported as an always-on ``surface-missing`` finding — a
renamed emitter must not silently drop out of the gate.

Suppression grammar (shared toolkit chassis)::

    # fabdet: disable=rule-id[,rule-id...]  # <reason naming the contract>

fabreg's ``suppression-stale`` judges every fabdet suppression through
``toolkit.ANALYZER_SPECS`` (this module implements the
``live_suppression_keys`` staleness protocol), so a suppression whose
finding no longer fires is itself a finding.

Exit codes: 0 clean, 1 findings, 2 usage/IO/det-table error.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import (
    Dict,
    Iterable,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Set,
    Tuple,
)

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import DEFAULT_EXCLUDES, Finding, iter_py_files

__version__ = "1.0"

RULES: Dict[str, str] = {
    "wallclock-in-det": (
        "wall/monotonic clock read (time.time/perf_counter/datetime.now"
        "/...) flowing into a declared det surface, or gating its "
        "output path"
    ),
    "unseeded-random-in-det": (
        "module-level random.*, os.urandom, uuid1/uuid4 or secrets.* "
        "value flowing into a det surface (random.Random(seed) draws "
        "are the sanctioned discipline and stay exempt)"
    ),
    "env-in-det": (
        "process-environment value (pid, id(), hostname, os.environ) "
        "flowing into a det surface — differs per host/process, "
        "identical input or not"
    ),
    "hash-order-hazard": (
        "builtin hash() or set/frozenset iteration order feeding a det "
        "surface — PYTHONHASHSEED-dependent bytes (in-process cache "
        "keys that never reach a surface stay silent)"
    ),
    "fs-order-hazard": (
        "os.listdir/scandir/glob/iterdir order feeding a det surface "
        "without a dominating sorted() — directory order is "
        "filesystem-dependent"
    ),
    "unsorted-serialize": (
        "json.dump to disk, or json.dumps feeding a det surface, "
        "without sort_keys=True or provably ordered construction — "
        "dict insertion order is code-path-dependent"
    ),
}

#: surface tiers — which byte-determinism contract a surface serves
TIERS = ("persisted", "cross-peer", "replay")

_MISSING_RULE = "surface-missing"  # always-on, like fabwire syntax-error


# ---------------------------------------------------------------------------
# det.toml — declarative surface table (tiny TOML subset, loud errors)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SurfaceSpec:
    """One ``[[surface]]`` row of det.toml."""

    name: str
    module: str
    tier: str
    doc: str
    functions: Tuple[str, ...] = ()   # fnmatch patterns over qualnames
    mode: str = "outputs"             # "outputs" | "det-dict"
    decorator: str = ""               # det-dict: decorator selecting fns
    sinks: Tuple[str, ...] = ()       # extra sink call leaves


@dataclass(frozen=True)
class DetSpec:
    surfaces: Tuple[SurfaceSpec, ...]


def default_det_file() -> Path:
    return Path(__file__).resolve().parent / "det.toml"


def _parse_value(raw: str, where: str):
    raw = raw.strip()
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw.lstrip("-").isdigit():
        return int(raw)
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        items: List[object] = []
        for part in inner.split(","):
            part = part.strip()
            if not part:
                continue
            if part.startswith('"') and part.endswith('"'):
                items.append(part[1:-1])
            elif part.lstrip("-").isdigit():
                items.append(int(part))
            else:
                raise ValueError(
                    f"{where}: list items must be \"quoted\" or integers"
                )
        return items
    raise ValueError(
        f"{where}: expected \"string\", integer, [list] or true/false"
    )


def parse_det(text: str, path: str = "<det>") -> DetSpec:
    """Parse the tiny TOML subset shared with wire.toml/pairs.toml.
    LOUD on any malformed line or missing key: a half-read surface
    table silently checking nothing would be config drift."""
    entries: List[Dict[str, object]] = []
    current: Optional[Dict[str, object]] = None
    for n, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            section = line[2:-2].strip()
            if section != "surface":
                raise ValueError(f"{path}:{n}: unknown section {line!r}")
            current = {}
            entries.append(current)
            continue
        if line.startswith("["):
            raise ValueError(f"{path}:{n}: unknown section {line!r}")
        if "=" not in line:
            raise ValueError(f"{path}:{n}: expected 'key = value'")
        if current is None:
            raise ValueError(f"{path}:{n}: key outside a [[surface]] entry")
        key, _, value = line.partition("=")
        if "#" in value and not value.strip().startswith('"'):
            value = value.split("#", 1)[0]
        current[key.strip()] = _parse_value(value, f"{path}:{n}")

    def strs(value: object, where: str) -> Tuple[str, ...]:
        if isinstance(value, str):
            return (value,)
        if isinstance(value, list) and all(isinstance(v, str) for v in value):
            return tuple(value)
        raise ValueError(f"{where}: expected a string or list of strings")

    surfaces: List[SurfaceSpec] = []
    seen_names: Set[str] = set()
    for i, e in enumerate(entries, start=1):
        where = f"{path}: [[surface]] #{i}"
        for k in ("name", "module", "tier", "doc"):
            if k not in e:
                raise ValueError(f"{where}: missing required key {k!r}")
        name = str(e["name"])
        if name in seen_names:
            raise ValueError(f"{where}: duplicate surface name {name!r}")
        seen_names.add(name)
        tier = str(e["tier"])
        if tier not in TIERS:
            raise ValueError(
                f"{where}: tier must be one of {'/'.join(TIERS)}, "
                f"got {tier!r}"
            )
        mode = str(e.get("mode", "outputs"))
        if mode not in ("outputs", "det-dict"):
            raise ValueError(
                f"{where}: mode must be \"outputs\" or \"det-dict\", "
                f"got {mode!r}"
            )
        functions = strs(e.get("functions", []), where)
        decorator = str(e.get("decorator", ""))
        if mode == "det-dict":
            if not decorator:
                raise ValueError(
                    f"{where}: det-dict surfaces need a 'decorator' "
                    f"selector"
                )
        elif not functions:
            raise ValueError(
                f"{where}: outputs surfaces need a non-empty 'functions' "
                f"list"
            )
        surfaces.append(
            SurfaceSpec(
                name=name,
                module=str(e["module"]),
                tier=tier,
                doc=str(e["doc"]),
                functions=functions,
                mode=mode,
                decorator=decorator,
                sinks=strs(e.get("sinks", []), where),
            )
        )
    return DetSpec(surfaces=tuple(surfaces))


def load_default_det() -> DetSpec:
    f = default_det_file()
    return parse_det(f.read_text(encoding="utf-8"), str(f))


# ---------------------------------------------------------------------------
# nondeterminism sources
# ---------------------------------------------------------------------------

#: wall/monotonic clock reads (any clock in a det surface is a hazard —
#: monotonic values differ per process even with identical input)
_WALL_EXACT = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns", "time.clock_gettime",
}
_DT_LEAVES = {"now", "utcnow", "today"}

#: random.Random(seed)/random.seed(n) construct the seeded discipline
#: the scorecard contract is built on; everything else on the module
#: draws the unseeded global stream
_RAND_EXEMPT_LEAVES = {"Random", "seed"}
_RAND_EXACT = {
    "os.urandom", "urandom", "uuid.uuid1", "uuid.uuid4", "uuid1", "uuid4",
}
#: numpy-style seeded constructors (fabdet never imports numpy; these
#: are matched purely on dotted-name shape)
_NP_RAND_EXEMPT = {"default_rng", "RandomState", "Generator", "seed"}

_ENV_EXACT = {
    "os.getpid", "getpid", "os.getppid", "getppid", "id",
    "socket.gethostname", "gethostname", "platform.node", "os.uname",
    "os.getenv", "getenv", "os.environ.get", "environ.get",
}

_FS_EXACT = {
    "os.listdir", "listdir", "os.scandir", "scandir",
    "glob.glob", "glob.iglob", "iglob",
}
_FS_LEAVES = {"iterdir", "rglob"}  # pathlib; bare .glob handled below

#: calls whose result is order- and value-independent of the input's
#: hazards (a count is deterministic even over an unordered set)
_CLEANSE_ALL = {"len", "bool", "isinstance", "hasattr", "callable"}
#: calls that impose a deterministic order (or are order-independent
#: folds) — they clear hash/fs order taint but keep value taints (a
#: sorted list of timestamps is still timestamps)
_CLEANSE_ORDER = {"sorted", "min", "max", "sum"}

#: container mutators that fold argument taint into the receiver
_MUTATORS = {"append", "add", "extend", "insert", "appendleft", "update",
             "setdefault"}

_ORDER_KINDS = {"hash", "fs"}
#: kinds reported through the five value rules ("json" is special-cased
#: into unsorted-serialize at surface boundaries; "param" is summary
#: plumbing)
_VALUE_KINDS = {"wall", "rand", "env", "hash", "fs"}


class Taint(NamedTuple):
    kind: str   # wall | rand | env | hash | fs | json | param
    rule: str   # rule id ("" for param)
    path: str   # file that introduced the taint
    line: int   # source line that introduced it
    desc: str   # dotted source name, or param index for kind="param"


def _strip_order(taints: Set[Taint]) -> Set[Taint]:
    return {t for t in taints if t.kind not in _ORDER_KINDS}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _classify_source(dn: str) -> Optional[Tuple[str, str]]:
    """dotted call name -> (taint kind, rule id), or None."""
    if dn in _WALL_EXACT:
        return ("wall", "wallclock-in-det")
    parts = dn.split(".")
    root, leaf = parts[0], parts[-1]
    if root == "datetime" and leaf in _DT_LEAVES:
        return ("wall", "wallclock-in-det")
    if dn in _RAND_EXACT:
        return ("rand", "unseeded-random-in-det")
    if root == "random" and leaf not in _RAND_EXEMPT_LEAVES:
        return ("rand", "unseeded-random-in-det")
    if root == "secrets":
        return ("rand", "unseeded-random-in-det")
    if "random" in parts[1:-1] and leaf not in _NP_RAND_EXEMPT:
        return ("rand", "unseeded-random-in-det")
    if dn in _ENV_EXACT:
        return ("env", "env-in-det")
    if dn in _FS_EXACT:
        return ("fs", "fs-order-hazard")
    if leaf in _FS_LEAVES and len(parts) > 1:
        return ("fs", "fs-order-hazard")
    if leaf == "glob" and len(parts) > 1 and root != "glob":
        return ("fs", "fs-order-hazard")
    if dn == "hash":
        return ("hash", "hash-order-hazard")
    return None


# ---------------------------------------------------------------------------
# program index: modules, imports, call resolution
# ---------------------------------------------------------------------------


def _path_dotted(posix: str) -> str:
    p = posix[:-3] if posix.endswith(".py") else posix
    return p.lstrip("./").replace("/", ".")


class _Module:
    """Per-file symbol map: top-level functions + Class.method, plus
    the import alias table call resolution crosses modules with."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.posix = Path(path).as_posix()
        self.dotted = _path_dotted(self.posix)
        self.tree = tree
        self.functions: Dict[str, ast.AST] = {}
        self.cls_of: Dict[str, str] = {}
        self.aliases: Dict[str, str] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        q = f"{node.name}.{sub.name}"
                        self.functions[q] = sub
                        self.cls_of[q] = node.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".", 1)[0]
                        self.aliases.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base_parts = self.dotted.split(".")[: -node.level - 1]
                    base = ".".join(
                        base_parts + ([node.module] if node.module else [])
                    )
                else:
                    base = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = (
                        f"{base}.{a.name}" if base else a.name
                    )


class _Summary(NamedTuple):
    """Interprocedural function summary: taints of the return value
    under clean arguments, parameter indices that flow to the return,
    and parameter indices that reach a det-surface sink inside."""

    ret: frozenset
    param_ret: frozenset
    param_surface: frozenset


_EMPTY_SUMMARY = _Summary(frozenset(), frozenset(), frozenset())


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)] + [
        p.arg for p in a.kwonlyargs
    ]


class _Program:
    """The whole-program view: module index, surface bindings, memoized
    summaries, and the finding sink."""

    def __init__(
        self,
        modules: Dict[str, _Module],
        det: DetSpec,
        active: Set[str],
    ):
        self.modules = modules
        self.det = det
        self.active = active
        self.by_dotted: Dict[str, _Module] = {
            m.dotted: m for m in modules.values()
        }
        self._summaries: Dict[Tuple[str, str], _Summary] = {}
        self._in_progress: Set[Tuple[str, str]] = set()
        self._findings: Dict[Tuple[str, str, int, int], Finding] = {}
        # (path, qualname) -> SurfaceSpec for outputs-mode surfaces;
        # det-dict specs are matched per module
        self.surfaces: Dict[Tuple[str, str], SurfaceSpec] = {}
        self.detdict: Dict[str, List[SurfaceSpec]] = {}
        self.missing: List[Finding] = []
        for mod in modules.values():
            for spec in det.surfaces:
                if not self._module_matches(mod.posix, spec.module):
                    continue
                if spec.mode == "det-dict":
                    self.detdict.setdefault(mod.path, []).append(spec)
                    continue
                for pat in spec.functions:
                    hits = [
                        q
                        for q in mod.functions
                        if q == pat or fnmatch.fnmatch(q, pat)
                    ]
                    if not hits:
                        self.missing.append(
                            Finding(
                                _MISSING_RULE, mod.path, 1, 0,
                                f"det.toml surface {spec.name!r} declares "
                                f"function {pat!r} absent from "
                                f"{spec.module} — the det gate is "
                                f"vacuously passing on it; update "
                                f"det.toml when an emitter moves",
                            )
                        )
                    for q in hits:
                        self.surfaces[(mod.path, q)] = spec

    @staticmethod
    def _module_matches(posix: str, pattern: str) -> bool:
        if "*" in pattern or "?" in pattern:
            return fnmatch.fnmatch(posix, pattern) or fnmatch.fnmatch(
                posix, "*/" + pattern
            )
        return posix == pattern or posix.endswith("/" + pattern)

    def find_module(self, dotted: str) -> Optional[_Module]:
        m = self.by_dotted.get(dotted)
        if m is not None:
            return m
        tail = "." + dotted
        hits = [
            mod for d, mod in self.by_dotted.items() if d.endswith(tail)
        ]
        return hits[0] if len(hits) == 1 else None

    def resolve(
        self, mod: _Module, dn: str, cur_class: Optional[str]
    ) -> Optional[Tuple[_Module, str, ast.AST]]:
        """Resolve a dotted call to (module, qualname, def) or None."""
        parts = dn.split(".")
        if parts[0] == "self" and cur_class is not None and len(parts) == 2:
            q = f"{cur_class}.{parts[1]}"
            fn = mod.functions.get(q)
            return (mod, q, fn) if fn is not None else None
        if len(parts) <= 2 and dn in mod.functions:
            return (mod, dn, mod.functions[dn])
        if parts[0] in mod.aliases:
            full = mod.aliases[parts[0]]
            if len(parts) > 1:
                full = full + "." + ".".join(parts[1:])
            fparts = full.split(".")
            for cut in (1, 2):
                if len(fparts) <= cut:
                    continue
                target = self.find_module(".".join(fparts[:-cut]))
                if target is None:
                    continue
                q = ".".join(fparts[-cut:])
                fn = target.functions.get(q)
                if fn is not None:
                    return (target, q, fn)
        return None

    def summary(self, mod: _Module, qual: str) -> _Summary:
        key = (mod.path, qual)
        cached = self._summaries.get(key)
        if cached is not None:
            return cached
        if key in self._in_progress:
            return _EMPTY_SUMMARY  # cycle: assume clean (no fixpoint)
        self._in_progress.add(key)
        try:
            fn = mod.functions[qual]
            w = _FlowWalker(self, mod, fn, qual, summary_mode=True)
            w.run()
            s = _Summary(
                frozenset(t for t in w.ret if t.kind != "param"),
                frozenset(
                    int(t.desc) for t in w.ret if t.kind == "param"
                ),
                frozenset(w.param_surface),
            )
        except RecursionError:
            s = _EMPTY_SUMMARY
        self._in_progress.discard(key)
        self._summaries[key] = s
        return s

    def emit(
        self, rule: str, path: str, line: int, col: int, msg: str
    ) -> None:
        if rule not in self.active:
            return
        key = (rule, path, line, col)
        if key not in self._findings:
            self._findings[key] = Finding(rule, path, line, col, msg)

    def findings(self) -> List[Finding]:
        out = list(self._findings.values()) + list(self.missing)
        out.sort(key=Finding.key)
        return out


# ---------------------------------------------------------------------------
# the flow-sensitive walker
# ---------------------------------------------------------------------------

#: per-kind remedy fragments for sink messages
_REMEDY = {
    "wall": (
        "the emitted bytes become clock-dependent; derive the value "
        "from input or move it to an observed/diagnostic field"
    ),
    "rand": (
        "draw from a seeded random.Random(seed) or keep the value out "
        "of the det bytes"
    ),
    "env": (
        "pid/host/env values diverge across processes and hosts on "
        "identical input"
    ),
    "hash": (
        "impose an order with sorted() before emitting — iteration "
        "order is PYTHONHASHSEED-dependent"
    ),
    "fs": (
        "wrap the directory listing in sorted() before emitting — "
        "directory order is filesystem-dependent"
    ),
}


def _union(sets: Iterable[Set[Taint]]) -> Set[Taint]:
    out: Set[Taint] = set()
    for s in sets:
        out |= s
    return out


def _has_exit(stmts: Sequence[ast.AST]) -> bool:
    for st in stmts:
        for sub in ast.walk(st):
            if isinstance(
                sub,
                (ast.Raise, ast.Return, ast.Break, ast.Continue,
                 ast.Yield, ast.YieldFrom),
            ):
                return True
    return False


def _provably_ordered(node: ast.AST) -> bool:
    """Value whose serialization cannot depend on dict/hash order."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_provably_ordered(e) for e in node.elts)
    if isinstance(node, ast.Call) and _dotted(node.func) == "sorted":
        return True
    if isinstance(node, ast.JoinedStr):
        return True
    return False


class _FlowWalker:
    """One pass over one function: statement-ordered, flow-sensitive
    (rebinding a name replaces its taint — ``x = sorted(x)`` cleanses),
    branch bodies walked inline in source order (taints union across
    branches; fabreg's det-hazard source-order semantics)."""

    def __init__(
        self,
        prog: _Program,
        mod: _Module,
        fn: ast.AST,
        qual: str,
        summary_mode: bool = False,
    ):
        self.prog = prog
        self.mod = mod
        self.fn = fn
        self.qual = qual
        self.summary_mode = summary_mode
        self.cur_class = mod.cls_of.get(qual)
        self.t: Dict[str, Set[Taint]] = {}
        self.ret: Set[Taint] = set()
        self.param_surface: Set[int] = set()
        self.surface: Optional[SurfaceSpec] = (
            None if summary_mode else prog.surfaces.get((mod.path, qual))
        )
        self.det_names: Set[str] = set()
        if summary_mode:
            for i, nm in enumerate(_param_names(fn)):
                self.t[nm] = {Taint("param", "", mod.path, 0, str(i))}
        else:
            for spec in prog.detdict.get(mod.path, []):
                if self._decorated_with(fn, spec.decorator):
                    self.det_names = {"det"}
                    for n in ast.walk(fn):
                        if (
                            isinstance(n, ast.Return)
                            and isinstance(n.value, (ast.Tuple, ast.List))
                            and n.value.elts
                            and isinstance(n.value.elts[0], ast.Name)
                        ):
                            self.det_names.add(n.value.elts[0].id)
                    break

    @staticmethod
    def _decorated_with(fn: ast.AST, name: str) -> bool:
        for d in fn.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            dn = _dotted(target)
            if dn and dn.rsplit(".", 1)[-1] == name:
                return True
        return False

    def run(self) -> None:
        self._stmts(self.fn.body)

    # -- statements ---------------------------------------------------------

    def _stmts(self, body: Sequence[ast.AST]) -> None:
        for st in body:
            self._stmt(st)

    def _stmt(self, st: ast.AST) -> None:
        if isinstance(
            st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # nested defs are walked via their own qualnames only
        if isinstance(st, ast.Assign):
            self._assign(st)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                ts = self._eval(st.value)
                if self._detdict_target(st.target):
                    self._det_emit(ts, st)
                else:
                    self._bind(st.target, ts)
        elif isinstance(st, ast.AugAssign):
            ts = self._eval(st.value)
            if self._detdict_target(st.target):
                self._det_emit(ts, st)
            elif isinstance(st.target, ast.Name):
                self.t.setdefault(st.target.id, set()).update(ts)
            elif isinstance(st.target, ast.Subscript) and isinstance(
                st.target.value, ast.Name
            ):
                self.t.setdefault(st.target.value.id, set()).update(ts)
        elif isinstance(st, ast.Expr):
            self._eval(st.value)
        elif isinstance(st, ast.Return):
            self._return(st)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            it = self._eval(st.iter)
            self._bind(st.target, it)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.While):
            ts = self._eval(st.test)
            self._control(ts, st, list(st.body) + list(st.orelse))
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.If):
            ts = self._eval(st.test)
            self._control(ts, st, list(st.body) + list(st.orelse))
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                ts = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, ts)
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                if h.name:
                    self.t[h.name] = set()
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
        elif isinstance(st, ast.Raise):
            if st.exc is not None:
                self._eval(st.exc)
        elif isinstance(st, ast.Assert):
            self._eval(st.test)
            if st.msg is not None:
                self._eval(st.msg)
        elif isinstance(st, ast.Delete):
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    self.t.pop(tgt.id, None)
        elif isinstance(st, getattr(ast, "Match", ())):
            self._eval(st.subject)
            for case in st.cases:
                self._stmts(case.body)

    def _assign(self, st: ast.Assign) -> None:
        ts = self._eval(st.value)
        for tgt in st.targets:
            if self._detdict_target(tgt):
                self._det_emit(ts, st)
                continue  # the det name itself stays clean (fabreg shape)
            if (
                isinstance(tgt, (ast.Tuple, ast.List))
                and isinstance(st.value, (ast.Tuple, ast.List))
                and len(tgt.elts) == len(st.value.elts)
            ):
                # elementwise unpack: taint only names actually bound
                # to a hazardous element
                for t_el, v_el in zip(tgt.elts, st.value.elts):
                    self._bind(t_el, self._eval(v_el))
                continue
            self._bind(tgt, ts)

    def _bind(self, target: ast.AST, ts: Set[Taint]) -> None:
        if isinstance(target, ast.Name):
            self.t[target.id] = set(ts)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, ts)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, ts)
        elif isinstance(target, ast.Subscript) and isinstance(
            target.value, ast.Name
        ):
            # container[key] = v: the container accumulates the VALUE's
            # taint; the key indexes storage and never becomes output
            # bytes itself (id()-keyed dedup maps stay silent)
            self.t.setdefault(target.value.id, set()).update(ts)

    def _detdict_target(self, tgt: ast.AST) -> bool:
        if not self.det_names:
            return False
        if isinstance(tgt, ast.Name) and tgt.id in self.det_names:
            return True
        return (
            isinstance(tgt, ast.Subscript)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id in self.det_names
        )

    def _return(self, st: ast.Return) -> None:
        ts = self._eval(st.value) if st.value is not None else set()
        if self.summary_mode:
            self.ret |= ts
        if self.surface is not None:
            self._emit_sink(
                ts, st,
                f"returned by det surface {self.surface.name!r}",
                self.surface,
            )
        if (
            self.det_names
            and isinstance(st.value, (ast.Tuple, ast.List))
            and st.value.elts
        ):
            first = st.value.elts[0]
            if isinstance(first, ast.Name):
                if first.id not in self.det_names:
                    self._det_emit(self.t.get(first.id, set()), st)
            else:
                self._det_emit(self._eval(first), st)

    def _control(
        self, ts: Set[Taint], node: ast.AST, body: Sequence[ast.AST]
    ) -> None:
        """A tainted branch condition that gates an exit of a declared
        surface makes the emitted stream clock/env-dependent."""
        if self.surface is None or self.summary_mode:
            return
        vts = [t for t in ts if t.kind in _VALUE_KINDS]
        if not vts or not _has_exit(body):
            return
        seen: Set[str] = set()
        for t in sorted(vts):
            if t.rule in seen:
                continue
            seen.add(t.rule)
            line = t.line if (t.path == self.mod.path and t.line) else node.lineno
            self.prog.emit(
                t.rule, self.mod.path, line, node.col_offset,
                f"{t.desc} gates the output path of det surface "
                f"{self.surface.name!r} [{self.surface.tier}] — a "
                f"replaying twin diverges when clock/environment "
                f"differ; derive the guard from input or suppress "
                f"naming the semantic contract",
            )

    # -- expressions --------------------------------------------------------

    def _eval(self, node: Optional[ast.AST]) -> Set[Taint]:
        if node is None:
            return set()
        if isinstance(node, ast.Constant):
            return set()
        if isinstance(node, ast.Name):
            return self.t.get(node.id, set())
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.Attribute):
            return self._eval(node.value)
        if isinstance(node, ast.Subscript):
            dn = _dotted(node.value)
            ts = self._eval(node.value) | self._eval(node.slice)
            if dn in ("os.environ", "environ"):
                ts = ts | {
                    Taint("env", "env-in-det", self.mod.path,
                          node.lineno, f"{dn}[...]")
                }
            return ts
        if isinstance(node, ast.BinOp):
            return self._eval(node.left) | self._eval(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand)
        if isinstance(node, ast.BoolOp):
            return _union(self._eval(v) for v in node.values)
        if isinstance(node, ast.Compare):
            ts = set(self._eval(node.left))
            for op, comp in zip(node.ops, node.comparators):
                cts = self._eval(comp)
                if isinstance(op, (ast.In, ast.NotIn)):
                    cts = _strip_order(cts)  # membership is order-free
                ts |= cts
            return ts
        if isinstance(node, (ast.Tuple, ast.List)):
            return _union(self._eval(e) for e in node.elts)
        if isinstance(node, ast.Set):
            return _union(self._eval(e) for e in node.elts) | {
                Taint("hash", "hash-order-hazard", self.mod.path,
                      node.lineno, "set literal")
            }
        if isinstance(node, ast.Dict):
            return _union(
                self._eval(e)
                for e in list(node.keys) + list(node.values)
                if e is not None
            )
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self._comp(node, [node.elt])
        if isinstance(node, ast.SetComp):
            return self._comp(node, [node.elt]) | {
                Taint("hash", "hash-order-hazard", self.mod.path,
                      node.lineno, "set comprehension")
            }
        if isinstance(node, ast.DictComp):
            return self._comp(node, [node.key, node.value])
        if isinstance(node, ast.IfExp):
            return (
                self._eval(node.test)
                | self._eval(node.body)
                | self._eval(node.orelse)
            )
        if isinstance(node, ast.JoinedStr):
            return _union(self._eval(v) for v in node.values)
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value)
        if isinstance(node, ast.Lambda):
            return set()
        if isinstance(node, ast.Starred):
            return self._eval(node.value)
        if isinstance(node, ast.Await):
            return self._eval(node.value)
        if isinstance(node, ast.NamedExpr):
            ts = self._eval(node.value)
            self._bind(node.target, ts)
            return ts
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            ts = self._eval(node.value) if node.value is not None else set()
            if self.surface is not None and not self.summary_mode:
                self._emit_sink(
                    ts, node,
                    f"yielded by det surface {self.surface.name!r}",
                    self.surface,
                )
            return set()
        if isinstance(node, ast.Slice):
            return (
                self._eval(node.lower)
                | self._eval(node.upper)
                | self._eval(node.step)
            )
        return set()

    def _comp(self, node: ast.AST, exprs: Sequence[ast.AST]) -> Set[Taint]:
        for gen in node.generators:
            it = self._eval(gen.iter)
            self._bind(gen.target, it)
            for cond in gen.ifs:
                self._eval(cond)
        return _union(self._eval(e) for e in exprs)

    # -- calls --------------------------------------------------------------

    def _call(self, node: ast.Call) -> Set[Taint]:
        dn = _dotted(node.func)
        leaf = node.func.attr if isinstance(node.func, ast.Attribute) else None
        base_ts: Set[Taint] = set()
        recv = None
        if isinstance(node.func, ast.Attribute):
            base_ts = self._eval(node.func.value)
            if isinstance(node.func.value, ast.Name):
                recv = node.func.value.id
        arg_ts = [self._eval(a) for a in node.args]
        kw_ts = [(kw.arg, self._eval(kw.value)) for kw in node.keywords]
        passed = _union(arg_ts) | _union(t for _, t in kw_ts)
        all_ts = passed | base_ts

        # det-dict sinks: det.update({...}) / det.setdefault(k, v)
        if (
            self.det_names
            and leaf in ("update", "setdefault")
            and recv is not None
            and recv in self.det_names
        ):
            self._det_emit(passed, node)
            return set()

        # receiver mutation folds argument taint into the receiver
        if leaf in _MUTATORS and recv is not None:
            self.t.setdefault(recv, set()).update(passed)
        if leaf == "sort" and recv is not None and recv in self.t:
            self.t[recv] = _strip_order(self.t[recv])

        if dn is not None:
            src = _classify_source(dn)
            if src is not None:
                kind, rule = src
                return all_ts | {
                    Taint(kind, rule, self.mod.path, node.lineno, dn + "()")
                }
            if dn in ("set", "frozenset"):
                return all_ts | {
                    Taint("hash", "hash-order-hazard", self.mod.path,
                          node.lineno, dn + "()")
                }
            if dn == "json.dump":
                self._json_dump(node)
                return set()
            if dn == "json.dumps":
                return passed | self._json_dumps(node)
            if dn in _CLEANSE_ALL:
                return set()
            if dn in _CLEANSE_ORDER:
                return _strip_order(all_ts)
            resolved = self.prog.resolve(self.mod, dn, self.cur_class)
            if resolved is not None:
                rmod, rqual, rfn = resolved
                spec = self.prog.surfaces.get((rmod.path, rqual))
                if spec is not None:
                    self._surface_args(spec, node, arg_ts, kw_ts)
                    return set()
                s = self.prog.summary(rmod, rqual)
                out: Set[Taint] = set(s.ret)
                if s.param_ret or s.param_surface:
                    pnames = _param_names(rfn)
                    offset = 1 if pnames[:1] == ["self"] else 0
                    for i, ts in enumerate(arg_ts):
                        self._param_flow(s, i + offset, ts, out, node, dn)
                    for kwname, ts in kw_ts:
                        if kwname is not None and kwname in pnames:
                            self._param_flow(
                                s, pnames.index(kwname), ts, out, node, dn
                            )
                        else:
                            out |= ts  # **kwargs: conservative
                return out

        # write-like sinks inside a declared surface function
        if (
            self.surface is not None
            and not self.summary_mode
            and leaf is not None
            and (
                leaf in ("write", "writelines")
                or leaf in self.surface.sinks
            )
        ):
            self._emit_sink(
                passed, node,
                f"written out by det surface {self.surface.name!r}",
                self.surface,
            )
            return set()
        if (
            self.summary_mode
            and leaf is not None
            and self.prog.surfaces.get((self.mod.path, self.qual))
            is not None
            and (
                leaf in ("write", "writelines")
                or leaf
                in self.prog.surfaces[(self.mod.path, self.qual)].sinks
            )
        ):
            for t in passed:
                if t.kind == "param":
                    self.param_surface.add(int(t.desc))
            return set()
        return all_ts

    def _param_flow(
        self,
        s: _Summary,
        idx: int,
        ts: Set[Taint],
        out: Set[Taint],
        node: ast.AST,
        dn: str,
    ) -> None:
        if idx in s.param_ret:
            out |= ts
        if idx in s.param_surface and ts:
            for t in ts:
                if t.kind == "param":
                    self.param_surface.add(int(t.desc))
            if not self.summary_mode:
                self._emit_sink(
                    ts, node,
                    f"passed through {dn}() into a det surface", None,
                )

    def _surface_args(
        self,
        spec: SurfaceSpec,
        node: ast.Call,
        arg_ts: Sequence[Set[Taint]],
        kw_ts: Sequence[Tuple[Optional[str], Set[Taint]]],
    ) -> None:
        for ts in list(arg_ts) + [t for _, t in kw_ts]:
            for t in ts:
                if t.kind == "param":
                    self.param_surface.add(int(t.desc))
            if not self.summary_mode:
                self._emit_sink(
                    ts, node,
                    f"passed to det surface {spec.name!r}", spec,
                )

    def _emit_sink(
        self,
        ts: Set[Taint],
        node: ast.AST,
        what: str,
        spec: Optional[SurfaceSpec],
    ) -> None:
        if self.summary_mode:
            return
        tier = f" [{spec.tier}]" if spec is not None else ""
        seen: Set[str] = set()
        for t in sorted(ts):
            if t.kind in _VALUE_KINDS and t.rule not in seen:
                seen.add(t.rule)
                self.prog.emit(
                    t.rule, self.mod.path, node.lineno, node.col_offset,
                    f"{t.desc} (line {t.line}) {what}{tier}: "
                    f"{_REMEDY[t.kind]}",
                )
            elif t.kind == "json" and "unsorted-serialize" not in seen:
                seen.add("unsorted-serialize")
                line = t.line if t.path == self.mod.path else node.lineno
                self.prog.emit(
                    "unsorted-serialize", self.mod.path, line,
                    node.col_offset,
                    f"json.dumps without sort_keys=True {what}{tier} — "
                    f"dict insertion order is code-path-dependent; pass "
                    f"sort_keys=True",
                )

    def _det_emit(self, ts: Set[Taint], node: ast.AST) -> None:
        seen: Set[str] = set()
        for t in sorted(ts):
            if t.kind not in _VALUE_KINDS or t.rule in seen:
                continue
            seen.add(t.rule)
            self.prog.emit(
                t.rule, self.mod.path, node.lineno, node.col_offset,
                f"{t.desc} flows into the deterministic scorecard "
                f"output of scenario {self.fn.name!r}: the chaos "
                f"gate's same-seed byte-diff will flap; move it to "
                f"the observed section or derive it from the seed",
            )

    def _json_dump(self, node: ast.Call) -> None:
        if self._json_ok(node):
            return
        self.prog.emit(
            "unsorted-serialize", self.mod.path, node.lineno,
            node.col_offset,
            "json.dump without sort_keys=True persists dict-order-"
            "dependent bytes (a persisted det surface by construction); "
            "pass sort_keys=True or dump a provably ordered value",
        )

    def _json_dumps(self, node: ast.Call) -> Set[Taint]:
        if self._json_ok(node):
            return set()
        return {
            Taint("json", "unsorted-serialize", self.mod.path,
                  node.lineno, "json.dumps()")
        }

    @staticmethod
    def _json_ok(node: ast.Call) -> bool:
        for kw in node.keywords:
            if kw.arg == "sort_keys":
                return not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                )
        return bool(node.args) and _provably_ordered(node.args[0])


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def analyze_sources(
    sources: Dict[str, str],
    rule_ids: Optional[Iterable[str]] = None,
    det: Optional[DetSpec] = None,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Analyze {path: source}.  ``det`` defaults to the packaged
    ``tools/det.toml`` (loud ValueError when missing/malformed)."""
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    for rid in active:
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
    if det is None:
        det = load_default_det()

    modules: Dict[str, _Module] = {}
    hard: List[Finding] = []
    for path, source in sorted(sources.items()):
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            hard.append(
                Finding(
                    "syntax-error", path, exc.lineno or 1,
                    exc.offset or 0, f"cannot parse: {exc.msg}",
                )
            )
            continue
        modules[path] = _Module(path, tree)

    prog = _Program(modules, det, active)
    for path, mod in sorted(modules.items()):
        for qual in sorted(mod.functions):
            _FlowWalker(prog, mod, mod.functions[qual], qual).run()

    by_path: Dict[str, List[Finding]] = {}
    for f in prog.findings():
        by_path.setdefault(f.path, []).append(f)
    findings: List[Finding] = list(hard)
    n_suppressed = 0
    for path in sorted(by_path):
        supp = toolkit.suppressed_rules(sources.get(path, ""), "fabdet")
        kept, suppressed = toolkit.apply_suppressions(by_path[path], supp)
        findings.extend(kept)
        n_suppressed += len(suppressed)
        if collect_suppressed is not None:
            collect_suppressed.extend(suppressed)
    findings.sort(key=Finding.key)
    stats = {"files": len(sources), "suppressed": n_suppressed}
    return findings, stats


def analyze_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
    det: Optional[DetSpec] = None,
) -> Tuple[List[Finding], int]:
    """Single-blob convenience (fixtures/tests)."""
    findings, stats = analyze_sources({path: source}, rule_ids, det)
    return findings, stats["suppressed"]


def analyze_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    det: Optional[DetSpec] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths, excludes)
    sources, io_findings = toolkit.read_sources(files)
    findings, stats = analyze_sources(sources, rule_ids, det)
    findings.extend(io_findings)
    findings.sort(key=Finding.key)
    stats["files"] = len(files)
    return findings, stats


def live_suppression_keys(
    sources: Dict[str, str], rules: Set[str]
) -> Set[Tuple[str, int, str]]:
    """The toolkit analyzer-registry staleness protocol (consumed by
    fabreg's suppression-stale): (normalized path, line, rule) for
    every fabdet suppression that still absorbs a finding."""
    needed = set(RULES) if "all" in rules else (rules & set(RULES))
    if not needed:
        return set()
    suppressed: List[Finding] = []
    analyze_sources(sources, needed, collect_suppressed=suppressed)
    return {
        (toolkit.normalize_path(f.path), f.line, f.rule)
        for f in suppressed
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fabdet",
        "whole-program byte-determinism taint analyzer for fabric-tpu "
        "(dependency-free; never imports the analyzed code)",
    )
    parser.add_argument(
        "--det",
        metavar="FILE",
        help="surface table (default: tools/det.toml next to this module)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=22)
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fabdet", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fabdet")
    if rc:
        return rc

    det: Optional[DetSpec] = None
    try:
        if args.det is not None:
            det = parse_det(
                Path(args.det).read_text(encoding="utf-8"), args.det
            )
        else:
            det = load_default_det()
    except (OSError, ValueError) as exc:
        print(f"fabdet: error: det table: {exc}", file=sys.stderr)
        return 2

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = analyze_paths(args.paths, rule_ids, excludes, det)

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fabdet: {len(findings)} finding(s) in {stats['files']} "
            f"file(s) ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
