"""fabwire — wire-format conformance analyzer for fabric-tpu.

fablint pins per-file syntax invariants, fabdep the import graph,
fabflow value ranges, fabreg the declarative tables, fablife resource
lifetimes.  The failure class none of them models is the one every
historical *wire* bug lived in: hand-rolled encode/decode pairs.  The
PR 8 unclamped ``retry_after_ms`` sleep, the PR 14
body-layout-keyed-to-revision desync, and the pre-PR 13
length-prefix-inflation truncation were all divergences between what
one end of a framing surface writes and what the other end trusts —
and the vectorized-ingest rev 4 multiplies that surface.  fabwire is a
symbolic wire-layout interpreter: it abstractly executes paired
encoders and decoders into field-layout summaries (struct format
strings, ``int.to_bytes``, length-prefix appends, per-revision
branches) and proves the two summaries agree, per negotiated revision,
without ever running the code.

Like its siblings it is pure ``ast`` on the shared ``tools/toolkit.py``
chassis: it never imports analyzed code and runs without
numpy/jax/cryptography.  Everything revision-specific lives in the
declarative table ``tools/wire.toml`` — rev 4 lands by adding rows
(codecs, fields, enum members, store twins), not analyzer code.

Rules
-----
encode-decode-skew   a declared codec pair whose encoder field layout
                     (order/width/endianness, loops as repeated
                     groups) diverges from its decoder's at any
                     declared revision — the PR 14 desync class.  Also
                     fires on a [[contract]] violation: a call to a
                     revision-keyed encoder (``encode_lanes``) without
                     its required ``version=`` key, and on a declared
                     encoder/decoder function missing from its module
                     (a rename must not silently drop the check).
rev-gate-drift       a [[field]] introduced at rev N whose encoder
                     write or decoder read is reachable under a
                     negotiated version < N (or gated at the wrong
                     rev), checked against the wire.toml revision
                     table; a declared field no layout token
                     references is table drift and fires too.
unbounded-wire-alloc a wire-decoded integer (struct.unpack ≥32-bit
                     field, reader u32/u64, int.from_bytes, decode_*
                     results) flowing into recv/read/range/bytearray/
                     sequence-repeat/sleep without a MAX_PAYLOAD-class
                     dominating bound (``min``/a terminal guard) —
                     u8/u16 reads are width-bounded, and [[trusted]]
                     helpers (checksum-before-trust, PR 13) are clean
                     sources.
status-untotal       an if/elif dispatch over ≥2 constants of one
                     [[enum]] family (OP_*/ST_*) with no ``else`` and
                     incomplete member coverage — adding a rev-4
                     opcode must never fall through silently; the
                     member list is also checked against the defining
                     module's constants (table drift is a finding).
frame-crc-gap        a [[store]] read twin that skips the header or
                     payload crc re-verify its write twin emits, a
                     write twin that frames without a checksum, or a
                     frame-touching function in a store module missing
                     from the store row (it would escape analysis).

Suppression
-----------
Per line, toolkit grammar: ``# fabwire: disable=rule-id  # <reason>``.
The reason must name the release/bound that makes the shape safe
(file-level sha256 seal, operator-owned trust domain, ...) — reviewed
via the NOTES_BUILD triage ledger, judged stale by fabreg through the
toolkit registry protocol.

Usage
-----
    python -m fabric_tpu.tools.fabwire [--json] [--list-rules]
        [--rules a,b] [--wire FILE] PATH...

Exit status: 0 = clean, 1 = findings, 2 = usage/IO/wire-table error
(a half-read wire table checking nothing would be silent drift — parse
errors are loud by design).
"""

from __future__ import annotations

import ast
import json
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    Finding,
    iter_py_files,
)

__version__ = "1.0"

RULES: Dict[str, str] = {
    "encode-decode-skew": (
        "a paired encoder/decoder whose field layouts "
        "(order/width/endianness, per revision) diverge, a "
        "revision-keyed encoder called without its required version= "
        "key, or a declared codec function missing from its module"
    ),
    "rev-gate-drift": (
        "a field introduced at rev N written or read on a path "
        "reachable under a negotiated version < N (checked against "
        "the tools/wire.toml revision table)"
    ),
    "unbounded-wire-alloc": (
        "a wire-decoded integer flows into recv/read/range/"
        "allocation/sleep without a MAX_PAYLOAD-class dominating "
        "bound (checksum-validated [[trusted]] lengths are clean)"
    ),
    "status-untotal": (
        "an if/elif dispatch over OP_*/ST_* constants missing a "
        "member without an explicit fail-closed else (or an [[enum]] "
        "member list drifted from the defining module)"
    ),
    "frame-crc-gap": (
        "a durability-store frame read twin that skips the header or "
        "payload crc re-verify its write twin emits"
    ),
}

#: wire framing is runtime-package discipline; tests craft deliberately
#: malformed frames all day (that is their job)
PKG_SCOPE = ("*fabric_tpu/*",)

#: struct format characters → (byte width, is-int).  ``s`` is a byte
#: field; pad/other codes are rejected (loud beats wrong).
_FMT_INT = {"b": 1, "B": 1, "h": 2, "H": 2, "i": 4, "I": 4,
            "l": 4, "L": 4, "q": 8, "Q": 8}
_ENDIAN_CHARS = {">": ">", "<": "<", "!": ">", "=": "=", "@": "="}

#: reader-object method leaves (the serve ``_Reader`` idiom)
_READER_INT_LEAVES = {"u8": 1, "u16": 2, "u32": 4, "u64": 8}
#: calls whose result is raw bytes fetched from the transport; a fetch
#: bound to a name later parsed (unpack / inlined helper) is a carrier,
#: not a field
_FETCH_LEAVES = {"read", "recv", "recv_from"}
#: measurement/checksum context — expressions inside these calls are
#: never wire fields and never consume placeholders
_OPAQUE_LEAVES = {"crc32", "len", "calcsize", "min", "max", "tell",
                  "seek", "getsize", "adler32"}

#: taint sinks for unbounded-wire-alloc: leaf name → 0-based index of
#: the length argument
_ALLOC_SINK_LEAVES = {"read": 0, "recv": 0, "recv_into": 1,
                      "bytearray": 0, "sleep": 0}
_WIDE_SOURCE_LEAVES = {"u32", "u64"}


# ---------------------------------------------------------------------------
# wire.toml
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class CodecSpec:
    name: str
    module: str
    encoder: str
    decoder: str
    revs: Tuple[int, ...]
    unwrap: bool = False
    doc: str = ""


@dataclass(frozen=True)
class FieldSpec:
    codec: str
    name: str
    rev: int
    gate: str


@dataclass(frozen=True)
class EnumSpec:
    prefix: str
    module: str
    members: Tuple[str, ...]


@dataclass(frozen=True)
class StoreSpec:
    name: str
    module: str
    writers: Tuple[str, ...]
    readers: Tuple[str, ...]
    checks: Tuple[str, ...]


@dataclass(frozen=True)
class WireSpec:
    surfaces: Tuple[str, ...] = ()
    codecs: Tuple[CodecSpec, ...] = ()
    fields: Tuple[FieldSpec, ...] = ()
    enums: Tuple[EnumSpec, ...] = ()
    stores: Tuple[StoreSpec, ...] = ()
    contracts: Tuple[Tuple[str, str], ...] = ()  # (function, require_kw)
    trusted: Tuple[str, ...] = ()
    sinks: Tuple[Tuple[str, int], ...] = ()  # (leaf, arg index)


def default_wire_file() -> Path:
    return Path(__file__).resolve().parent / "wire.toml"


def _parse_value(raw: str, where: str):
    raw = raw.strip()
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw.lstrip("-").isdigit():
        return int(raw)
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        items: List[object] = []
        for part in inner.split(","):
            part = part.strip()
            if not part:
                continue
            if part.startswith('"') and part.endswith('"'):
                items.append(part[1:-1])
            elif part.lstrip("-").isdigit():
                items.append(int(part))
            else:
                raise ValueError(
                    f"{where}: list items must be \"quoted\" or integers"
                )
        return items
    raise ValueError(
        f"{where}: expected \"string\", integer, [list] or true/false"
    )


_SECTIONS = ("surface", "codec", "field", "enum", "store", "contract",
             "trusted", "sink")


def parse_wire(text: str, path: str = "<wire>") -> WireSpec:
    """Parse the tiny TOML subset shared with pairs.toml/layers.toml.
    LOUD on any malformed line or missing key: a half-read wire table
    silently checking nothing would be config drift."""
    entries: List[Tuple[str, Dict[str, object]]] = []
    current: Optional[Dict[str, object]] = None
    for n, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            section = line[2:-2].strip()
            if section not in _SECTIONS:
                raise ValueError(f"{path}:{n}: unknown section {line!r}")
            current = {}
            entries.append((section, current))
            continue
        if line.startswith("["):
            raise ValueError(f"{path}:{n}: unknown section {line!r}")
        if "=" not in line:
            raise ValueError(f"{path}:{n}: expected 'key = value'")
        if current is None:
            raise ValueError(f"{path}:{n}: key outside a [[section]] entry")
        key, _, value = line.partition("=")
        if "#" in value and not value.strip().startswith('"'):
            value = value.split("#", 1)[0]
        current[key.strip()] = _parse_value(value, f"{path}:{n}")

    def need(entry: Dict[str, object], keys: Sequence[str], where: str):
        for k in keys:
            if k not in entry:
                raise ValueError(f"{where}: missing required key {k!r}")

    def strs(value: object, where: str) -> Tuple[str, ...]:
        if isinstance(value, str):
            return (value,)
        if isinstance(value, list) and all(
            isinstance(v, str) for v in value
        ):
            return tuple(value)
        raise ValueError(f"{where}: expected a string or list of strings")

    surfaces: List[str] = []
    codecs: List[CodecSpec] = []
    fields: List[FieldSpec] = []
    enums: List[EnumSpec] = []
    stores: List[StoreSpec] = []
    contracts: List[Tuple[str, str]] = []
    trusted: List[str] = []
    sinks: List[Tuple[str, int]] = []
    for i, (section, e) in enumerate(entries, start=1):
        where = f"{path}: [[{section}]] #{i}"
        if section == "surface":
            need(e, ("module",), where)
            surfaces.append(str(e["module"]))
        elif section == "codec":
            need(e, ("name", "module", "encoder", "decoder", "revs"), where)
            revs = e["revs"]
            if not (isinstance(revs, list) and revs and all(
                isinstance(r, int) for r in revs
            )):
                raise ValueError(
                    f"{where}: revs must be a non-empty list of integers"
                )
            codecs.append(CodecSpec(
                name=str(e["name"]), module=str(e["module"]),
                encoder=str(e["encoder"]), decoder=str(e["decoder"]),
                revs=tuple(sorted(revs)),
                unwrap=bool(e.get("unwrap", False)),
                doc=str(e.get("doc", "")),
            ))
        elif section == "field":
            need(e, ("codec", "name", "rev"), where)
            if not isinstance(e["rev"], int):
                raise ValueError(f"{where}: rev must be an integer")
            fields.append(FieldSpec(
                codec=str(e["codec"]), name=str(e["name"]),
                rev=int(e["rev"]), gate=str(e.get("gate", e["name"])),
            ))
        elif section == "enum":
            need(e, ("prefix", "module", "members"), where)
            members = strs(e["members"], where)
            if not members:
                raise ValueError(f"{where}: members must be non-empty")
            enums.append(EnumSpec(
                prefix=str(e["prefix"]), module=str(e["module"]),
                members=members,
            ))
        elif section == "store":
            need(e, ("name", "module", "writers", "readers"), where)
            checks = strs(e.get("checks", ["header", "payload"]), where)
            for c in checks:
                if c not in ("header", "payload"):
                    raise ValueError(
                        f"{where}: checks entries must be "
                        f"\"header\" or \"payload\", got {c!r}"
                    )
            stores.append(StoreSpec(
                name=str(e["name"]), module=str(e["module"]),
                writers=strs(e["writers"], where),
                readers=strs(e["readers"], where),
                checks=checks,
            ))
        elif section == "contract":
            need(e, ("function", "require_kw"), where)
            contracts.append((str(e["function"]), str(e["require_kw"])))
        elif section == "trusted":
            need(e, ("function",), where)
            trusted.append(str(e["function"]))
        elif section == "sink":
            need(e, ("function", "arg"), where)
            if not isinstance(e["arg"], int) or e["arg"] < 0:
                raise ValueError(f"{where}: arg must be an index >= 0")
            sinks.append((str(e["function"]), int(e["arg"])))
    codec_names = {c.name for c in codecs}
    for f in fields:
        if f.codec not in codec_names:
            raise ValueError(
                f"{path}: [[field]] {f.name!r} names unknown codec "
                f"{f.codec!r}"
            )
    return WireSpec(
        surfaces=tuple(surfaces), codecs=tuple(codecs),
        fields=tuple(fields), enums=tuple(enums), stores=tuple(stores),
        contracts=tuple(contracts), trusted=tuple(trusted),
        sinks=tuple(sinks),
    )


def load_default_wire() -> WireSpec:
    f = default_wire_file()
    return parse_wire(f.read_text(encoding="utf-8"), str(f))


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------


def _leaf(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _names_in(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _const_str(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_int(node: ast.expr) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


class _ModuleMap:
    """Import-free per-file symbol map: module struct.Struct constants,
    string constants, functions (plain and Class.method), and int
    constants (for enum drift checks)."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.structs: Dict[str, str] = {}
        self.str_consts: Dict[str, str] = {}
        self.int_consts: Dict[str, int] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                v = node.value
                if isinstance(v, ast.Call) and _leaf(v.func) == "Struct" \
                        and v.args:
                    fmt = _const_str(v.args[0])
                    if fmt is not None:
                        self.structs[name] = fmt
                elif _const_str(v) is not None:
                    self.str_consts[name] = _const_str(v)  # type: ignore
                elif _const_int(v) is not None:
                    self.int_consts[name] = _const_int(v)  # type: ignore
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(node, ast.FunctionDef):
                    self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        self.functions[f"{node.name}.{sub.name}"] = sub

    def lookup(self, name: str) -> Optional[ast.FunctionDef]:
        """Resolve ``fn`` or ``Class.method``; a bare leaf also matches
        a unique method of any class in this module."""
        if name in self.functions:
            return self.functions[name]
        hits = [
            fn for qual, fn in self.functions.items()
            if qual.rsplit(".", 1)[-1] == name
        ]
        if len(hits) == 1:
            return hits[0]
        return None


# ---------------------------------------------------------------------------
# layout tokens
# ---------------------------------------------------------------------------


@dataclass
class Tok:
    kind: str               # "int" | "bytes" | "group"
    size: int = 0           # int width / fixed bytes length (0 unknown)
    endian: str = ">"
    rev: int = 1            # minimum revision that carries this token
    line: int = 0
    names: Set[str] = field(default_factory=set)
    sub: List["Tok"] = field(default_factory=list)
    pending: Optional[str] = None  # fetched-carrier name, resolvable
    splice: bool = False    # consumed carrier: flatten transparently

    def describe(self) -> str:
        if self.kind == "int":
            e = {"<": "le", ">": "be", "=": "ne"}.get(self.endian, "?")
            return f"u{self.size * 8}{e}" if self.size != 1 else "u8"
        if self.kind == "bytes":
            return f"bytes[{self.size}]" if self.size else "bytes"
        inner = " ".join(t.describe() for t in self.sub)
        return f"group({inner})"


def _fmt_toks(fmt: str, line: int, rev: int, where: str) -> List[Tok]:
    """struct format string → layout tokens (LOUD on unknown codes)."""
    endian = ">"
    i = 0
    if fmt and fmt[0] in _ENDIAN_CHARS:
        endian = _ENDIAN_CHARS[fmt[0]]
        i = 1
    out: List[Tok] = []
    count = ""
    while i < len(fmt):
        ch = fmt[i]
        i += 1
        if ch.isdigit():
            count += ch
            continue
        n = int(count) if count else 1
        count = ""
        if ch in _FMT_INT:
            for _ in range(n):
                out.append(Tok("int", _FMT_INT[ch], endian, rev, line))
        elif ch == "s":
            out.append(Tok("bytes", n, endian, rev, line))
        elif ch == "x":
            out.append(Tok("bytes", n, endian, rev, line))
        elif ch.isspace():
            continue
        else:
            raise ValueError(
                f"{where}: unsupported struct format code {ch!r} in "
                f"{fmt!r}"
            )
    return out


def _flatten(toks: Sequence[Tok]) -> List[Tok]:
    out: List[Tok] = []
    for t in toks:
        out.append(t)
        if t.kind == "group":
            out.extend(_flatten(t.sub))
    return out


def _project(toks: Sequence[Tok], rev: int) -> List[Tok]:
    out: List[Tok] = []
    for t in toks:
        if t.rev > rev:
            continue
        if t.kind == "group":
            g = Tok("group", 0, t.endian, t.rev, t.line, set(t.names),
                    _project(t.sub, rev))
            out.append(g)
        else:
            out.append(t)
    return out


# ---------------------------------------------------------------------------
# symbolic interpretation — shared machinery
# ---------------------------------------------------------------------------


class _Interp:
    """Base for the encoder/decoder summarizers: module-map access,
    helper resolution with cycle guard, revision-gate stack."""

    def __init__(self, mod: _ModuleMap, maps: Dict[str, "_ModuleMap"],
                 fields: Sequence[FieldSpec], seen: Optional[Set[str]] = None):
        self.mod = mod
        self.maps = maps
        self.fields = fields
        self.rev_stack: List[int] = [1]
        self.gate_stack: List[Set[str]] = [set()]
        self.seen = seen if seen is not None else set()

    # -- helper resolution --------------------------------------------------
    def resolve_helper(self, name: str) -> Optional[Tuple[_ModuleMap,
                                                          ast.FunctionDef]]:
        fn = self.mod.lookup(name)
        if fn is not None:
            return self.mod, fn
        hits = []
        for m in self.maps.values():
            f = m.functions.get(name)
            if f is not None:
                hits.append((m, f))
        if len(hits) == 1:
            return hits[0]
        return None

    # -- revision gates -----------------------------------------------------
    def cond_rev(self, test: ast.expr) -> Optional[int]:
        """Map a guard condition to the minimum revision under which its
        body runs: ``version >= N`` / ``version == N``, a gate-parameter
        presence check (``deadline_ms is not None``), or None."""
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            revs = [self.cond_rev(v) for v in test.values]
            revs = [r for r in revs if r is not None]
            return max(revs) if revs else None
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            left, op, right = test.left, test.ops[0], test.comparators[0]
            if isinstance(left, ast.Name) and left.id == "version":
                n = _const_int(right)
                if n is not None:
                    if isinstance(op, (ast.GtE, ast.Eq)):
                        return n
                    if isinstance(op, ast.Gt):
                        return n + 1
            if isinstance(op, (ast.IsNot,)) and isinstance(
                right, ast.Constant
            ) and right.value is None:
                names = _names_in(left)
                revs = [
                    f.rev for f in self.fields
                    if f.gate in names or f.name in names
                ]
                if revs:
                    return max(revs)
        return None

    @property
    def rev(self) -> int:
        return max(self.rev_stack)

    @property
    def gates(self) -> Set[str]:
        out: Set[str] = set()
        for g in self.gate_stack:
            out |= g
        return out

    def enter(self, test: ast.expr):
        r = self.cond_rev(test)
        self.rev_stack.append(r if r is not None else self.rev)
        self.gate_stack.append(_names_in(test) if r is not None else set())

    def leave(self):
        self.rev_stack.pop()
        self.gate_stack.pop()

    def stamp(self, toks: List[Tok], extra: Optional[Set[str]] = None
              ) -> List[Tok]:
        rev, gates = self.rev, self.gates
        for t in _flatten(toks):
            t.rev = max(t.rev, rev)
            t.names |= gates
            if extra:
                t.names |= extra
        return toks


# ---------------------------------------------------------------------------
# encoder summarization
# ---------------------------------------------------------------------------


class _Enc(_Interp):
    """Walk an encoder body tracking byte buffers: list/bytearray
    accumulators, ``+=``/``append``/``extend``, helper inlining,
    ``.write()`` emissions, and the returned expression."""

    def __init__(self, mod, maps, fields, seen=None):
        super().__init__(mod, maps, fields, seen)
        self.buffers: Dict[str, List[Tok]] = {}
        self.out_stream: List[Tok] = []
        self.result: Optional[List[Tok]] = None

    def summarize(self, fn: ast.FunctionDef) -> List[Tok]:
        # the guard is per call chain (recursion), not a memo: the same
        # helper legitimately contributes once per call site
        key = f"{self.mod.path}:{fn.name}:enc"
        if key in self.seen:
            return []
        self.seen.add(key)
        try:
            self.walk_body(fn.body)
        finally:
            self.seen.discard(key)
        if self.result is not None:
            return self.result
        if self.out_stream:
            return self.out_stream
        # a mutating helper (fills its first buffer parameter)
        if fn.args.args:
            first = fn.args.args[0].arg
            if first in self.buffers:
                return self.buffers[first]
        return []

    # -- statements ---------------------------------------------------------
    def walk_body(self, body: Sequence[ast.stmt]):
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt):
        if isinstance(stmt, ast.Assign):
            toks = self.emit(stmt.value)
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.buffers[tgt.id] = toks
        elif isinstance(stmt, ast.AugAssign) and isinstance(
            stmt.op, ast.Add
        ) and isinstance(stmt.target, ast.Name):
            self.buffers.setdefault(stmt.target.id, []).extend(
                self.emit(stmt.value)
            )
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            self.call_stmt(stmt.value)
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            toks = self.emit(stmt.value)
            if toks and self.result is None:
                self.result = toks
        elif isinstance(stmt, ast.If):
            self.enter(stmt.test)
            self.walk_body(stmt.body)
            self.leave()
            self.walk_body(stmt.orelse)
        elif isinstance(stmt, (ast.For, ast.While)):
            self.group_scope(stmt)
        elif isinstance(stmt, ast.Try):
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            self.walk_body(stmt.finalbody)
        elif isinstance(stmt, ast.With):
            self.walk_body(stmt.body)

    def group_scope(self, loop):
        marks = {k: len(v) for k, v in self.buffers.items()}
        out_mark = len(self.out_stream)
        self.walk_body(loop.body)
        for name, buf in list(self.buffers.items()):
            mark = marks.get(name, 0)
            new = buf[mark:]
            if new:
                del buf[mark:]
                buf.append(Tok("group", 0, ">", min(t.rev for t in new),
                               loop.lineno, set(), new))
        new_out = self.out_stream[out_mark:]
        if new_out:
            del self.out_stream[out_mark:]
            self.out_stream.append(
                Tok("group", 0, ">", min(t.rev for t in new_out),
                    loop.lineno, set(), new_out)
            )

    def call_stmt(self, call: ast.Call):
        leaf = _leaf(call.func)
        if leaf in ("append", "extend") and isinstance(
            call.func, ast.Attribute
        ) and isinstance(call.func.value, ast.Name) and call.args:
            name = call.func.value.id
            self.buffers.setdefault(name, []).extend(
                self.emit(call.args[0])
            )
            return
        if leaf == "write" and call.args:
            self.out_stream.extend(self.emit(call.args[0]))
            return
        # mutating helper: first arg names a tracked buffer
        if leaf and call.args and isinstance(call.args[0], ast.Name) \
                and call.args[0].id in self.buffers:
            resolved = self.resolve_helper(leaf)
            if resolved is not None:
                mod, fn = resolved
                sub = _Enc(mod, self.maps, self.fields, self.seen)
                toks = sub.summarize(fn)
                if toks:
                    extra = set()
                    for a in call.args[1:]:
                        extra |= _names_in(a)
                    self.buffers[call.args[0].id].extend(
                        self.stamp(toks, extra)
                    )

    # -- emitted-bytes expressions ------------------------------------------
    def emit(self, node: ast.expr) -> List[Tok]:
        if isinstance(node, ast.Call):
            return self.emit_call(node)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            return self.emit(node.left) + self.emit(node.right)
        if isinstance(node, ast.Name):
            if node.id in self.buffers:
                return list(self.buffers[node.id])
            return self.stamp(
                [Tok("bytes", 0, ">", 1, node.lineno)], {node.id}
            )
        if isinstance(node, (ast.List, ast.Tuple)):
            out: List[Tok] = []
            for elt in node.elts:
                out.extend(self.emit(elt))
            return out
        if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
            return self.stamp(
                [Tok("bytes", len(node.value), ">", 1, node.lineno)]
            )
        if isinstance(node, ast.IfExp):
            return self.emit(node.body)
        # opaque bytes expression (encode(), SerializeToString(), slices)
        return self.stamp(
            [Tok("bytes", 0, ">", 1, getattr(node, "lineno", 0))],
            _names_in(node),
        )

    def emit_call(self, call: ast.Call) -> List[Tok]:
        leaf = _leaf(call.func)
        where = f"{self.mod.path}:{call.lineno}"
        if leaf == "pack":
            fmt: Optional[str] = None
            args = call.args
            if isinstance(call.func, ast.Attribute):
                base = call.func.value
                if isinstance(base, ast.Name) and base.id in \
                        self.mod.structs:
                    fmt = self.mod.structs[base.id]
                elif _leaf(base) == "struct" or isinstance(base, ast.Name):
                    if args:
                        fmt = _const_str(args[0]) or (
                            self.mod.str_consts.get(args[0].id)
                            if isinstance(args[0], ast.Name) else None
                        )
                        if fmt is not None:
                            args = args[1:]
            if fmt is not None:
                toks = _fmt_toks(fmt, call.lineno, 1, where)
                for tok, arg in zip(toks, args):
                    tok.names |= _names_in(arg)
                return self.stamp(toks)
            return self.stamp(
                [Tok("bytes", 0, ">", 1, call.lineno)], _names_in(call)
            )
        if leaf == "to_bytes":
            size = _const_int(call.args[0]) if call.args else None
            endian = ">"
            if len(call.args) > 1:
                e = _const_str(call.args[1])
                endian = "<" if e == "little" else ">"
            return self.stamp(
                [Tok("int", size or 0, endian, 1, call.lineno)],
                _names_in(call.func),
            )
        if leaf == "join" and call.args and isinstance(
            call.args[0], ast.Name
        ) and call.args[0].id in self.buffers:
            return list(self.buffers[call.args[0].id])
        if leaf in ("bytes", "bytearray", "memoryview") and call.args:
            return self.emit(call.args[0])
        if leaf is not None:
            resolved = self.resolve_helper(leaf)
            if resolved is not None:
                mod, fn = resolved
                sub = _Enc(mod, self.maps, self.fields, self.seen)
                toks = sub.summarize(fn)
                if toks:
                    extra: Set[str] = set()
                    for a in call.args:
                        extra |= _names_in(a)
                    return self.stamp(toks, extra)
        return self.stamp(
            [Tok("bytes", 0, ">", 1, call.lineno)], _names_in(call)
        )


# ---------------------------------------------------------------------------
# decoder summarization
# ---------------------------------------------------------------------------


class _Dec(_Interp):
    """Walk a decoder body collecting reads in evaluation order.
    Fetched/sliced byte carriers become placeholders at their binding
    site; a later parse (unpack or inlined helper) replaces the
    placeholder in place, so offset-style decoders keep wire order."""

    def __init__(self, mod, maps, fields, seen=None, endian: str = ">"):
        super().__init__(mod, maps, fields, seen)
        self.default_endian = endian
        self.out: List[Tok] = []
        self.pending: Dict[str, Tok] = {}
        self.local_strs: Dict[str, str] = {}

    def summarize(self, fn: ast.FunctionDef, unwrap: bool = False
                  ) -> List[Tok]:
        # per-chain cycle guard, not a memo (see _Enc.summarize)
        key = f"{self.mod.path}:{fn.name}:dec"
        if key in self.seen:
            return []
        self.seen.add(key)
        body: Sequence[ast.stmt] = fn.body
        if unwrap:
            loop = self._find_loop(fn.body)
            if loop is not None:
                body = loop.body
        try:
            self.walk_body(body)
        finally:
            self.seen.discard(key)
        return self.out

    @staticmethod
    def _find_loop(body: Sequence[ast.stmt]):
        """First scan loop, looking through with/try wrappers (the
        recovery readers open their file first)."""
        for stmt in body:
            if isinstance(stmt, (ast.While, ast.For)):
                return stmt
            if isinstance(stmt, (ast.With, ast.Try)):
                found = _Dec._find_loop(stmt.body)
                if found is not None:
                    return found
        return None

    def walk_body(self, body: Sequence[ast.stmt]):
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.stmt):
        if isinstance(stmt, ast.Assign):
            targets: List[str] = []
            tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
            if isinstance(tgt, ast.Name):
                targets = [tgt.id]
            elif isinstance(tgt, ast.Tuple):
                targets = [
                    e.id for e in tgt.elts if isinstance(e, ast.Name)
                ]
            s = _const_str(stmt.value)
            if targets and s is not None:
                self.local_strs[targets[0]] = s
                return
            toks = self.reads(stmt.value, targets=targets)
            self.out.extend(toks)
        elif isinstance(stmt, ast.AugAssign):
            self.out.extend(self.reads(stmt.value))
        elif isinstance(stmt, ast.Expr):
            self.out.extend(self.reads(stmt.value))
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            self.out.extend(self.reads(stmt.value))
        elif isinstance(stmt, ast.If):
            self.out.extend(self.reads(stmt.test))
            self.enter(stmt.test)
            self.walk_body(stmt.body)
            self.leave()
            self.walk_body(stmt.orelse)
        elif isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self.out.extend(self.reads(stmt.iter))
            mark = len(self.out)
            self.walk_body(stmt.body)
            new = self.out[mark:]
            if new:
                del self.out[mark:]
                self.out.append(
                    Tok("group", 0, ">", min(t.rev for t in new),
                        stmt.lineno, set(), new)
                )
        elif isinstance(stmt, ast.Try):
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            self.walk_body(stmt.finalbody)
        elif isinstance(stmt, ast.With):
            self.walk_body(stmt.body)
        elif isinstance(stmt, (ast.Raise, ast.Pass, ast.Break,
                               ast.Continue)):
            return

    # -- read-producing expressions -----------------------------------------
    def reads(self, node: ast.expr,
              targets: Optional[List[str]] = None) -> List[Tok]:
        toks = self._reads(node)
        label = set(targets or ())
        if label:
            for t in _flatten(toks):
                t.names |= label
        if targets and toks:
            # positional labels for tuple-unpacked struct fields
            flat = [t for t in toks if t.kind != "group"]
            if len(targets) == len(flat):
                for name, t in zip(targets, flat):
                    t.names.add(name)
            tail = toks[-1]
            if tail.pending is not None and len(targets) >= 1:
                self.pending[targets[0]] = tail
                tail.pending = targets[0]
        return self.stamp(toks)

    def _reads(self, node: ast.expr) -> List[Tok]:
        if isinstance(node, ast.Call):
            return self._reads_call(node)
        if isinstance(node, ast.Subscript):
            if isinstance(node.slice, ast.Slice):
                return [Tok("bytes", 0, ">", 1, node.lineno,
                            pending="")]
            return self._reads(node.value) if isinstance(
                node.value, ast.Call
            ) else []
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            inner = self._reads(node.elt)
            iter_toks: List[Tok] = []
            for gen in node.generators:
                iter_toks.extend(self._reads(gen.iter))
            if inner:
                return iter_toks + [
                    Tok("group", 0, ">", 1, node.lineno, set(), inner)
                ]
            return iter_toks
        if isinstance(node, ast.IfExp):
            return self._reads(node.body)
        if isinstance(node, ast.BoolOp):
            out: List[Tok] = []
            for v in node.values:
                out.extend(self._reads(v))
            return out
        if isinstance(node, ast.Compare):
            out = self._reads(node.left)
            for c in node.comparators:
                out.extend(self._reads(c))
            return out
        if isinstance(node, ast.BinOp):
            return self._reads(node.left) + self._reads(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._reads(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                out.extend(self._reads(e))
            return out
        if isinstance(node, ast.Attribute):
            return []
        return []

    def _fmt_of(self, arg: ast.expr) -> Optional[str]:
        s = _const_str(arg)
        if s is not None:
            return s
        if isinstance(arg, ast.Name):
            return self.local_strs.get(arg.id) or \
                self.mod.str_consts.get(arg.id)
        return None

    def _reads_call(self, call: ast.Call) -> List[Tok]:
        leaf = _leaf(call.func)
        where = f"{self.mod.path}:{call.lineno}"
        if leaf in _OPAQUE_LEAVES:
            return []
        if leaf in _READER_INT_LEAVES:
            return [Tok("int", _READER_INT_LEAVES[leaf],
                        self.default_endian, 1, call.lineno)]
        if leaf == "take":
            inner: List[Tok] = []
            for a in call.args:
                inner.extend(self._reads(a))
            return inner + [Tok("bytes", 0, ">", 1, call.lineno)]
        if leaf in ("unpack", "unpack_from"):
            fmt: Optional[str] = None
            buf_arg: Optional[ast.expr] = None
            args = call.args
            if isinstance(call.func, ast.Attribute):
                base = call.func.value
                if isinstance(base, ast.Name) and base.id in \
                        self.mod.structs:
                    fmt = self.mod.structs[base.id]
                    buf_arg = args[0] if args else None
                else:
                    if args:
                        fmt = self._fmt_of(args[0])
                        buf_arg = args[1] if len(args) > 1 else None
            if fmt is None:
                return []
            toks = _fmt_toks(fmt, call.lineno, 1, where)
            return self._place(toks, buf_arg, call.lineno)
        if leaf in _FETCH_LEAVES:
            if not call.args:
                return []  # whole-stream read, not a field
            inner = []
            for a in call.args:
                inner.extend(self._reads(a))
            return inner + [Tok("bytes", 0, ">", 1, call.lineno,
                                pending="")]
        if leaf == "from_bytes":
            size = 0
            if call.args and isinstance(call.args[0], ast.Subscript) \
                    and isinstance(call.args[0].slice, ast.Slice):
                lo = call.args[0].slice.lower
                hi = call.args[0].slice.upper
                lo_v = 0 if lo is None else _const_int(lo)
                hi_v = _const_int(hi) if hi is not None else None
                if lo_v is not None and hi_v is not None:
                    size = hi_v - lo_v
            endian = ">"
            if len(call.args) > 1 and _const_str(call.args[1]) == "little":
                endian = "<"
            buf_arg = call.args[0] if call.args else None
            toks = [Tok("int", size, endian, 1, call.lineno)]
            return self._place(toks, buf_arg, call.lineno)
        if leaf in ("bytes", "memoryview") and call.args:
            return self._reads(call.args[0])
        if leaf == "decode" and isinstance(call.func, ast.Attribute):
            return self._reads(call.func.value)
        if leaf is not None:
            resolved = self.resolve_helper(leaf)
            if resolved is not None:
                mod, fn = resolved
                sub = _Dec(mod, self.maps, self.fields, self.seen,
                           self.default_endian)
                toks = sub.summarize(fn)
                if toks and _is_fetch_summary(toks):
                    # a fetch wrapper (_recv_exact): its result is a raw
                    # carrier a later parse may consume
                    return [Tok("bytes", 0, ">", 1, call.lineno,
                                pending="")]
                if toks:
                    buf_arg = call.args[0] if call.args else None
                    return self._place(toks, buf_arg, call.lineno)
        # unknown call: reads happen in its arguments (pickle.loads(...))
        out: List[Tok] = []
        for a in call.args:
            out.extend(self._reads(a))
        return out

    def _place(self, toks: List[Tok], buf_arg: Optional[ast.expr],
               line: int) -> List[Tok]:
        """Parsed tokens replace the placeholder of the carrier they
        consume (keeping wire order for offset-style decoders); parses
        of the primary buffer append at the current position."""
        if isinstance(buf_arg, ast.Name) and buf_arg.id in self.pending:
            ph = self.pending.pop(buf_arg.id)
            ph.kind = "group"
            ph.size = 0
            ph.pending = None
            ph.splice = True
            ph.sub = toks
            for t in _flatten(toks):
                t.rev = max(t.rev, ph.rev)
                t.names |= ph.names
            return []
        return toks


def _is_fetch_summary(toks: Sequence[Tok]) -> bool:
    """True when a helper's layout is nothing but raw fetches — it is a
    transport wrapper, not a parser."""
    leaves = [t for t in _flatten(toks) if t.kind != "group"]
    return bool(leaves) and all(
        t.kind == "bytes" and t.pending is not None for t in leaves
    )


def _resolve_placeholders(toks: List[Tok]) -> List[Tok]:
    """Consumed carriers (splice groups) flatten transparently;
    unconsumed fetches stay plain bytes fields."""
    out: List[Tok] = []
    for t in toks:
        if t.kind == "group":
            inner = _resolve_placeholders(t.sub)
            if t.splice:
                out.extend(inner)
                continue
            t.sub = inner
            out.append(t)
        else:
            t.pending = None
            out.append(t)
    return out


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------


def _compare_layouts(enc: List[Tok], dec: List[Tok], rev: int,
                     codec: CodecSpec, path: str,
                     out: List[Finding]) -> None:
    e = _project(enc, rev)
    d = _project(dec, rev)
    _compare_seq(e, d, rev, codec, path, out, "body")


def _compare_seq(e: List[Tok], d: List[Tok], rev: int, codec: CodecSpec,
                 path: str, out: List[Finding], where: str) -> None:
    for i in range(min(len(e), len(d))):
        te, td = e[i], d[i]
        line = td.line or te.line
        if te.kind != td.kind:
            out.append(Finding(
                "encode-decode-skew", path, line, 0,
                f"codec {codec.name} rev {rev}: {where} field #{i + 1} "
                f"encodes as {te.describe()} (line {te.line}) but "
                f"decodes as {td.describe()}",
            ))
            return
        if te.kind == "int":
            if te.size and td.size and te.size != td.size:
                out.append(Finding(
                    "encode-decode-skew", path, line, 0,
                    f"codec {codec.name} rev {rev}: {where} field "
                    f"#{i + 1} width skew: encoder {te.describe()} "
                    f"(line {te.line}) vs decoder {td.describe()}",
                ))
                return
            if te.size != 1 and td.size != 1 and te.endian != td.endian:
                out.append(Finding(
                    "encode-decode-skew", path, line, 0,
                    f"codec {codec.name} rev {rev}: {where} field "
                    f"#{i + 1} endianness skew: encoder "
                    f"{te.describe()} (line {te.line}) vs decoder "
                    f"{td.describe()}",
                ))
                return
        elif te.kind == "bytes":
            if te.size and td.size and te.size != td.size:
                out.append(Finding(
                    "encode-decode-skew", path, line, 0,
                    f"codec {codec.name} rev {rev}: {where} field "
                    f"#{i + 1} fixed-length skew: encoder "
                    f"{te.describe()} (line {te.line}) vs decoder "
                    f"{td.describe()}",
                ))
                return
        else:
            _compare_seq(te.sub, td.sub, rev, codec, path, out,
                         f"{where} group #{i + 1}")
    if len(e) != len(d):
        longer, side = (e, "encoder") if len(e) > len(d) else (d, "decoder")
        t = longer[min(len(e), len(d))]
        out.append(Finding(
            "encode-decode-skew", path, t.line, 0,
            f"codec {codec.name} rev {rev}: {side} emits "
            f"{abs(len(e) - len(d))} extra {where} field(s) starting "
            f"with {t.describe()} — the other side never "
            f"{'reads' if side == 'encoder' else 'writes'} them",
        ))


# ---------------------------------------------------------------------------
# unbounded-wire-alloc (flow-sensitive taint, intraprocedural)
# ---------------------------------------------------------------------------


class _AllocChecker:
    def __init__(self, path: str, wire: WireSpec, out: List[Finding]):
        self.path = path
        self.wire = wire
        self.out = out
        self.sinks = dict(_ALLOC_SINK_LEAVES)
        for leaf, idx in wire.sinks:
            self.sinks[leaf] = idx
        self.trusted = set(wire.trusted)

    def check_function(self, fn: ast.FunctionDef) -> None:
        self.walk(fn.body, set())

    # -- taint lattice over statement order ---------------------------------
    def walk(self, body: Sequence[ast.stmt], tainted: Set[str]) -> Set[str]:
        for stmt in body:
            tainted = self.stmt(stmt, tainted)
        return tainted

    def stmt(self, stmt: ast.stmt, tainted: Set[str]) -> Set[str]:
        if isinstance(stmt, ast.Assign):
            self.scan_sinks(stmt.value, tainted)
            new = self.taints_of(stmt.value, tainted)
            tgt = stmt.targets[0] if len(stmt.targets) == 1 else None
            names = []
            if isinstance(tgt, ast.Name):
                names = [tgt.id]
            elif isinstance(tgt, ast.Tuple):
                names = [e.id for e in tgt.elts
                         if isinstance(e, ast.Name)]
            if new is None:
                tainted = tainted - set(names)
            elif new == "wide":
                tainted = tainted | set(names)
            elif new == "fmt" and names:
                fmt_widths = self.fmt_widths(stmt.value)
                if fmt_widths is not None and len(fmt_widths) == len(names):
                    wide = {
                        n for n, w in zip(names, fmt_widths) if w >= 4
                    }
                    tainted = (tainted - set(names)) | wide
                else:
                    tainted = tainted | set(names)
            return tainted
        if isinstance(stmt, ast.AugAssign):
            self.scan_sinks(stmt.value, tainted)
            return tainted
        if isinstance(stmt, ast.Expr):
            self.scan_sinks(stmt.value, tainted)
            return tainted
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.scan_sinks(stmt.value, tainted)
            return tainted
        if isinstance(stmt, ast.If):
            self.scan_sinks(stmt.test, tainted)
            bounded = self.guard_bounds(stmt.test) & tainted
            body_taint = tainted - bounded if self.guard_is_upper(
                stmt.test
            ) else set(tainted)
            after_body = self.walk(stmt.body, set(body_taint))
            self.walk(stmt.orelse, set(tainted))
            if bounded and self.terminates(stmt.body):
                # `if x > BOUND: raise/return/break` — fallthrough is
                # the bounded path
                return tainted - bounded
            return tainted | (after_body - body_taint)
        if isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self.scan_sinks(stmt.iter, tainted)
            else:
                self.scan_sinks(stmt.test, tainted)
            t = self.walk(stmt.body, set(tainted))
            self.walk(stmt.orelse, set(tainted))
            return tainted | t
        if isinstance(stmt, ast.Try):
            t = self.walk(stmt.body, set(tainted))
            for h in stmt.handlers:
                self.walk(h.body, set(tainted))
            t = self.walk(stmt.orelse, t)
            return self.walk(stmt.finalbody, t)
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self.scan_sinks(item.context_expr, tainted)
            return self.walk(stmt.body, tainted)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return tainted
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self.scan_sinks(node, tainted)
        return tainted

    # -- sources ------------------------------------------------------------
    def taints_of(self, value: ast.expr, tainted: Set[str]
                  ) -> Optional[str]:
        """None = clean, "wide" = taint all targets, "fmt" = per-field
        by struct width."""
        if isinstance(value, ast.Call):
            leaf = _leaf(value.func)
            if leaf in self.trusted:
                return None
            if leaf == "min":
                return None
            if leaf in _WIDE_SOURCE_LEAVES:
                return "wide"
            if leaf in ("unpack", "unpack_from"):
                return "fmt"
            if leaf == "from_bytes":
                return "wide"
            if leaf is not None and leaf.startswith("decode_"):
                return "wide"
            return None
        if isinstance(value, ast.Name):
            return "wide" if value.id in tainted else None
        if isinstance(value, ast.BinOp):
            if _names_in(value) & tainted:
                return "wide"
            return None
        if isinstance(value, ast.Subscript):
            base = value.value
            if isinstance(base, ast.Call):
                return self.taints_of(base, tainted)
            return None
        if isinstance(value, ast.IfExp):
            a = self.taints_of(value.body, tainted)
            b = self.taints_of(value.orelse, tainted)
            return a or b
        return None

    def fmt_widths(self, value: ast.expr) -> Optional[List[int]]:
        call = value
        if isinstance(call, ast.Subscript):
            call = call.value  # unpack(...)[0]
        if not isinstance(call, ast.Call):
            return None
        leaf = _leaf(call.func)
        fmt: Optional[str] = None
        if leaf in ("unpack", "unpack_from") and call.args:
            fmt = _const_str(call.args[0])
        if fmt is None:
            return None
        try:
            toks = _fmt_toks(fmt, 0, 1, "<fmt>")
        except ValueError:
            return None
        widths = [t.size for t in toks if t.kind == "int"]
        if isinstance(value, ast.Subscript):
            idx = _const_int(value.slice) if isinstance(
                value.slice, ast.expr
            ) else None
            if idx is not None and 0 <= idx < len(widths):
                return None if widths[idx] < 4 else [8]
            return [8]
        return widths

    # -- guards -------------------------------------------------------------
    def guard_bounds(self, test: ast.expr) -> Set[str]:
        """Names bounded when this comparison decides a terminal body:
        any Compare mentioning the name against something else."""
        out: Set[str] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Compare):
                out |= _names_in(node)
        return out

    def guard_is_upper(self, test: ast.expr) -> bool:
        """``if x <= BOUND:`` — the body itself is the bounded branch."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            return isinstance(test.ops[0], (ast.Lt, ast.LtE))
        return False

    def terminates(self, body: Sequence[ast.stmt]) -> bool:
        return bool(body) and isinstance(
            body[-1], (ast.Raise, ast.Return, ast.Break, ast.Continue)
        )

    # -- sinks --------------------------------------------------------------
    def scan_sinks(self, node: ast.expr, tainted: Set[str]) -> None:
        if not tainted:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                leaf = _leaf(sub.func)
                if leaf == "range" and sub.args:
                    arg = sub.args[-1] if len(sub.args) <= 2 else \
                        sub.args[1]
                    self.sink_arg(arg, tainted, "range", sub.lineno)
                elif leaf in self.sinks:
                    idx = self.sinks[leaf]
                    if idx < len(sub.args):
                        self.sink_arg(sub.args[idx], tainted, leaf,
                                      sub.lineno)
            elif isinstance(sub, ast.BinOp) and isinstance(
                sub.op, ast.Mult
            ):
                for side, other in ((sub.left, sub.right),
                                    (sub.right, sub.left)):
                    if isinstance(other, (ast.Constant, ast.List)) and \
                            isinstance(
                                getattr(other, "value", other),
                                (bytes, str, list),
                            ):
                        self.sink_arg(side, tainted, "sequence-repeat",
                                      sub.lineno)

    def sink_arg(self, arg: ast.expr, tainted: Set[str], sink: str,
                 line: int) -> None:
        for node in ast.walk(arg):
            if isinstance(node, ast.Call) and _leaf(node.func) in (
                "min",
            ):
                return  # clamped at the sink
        names = _names_in(arg) & tainted
        if names:
            self.out.append(Finding(
                "unbounded-wire-alloc", self.path, line, 0,
                f"wire-decoded length {sorted(names)[0]!r} reaches "
                f"{sink} without a MAX_PAYLOAD-class dominating bound "
                f"(clamp with min() or guard-and-raise before use)",
            ))


# ---------------------------------------------------------------------------
# status-untotal
# ---------------------------------------------------------------------------


def _enum_of(leaf: str, enums: Sequence[EnumSpec]) -> Optional[EnumSpec]:
    for e in enums:
        if leaf.startswith(e.prefix):
            return e
    return None


def _dispatch_consts(test: ast.expr, enums: Sequence[EnumSpec]
                     ) -> Tuple[Optional[EnumSpec], Set[str], Optional[str]]:
    """(enum, member leaves, subject dump) for ``x == ST_*`` /
    ``x in (ST_A, ST_B)`` comparisons."""
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return None, set(), None
    op = test.ops[0]
    right = test.comparators[0]
    if isinstance(op, ast.Eq):
        leaf = _leaf(right) if isinstance(
            right, (ast.Name, ast.Attribute)
        ) else None
        if leaf is None:
            return None, set(), None
        enum = _enum_of(leaf, enums)
        if enum is None:
            return None, set(), None
        return enum, {leaf}, ast.dump(test.left)
    if isinstance(op, ast.In) and isinstance(right, (ast.Tuple, ast.Set,
                                                     ast.List)):
        leaves = set()
        enum = None
        for e in right.elts:
            leaf = _leaf(e) if isinstance(
                e, (ast.Name, ast.Attribute)
            ) else None
            if leaf is None:
                return None, set(), None
            found = _enum_of(leaf, enums)
            if found is None:
                return None, set(), None
            if enum is None:
                enum = found
            leaves.add(leaf)
        return enum, leaves, ast.dump(test.left)
    return None, set(), None


def _check_dispatches(path: str, tree: ast.Module,
                      enums: Sequence[EnumSpec],
                      out: List[Finding]) -> None:
    if not enums:
        return
    chain_members: Set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.If) or id(node) in chain_members:
            continue
        enum, covered, subject = _dispatch_consts(node.test, enums)
        if enum is None:
            continue
        arms = 1
        cur = node
        while len(cur.orelse) == 1 and isinstance(cur.orelse[0], ast.If):
            nxt = cur.orelse[0]
            e2, c2, s2 = _dispatch_consts(nxt.test, enums)
            if e2 is not enum or s2 != subject:
                break
            chain_members.add(id(nxt))
            covered |= c2
            arms += 1
            cur = nxt
        has_else = bool(cur.orelse)
        if arms < 2 or has_else:
            continue
        missing = [m for m in enum.members if m not in covered]
        if missing:
            out.append(Finding(
                "status-untotal", path, node.lineno, node.col_offset,
                f"dispatch over {enum.prefix}* covers "
                f"{len(covered)}/{len(enum.members)} members with no "
                f"fail-closed else: missing {', '.join(missing)}",
            ))


def _check_enum_drift(path: str, mod: _ModuleMap,
                      enums: Sequence[EnumSpec],
                      out: List[Finding]) -> None:
    for enum in enums:
        if not toolkit.normalize_path(path).endswith(enum.module):
            continue
        actual = {
            name for name in mod.int_consts
            if name.startswith(enum.prefix)
        }
        declared = set(enum.members)
        if actual != declared:
            extra = sorted(actual - declared)
            gone = sorted(declared - actual)
            bits = []
            if extra:
                bits.append(f"module adds {', '.join(extra)}")
            if gone:
                bits.append(f"table lists vanished {', '.join(gone)}")
            out.append(Finding(
                "status-untotal", path, 1, 0,
                f"[[enum]] {enum.prefix}* member list drifted from "
                f"{enum.module}: {'; '.join(bits)} — update "
                f"tools/wire.toml",
            ))


# ---------------------------------------------------------------------------
# frame-crc-gap
# ---------------------------------------------------------------------------


def _calls_in(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            leaf = _leaf(node.func)
            if leaf:
                out.add(leaf)
    return out


def _has_crc_compare(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _leaf(sub.func) in (
                    "crc32", "adler32"
                ):
                    return True
    return False


def _check_stores(path: str, mod: _ModuleMap,
                  stores: Sequence[StoreSpec],
                  out: List[Finding]) -> None:
    norm = toolkit.normalize_path(path)
    rows = [s for s in stores if norm.endswith(s.module)]
    if not rows:
        return
    listed: Set[str] = set()
    for s in rows:
        for role, names in (("writer", s.writers), ("reader", s.readers)):
            for qual in names:
                listed.add(qual.rsplit(".", 1)[-1])
                fn = mod.functions.get(qual) or mod.lookup(qual)
                if fn is None:
                    out.append(Finding(
                        "frame-crc-gap", path, 1, 0,
                        f"store {s.name}: declared {role} {qual!r} not "
                        f"found in {s.module} — wire.toml row is stale",
                    ))
                    continue
                calls = _calls_in(fn)
                if role == "writer":
                    if "header" in s.checks and \
                            "frame_header" not in calls:
                        out.append(Finding(
                            "frame-crc-gap", path, fn.lineno, 0,
                            f"store {s.name}: writer {qual} frames "
                            f"without the crc'd length header "
                            f"(frame_header)",
                        ))
                    if "payload" in s.checks and "crc32" not in calls:
                        out.append(Finding(
                            "frame-crc-gap", path, fn.lineno, 0,
                            f"store {s.name}: writer {qual} emits a "
                            f"frame with no payload checksum",
                        ))
                else:
                    if "header" in s.checks and \
                            "read_frame_header" not in calls:
                        out.append(Finding(
                            "frame-crc-gap", path, fn.lineno, 0,
                            f"store {s.name}: reader {qual} skips the "
                            f"header crc re-verify (read_frame_header)",
                        ))
                    if "payload" in s.checks and not _has_crc_compare(fn):
                        out.append(Finding(
                            "frame-crc-gap", path, fn.lineno, 0,
                            f"store {s.name}: reader {qual} never "
                            f"compares the payload crc32 — torn or "
                            f"rotted frames would be trusted",
                        ))
    # completeness: every frame-touching function must be in a row
    frame_leaves = {"frame_header", "read_frame_header", "crc32"}
    for qual, fn in mod.functions.items():
        leaf_name = qual.rsplit(".", 1)[-1]
        if leaf_name in ("frame_header", "read_frame_header"):
            continue  # the helpers themselves
        if leaf_name in listed:
            continue
        if _calls_in(fn) & frame_leaves:
            out.append(Finding(
                "frame-crc-gap", path, fn.lineno, 0,
                f"{qual} touches frame helpers/checksums but is not "
                f"listed in any wire.toml [[store]] row for this "
                f"module — it would escape write/read twin analysis",
            ))


# ---------------------------------------------------------------------------
# per-file + codec analysis
# ---------------------------------------------------------------------------


def _check_contracts(path: str, tree: ast.Module, wire: WireSpec,
                     out: List[Finding]) -> None:
    if not wire.contracts:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = _leaf(node.func)
        for func, kw in wire.contracts:
            if leaf != func:
                continue
            if any(k.arg == kw for k in node.keywords):
                continue
            if any(k.arg is None for k in node.keywords):
                continue  # **kwargs forwarding may carry it
            out.append(Finding(
                "encode-decode-skew", path, node.lineno,
                node.col_offset,
                f"{func}() called without {kw}= — the body layout is "
                f"keyed to the negotiated frame revision; omitting it "
                f"emits a current-rev body onto a possibly-downgraded "
                f"connection (the PR 14 desync class)",
            ))


def _check_codecs(path: str, mod: _ModuleMap,
                  maps: Dict[str, _ModuleMap], wire: WireSpec,
                  out: List[Finding]) -> None:
    norm = toolkit.normalize_path(path)
    for codec in wire.codecs:
        if not norm.endswith(codec.module):
            continue
        fields = [f for f in wire.fields if f.codec == codec.name]
        enc_fn = mod.functions.get(codec.encoder) or \
            mod.lookup(codec.encoder)
        dec_fn = mod.functions.get(codec.decoder) or \
            mod.lookup(codec.decoder)
        for role, name, fn in (("encoder", codec.encoder, enc_fn),
                               ("decoder", codec.decoder, dec_fn)):
            if fn is None:
                out.append(Finding(
                    "encode-decode-skew", path, 1, 0,
                    f"codec {codec.name}: declared {role} {name!r} not "
                    f"found in {codec.module} — a renamed function "
                    f"must not silently drop out of wire analysis",
                ))
        if enc_fn is None or dec_fn is None:
            continue
        try:
            enc_toks = _Enc(mod, maps, fields).summarize(enc_fn)
            dec_toks = _resolve_placeholders(
                _Dec(mod, maps, fields).summarize(dec_fn,
                                                  unwrap=codec.unwrap)
            )
        except ValueError as exc:
            out.append(Finding(
                "encode-decode-skew", path, 1, 0,
                f"codec {codec.name}: cannot summarize layout: {exc}",
            ))
            continue
        for rev in codec.revs:
            _compare_layouts(enc_toks, dec_toks, rev, codec, path, out)
        _check_fields(codec, fields, enc_toks, dec_toks, path, out)


def _check_fields(codec: CodecSpec, fields: Sequence[FieldSpec],
                  enc_toks: List[Tok], dec_toks: List[Tok],
                  path: str, out: List[Finding]) -> None:
    for f in fields:
        want = {f.name, f.gate}
        for side, toks in (("encoder", enc_toks), ("decoder", dec_toks)):
            hits = [t for t in _flatten(toks)
                    if t.kind != "group" and (t.names & want)]
            if not hits:
                out.append(Finding(
                    "rev-gate-drift", path, 1, 0,
                    f"codec {codec.name}: declared rev-{f.rev} field "
                    f"{f.name!r} has no {side} token referencing it — "
                    f"the wire.toml revision table drifted from the "
                    f"code",
                ))
                continue
            for t in hits:
                if t.rev != f.rev:
                    out.append(Finding(
                        "rev-gate-drift", path, t.line, 0,
                        f"codec {codec.name}: field {f.name!r} is "
                        f"introduced at rev {f.rev} but the {side} "
                        f"{'writes' if side == 'encoder' else 'reads'} "
                        f"it on a path reachable at rev {t.rev} — an "
                        f"old peer would mis-frame the body",
                    ))
                    break


class _FileAnalyzer:
    def __init__(self, path: str, tree: ast.Module,
                 maps: Dict[str, _ModuleMap], wire: WireSpec,
                 active: Set[str]):
        self.path = path
        self.tree = tree
        self.maps = maps
        self.wire = wire
        self.active = active
        self.mod = maps[path]

    def run(self) -> List[Finding]:
        out: List[Finding] = []
        if "encode-decode-skew" in self.active or \
                "rev-gate-drift" in self.active:
            codec_out: List[Finding] = []
            _check_codecs(self.path, self.mod, self.maps, self.wire,
                          codec_out)
            out.extend(
                f for f in codec_out if f.rule in self.active
            )
        if "encode-decode-skew" in self.active:
            _check_contracts(self.path, self.tree, self.wire, out)
        if "unbounded-wire-alloc" in self.active:
            checker = _AllocChecker(self.path, self.wire, out)
            for node in ast.walk(self.tree):
                if isinstance(node, ast.FunctionDef):
                    checker.check_function(node)
        if "status-untotal" in self.active:
            _check_dispatches(self.path, self.tree, self.wire.enums, out)
            _check_enum_drift(self.path, self.mod, self.wire.enums, out)
        if "frame-crc-gap" in self.active:
            _check_stores(self.path, self.mod, self.wire.stores, out)
        return out


# ---------------------------------------------------------------------------
# drivers (the toolkit analyzer contract)
# ---------------------------------------------------------------------------


def analyze_sources(
    sources: Dict[str, str],
    rule_ids: Optional[Iterable[str]] = None,
    wire: Optional[WireSpec] = None,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Analyze {path: source}.  ``wire`` defaults to the packaged
    ``tools/wire.toml`` (loud ValueError when missing/malformed)."""
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    for rid in active:
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
    if wire is None:
        wire = load_default_wire()

    maps: Dict[str, _ModuleMap] = {}
    trees: Dict[str, ast.Module] = {}
    findings: List[Finding] = []
    for path, source in sorted(sources.items()):
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(
                Finding(
                    "syntax-error", path, exc.lineno or 1,
                    exc.offset or 0, f"cannot parse: {exc.msg}",
                )
            )
            continue
        trees[path] = tree
        maps[path] = _ModuleMap(path, tree)

    n_suppressed = 0
    for path, tree in sorted(trees.items()):
        raw = _FileAnalyzer(path, tree, maps, wire, active).run()
        supp = toolkit.suppressed_rules(sources[path], "fabwire")
        kept, suppressed = toolkit.apply_suppressions(raw, supp)
        findings.extend(kept)
        n_suppressed += len(suppressed)
        if collect_suppressed is not None:
            collect_suppressed.extend(suppressed)
    findings.sort(key=Finding.key)
    stats = {"files": len(sources), "suppressed": n_suppressed}
    return findings, stats


def analyze_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
    wire: Optional[WireSpec] = None,
) -> Tuple[List[Finding], int]:
    """Single-blob convenience (fixtures/tests)."""
    findings, stats = analyze_sources({path: source}, rule_ids, wire)
    return findings, stats["suppressed"]


def analyze_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    wire: Optional[WireSpec] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths, excludes)
    sources, io_findings = toolkit.read_sources(files)
    findings, stats = analyze_sources(sources, rule_ids, wire)
    findings.extend(io_findings)
    findings.sort(key=Finding.key)
    stats["files"] = len(files)
    return findings, stats


def live_suppression_keys(
    sources: Dict[str, str], rules: Set[str]
) -> Set[Tuple[str, int, str]]:
    """The toolkit analyzer-registry staleness protocol (consumed by
    fabreg's suppression-stale): (normalized path, line, rule) for
    every fabwire suppression that still absorbs a finding."""
    needed = set(RULES) if "all" in rules else (rules & set(RULES))
    if not needed:
        return set()
    suppressed: List[Finding] = []
    analyze_sources(sources, needed, collect_suppressed=suppressed)
    return {
        (toolkit.normalize_path(f.path), f.line, f.rule)
        for f in suppressed
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fabwire",
        "wire-format conformance analyzer for fabric-tpu "
        "(dependency-free; never imports the analyzed code)",
    )
    parser.add_argument(
        "--wire",
        metavar="FILE",
        help="wire table (default: tools/wire.toml next to this module)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=21)
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fabwire", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fabwire")
    if rc:
        return rc

    wire: Optional[WireSpec] = None
    try:
        if args.wire is not None:
            wire = parse_wire(
                Path(args.wire).read_text(encoding="utf-8"), args.wire
            )
        else:
            wire = load_default_wire()
    except (OSError, ValueError) as exc:
        print(f"fabwire: error: wire table: {exc}", file=sys.stderr)
        return 2

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = analyze_paths(args.paths, rule_ids, excludes, wire)

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fabwire: {len(findings)} finding(s) in {stats['files']} "
            f"file(s) ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
