"""fabtrace — device-plane trace-discipline analyzer for fabric-tpu.

The serve registry enforces "steady state is provably compile-free" at
RUNTIME (``program_for`` on an unwarmed bucket raises).  fabtrace is the
static twin of that bucket discipline: an abstract interpreter over the
device tier and its hot-path callers that tracks two facts per value —
*shape provenance* (drawn from the bucket ladder / module constants vs.
data-dependent) and *residency* (host vs. device vs. tracer) — and pins
the JAX-plane invariants none of the six sibling analyzers see: a jit
call site going shape-polymorphic, a hidden host sync landing inside a
pipeline stage, per-lane host<->device conversions inside loops (the
columnar-ingest worklist), and traced values escaping the trace.

Like fabwire/fablife, the repo-specific knowledge lives in a declarative
table, ``tools/hotpath.toml``, not in the analyzer: which functions are
pipeline stages (and which of them are legal sync boundaries), which
modules form the device tier, which call leaves are host<->device
conversions, which functions project onto the bucket ladder, which
module constants are static shape sources, and which helpers shape their
output from a size argument.  Extending the pipeline extends the table —
the analyzer does not change.

Rules
-----
recompile-hazard    a jit/pjit call site fed an argument whose shape is
                    provably data-dependent (built from ``len()`` /
                    ``.shape`` sizes that never pass through a declared
                    bucket-ladder projection).  Steady state must be
                    statically compile-free: every device-bound shape
                    comes from the bucket ladder or a module constant.
static-arg-churn    a ``static_argnums``/``static_argnames`` parameter
                    of a jitted callable fed a per-call-varying value at
                    a call site — every distinct value is a separate
                    compile-cache entry (compile-cache explosion).
host-sync-hot-path  ``.item()``, ``float()``/``int()``/``bool()`` on a
                    device value, ``np.asarray(device_val)``,
                    ``device_get`` or ``.block_until_ready()`` inside a
                    function hotpath.toml declares a pipeline stage.
                    Syncs are legal only at declared stage boundaries
                    (``boundary = true`` rows).
transfer-in-loop    a declared host<->device conversion leaf (or a local
                    helper that performs one) called inside a per-lane /
                    per-tx loop body in a declared device-tier module.
                    Every finding is one row of the vectorized-ingest
                    refactor worklist (ROADMAP open item #1).
tracer-leak         a value derived from a traced function's inputs
                    stored into instance state, a global, or an
                    enclosing-scope container — the tracer outlives the
                    traced call and poisons later traces.
jit-impure          impure host calls (time.*, random.*, np.random.*,
                    os.environ/os.getenv, print, np.asarray/np.array,
                    ``.block_until_ready()``) or reads of mutated module
                    state inside a traced body: they run once at trace
                    time, bake one value into the compiled program, or
                    force a host sync.  Promoted from fablint's name
                    heuristic (PR 18), behavior-pinned.

Abstract domains
----------------
Shape provenance is a three-point lattice per size expression: STATIC
(int literals, declared ladder constants, module int constants, and any
value returned by a declared ``[[bucket]]`` projection — ``_bucket``,
``_next_pow2``, ``bucket_for`` — regardless of its argument), DATA
(``len()``, ``.shape[...]``, ``sum()`` and arithmetic over them), and
UNKNOWN (parameters, opaque calls).  Arrays carry the provenance of the
size argument that built them (``np.zeros((20, n))`` is DATA-shaped when
``n`` is; a declared ``[[shaper]]`` helper is classified by its declared
size argument).  Only provably-DATA shapes fire — UNKNOWN stays silent,
so the rule reports certain hazards, not every unproven site.

Residency is host / device / unknown: jit-callable results, ``jnp.*``
calls and ``device_put`` produce device values; ``np.*`` constructors
and ``device_get`` produce host values.  "Tracer" residency is implied
by position: any value inside a traced body is a tracer, which is what
the tracer-leak and jit-impure rules key on.

Never imports the analyzed code (pure ``ast`` on the toolkit chassis) —
runs identically with or without jax/numpy/cryptography installed.

Suppression
-----------
Per line, toolkit grammar: ``# fabtrace: disable=rule-id  # <reason>``.
The reason must name the bound that makes the site safe (one-time
per-kernel shipping, chunk-granular drain, trace-time constant bounded
by the tower size, ...) — reviewed via the NOTES_BUILD triage ledger,
judged stale by fabreg through the toolkit registry protocol.

Usage
-----
    python -m fabric_tpu.tools.fabtrace [--json] [--list-rules]
        [--rules a,b] [--hotpath FILE] PATH...

Exit status: 0 = clean, 1 = findings, 2 = usage/IO/hotpath-table error
(a half-read stage table checking nothing would be silent drift — parse
errors are loud by design).
"""

from __future__ import annotations

import ast
import json
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    Finding,
    iter_py_files,
)

__version__ = "1.0"

RULES: Dict[str, str] = {
    "recompile-hazard": (
        "a jit/pjit call site fed an argument whose shape is provably "
        "data-dependent (len()/.shape sizes that never pass a declared "
        "bucket-ladder projection) — steady state must be statically "
        "compile-free"
    ),
    "static-arg-churn": (
        "a static_argnums/static_argnames parameter of a jitted "
        "callable fed a per-call-varying value: every distinct value is "
        "a separate compile-cache entry"
    ),
    "host-sync-hot-path": (
        ".item(), float()/int()/bool() on a device value, "
        "np.asarray(device_val), device_get or block_until_ready inside "
        "a declared pipeline stage (tools/hotpath.toml; syncs are legal "
        "only at boundary = true stages)"
    ),
    "transfer-in-loop": (
        "a declared host<->device conversion called inside a per-lane/"
        "per-tx loop body in a device-tier module — one row of the "
        "vectorized-ingest refactor worklist"
    ),
    "tracer-leak": (
        "a value derived from a traced function's inputs stored into "
        "instance state, a global, or an enclosing-scope container "
        "that outlives the traced call"
    ),
    "jit-impure": (
        "impure/host call (time.*, random.*, np.random.*, os.environ/"
        "os.getenv, print, np.asarray/np.array, .block_until_ready()) "
        "or a read of mutated module state inside a traced body"
    ),
}

#: device-plane discipline is runtime-package business; tests craft
#: shape-polymorphic and syncing fixtures all day (that is their job)
PKG_SCOPE = ("*fabric_tpu/*",)

#: shape-provenance lattice points
_STATIC, _DATA, _UNKNOWN = "static", "data", "unknown"
#: residency lattice points
_HOST, _DEVICE, _RES_UNKNOWN = "host", "device", "unknown"

_NP_ROOTS = {"np", "numpy"}
_DEV_ROOTS = {"jnp", "jax"}
#: array constructors whose first argument IS the output shape
_SHAPE_CTORS = {"zeros", "ones", "full", "empty"}
#: container-mutator method leaves (tracer-leak escape sinks and the
#: module-mutable-state detector)
_MUTATORS = {"append", "extend", "add", "update", "insert", "setdefault"}

#: jit-impure call sets (fablint parity, PR 18 migration) + the os/env
#: reads the dataflow promotion adds
_IMPURE_ROOTS = {"time", "random"}
_IMPURE_DOTTED = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "np.random", "numpy.random",
    "os.getenv", "os.urandom", "os.putenv",
}
_IMPURE_ENV = {"os.environ", "environ"}


# ---------------------------------------------------------------------------
# hotpath.toml
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class StageSpec:
    module: str
    function: str
    boundary: bool = False


@dataclass(frozen=True)
class HotpathSpec:
    stages: Tuple[StageSpec, ...] = ()
    devices: Tuple[str, ...] = ()
    transfers: Tuple[str, ...] = ()
    buckets: Tuple[str, ...] = ()
    ladders: Tuple[str, ...] = ()
    shapers: Tuple[Tuple[str, int], ...] = ()


def default_hotpath_file() -> Path:
    return Path(__file__).resolve().parent / "hotpath.toml"


def _parse_value(raw: str, where: str):
    raw = raw.strip()
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw.lstrip("-").isdigit():
        return int(raw)
    raise ValueError(
        f"{where}: expected \"string\", integer or true/false"
    )


_SECTIONS = ("stage", "device", "transfer", "bucket", "ladder", "shaper")

#: per-section (required keys, optional keys with defaults)
_SECTION_KEYS: Dict[str, Tuple[Tuple[str, ...], Dict[str, object]]] = {
    "stage": (("module", "function"), {"boundary": False}),
    "device": (("module",), {}),
    "transfer": (("call",), {}),
    "bucket": (("function",), {}),
    "ladder": (("name",), {}),
    "shaper": (("function", "arg"), {}),
}


def parse_hotpath(text: str, path: str = "<hotpath>") -> HotpathSpec:
    """Parse the tiny TOML subset shared with wire.toml/pairs.toml/
    layers.toml.  LOUD on any malformed line, unknown section, unknown
    key or missing key: a half-read stage table silently checking
    nothing would be config drift."""
    entries: List[Tuple[str, Dict[str, object], int]] = []
    current: Optional[Dict[str, object]] = None
    for n, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            section = line[2:-2].strip()
            if section not in _SECTIONS:
                raise ValueError(f"{path}:{n}: unknown section {line!r}")
            current = {}
            entries.append((section, current, n))
            continue
        if line.startswith("["):
            raise ValueError(f"{path}:{n}: unknown section {line!r}")
        if "=" not in line:
            raise ValueError(f"{path}:{n}: expected 'key = value'")
        if current is None:
            raise ValueError(f"{path}:{n}: key outside a [[section]] entry")
        key, _, value = line.partition("=")
        if "#" in value and not value.strip().startswith('"'):
            value = value.split("#", 1)[0]
        current[key.strip()] = _parse_value(
            value, f"{path}:{n}: {key.strip()}"
        )

    stages: List[StageSpec] = []
    devices: List[str] = []
    transfers: List[str] = []
    buckets: List[str] = []
    ladders: List[str] = []
    shapers: List[Tuple[str, int]] = []
    for section, entry, n in entries:
        where = f"{path}:{n}: [[{section}]]"
        required, optional = _SECTION_KEYS[section]
        for key in required:
            if key not in entry:
                raise ValueError(f"{where}: missing required key {key!r}")
        for key in entry:
            if key not in required and key not in optional:
                raise ValueError(f"{where}: unknown key {key!r}")
        for key, val in entry.items():
            want = bool if key == "boundary" else (
                int if key == "arg" else str
            )
            if not isinstance(val, want):
                raise ValueError(
                    f"{where}: {key} must be a {want.__name__}"
                )
        if section in ("stage", "device"):
            mod = entry["module"]
            if not str(mod).endswith(".py"):
                raise ValueError(
                    f"{where}: module must be a .py path, got {mod!r}"
                )
        if section == "stage":
            if not entry["function"]:
                raise ValueError(f"{where}: function must be non-empty")
            stages.append(
                StageSpec(
                    str(entry["module"]), str(entry["function"]),
                    bool(entry.get("boundary", False)),
                )
            )
        elif section == "device":
            devices.append(str(entry["module"]))
        elif section == "transfer":
            if not entry["call"]:
                raise ValueError(f"{where}: call must be non-empty")
            transfers.append(str(entry["call"]))
        elif section == "bucket":
            if not entry["function"]:
                raise ValueError(f"{where}: function must be non-empty")
            buckets.append(str(entry["function"]))
        elif section == "ladder":
            if not entry["name"]:
                raise ValueError(f"{where}: name must be non-empty")
            ladders.append(str(entry["name"]))
        elif section == "shaper":
            if not entry["function"]:
                raise ValueError(f"{where}: function must be non-empty")
            if int(entry["arg"]) < 0:
                raise ValueError(f"{where}: arg must be >= 0")
            shapers.append((str(entry["function"]), int(entry["arg"])))
    return HotpathSpec(
        stages=tuple(stages),
        devices=tuple(devices),
        transfers=tuple(transfers),
        buckets=tuple(buckets),
        ladders=tuple(ladders),
        shapers=tuple(shapers),
    )


def load_default_hotpath() -> HotpathSpec:
    path = default_hotpath_file()
    return parse_hotpath(path.read_text(encoding="utf-8"), str(path))


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------


def _leaf(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root(node: ast.expr) -> Optional[str]:
    dn = _dotted(node)
    return dn.split(".", 1)[0] if dn else None


def _is_jit_expr(node: ast.AST) -> bool:
    """True for jax.jit / jit / pjit / partial(jax.jit, ...) shapes."""
    dn = _dotted(node)
    if dn in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return True
    if isinstance(node, ast.Call):
        fn = _dotted(node.func)
        if fn in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    return False


def _transfer_match(func: ast.expr, transfers: Sequence[str]) -> Optional[str]:
    """The declared conversion a call matches: dotted rows need the
    dotted suffix, bare rows match the call leaf."""
    dn = _dotted(func)
    leaf = _leaf(func)
    for declared in transfers:
        if "." in declared:
            if dn == declared or (dn and dn.endswith("." + declared)):
                return declared
        elif leaf == declared:
            return declared
    return None


def _const_strs(node: ast.expr) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
        return out
    return []


def _const_ints(node: ast.expr) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return out
    return []


def _local_stores(fn: ast.AST) -> Set[str]:
    """Every name the function binds locally (params included)."""
    out: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            out.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            out.add(node.id)
    return out


# ---------------------------------------------------------------------------
# per-module index: functions, constants, jit callables, traced bodies
# ---------------------------------------------------------------------------


@dataclass
class _JitInfo:
    """One jitted callable: its callable leaf name, the traced body when
    resolvable in-module, and the declared static arguments."""

    name: str
    fn: Optional[ast.FunctionDef] = None
    static_names: Tuple[str, ...] = ()
    static_nums: Tuple[int, ...] = ()
    params: Tuple[str, ...] = ()


def _jit_statics(
    keywords: Sequence[ast.keyword],
) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    names: Tuple[str, ...] = ()
    nums: Tuple[int, ...] = ()
    for kw in keywords:
        if kw.arg == "static_argnames":
            names = tuple(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            nums = tuple(_const_ints(kw.value))
    return names, nums


def _decorator_statics(
    dec: ast.expr,
) -> Optional[Tuple[Tuple[str, ...], Tuple[int, ...]]]:
    """(static_argnames, static_argnums) when the decorator is a jit
    shape, else None."""
    if not _is_jit_expr(dec):
        return None
    if isinstance(dec, ast.Call):
        return _jit_statics(dec.keywords)
    return (), ()


def _fn_params(fn: ast.FunctionDef) -> Tuple[str, ...]:
    args = fn.args
    return tuple(
        a.arg for a in list(args.posonlyargs) + list(args.args)
    )


class _ModIndex:
    """Import-free per-file symbol map: functions (plain and
    Class.method), module int constants, jit callables + traced bodies,
    jit factories, and module-level mutable state."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.int_consts: Dict[str, int] = {}
        self.jit_callables: Dict[str, _JitInfo] = {}
        self.traced: List[ast.FunctionDef] = []
        self.mutable_globals: Set[str] = set()
        self._collect_functions()
        self._collect_consts_and_mutables()
        self._collect_jit()

    def _collect_functions(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.FunctionDef):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        self.functions[f"{node.name}.{sub.name}"] = sub
        # nested defs (closure kernels: pairing's run, registry's
        # traced) resolve by bare name only when unambiguous
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name not in self.functions \
                    and not any(
                        q.rsplit(".", 1)[-1] == node.name
                        for q in self.functions
                    ):
                self.functions[node.name] = node

    def lookup(self, name: str) -> Optional[ast.FunctionDef]:
        if name in self.functions:
            return self.functions[name]
        hits = [
            fn for qual, fn in self.functions.items()
            if qual.rsplit(".", 1)[-1] == name
        ]
        if len(hits) == 1:
            return hits[0]
        return None

    def _collect_consts_and_mutables(self) -> None:
        candidates: Set[str] = set()
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                v = node.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                        and not isinstance(v.value, bool):
                    self.int_consts[name] = v.value
                elif isinstance(v, (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp,
                                    ast.SetComp)):
                    candidates.add(name)
                elif isinstance(v, ast.Call) and _leaf(v.func) in (
                    "list", "dict", "set", "defaultdict", "deque",
                ):
                    candidates.add(name)
        if not candidates:
            return
        mutated: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in (_MUTATORS | {"pop", "clear"}) \
                    and isinstance(node.func.value, ast.Name):
                mutated.add(node.func.value.id)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name):
                        mutated.add(t.value.id)
        self.mutable_globals = candidates & mutated

    def _collect_jit(self) -> None:
        traced_names: Set[str] = set()
        factories: Set[str] = set()
        for qual, fn in self.functions.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None \
                        and isinstance(node.value, ast.Call) \
                        and _is_jit_expr(node.value.func):
                    factories.add(qual.rsplit(".", 1)[-1])
        # decorated traced functions
        for fn in self.functions.values():
            for dec in fn.decorator_list:
                statics = _decorator_statics(dec)
                if statics is None:
                    continue
                names, nums = statics
                self.traced.append(fn)
                self.jit_callables[fn.name] = _JitInfo(
                    fn.name, fn, names, nums, _fn_params(fn)
                )
                break
        # jit-wrap call sites: fn_jit = jax.jit(fn, ...) and the names
        # they trace
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _is_jit_expr(node.func):
                if node.args and isinstance(node.args[0], ast.Name):
                    traced_names.add(node.args[0].id)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            leaf = None
            if isinstance(target, ast.Name):
                leaf = target.id
            elif isinstance(target, ast.Attribute):
                leaf = target.attr
            if leaf is None:
                continue
            v = node.value
            if isinstance(v, ast.Call) and _is_jit_expr(v.func) \
                    and not _is_jit_expr(v):
                # X = partial(jax.jit, ...) binds the transform, not a
                # callable over arrays — only direct jax.jit(...) counts
                pass
            if isinstance(v, ast.Call) and _dotted(v.func) in (
                "jax.jit", "jit", "pjit", "jax.pjit",
            ):
                names, nums = _jit_statics(v.keywords)
                traced_fn = None
                params: Tuple[str, ...] = ()
                if v.args and isinstance(v.args[0], ast.Name):
                    traced_fn = self.lookup(v.args[0].id)
                    if traced_fn is not None:
                        params = _fn_params(traced_fn)
                self.jit_callables.setdefault(
                    leaf, _JitInfo(leaf, traced_fn, names, nums, params)
                )
            elif isinstance(v, ast.Call) and _leaf(v.func) in factories:
                self.jit_callables.setdefault(leaf, _JitInfo(leaf))
        # functions traced via jax.jit(name) without a decorator
        for fn in self.functions.values():
            if fn.name in traced_names and fn not in self.traced:
                self.traced.append(fn)


# ---------------------------------------------------------------------------
# shape-provenance / residency engine
# ---------------------------------------------------------------------------


def _combine(*tags: str) -> str:
    if any(t == _DATA for t in tags):
        return _DATA
    if tags and all(t == _STATIC for t in tags):
        return _STATIC
    return _UNKNOWN


class _FnScan:
    """One function's forward pass: builds the size/array environment in
    statement order and checks jit call sites (recompile-hazard +
    static-arg-churn) and, for declared stage functions, host syncs.
    Nested function bodies are separate scopes (and, for stages,
    separate execution times — a closure dispatched now but drained at
    the boundary must not be charged to this stage)."""

    def __init__(
        self,
        path: str,
        spec: HotpathSpec,
        mod: _ModIndex,
        jit_table: Dict[str, _JitInfo],
        active: Set[str],
        out: List[Finding],
        sync_stage: Optional[str] = None,
    ):
        self.path = path
        self.spec = spec
        self.mod = mod
        self.jit_table = jit_table
        self.active = active
        self.out = out
        self.sync_stage = sync_stage
        self.sizes: Dict[str, str] = {}
        self.arrays: Dict[str, Tuple[str, str]] = {}
        self.shapers = dict(spec.shapers)
        self.ladders = set(spec.ladders)
        self.buckets = set(spec.buckets)

    # -- sizes -------------------------------------------------------------
    def size_tag(self, node: ast.expr) -> str:
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool) or isinstance(node.value, int):
                return _STATIC
            return _UNKNOWN
        if isinstance(node, ast.Name):
            if node.id in self.sizes:
                return self.sizes[node.id]
            if node.id in self.ladders or node.id in self.mod.int_consts:
                return _STATIC
            return _UNKNOWN
        if isinstance(node, ast.Attribute):
            if node.attr in self.ladders:
                return _STATIC
            return _UNKNOWN
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Attribute) and base.attr == "shape":
                return _DATA
            if isinstance(base, ast.Name) and base.id in self.ladders:
                return _STATIC
            if isinstance(base, ast.Attribute) and base.attr in self.ladders:
                return _STATIC
            return _UNKNOWN
        if isinstance(node, ast.Call):
            leaf = _leaf(node.func)
            if leaf in self.buckets:
                return _STATIC
            if leaf in ("len", "sum"):
                return _DATA
            if leaf in ("min", "max") and node.args:
                return _combine(*(self.size_tag(a) for a in node.args))
            return _UNKNOWN
        if isinstance(node, ast.BinOp):
            return _combine(self.size_tag(node.left),
                            self.size_tag(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.size_tag(node.operand)
        if isinstance(node, ast.IfExp):
            return _combine(self.size_tag(node.body),
                            self.size_tag(node.orelse))
        return _UNKNOWN

    def _shape_arg_tag(self, node: ast.expr) -> str:
        if isinstance(node, (ast.Tuple, ast.List)):
            if not node.elts:
                return _UNKNOWN
            return _combine(*(self.size_tag(e) for e in node.elts))
        return self.size_tag(node)

    # -- arrays ------------------------------------------------------------
    def array_info(self, node: ast.expr) -> Tuple[str, str]:
        if isinstance(node, ast.Name):
            return self.arrays.get(node.id, (_UNKNOWN, _RES_UNKNOWN))
        if isinstance(node, ast.Starred):
            return self.array_info(node.value)
        if isinstance(node, ast.Subscript):
            _shape, res = self.array_info(node.value)
            return (_UNKNOWN, res)
        if isinstance(node, ast.BinOp):
            return self.array_info(node.left)
        if isinstance(node, ast.Call):
            return self._call_info(node)
        return (_UNKNOWN, _RES_UNKNOWN)

    def _call_info(self, call: ast.Call) -> Tuple[str, str]:
        func = call.func
        leaf = _leaf(func)
        root = _root(func)
        res = _RES_UNKNOWN
        if root in _NP_ROOTS:
            res = _HOST
        elif root in _DEV_ROOTS:
            res = _DEVICE
        if leaf in _SHAPE_CTORS and call.args:
            return (self._shape_arg_tag(call.args[0]), res)
        if leaf == "arange" and call.args:
            return (self.size_tag(call.args[0]), res)
        if leaf in ("asarray", "array") and call.args:
            inner_shape, inner_res = self.array_info(call.args[0])
            return (inner_shape, res if res != _RES_UNKNOWN else inner_res)
        if leaf == "device_put" and call.args:
            return (self.array_info(call.args[0])[0], _DEVICE)
        if leaf == "device_get" and call.args:
            return (self.array_info(call.args[0])[0], _HOST)
        if leaf in self.shapers:
            idx = self.shapers[leaf]
            if idx < len(call.args):
                return (self.size_tag(call.args[idx]), res)
            return (_UNKNOWN, res)
        if leaf in self.jit_table:
            return (_UNKNOWN, _DEVICE)
        if leaf == "reshape" and isinstance(func, ast.Attribute):
            base = self.array_info(func.value)
            shape = _combine(
                *(self.size_tag(a) for a in call.args)
            ) if call.args else _UNKNOWN
            return (shape, base[1] if res == _RES_UNKNOWN else res)
        if leaf in ("astype", "copy", "ravel", "flatten") \
                and isinstance(func, ast.Attribute):
            return self.array_info(func.value)
        if res != _RES_UNKNOWN:
            # any other np.*/jnp.* call: shape unknown, residency by root
            return (_UNKNOWN, res)
        return (_UNKNOWN, _RES_UNKNOWN)

    # -- statement walk ----------------------------------------------------
    def run(self, fn: ast.AST) -> None:
        self._stmts(fn.body)

    def _stmts(self, body: Sequence[ast.stmt]) -> None:
        for st in body:
            self._stmt(st)

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            self._expr(st.value)
            self._bind(st.targets, st.value)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(st.value)
                self._bind([st.target], st.value)
        elif isinstance(st, ast.AugAssign):
            self._expr(st.value)
            if isinstance(st.target, ast.Name):
                old = self.sizes.get(st.target.id, _UNKNOWN)
                self.sizes[st.target.id] = _combine(
                    old, self.size_tag(st.value)
                )
        elif isinstance(st, (ast.Return, ast.Expr)):
            if st.value is not None:
                self._expr(st.value)
        elif isinstance(st, ast.Assert):
            self._expr(st.test)
        elif isinstance(st, ast.Raise):
            if st.exc is not None:
                self._expr(st.exc)
        elif isinstance(st, ast.If):
            self._expr(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter)
            self._clear_target(st.target)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.While):
            self._expr(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._clear_target(item.optional_vars)
            self._stmts(st.body)
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)

    def _clear_target(self, target: ast.expr) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                self.sizes.pop(node.id, None)
                self.arrays.pop(node.id, None)

    def _bind(self, targets: Sequence[ast.expr], value: ast.expr) -> None:
        if len(targets) != 1 or not isinstance(targets[0], ast.Name):
            for t in targets:
                self._clear_target(t)
            return
        name = targets[0].id
        self.sizes[name] = self.size_tag(value)
        self.arrays[name] = self.array_info(value)

    # -- call-site checks --------------------------------------------------
    def _expr(self, node: ast.expr) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub)

    def _check_call(self, call: ast.Call) -> None:
        # the sync pass re-walks declared stage functions the general
        # pass already scanned — jit-site checks run only in the
        # general pass or every stage hazard would be reported twice
        if self.sync_stage is not None:
            self._check_sync(call)
            return
        leaf = _leaf(call.func)
        info = self.jit_table.get(leaf) if leaf else None
        if info is not None:
            self._check_jit_site(call, info)

    def _check_jit_site(self, call: ast.Call, info: _JitInfo) -> None:
        static_positions: Set[int] = set(info.static_nums)
        for nm in info.static_names:
            if nm in info.params:
                static_positions.add(info.params.index(nm))
        if "recompile-hazard" in self.active:
            for i, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred):
                    shape, _res = self.array_info(arg.value)
                elif i in static_positions:
                    continue
                else:
                    shape, _res = self.array_info(arg)
                if shape == _DATA:
                    self.out.append(
                        Finding(
                            "recompile-hazard", self.path,
                            call.lineno, call.col_offset,
                            f"argument {i} of jitted callable "
                            f"{info.name!r} has a data-dependent shape "
                            f"(never passed through the bucket ladder): "
                            f"every distinct batch size is a fresh XLA "
                            f"compile",
                        )
                    )
            for kw in call.keywords:
                if kw.arg is None or kw.arg in info.static_names:
                    continue
                shape, _res = self.array_info(kw.value)
                if shape == _DATA:
                    self.out.append(
                        Finding(
                            "recompile-hazard", self.path,
                            call.lineno, call.col_offset,
                            f"argument {kw.arg!r} of jitted callable "
                            f"{info.name!r} has a data-dependent shape "
                            f"(never passed through the bucket ladder): "
                            f"every distinct batch size is a fresh XLA "
                            f"compile",
                        )
                    )
        if "static-arg-churn" in self.active:
            churned: List[str] = []
            for i in static_positions:
                if i < len(call.args) and not isinstance(
                    call.args[i], ast.Starred
                ) and self.size_tag(call.args[i]) == _DATA:
                    churned.append(
                        info.params[i] if i < len(info.params) else str(i)
                    )
            for kw in call.keywords:
                if kw.arg in info.static_names \
                        and self.size_tag(kw.value) == _DATA:
                    churned.append(kw.arg)
            for nm in churned:
                self.out.append(
                    Finding(
                        "static-arg-churn", self.path,
                        call.lineno, call.col_offset,
                        f"static argument {nm!r} of jitted callable "
                        f"{info.name!r} is fed a per-call-varying value: "
                        f"every distinct value is a separate "
                        f"compile-cache entry",
                    )
                )

    def _check_sync(self, call: ast.Call) -> None:
        func = call.func
        leaf = _leaf(func)
        dn = _dotted(func)
        bad: Optional[str] = None
        if leaf == "block_until_ready":
            bad = ".block_until_ready()"
        elif leaf == "item" and not call.args \
                and isinstance(func, ast.Attribute) \
                and self.array_info(func.value)[1] == _DEVICE:
            bad = ".item()"
        elif dn in ("float", "int", "bool") and len(call.args) == 1 \
                and self.array_info(call.args[0])[1] == _DEVICE:
            bad = f"{dn}()"
        elif dn in ("np.asarray", "numpy.asarray", "np.array",
                    "numpy.array") and call.args \
                and self.array_info(call.args[0])[1] == _DEVICE:
            bad = dn
        elif leaf == "device_get" and call.args:
            bad = "device_get"
        if bad is not None:
            self.out.append(
                Finding(
                    "host-sync-hot-path", self.path,
                    call.lineno, call.col_offset,
                    f"{bad} inside pipeline stage {self.sync_stage!r}: "
                    f"host syncs are legal only at declared stage "
                    f"boundaries (tools/hotpath.toml boundary = true)",
                )
            )


# ---------------------------------------------------------------------------
# transfer-in-loop
# ---------------------------------------------------------------------------


def _loop_calls(loop: ast.AST) -> List[ast.Call]:
    """Call nodes that execute per iteration.  A For's iter and a
    comprehension's FIRST iterable are evaluated once and excluded;
    nested function defs run at another time and are excluded (they are
    scanned as functions of their own)."""
    once: Set[int] = set()
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        for sub in ast.walk(loop.iter):
            once.add(id(sub))
    elif isinstance(loop, (ast.ListComp, ast.SetComp, ast.DictComp,
                           ast.GeneratorExp)):
        # the FIRST generator's iterable is evaluated once, eagerly;
        # later generators and all ifs run per iteration
        if loop.generators:
            for sub in ast.walk(loop.generators[0].iter):
                once.add(id(sub))
    out: List[ast.Call] = []
    skip: Set[int] = set()
    for sub in ast.walk(loop):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and sub is not loop:
            for inner in ast.walk(sub):
                skip.add(id(inner))
            skip.discard(id(sub))
    for sub in ast.walk(loop):
        if id(sub) in skip or id(sub) in once or sub is loop:
            continue
        if isinstance(sub, ast.Call):
            out.append(sub)
    return out


def _iter_loops(fn: ast.AST):
    """Loop nodes of one function, excluding nested function scopes."""
    skip: Set[int] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and sub is not fn:
            for inner in ast.walk(sub):
                skip.add(id(inner))
            skip.discard(id(sub))
    for sub in ast.walk(fn):
        if id(sub) in skip:
            continue
        if isinstance(sub, (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
                            ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            yield sub


def _check_transfers(
    path: str,
    mod: _ModIndex,
    spec: HotpathSpec,
    out: List[Finding],
) -> None:
    # local helpers that perform a conversion directly (one level of
    # interprocedural reach: a loop over self._key_limbs(key) is a
    # per-lane conversion even though int_to_limbs is one call away)
    bearing: Dict[str, str] = {}
    for qual, fn in mod.functions.items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                declared = _transfer_match(node.func, spec.transfers)
                if declared is not None:
                    bearing[qual.rsplit(".", 1)[-1]] = declared
                    break
    seen: Set[int] = set()
    scanned_fns = list(dict.fromkeys(mod.functions.values()))
    for fn in scanned_fns:
        for loop in _iter_loops(fn):
            for call in _loop_calls(loop):
                if id(call) in seen:
                    continue
                declared = _transfer_match(call.func, spec.transfers)
                if declared is not None:
                    seen.add(id(call))
                    out.append(
                        Finding(
                            "transfer-in-loop", path,
                            call.lineno, call.col_offset,
                            f"host<->device conversion {declared!r} "
                            f"inside a per-lane loop in "
                            f"{getattr(fn, 'name', '<module>')!r} — one "
                            f"row of the vectorized-ingest worklist "
                            f"(hoist or batch the conversion)",
                        )
                    )
                    continue
                # module-map resolution is only sound for local calls:
                # bare names and self.X methods.  other.validate(...) is
                # some other object's method that merely shares a leaf.
                is_local = isinstance(call.func, ast.Name) or (
                    isinstance(call.func, ast.Attribute)
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id in ("self", "cls")
                )
                leaf = _leaf(call.func)
                if is_local and leaf in bearing and leaf not in spec.buckets:
                    seen.add(id(call))
                    out.append(
                        Finding(
                            "transfer-in-loop", path,
                            call.lineno, call.col_offset,
                            f"call to {leaf!r} (which performs "
                            f"{bearing[leaf]!r}) inside a per-lane loop "
                            f"in {getattr(fn, 'name', '<module>')!r} — "
                            f"one row of the vectorized-ingest worklist "
                            f"(hoist or batch the conversion)",
                        )
                    )


# ---------------------------------------------------------------------------
# tracer-leak + jit-impure (traced bodies)
# ---------------------------------------------------------------------------


def _tainted_names(fn: ast.FunctionDef) -> Set[str]:
    """Names derived from the traced function's inputs (params and
    anything computed from them or from device ops) — the values that
    are tracers during a trace."""
    tainted: Set[str] = set(_fn_params(fn))
    args = fn.args
    for a in list(args.kwonlyargs) + (
        [args.vararg] if args.vararg else []
    ) + ([args.kwarg] if args.kwarg else []):
        tainted.add(a.arg)

    def expr_tainted(node: ast.expr) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in tainted:
                return True
            if isinstance(sub, ast.Call) and _root(sub.func) in _DEV_ROOTS:
                return True
        return False

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and expr_tainted(node.value):
            tainted.add(node.targets[0].id)
    return tainted


def _check_tracer_leak(
    path: str, fn: ast.FunctionDef, out: List[Finding]
) -> None:
    tainted = _tainted_names(fn)
    local = _local_stores(fn)
    declared_global: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            declared_global.update(node.names)

    def value_tainted(node: ast.expr) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in tainted:
                return True
            if isinstance(sub, ast.Call) and _root(sub.func) in _DEV_ROOTS:
                return True
        return False

    def flag(node: ast.AST, where: str) -> None:
        out.append(
            Finding(
                "tracer-leak", path, node.lineno, node.col_offset,
                f"traced value escapes {where} in traced function "
                f"{fn.name!r}: the tracer outlives the trace and "
                f"poisons later calls",
            )
        )

    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not value_tainted(node.value):
                # a global-declared name rebound even to a pure value
                # still leaks trace-scoped state across calls
                if not any(
                    isinstance(t, ast.Name) and t.id in declared_global
                    for t in targets
                ):
                    continue
            for t in targets:
                if isinstance(t, ast.Attribute):
                    flag(node, "into instance/module state")
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id not in local:
                    flag(node, "into an enclosing-scope container")
                elif isinstance(t, ast.Name) and t.id in declared_global:
                    flag(node, "through a global/nonlocal binding")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id not in local \
                and any(value_tainted(a) for a in node.args):
            flag(node, "into an enclosing-scope container")


def _check_jit_impure(
    path: str, fn: ast.FunctionDef, mod: _ModIndex, out: List[Finding]
) -> None:
    local = _local_stores(fn)

    def impure_call(node: ast.Call) -> Optional[str]:
        dn = _dotted(node.func)
        if dn == "print":
            return "print"
        if dn is not None:
            root = dn.split(".")[0]
            if root in _IMPURE_ROOTS:
                return dn
            if any(dn == d or dn.startswith(d + ".")
                   for d in _IMPURE_DOTTED):
                return dn
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "block_until_ready":
            return ".block_until_ready()"
        return None

    # ast.walk is breadth-first: a flagged Subscript is seen before its
    # inner os.environ Attribute — counting both would double-report
    env_counted: Set[int] = set()
    for node in ast.walk(fn):
        bad: Optional[str] = None
        if isinstance(node, ast.Call):
            bad = impure_call(node)
        elif isinstance(node, ast.Subscript) \
                and _dotted(node.value) in _IMPURE_ENV:
            bad = "os.environ[...]"
            env_counted.add(id(node.value))
        elif isinstance(node, ast.Attribute) and node.attr == "environ" \
                and _dotted(node) in _IMPURE_ENV \
                and id(node) not in env_counted:
            bad = "os.environ"
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in mod.mutable_globals \
                and node.id not in local:
            bad = f"mutable module state {node.id!r}"
        if bad is not None:
            out.append(
                Finding(
                    "jit-impure", path, node.lineno, node.col_offset,
                    f"{bad} inside traced function {fn.name!r}: runs at "
                    f"trace time / forces a host sync, not per call",
                )
            )


# ---------------------------------------------------------------------------
# per-file analysis
# ---------------------------------------------------------------------------


def _qualnames(tree: ast.Module):
    """(qualname, FunctionDef) pairs: top-level, Class.method, and
    nested defs under their bare name."""
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    yield f"{node.name}.{sub.name}", sub


def _stage_functions(
    path: str, tree: ast.Module, spec: HotpathSpec
) -> List[Tuple[ast.FunctionDef, StageSpec]]:
    posix = Path(path).as_posix()
    rows = [s for s in spec.stages if posix.endswith(s.module)]
    if not rows:
        return []
    out: List[Tuple[ast.FunctionDef, StageSpec]] = []
    quals = list(_qualnames(tree))
    for row in rows:
        for qual, fn in quals:
            if qual == row.function or (
                "." not in row.function
                and qual.rsplit(".", 1)[-1] == row.function
            ):
                out.append((fn, row))
    return out


class _FileAnalyzer:
    def __init__(
        self,
        path: str,
        tree: ast.Module,
        mods: Dict[str, _ModIndex],
        jit_table: Dict[str, _JitInfo],
        spec: HotpathSpec,
        active: Set[str],
    ):
        self.path = path
        self.tree = tree
        self.mod = mods[path]
        self.jit_table = jit_table
        self.spec = spec
        self.active = active

    def run(self) -> List[Finding]:
        out: List[Finding] = []
        posix = Path(self.path).as_posix()
        if {"recompile-hazard", "static-arg-churn"} & self.active:
            for fn in dict.fromkeys(self.mod.functions.values()):
                _FnScan(
                    self.path, self.spec, self.mod, self.jit_table,
                    self.active, out,
                ).run(fn)
        if "host-sync-hot-path" in self.active:
            for fn, row in _stage_functions(self.path, self.tree, self.spec):
                if row.boundary:
                    continue
                _FnScan(
                    self.path, self.spec, self.mod, self.jit_table,
                    self.active, out, sync_stage=row.function,
                ).run(fn)
        if "transfer-in-loop" in self.active and any(
            posix.endswith(m) for m in self.spec.devices
        ):
            _check_transfers(self.path, self.mod, self.spec, out)
        if {"tracer-leak", "jit-impure"} & self.active:
            for fn in self.mod.traced:
                if "tracer-leak" in self.active:
                    _check_tracer_leak(self.path, fn, out)
                if "jit-impure" in self.active:
                    _check_jit_impure(self.path, fn, self.mod, out)
        return [f for f in out if f.rule in self.active]


# ---------------------------------------------------------------------------
# drivers (the toolkit analyzer contract)
# ---------------------------------------------------------------------------


def analyze_sources(
    sources: Dict[str, str],
    rule_ids: Optional[Iterable[str]] = None,
    hotpath: Optional[HotpathSpec] = None,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Analyze {path: source}.  ``hotpath`` defaults to the packaged
    ``tools/hotpath.toml`` (loud ValueError when missing/malformed)."""
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    for rid in active:
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
    if hotpath is None:
        hotpath = load_default_hotpath()

    mods: Dict[str, _ModIndex] = {}
    trees: Dict[str, ast.Module] = {}
    findings: List[Finding] = []
    for path, source in sorted(sources.items()):
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(
                Finding(
                    "syntax-error", path, exc.lineno or 1,
                    exc.offset or 0, f"cannot parse: {exc.msg}",
                )
            )
            continue
        trees[path] = tree
        mods[path] = _ModIndex(path, tree)

    # the cross-file jit-callable table: a leaf defined jitted anywhere
    # (verify_batch_jit in p256_kernel) is a jit call site everywhere
    # (tpu_provider's self._pk.verify_batch_jit)
    jit_table: Dict[str, _JitInfo] = {}
    for path in sorted(mods):
        for leaf, info in mods[path].jit_callables.items():
            jit_table.setdefault(leaf, info)

    n_suppressed = 0
    for path, tree in sorted(trees.items()):
        raw = _FileAnalyzer(
            path, tree, mods, jit_table, hotpath, active
        ).run()
        raw.sort(key=Finding.key)
        supp = toolkit.suppressed_rules(sources[path], "fabtrace")
        kept, suppressed = toolkit.apply_suppressions(raw, supp)
        findings.extend(kept)
        n_suppressed += len(suppressed)
        if collect_suppressed is not None:
            collect_suppressed.extend(suppressed)
    findings.sort(key=Finding.key)
    stats = {"files": len(sources), "suppressed": n_suppressed}
    return findings, stats


def analyze_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
    hotpath: Optional[HotpathSpec] = None,
) -> Tuple[List[Finding], int]:
    """Single-blob convenience (fixtures/tests)."""
    findings, stats = analyze_sources({path: source}, rule_ids, hotpath)
    return findings, stats["suppressed"]


def analyze_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    hotpath: Optional[HotpathSpec] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths, excludes)
    sources, io_findings = toolkit.read_sources(files)
    findings, stats = analyze_sources(sources, rule_ids, hotpath)
    findings.extend(io_findings)
    findings.sort(key=Finding.key)
    stats["files"] = len(files)
    return findings, stats


def live_suppression_keys(
    sources: Dict[str, str], rules: Set[str]
) -> Set[Tuple[str, int, str]]:
    """The toolkit analyzer-registry staleness protocol (consumed by
    fabreg's suppression-stale): (normalized path, line, rule) for
    every fabtrace suppression that still absorbs a finding."""
    needed = set(RULES) if "all" in rules else (rules & set(RULES))
    if not needed:
        return set()
    suppressed: List[Finding] = []
    analyze_sources(sources, needed, collect_suppressed=suppressed)
    return {
        (toolkit.normalize_path(f.path), f.line, f.rule)
        for f in suppressed
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fabtrace",
        "device-plane trace-discipline analyzer for fabric-tpu "
        "(dependency-free; never imports the analyzed code)",
    )
    parser.add_argument(
        "--hotpath",
        metavar="FILE",
        help="pipeline-stage table (default: tools/hotpath.toml next to "
        "this module)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=20)
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fabtrace", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fabtrace")
    if rc:
        return rc

    hotpath: Optional[HotpathSpec] = None
    try:
        if args.hotpath is not None:
            hotpath = parse_hotpath(
                Path(args.hotpath).read_text(encoding="utf-8"),
                args.hotpath,
            )
        else:
            hotpath = load_default_hotpath()
    except (OSError, ValueError) as exc:
        print(f"fabtrace: error: hotpath table: {exc}", file=sys.stderr)
        return 2

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = analyze_paths(args.paths, rule_ids, excludes, hotpath)

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fabtrace: {len(findings)} finding(s) in {stats['files']} "
            f"file(s) ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
