"""crashchild — the subprocess peer the fabcrash crash matrix kills.

The fabchaos ``crash_single`` / ``crash_matrix`` scenarios need a REAL
peer process to die mid-commit: in-process fault injection can raise at
a seam, but only a process death exercises what the durability seams
actually promise — fsync ordering, torn tails, sqlite WAL rollback, and
restart recovery.  This module is that process, kept import-light (no
jax, no numpy, no crypto backends) so a matrix run's many child
processes start in fractions of a second.

Three entry points:

* :func:`build_stream` (called in-process by the fabchaos parent) —
  deterministically builds a multi-channel stream of endorsed blocks
  (valid lanes, MVCC-conflict lanes, private-data collections) plus the
  coordinator-style cleartext pvt payloads, serialized under a stream
  directory.  Signatures come from a seeded null signer: structurally
  valid envelopes (txparse parses them) whose crypto is never checked —
  the crash surface under test is the COMMIT plane, not the validator.

* ``commit`` mode — opens one :class:`~fabric_tpu.ledger.kvledger.
  KVLedger` per channel (restart recovery runs implicitly) and drives
  the remaining blocks through per-channel
  :class:`~fabric_tpu.peer.pipeline.CommitPipeline` instances, so kill
  points inside ``pipeline.commit`` / ``kvledger.commit`` /
  ``blockstore.append`` / ``persistent.commit.mid`` fire on the real
  stage-B thread.  Armed via ``FABRIC_TPU_CRASH_SITES`` in the child's
  environment; a kill exits with
  :data:`~fabric_tpu.common.faults.KILL_EXIT_CODE`.

* ``recover`` mode — reopens the ledgers (recovery repairs torn tails /
  replays the state gap), then RE-PULLS every missing block over the
  existing deliver failover path (two endpoints serving the stream; the
  parent arms a ``deliver.pull`` flap so failover is actually taken),
  commits them, and writes ``digest.json``: per-channel chain-file
  sha256, commit hash, concatenated VALID/INVALID masks, and full
  state/hashed/pvt row digests.  The parent byte-diffs this digest
  against the no-crash run's.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import struct
import sys
from typing import Dict, List, Optional, Tuple

from fabric_tpu.common.retry import RetryPolicy
from fabric_tpu.deliver.client import BlockDeliverer
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.peer.pipeline import CommitPipeline
from fabric_tpu.protos import ab_pb2, common_pb2, protoutil

NAMESPACE = "cc"
COLLECTION = "secret"


# ---------------------------------------------------------------------------
# Stream construction (parent side)
# ---------------------------------------------------------------------------


class _NullSigner:
    """Structurally-valid, crypto-free signing identity: deterministic
    seeded nonces (stable tx_ids) and content-hash 'signatures'.  The
    commit plane never verifies them; txparse only needs the envelope
    shape."""

    def __init__(self, msp_id: str, rng):
        self.msp_id = msp_id
        self._serialized = protoutil.serialize_identity(
            msp_id, b"crash:" + rng.getrandbits(64).to_bytes(8, "big")
        )
        self._rng = rng

    def serialize(self) -> bytes:
        return self._serialized

    def new_nonce(self) -> bytes:
        return self._rng.getrandbits(192).to_bytes(24, "big")

    def sign(self, msg: bytes) -> bytes:
        return hashlib.sha256(b"nullsig|" + msg).digest()


def _tx_envelope(client, endorser, channel_id: str, txrw) -> bytes:
    from fabric_tpu.endorser import (
        create_proposal,
        create_signed_tx,
        endorse_proposal,
    )
    from fabric_tpu.ledger.rwset_proto import serialize_tx_rwset

    bundle = create_proposal(client, channel_id, NAMESPACE, [b"invoke"])
    responses = [
        endorse_proposal(bundle, endorser, serialize_tx_rwset(txrw))
    ]
    return create_signed_tx(bundle, client, responses).SerializeToString()


def build_stream(
    stream_dir: str,
    seed: int,
    n_channels: int = 3,
    n_blocks: int = 6,
) -> None:
    """Deterministic multi-channel block stream + pvt payloads on disk.

    Per block and channel: tx0 writes a hot key with an oversized value
    (every block frame exceeds the Python write buffer, so the payload
    bypasses the buffer while the trailing checksum stays buffered — a
    pre-fsync kill on ANY channel then leaves a GENUINELY torn frame
    for recovery to truncate), tx1 carries a stale read (always an MVCC
    conflict: masks are never all-VALID), tx2 writes a rotating key,
    tx3 writes a private collection (hashed writes on-block, cleartext
    in the pvt sidecar)."""
    import random

    from fabric_tpu.ledger import rwset as rw
    from fabric_tpu.protos import kv_rwset_pb2

    os.makedirs(stream_dir, exist_ok=True)
    pvt_json: Dict[str, Dict[str, List] ] = {}
    for ch in range(n_channels):
        rng = random.Random(seed * 1000003 + 7919 * ch)
        client = _NullSigner("CrashMSP", rng)
        endorser = _NullSigner("CrashMSP", rng)
        channel_id = f"ch{ch}"
        model: Dict[str, Tuple[int, int]] = {}
        hashed_model: Dict[bytes, Tuple[int, int]] = {}
        prev = b""
        frames = bytearray()
        pvt_json[str(ch)] = {}
        big = 12288
        for bn in range(n_blocks):
            hot = f"hot{ch}"
            rot = f"k{bn % 5}"

            def claim(committed):
                return rw.Version(*committed) if committed else None

            txs = []
            # tx0: correct read claim + write of the hot key (valid)
            txs.append(
                rw.TxRwSet((
                    rw.NsRwSet(
                        NAMESPACE,
                        (rw.KVRead(hot, claim(model.get(hot))),),
                        (rw.KVWrite(hot, False, bytes([bn & 0xFF]) * big),),
                    ),
                ))
            )
            # tx1: stale claim -> deterministic MVCC conflict lane
            txs.append(
                rw.TxRwSet((
                    rw.NsRwSet(
                        NAMESPACE,
                        (rw.KVRead(hot, rw.Version(bn, 99)),),
                        (rw.KVWrite(hot, False, b"loser"),),
                    ),
                ))
            )
            # tx2: rotating key, correct claim (valid)
            txs.append(
                rw.TxRwSet((
                    rw.NsRwSet(
                        NAMESPACE,
                        (rw.KVRead(rot, claim(model.get(rot))),),
                        (rw.KVWrite(rot, False, b"v%d" % bn),),
                    ),
                ))
            )
            # tx3: private collection write (+ read of the previous
            # pvt key at its true hashed version)
            pkey = f"p{ch}_{bn}"
            pval = b"secret %d %d" % (ch, bn)
            kh = hashlib.sha256(pkey.encode()).digest()
            reads = ()
            prev_kh = hashlib.sha256(f"p{ch}_{bn-1}".encode()).digest()
            if prev_kh in hashed_model:
                reads = (
                    rw.KVReadHash(
                        prev_kh, rw.Version(*hashed_model[prev_kh])
                    ),
                )
            txs.append(
                rw.TxRwSet((
                    rw.NsRwSet(
                        NAMESPACE,
                        (),
                        (),
                        (),
                        (
                            rw.CollHashedRwSet(
                                COLLECTION,
                                reads,
                                (
                                    rw.KVWriteHash(
                                        kh,
                                        False,
                                        hashlib.sha256(pval).digest(),
                                    ),
                                ),
                                (),
                            ),
                        ),
                    ),
                ))
            )
            kv = kv_rwset_pb2.KVRWSet()
            w = kv.writes.add()
            w.key = pkey
            w.value = pval
            pvt_json[str(ch)][str(bn)] = [
                [3, NAMESPACE, COLLECTION, kv.SerializeToString().hex()]
            ]

            block = protoutil.new_block(bn, prev)
            for txrw in txs:
                block.data.data.append(
                    _tx_envelope(client, endorser, channel_id, txrw)
                )
            protoutil.seal_block(block)
            prev = protoutil.block_header_hash(block.header)
            raw = block.SerializeToString()
            frames += struct.pack("<I", len(raw)) + raw

            # the model mirrors the sequential MVCC outcome: tx0/tx2/tx3
            # are valid by construction, tx1 always conflicts
            model[hot] = (bn, 0)
            model[rot] = (bn, 2)
            hashed_model[kh] = (bn, 3)
        with open(os.path.join(stream_dir, f"ch{ch}.bin"), "wb") as f:
            f.write(frames)
    with open(os.path.join(stream_dir, "pvt.json"), "w") as f:
        json.dump(pvt_json, f, sort_keys=True)
    with open(os.path.join(stream_dir, "meta.json"), "w") as f:
        json.dump({"channels": n_channels, "blocks": n_blocks}, f,
                  sort_keys=True)


# ---------------------------------------------------------------------------
# Child side: load, commit, recover, digest
# ---------------------------------------------------------------------------


def load_stream(stream_dir: str):
    with open(os.path.join(stream_dir, "meta.json")) as f:
        meta = json.load(f)
    blocks: List[List[common_pb2.Block]] = []
    pvt: List[Dict[int, Dict[Tuple[int, str, str], bytes]]] = []
    with open(os.path.join(stream_dir, "pvt.json")) as f:
        pvt_json = json.load(f)
    for ch in range(meta["channels"]):
        chain: List[common_pb2.Block] = []
        with open(os.path.join(stream_dir, f"ch{ch}.bin"), "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            chain.append(
                protoutil.unmarshal(common_pb2.Block, data[off : off + ln])
            )
            off += ln
        blocks.append(chain)
        per_block: Dict[int, Dict[Tuple[int, str, str], bytes]] = {}
        for bn, entries in pvt_json.get(str(ch), {}).items():
            per_block[int(bn)] = {
                (tx, ns, coll): bytes.fromhex(raw)
                for tx, ns, coll, raw in entries
            }
        pvt.append(per_block)
    return meta, blocks, pvt


def _open_ledgers(workdir: str, n_channels: int) -> List[KVLedger]:
    ledger_dir = os.path.join(workdir, "ledger")
    return [
        KVLedger(ledger_dir, f"ch{ch}", persistent=True)
        for ch in range(n_channels)
    ]


class _LedgerChannel:
    """The minimal channel surface CommitPipeline drives: stage A is a
    no-op (no validator in the crash child — the commit plane is the
    surface under test), stage B is the real KVLedger.commit with the
    coordinator-assembled pvt payloads."""

    def __init__(self, channel_id, ledger, pvt_by_block):
        self.channel_id = channel_id
        self.ledger = ledger
        self.pvt_by_block = pvt_by_block

    def prepare_block(self, block):
        return None

    def store_block(self, block, prepared=None):
        return self.ledger.commit(
            block, pvt_data=self.pvt_by_block.get(block.header.number)
        )


def cmd_commit(workdir: str, stream_dir: str) -> int:
    meta, blocks, pvt = load_stream(stream_dir)
    ledgers = _open_ledgers(workdir, meta["channels"])
    errors: List[str] = []
    pipes = [
        CommitPipeline(
            _LedgerChannel(f"ch{ch}", ledgers[ch], pvt[ch]),
            on_error=lambda b, exc, ch=ch: errors.append(
                f"ch{ch} block {b.header.number}: {exc}"
            ),
        )
        for ch in range(meta["channels"])
    ]
    start = [lg.height for lg in ledgers]
    try:
        for bn in range(meta["blocks"]):
            for ch in range(meta["channels"]):
                if bn < start[ch]:
                    continue  # already durable from a previous life
                pipes[ch].submit(blocks[ch][bn])
        for pipe in pipes:
            if not pipe.drain(timeout=60):
                errors.append("pipeline failed to drain")
    finally:
        for pipe in pipes:
            pipe.stop()
        for lg in ledgers:
            lg.close()
    if errors:
        print("\n".join(errors), file=sys.stderr)
        return 1
    return 0


def _seek_start(env: common_pb2.Envelope) -> int:
    payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
    seek = protoutil.unmarshal(ab_pb2.SeekInfo, payload.data)
    return seek.start.specified.number


def cmd_recover(workdir: str, stream_dir: str) -> int:
    meta, blocks, pvt = load_stream(stream_dir)
    ledgers = _open_ledgers(workdir, meta["channels"])
    try:
        for ch, ledger in enumerate(ledgers):
            remaining = meta["blocks"] - ledger.height
            if remaining <= 0:
                continue

            def endpoint(chain):
                def serve(env):
                    for b in chain[_seek_start(env) :]:
                        resp = ab_pb2.DeliverResponse()
                        resp.block.CopyFrom(b)
                        yield resp

                return serve

            committed: List[int] = []

            def on_block(block, ledger=ledger, ch=ch):
                ledger.commit(
                    block,
                    pvt_data=pvt[ch].get(block.header.number),
                )
                committed.append(block.header.number)

            deliverer = BlockDeliverer(
                f"ch{ch}",
                [endpoint(blocks[ch]), endpoint(blocks[ch])],
                on_block=on_block,
                next_block=lambda ledger=ledger: ledger.height,
                retry_policy=RetryPolicy(
                    base_s=0.01, multiplier=2.0, cap_s=0.05, deadline_s=30.0
                ),
            )
            got = deliverer.run(max_blocks=remaining)
            if got != remaining:
                print(
                    f"ch{ch}: re-pulled {got}/{remaining} blocks",
                    file=sys.stderr,
                )
                return 1
        digest = {
            f"ch{ch}": _digest(
                ledger,
                os.path.join(workdir, "ledger", f"ch{ch}.chain"),
            )
            for ch, ledger in enumerate(ledgers)
        }
    finally:
        for lg in ledgers:
            lg.close()
    with open(os.path.join(workdir, "digest.json"), "w") as f:
        json.dump(digest, f, sort_keys=True, indent=1)
    return 0


def _digest(ledger: KVLedger, chain_path: str) -> Dict[str, object]:
    """Everything the crash matrix byte-diffs: chain bytes, commit-hash
    chain, stored VALID/INVALID masks, and the full derived state."""
    out: Dict[str, object] = {
        "height": ledger.height,
        "commit_hash": ledger.commit_hash.hex(),
        "savepoint": ledger.state_db.savepoint(),
    }
    with open(chain_path, "rb") as f:
        out["chain_sha"] = hashlib.sha256(f.read()).hexdigest()
    masks = hashlib.sha256()
    for n in range(ledger.height):
        block = ledger.block_store.get_block_by_number(n)
        masks.update(
            bytes(block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER])
        )
    out["masks_sha"] = masks.hexdigest()
    state = hashlib.sha256()
    for ns, key, vv in ledger.state_db.iter_all_state():
        state.update(
            repr((ns, key, vv.value, vv.version.block_num, vv.version.tx_num)).encode()
        )
    out["state_sha"] = state.hexdigest()
    hashed = hashlib.sha256()
    for ns, coll, kh, vv in ledger.state_db.iter_all_hashed():
        hashed.update(
            repr((ns, coll, kh, vv.value, vv.version.block_num, vv.version.tx_num)).encode()
        )
    out["hashed_sha"] = hashed.hexdigest()
    pvt = hashlib.sha256()
    for ns, coll, key, vv in ledger.state_db.iter_all_pvt():
        pvt.update(
            repr((ns, coll, key, vv.value, vv.version.block_num, vv.version.tx_num)).encode()
        )
    out["pvt_sha"] = pvt.hexdigest()
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="crashchild",
        description="fabcrash subprocess peer: commit a block stream "
        "(killable via FABRIC_TPU_CRASH_SITES) or recover + re-pull + "
        "digest",
    )
    ap.add_argument("mode", choices=("commit", "recover"))
    ap.add_argument("--dir", required=True, help="working directory (ledgers + digest)")
    ap.add_argument("--stream", required=True, help="stream directory from build_stream")
    args = ap.parse_args(argv)
    os.makedirs(args.dir, exist_ok=True)
    if args.mode == "commit":
        return cmd_commit(args.dir, args.stream)
    return cmd_recover(args.dir, args.stream)


if __name__ == "__main__":
    sys.exit(main())
