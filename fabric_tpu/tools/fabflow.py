"""fabflow — value-range + dtype abstract interpreter for fabric-tpu.

The whole ops layer rests on a hand-tuned headroom argument: radix-2^13
limbs whose <2^27 partial products are accumulated in uint32/int32 lanes
(fabric_tpu/ops/bignum.py) — one wrong widening or one extra
accumulation and a signature silently verifies wrong.  fablint checks
per-file syntax invariants and fabdep checks the import/concurrency
graph; fabflow checks the *arithmetic itself*: it abstractly interprets
the limb kernels over an interval domain (never importing the analyzed
code — same contract as fablint/fabdep, runs without jax/cryptography)
and mechanizes the 20·2^27 < 2^32 accumulator proof, plus a mask-
soundness pass proving the validation flag paths fail closed.

Analysis 1 — limb value-range / dtype (the LIMB tier: ops/, common/p256,
common/fp256bn, crypto/hostec, ledger/mvcc_device):

  Every function is interpreted flow-sensitively under the module's
  documented canonical-limb contract (array parameters hold limbs in
  [0, LIMB_MASK], dtype uint32; ``int``-annotated parameters are
  arbitrary Python ints, which cannot overflow).  Intervals propagate
  through ``+ - * << >> & | ^ % //``, ``astype``/dtype constructors and
  np/jnp promotion; Python loops with concrete trip counts (the CIOS
  outer loop, ``lax.fori_loop(0, NLIMBS, ...)``) are unrolled
  abstractly, and unknown-trip loops (``lax.scan``/``while``) run to a
  widening fixpoint.  Calls into other analyzed modules are summarized
  interprocedurally (memoized per argument signature).  MontCtx
  instances are modeled by a contract table (per-limb scalars are
  13-bit; ``qm_term(q, j) <= q << LIMB_BITS``) — the table IS the
  per-limb fact base the headroom proof rests on.

  Unknown values (⊤) produce no findings: the gate proves what it can
  reach and stays quiet where precision runs out, so every finding is a
  computed bound, never a shrug.

Analysis 2 — mask soundness (the MASK tier: validation/, ledger/txparse,
parallel/, peer/pipeline): in every *flag-producing* function (one that
references TxValidationCode or calls ``set_flag``), each exception
handler must fail closed — raise, assign/return an INVALID-family code,
return an error string, delegate to a fallback validator, or hand the
exception object to a callback/logger — and must never write VALID (or
re-write NOT_VALIDATED, which leaves the flag unset).  Early ``return
TxValidationCode.VALID`` from inside a conditional is likewise flagged:
VALID is only ever assigned at the designated end of assembly.

Rules
-----
limb-overflow       a lane interval may exceed its container dtype's
                    capacity (uint32/int32/...); message carries the
                    computed worst-case interval
dtype-narrowing     astype / dtype constructor that can truncate a live
                    value (known interval outside the target range)
float-contamination a float operand (or true division ``/``) entering
                    an integer kernel lane
const-drift         re-hardcoded 13 / 20 / 0x1fff / 8192 / 260 in an
                    arithmetic context instead of LIMB_BITS / NLIMBS /
                    LIMB_MASK / RADIX_BITS from fabric_tpu.ops.bignum
mask-fail-open      an exception handler or early return in a
                    flag-producing function that can leave a lane VALID
                    or the flag unset

Suppression
-----------
Per line: ``# fabflow: disable=<rule>[,<rule>]  # <computed bound>``.  The
reason must state the actual worst-case interval the headroom bet rests
on (tests/test_fabflow.py enforces a numeric bound in every reason).

Usage
-----
    python -m fabric_tpu.tools.fabflow [--json] [--list-rules]
                                       [--rules a,b] PATH...

Exit status: 0 = clean, 1 = findings, 2 = usage/IO error.
"""

from __future__ import annotations

import ast
import json
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    FileContext,
    Finding,
    iter_py_files,
)

__version__ = "1.0"

# --------------------------------------------------------------------------
# Configuration
# --------------------------------------------------------------------------

#: The canonical limb constants (fabric_tpu/ops/bignum.py).  fabflow
#: never imports analyzed code, so it carries its own copies; the
#: const-drift rule keeps the rest of the repo honest about importing
#: the real ones.
LIMB_BITS = 13
NLIMBS = 20
LIMB_MASK = (1 << LIMB_BITS) - 1
RADIX_BITS = LIMB_BITS * NLIMBS

#: hostec_np's pair-condensed compute form (crypto/hostec_np.py):
#: adjacent radix-2^13 limbs packed two-per-uint64 at radix 2^26, with
#: one spare pair-limb of Montgomery headroom.  The L4/L32 bounds are
#: the proven `_mul_kernel` input contracts (lazy limbs carried by the
#: _FE wrapper before they exceed these).
PAIR_BITS = 2 * LIMB_BITS
PAIR_MASK = (1 << PAIR_BITS) - 1
NPAIRS = NLIMBS // 2 + 1
PAIR_L4 = 4 * (PAIR_MASK + 1) - 1
PAIR_L32 = 32 * (PAIR_MASK + 1) - 1

#: Files whose lane arithmetic carries the limb headroom contract.
#: crypto/hostbn.py rides the SAME pair-limb contracts as hostec_np
#: (PairMat/L4/L32 bounds below): its tower/group-law code drives
#: hostec_np's proven kernels with the BN modulus — the MontCtx bound
#: (m < 2^256) and the per-limb L4/L32 input contracts are
#: modulus-independent, so the mechanized headroom proof transfers.
LIMB_TIER = (
    "*fabric_tpu/ops/*.py",
    "*fabric_tpu/common/p256.py",
    "*fabric_tpu/common/fp256bn.py",
    "*fabric_tpu/crypto/hostec.py",
    "*fabric_tpu/crypto/hostec_np.py",
    "*fabric_tpu/crypto/hostbn.py",
    "*fabric_tpu/ledger/mvcc_device.py",
)

#: The device-lane subset of the limb tier: unannotated parameters here
#: are canonical limb arrays; everywhere else in the tier they are host
#: Python ints (no container to overflow).
LANE_FILES = (
    "*fabric_tpu/ops/*.py",
    "*fabric_tpu/ledger/mvcc_device.py",
)

#: Files whose exception discipline decides the VALID/INVALID mask.
#: serve/ joined with the sidecar (PR 8): the client shim's degrade
#: path RE-DERIVES the mask in-process on sidecar death, so its
#: handlers are as mask-load-bearing as the validator's own.
#: common/fabobs.py joined with the observability registry (PR 10): its
#: hooks run INSIDE every mask-critical seam, so the tier proves the
#: wrappers themselves never write a flag or fail open — obs code must
#: be provably unable to alter masks, not just trusted not to.
MASK_TIER = (
    "*fabric_tpu/validation/*.py",
    "*fabric_tpu/ledger/txparse.py",
    "*fabric_tpu/parallel/*.py",
    "*fabric_tpu/peer/pipeline.py",
    "*fabric_tpu/serve/*.py",
    "*fabric_tpu/common/fabobs.py",
)

#: Hardcoded literal -> the canonical name that should be imported.
DRIFT_CONSTANTS = {
    13: "LIMB_BITS",
    20: "NLIMBS",
    8191: "LIMB_MASK",
    8192: "1 << LIMB_BITS",
    260: "RADIX_BITS",
}

#: TxValidationCode members that may never be written in an exception
#: handler: VALID fails open, NOT_VALIDATED leaves the flag unset.
FAIL_OPEN_MEMBERS = {"VALID", "NOT_VALIDATED"}

#: Interpreter budgets: loop-unroll cap, fixpoint iteration cap, and
#: abstract-step budget per analyzed function (bail to ⊤ beyond).
MAX_UNROLL = 512
MAX_FIXPOINT = 24
FUNC_STEP_BUDGET = 400_000
MAX_CALL_DEPTH = 10

# --------------------------------------------------------------------------
# Findings / suppression plumbing (tools.toolkit, shared with
# fablint/fabdep/fabreg)
# --------------------------------------------------------------------------


RULES: Dict[str, str] = {
    "limb-overflow": (
        "computed lane interval may exceed the container dtype's capacity"
    ),
    "dtype-narrowing": (
        "astype/dtype constructor can truncate a live value (known "
        "interval outside the target dtype's range)"
    ),
    "float-contamination": (
        "float operand or true division '/' entering an integer kernel lane"
    ),
    "const-drift": (
        "re-hardcoded limb constant (13/20/0x1fff/8192/260); import "
        "LIMB_BITS/NLIMBS/LIMB_MASK/RADIX_BITS from fabric_tpu.ops.bignum"
    ),
    "mask-fail-open": (
        "exception handler or early return in a flag-producing function "
        "can leave a lane VALID or the flag unset"
    ),
}

def parse_suppressions(source: str) -> Dict[int, Tuple[Set[str], str]]:
    """line -> (disabled rule ids, reason text)."""
    return toolkit.parse_suppressions(source, "fabflow")


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------------------
# Interval domain
# --------------------------------------------------------------------------

_INF = float("inf")

#: Widening thresholds: the limb-proof landmarks (LIMB_MASK, 2^26/2^27
#: partial products, dtype capacities) so loop-carried accumulators
#: stabilize on the bound that actually matters.
_THRESHOLDS = sorted(
    {
        0, 1, 2, 16, 255, 256, LIMB_MASK, 1 << LIMB_BITS, 65535, 65536,
        1 << 26, 1 << 27, NLIMBS << 27, (1 << 31) - 1, 1 << 31,
        (1 << 32) - 1, 1 << 32, (1 << 63) - 1, (1 << 64) - 1,
        1 << 256, 1 << RADIX_BITS,
    }
)
_NEG_THRESHOLDS = sorted({-t for t in _THRESHOLDS})


class Interval:
    """[lo, hi] over Python ints; None = unbounded on that side."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo: Optional[int], hi: Optional[int]):
        self.lo = lo
        self.hi = hi

    def __repr__(self) -> str:
        lo = "-inf" if self.lo is None else self.lo
        hi = "+inf" if self.hi is None else self.hi
        return f"[{lo}, {hi}]"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Interval)
            and self.lo == other.lo
            and self.hi == other.hi
        )

    def __hash__(self) -> int:
        return hash((self.lo, self.hi))

    # -- helpers ----------------------------------------------------------
    def _flo(self) -> float:
        return -_INF if self.lo is None else self.lo

    def _fhi(self) -> float:
        return _INF if self.hi is None else self.hi

    @property
    def is_top(self) -> bool:
        return self.lo is None and self.hi is None

    def const(self) -> Optional[int]:
        """The single concrete value, if this interval is a point."""
        if self.lo is not None and self.lo == self.hi:
            return self.lo
        return None

    def nonneg(self) -> bool:
        return self.lo is not None and self.lo >= 0

    def within(self, lo: Optional[int], hi: Optional[int]) -> bool:
        if lo is not None and (self.lo is None or self.lo < lo):
            return False
        if hi is not None and (self.hi is None or self.hi > hi):
            return False
        return True

    @staticmethod
    def _wrap(v: float) -> Optional[int]:
        return None if v in (_INF, -_INF) else int(v)

    @classmethod
    def from_f(cls, lo: float, hi: float) -> "Interval":
        return cls(cls._wrap(lo), cls._wrap(hi))

    # -- lattice ----------------------------------------------------------
    def join(self, other: "Interval") -> "Interval":
        return Interval.from_f(
            min(self._flo(), other._flo()), max(self._fhi(), other._fhi())
        )

    def widen(self, newer: "Interval") -> "Interval":
        """Jump each moving bound to the next proof landmark so loop
        fixpoints terminate in a handful of sweeps."""
        lo: Optional[int]
        hi: Optional[int]
        if newer._flo() < self._flo():
            lo = None
            for t in reversed(_NEG_THRESHOLDS + _THRESHOLDS):
                if newer.lo is not None and t <= newer.lo:
                    lo = t
                    break
        else:
            lo = self.lo
        if newer._fhi() > self._fhi():
            hi = None
            for t in _NEG_THRESHOLDS + _THRESHOLDS:
                if newer.hi is not None and t >= newer.hi:
                    hi = t
                    break
        else:
            hi = self.hi
        return Interval(lo, hi)

    # -- arithmetic -------------------------------------------------------
    def add(self, o: "Interval") -> "Interval":
        return Interval.from_f(self._flo() + o._flo(), self._fhi() + o._fhi())

    def sub(self, o: "Interval") -> "Interval":
        return Interval.from_f(self._flo() - o._fhi(), self._fhi() - o._flo())

    def neg(self) -> "Interval":
        return Interval.from_f(-self._fhi(), -self._flo())

    def mul(self, o: "Interval") -> "Interval":
        cands = []
        for x in (self._flo(), self._fhi()):
            for y in (o._flo(), o._fhi()):
                if x == 0 or y == 0:
                    cands.append(0)
                else:
                    cands.append(x * y)
        return Interval.from_f(min(cands), max(cands))

    def lshift(self, o: "Interval") -> "Interval":
        if o.lo is None or o.lo < 0 or o.hi is None or o.hi > 512:
            return TOP_IVL
        return self.mul(Interval(1 << o.lo, 1 << o.hi))

    def rshift(self, o: "Interval") -> "Interval":
        if o.lo is None or o.lo < 0:
            return TOP_IVL
        khi = 512 if o.hi is None else min(o.hi, 512)
        cands = []
        for x in (self.lo, self.hi):
            for k in (o.lo, khi):
                if x is None:
                    return Interval(
                        None if self.lo is None else min(self.lo >> o.lo, -1, 0),
                        None if self.hi is None else max(self.hi >> o.lo, 0),
                    )
                cands.append(x >> k)
        return Interval(min(cands), max(cands))

    def and_(self, o: "Interval") -> "Interval":
        # x & m ∈ [0, m] for m >= 0, regardless of x's sign (two's
        # complement semantics of Python ints); symmetric in the mask.
        outs = []
        if o.nonneg() and o.hi is not None:
            outs.append(Interval(0, o.hi))
        if self.nonneg() and self.hi is not None:
            outs.append(Interval(0, self.hi))
        if not outs:
            return TOP_IVL
        best = outs[0]
        for iv in outs[1:]:
            if iv.hi is not None and (best.hi is None or iv.hi < best.hi):
                best = iv
        return best

    def or_(self, o: "Interval") -> "Interval":
        if self.nonneg() and o.nonneg():
            # a | b <= a + b for non-negative operands
            return Interval.from_f(
                max(self._flo(), o._flo()), self._fhi() + o._fhi()
            )
        return TOP_IVL

    def xor(self, o: "Interval") -> "Interval":
        if self.nonneg() and o.nonneg():
            return Interval.from_f(0, self._fhi() + o._fhi())
        return TOP_IVL

    def mod(self, o: "Interval") -> "Interval":
        if o.lo is not None and o.lo > 0 and o.hi is not None:
            if self.nonneg() and self.hi is not None and self.hi < o.lo:
                return self
            return Interval(0, o.hi - 1)
        return TOP_IVL

    def floordiv(self, o: "Interval") -> "Interval":
        if o.lo is None or o.lo < 1 or o.hi is None:
            return TOP_IVL
        if self.lo is None or self.hi is None:
            return TOP_IVL
        cands = [
            x // y for x in (self.lo, self.hi) for y in (o.lo, o.hi)
        ]
        return Interval(min(cands), max(cands))


TOP_IVL = Interval(None, None)

# --------------------------------------------------------------------------
# Dtypes
# --------------------------------------------------------------------------

#: name -> (min, max, is_float).  'pyint'/'pyfloat' are host Python
#: scalars (no container to overflow).
DTYPES: Dict[str, Tuple[Optional[int], Optional[int], bool]] = {
    "bool": (0, 1, False),
    "uint8": (0, (1 << 8) - 1, False),
    "uint16": (0, (1 << 16) - 1, False),
    "uint32": (0, (1 << 32) - 1, False),
    "uint64": (0, (1 << 64) - 1, False),
    "int8": (-(1 << 7), (1 << 7) - 1, False),
    "int16": (-(1 << 15), (1 << 15) - 1, False),
    "int32": (-(1 << 31), (1 << 31) - 1, False),
    "int64": (-(1 << 63), (1 << 63) - 1, False),
    "float16": (None, None, True),
    "float32": (None, None, True),
    "float64": (None, None, True),
    "pyint": (None, None, False),
    "pyfloat": (None, None, True),
}

_INT_WIDTH = {
    "bool": 8, "uint8": 8, "int8": 8, "uint16": 16, "int16": 16,
    "uint32": 32, "int32": 32, "uint64": 64, "int64": 64,
}


def dtype_is_float(dt: Optional[str]) -> bool:
    return dt is not None and DTYPES.get(dt, (None, None, False))[2]


def dtype_is_lane_int(dt: Optional[str]) -> bool:
    """A fixed-width integer lane (NOT a host Python int)."""
    return dt in _INT_WIDTH and dt != "bool"


def promote(d1: Optional[str], d2: Optional[str]) -> Optional[str]:
    """jax-x32-flavored promotion, just precise enough for the kernels:
    python scalars are weak, float wins, mixed signedness goes signed at
    the wider width."""
    if d1 == d2:
        return d1
    if d1 is None or d2 is None:
        return None
    if d1 == "pyint":
        return d2 if d2 != "bool" else "pyint"
    if d2 == "pyint":
        return d1 if d1 != "bool" else "pyint"
    f1, f2 = dtype_is_float(d1), dtype_is_float(d2)
    if f1 or f2:
        if d1 == "pyfloat":
            return d2 if f2 else "float32"
        if d2 == "pyfloat":
            return d1 if f1 else "float32"
        if f1 and f2:
            return d1 if _FLOAT_ORDER.get(d1, 0) >= _FLOAT_ORDER.get(d2, 0) else d2
        return d1 if f1 else d2
    if d1 == "bool":
        return d2
    if d2 == "bool":
        return d1
    w = max(_INT_WIDTH[d1], _INT_WIDTH[d2])
    signed = d1.startswith("int") or d2.startswith("int")
    return ("int" if signed else "uint") + str(w)


_FLOAT_ORDER = {"float16": 1, "float32": 2, "float64": 3, "pyfloat": 2}


# --------------------------------------------------------------------------
# Abstract values
# --------------------------------------------------------------------------


class AbsVal:
    """Base abstract value; UNKNOWN (⊤) is the silent default."""

    def key(self, depth: int = 3):
        return "?"


class _Unknown(AbsVal):
    def __repr__(self) -> str:
        return "⊤"


UNKNOWN = _Unknown()


class NoneVal(AbsVal):
    def __repr__(self) -> str:
        return "None"

    def key(self, depth: int = 3):
        return "None"


NONE = NoneVal()


class Num(AbsVal):
    """An integer/float lane (scalar or array): interval + dtype."""

    __slots__ = ("ivl", "dtype")

    def __init__(self, ivl: Interval, dtype: Optional[str]):
        self.ivl = ivl
        self.dtype = dtype

    def __repr__(self) -> str:
        return f"Num({self.ivl}, {self.dtype})"

    def key(self, depth: int = 3):
        return ("N", self.ivl.lo, self.ivl.hi, self.dtype)

    def const(self) -> Optional[int]:
        if self.dtype in ("pyint", "bool") or self.dtype is None:
            return self.ivl.const()
        return None


def num_const(v: int) -> Num:
    return Num(Interval(v, v), "pyint")


def num_bool(v: Optional[bool] = None) -> Num:
    if v is None:
        return Num(Interval(0, 1), "bool")
    return Num(Interval(int(v), int(v)), "bool")


LIMB_DTYPE = "uint32"


def limb_num() -> Num:
    """The canonical-limb parameter assumption: [0, LIMB_MASK] uint32."""
    return Num(Interval(0, LIMB_MASK), LIMB_DTYPE)


class SeqVal(AbsVal):
    """List/tuple: known items, or an element summary when unknown."""

    __slots__ = ("items", "elem", "mutable")

    def __init__(
        self,
        items: Optional[List[AbsVal]] = None,
        elem: AbsVal = UNKNOWN,
        mutable: bool = True,
    ):
        self.items = items
        self.elem = elem
        self.mutable = mutable

    def __repr__(self) -> str:
        if self.items is not None:
            return f"Seq[{len(self.items)}]"
        return f"Seq[?:{self.elem!r}]"

    def key(self, depth: int = 3):
        if depth <= 0:
            return "Seq…"
        if self.items is not None:
            if len(self.items) > 24:
                return ("S", len(self.items), self.summary().key(depth - 1))
            return ("S",) + tuple(v.key(depth - 1) for v in self.items)
        return ("S?", self.elem.key(depth - 1))

    def summary(self) -> AbsVal:
        if self.items is None:
            return self.elem
        out: Optional[AbsVal] = None
        for it in self.items:
            out = it if out is None else join(out, it)
        return out if out is not None else UNKNOWN

    def getitem(self, idx: Optional[int]) -> AbsVal:
        if self.items is not None and idx is not None:
            if -len(self.items) <= idx < len(self.items):
                return self.items[idx]
            return UNKNOWN
        return self.summary()


def limb_seq(n: int = NLIMBS, dtype: str = LIMB_DTYPE) -> SeqVal:
    return SeqVal(items=[Num(Interval(0, LIMB_MASK), dtype) for _ in range(n)])


class ConstVal(AbsVal):
    """A concrete non-numeric Python constant (str/bytes)."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def key(self, depth: int = 3):
        return ("C", repr(self.value)[:40])


class FuncVal(AbsVal):
    """A function defined in an analyzed module (optionally bound)."""

    __slots__ = ("mod", "node", "qualname", "selfval")

    def __init__(self, mod, node, qualname, selfval=None):
        self.mod = mod
        self.node = node
        self.qualname = qualname
        self.selfval = selfval

    def key(self, depth: int = 3):
        return ("F", self.mod.name, self.qualname)


class ClassVal(AbsVal):
    __slots__ = ("mod", "node")

    def __init__(self, mod, node):
        self.mod = mod
        self.node = node

    def key(self, depth: int = 3):
        return ("K", self.mod.name, self.node.name)


class InstanceVal(AbsVal):
    """An instance of an analyzed class: attr map + optional contract."""

    __slots__ = ("cls_name", "attrs", "contract", "clsval")

    def __init__(self, cls_name, attrs=None, contract=None, clsval=None):
        self.cls_name = cls_name
        self.attrs = attrs if attrs is not None else {}
        self.contract = contract
        self.clsval = clsval

    def key(self, depth: int = 3):
        return ("I", self.cls_name, self.contract)


class ModVal(AbsVal):
    """Reference to an analyzed module or an intrinsic namespace."""

    __slots__ = ("modinfo", "intrinsic")

    def __init__(self, modinfo=None, intrinsic: Optional[str] = None):
        self.modinfo = modinfo
        self.intrinsic = intrinsic

    def key(self, depth: int = 3):
        return ("M", self.intrinsic or (self.modinfo and self.modinfo.name))


class IntrinsicVal(AbsVal):
    """A builtin/numpy/jax callable modeled by a handler."""

    __slots__ = ("name", "handler")

    def __init__(self, name: str, handler):
        self.name = name
        self.handler = handler

    def key(self, depth: int = 3):
        return ("X", self.name)


class MethodVal(AbsVal):
    """A recognized method on an abstract receiver (astype, append...)."""

    __slots__ = ("name", "recv")

    def __init__(self, name: str, recv: AbsVal):
        self.name = name
        self.recv = recv

    def key(self, depth: int = 3):
        return ("m", self.name, self.recv.key(depth - 1))


class RangeVal(AbsVal):
    """range() with possibly-unknown bounds."""

    __slots__ = ("lo", "hi", "step")

    def __init__(self, lo: Num, hi: Num, step: int = 1):
        self.lo = lo
        self.hi = hi
        self.step = step

    def key(self, depth: int = 3):
        return ("R", self.lo.key(1), self.hi.key(1), self.step)


def join(a: AbsVal, b: AbsVal) -> AbsVal:
    if a is b:
        return a
    if isinstance(a, Num) and isinstance(b, Num):
        dt = a.dtype if a.dtype == b.dtype else promote(a.dtype, b.dtype)
        return Num(a.ivl.join(b.ivl), dt)
    if isinstance(a, SeqVal) and isinstance(b, SeqVal):
        if (
            a.items is not None
            and b.items is not None
            and len(a.items) == len(b.items)
        ):
            return SeqVal(
                items=[join(x, y) for x, y in zip(a.items, b.items)]
            )
        return SeqVal(items=None, elem=join(a.summary(), b.summary()))
    if isinstance(a, NoneVal) and isinstance(b, NoneVal):
        return NONE
    # a guarded optional import (`try: import numpy as np / except
    # ImportError: np = None`) joins the module with None at module
    # scope; keep the module binding — the limb kernels only execute in
    # the dependency-present world, and that is the world whose value
    # ranges the gate must prove (joining to ⊤ would silence them).
    if isinstance(a, ModVal) and isinstance(b, NoneVal):
        return a
    if isinstance(b, ModVal) and isinstance(a, NoneVal):
        return b
    if (
        isinstance(a, ConstVal)
        and isinstance(b, ConstVal)
        and a.value == b.value
    ):
        return a
    if isinstance(a, InstanceVal) and isinstance(b, InstanceVal):
        if a.cls_name == b.cls_name and a.contract == b.contract:
            return a
    if isinstance(a, FuncVal) and isinstance(b, FuncVal):
        if a.qualname == b.qualname and a.mod is b.mod:
            return a
    return UNKNOWN


def widen_val(prev: AbsVal, newer: AbsVal) -> AbsVal:
    if isinstance(prev, Num) and isinstance(newer, Num):
        dt = prev.dtype if prev.dtype == newer.dtype else promote(
            prev.dtype, newer.dtype
        )
        return Num(prev.ivl.widen(newer.ivl), dt)
    if (
        isinstance(prev, SeqVal)
        and isinstance(newer, SeqVal)
        and prev.items is not None
        and newer.items is not None
        and len(prev.items) == len(newer.items)
    ):
        return SeqVal(
            items=[widen_val(x, y) for x, y in zip(prev.items, newer.items)]
        )
    j = join(prev, newer)
    if isinstance(j, SeqVal) and isinstance(prev, SeqVal):
        if isinstance(prev.summary(), Num) and isinstance(j.summary(), Num):
            return SeqVal(
                items=None,
                elem=widen_val(prev.summary(), j.summary()),
            )
    return j


# --------------------------------------------------------------------------
# Module universe
# --------------------------------------------------------------------------


class ModuleInfo:
    """One parsed source file: AST + import map + lazily-built globals."""

    def __init__(self, name: str, path: str, tree: ast.Module, source: str):
        self.name = name
        self.path = path
        self.tree = tree
        self.source = source
        self.imports: Dict[str, str] = {}       # alias -> dotted module
        self.import_froms: Dict[str, Tuple[str, str]] = {}  # name -> (mod, attr)
        self.functions: Dict[str, ast.AST] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.globals: Dict[str, AbsVal] = {}
        self.eval_state = "new"  # new | evaluating | done
        self._index()

    def _index(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    if alias.name != "*":
                        self.import_froms[alias.asname or alias.name] = (
                            node.module, alias.name
                        )


def module_name_for(path: str) -> str:
    parts = Path(path).as_posix().split("/")
    if "fabric_tpu" in parts:
        i = parts.index("fabric_tpu")
        dotted = ".".join(parts[i:])
    else:
        dotted = parts[-1]
    if dotted.endswith(".py"):
        dotted = dotted[: -len(".py")]
    if dotted.endswith(".__init__"):
        dotted = dotted[: -len(".__init__")]
    return dotted


#: intrinsic namespaces recognized by dotted import name
_INTRINSIC_MODULES = {
    "numpy": "numpy",
    "jax": "jax",
    "jax.numpy": "numpy",
    "jax.lax": "lax",
    "jax.ops": "jaxops",
    "math": "math",
    "os": "opaque",
    "threading": "opaque",
    "contextlib": "opaque",
    "functools": "functools",
    "hashlib": "opaque",
    "secrets": "opaque",
    "typing": "opaque",
    "enum": "opaque",
    "queue": "opaque",
    "time": "opaque",
}


# --------------------------------------------------------------------------
# Contracts: MontCtx (the per-limb fact base of the headroom proof)
# --------------------------------------------------------------------------


def _montctx_attr(name: str) -> AbsVal:
    if name in ("m_limbs", "r2_limbs", "one_mont", "one"):
        return limb_seq()
    if name in ("m_scalars",):
        return limb_seq()
    if name in ("m_scalars_i32",):
        return limb_seq(dtype="int32")
    if name == "m0inv":
        return Num(Interval(0, LIMB_MASK), "uint32")
    if name == "km_scalars_i32":
        # dict k -> int32 limb tuple; modeled as "subscript anything ->
        # int32 limb seq" via a SeqVal summary
        return SeqVal(items=None, elem=limb_seq(dtype="int32"))
    if name == "m":
        return Num(Interval(1, (1 << 256) - 1), "pyint")
    if name == "limb_shift_decomp":
        # per-limb (hi, lo) with 2^hi - 2^lo == m_j < 2^13, so hi <= 13
        # and -1 <= lo < hi (lo == -1 marks a plain power of two)
        return SeqVal(
            items=None,
            elem=SeqVal(
                items=[
                    Num(Interval(0, LIMB_BITS), "pyint"),
                    Num(Interval(-1, LIMB_BITS - 1), "pyint"),
                ],
                mutable=False,
            ),
        )
    return UNKNOWN


def _montctx_method(name: str):
    if name == "qm_term":
        def qm_term(args, kwargs, interp, node):
            # q * m_j as shifts/subtracts or a plain multiply; every form
            # is bounded by q << LIMB_BITS (m_j < 2^13), never negative.
            q = args[0] if args else UNKNOWN
            hi: Optional[int] = None
            if isinstance(q, Num) and q.ivl.hi is not None:
                hi = q.ivl.hi << LIMB_BITS
            return Num(Interval(0, hi), "uint32")
        return qm_term
    if name == "const":
        def const(args, kwargs, interp, node):
            return limb_seq()
        return const
    return None


# --------------------------------------------------------------------------
# Control-flow signals
# --------------------------------------------------------------------------


class _Budget(Exception):
    pass


class _BreakSig(Exception):
    pass


class _ContinueSig(Exception):
    pass


# --------------------------------------------------------------------------
# The abstract interpreter
# --------------------------------------------------------------------------


class Analyzer:
    """Drives interprocedural interval analysis over a module universe."""

    def __init__(
        self,
        universe: Dict[str, ModuleInfo],
        enabled_rules: Set[str],
        suppressions: Dict[str, Dict[int, Tuple[Set[str], str]]],
    ):
        self.universe = universe
        self.enabled = enabled_rules
        self.suppressions = suppressions
        self.findings: Dict[Tuple[str, int, str], Finding] = {}
        self.suppressed = 0
        self._suppressed_keys: Set[Tuple[str, int, str]] = set()
        self.suppressed_findings: List[Finding] = []
        self.memo: Dict[tuple, AbsVal] = {}
        self.in_flight: Set[tuple] = set()

    # -- findings ---------------------------------------------------------
    def report(
        self, rule: str, mod: ModuleInfo, node: ast.AST, message: str
    ) -> None:
        if rule not in self.enabled:
            return
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        key = (mod.path, line, rule)
        if key in self.findings or key in self._suppressed_keys:
            return
        sup = self.suppressions.get(mod.path, {}).get(line)
        if sup is not None and (rule in sup[0] or "all" in sup[0]):
            self.suppressed += 1
            self._suppressed_keys.add(key)
            self.suppressed_findings.append(
                Finding(rule, mod.path, line, col, message)
            )
            return
        self.findings[key] = Finding(rule, mod.path, line, col, message)

    # -- module env -------------------------------------------------------
    def resolve_module(self, dotted: str) -> Optional[ModuleInfo]:
        mod = self.universe.get(dotted)
        if mod is not None:
            return mod
        # the txflags/validation shim family: exact name only, no guessing
        return None

    def module_env(self, mod: ModuleInfo) -> Dict[str, AbsVal]:
        if mod.eval_state == "done":
            return mod.globals
        if mod.eval_state == "evaluating":
            return mod.globals  # import cycle: partial env is sound (⊤s)
        mod.eval_state = "evaluating"
        interp = Interp(self, mod, dict(mod.globals), depth=0,
                        budget=[FUNC_STEP_BUDGET])
        try:
            interp.exec_block(mod.tree.body)
        except _Budget:
            pass
        except RecursionError:
            pass
        mod.globals.update(interp.env)
        mod.eval_state = "done"
        return mod.globals

    # -- interprocedural summaries ---------------------------------------
    def call_function(
        self,
        fv: FuncVal,
        args: List[AbsVal],
        kwargs: Dict[str, AbsVal],
        depth: int,
        budget: List[int],
    ) -> AbsVal:
        if depth > MAX_CALL_DEPTH:
            return UNKNOWN
        node = fv.node
        if isinstance(node, ast.Lambda):
            return self._run_callable(fv, node, args, kwargs, depth, budget)
        key = (
            fv.mod.name,
            fv.qualname,
            tuple(a.key() for a in args),
            tuple(sorted((k, v.key()) for k, v in kwargs.items())),
        )
        if key in self.memo:
            return self.memo[key]
        if key in self.in_flight:
            return UNKNOWN  # recursion
        self.in_flight.add(key)
        try:
            out = self._run_callable(fv, node, args, kwargs, depth, budget)
        finally:
            self.in_flight.discard(key)
        self.memo[key] = out
        return out

    def _run_callable(self, fv, node, args, kwargs, depth, budget) -> AbsVal:
        env: Dict[str, AbsVal] = {}
        a = node.args
        pos = list(args)
        params = list(a.posonlyargs) + list(a.args)
        if fv.selfval is not None:
            pos = [fv.selfval] + pos
        defaults = list(a.defaults)
        # align defaults to the tail of params
        def_off = len(params) - len(defaults)
        for i, p in enumerate(params):
            if i < len(pos):
                env[p.arg] = pos[i]
            elif p.arg in kwargs:
                env[p.arg] = kwargs[p.arg]
            elif i >= def_off:
                env[p.arg] = Interp(
                    self, fv.mod, {}, depth, budget
                ).eval(defaults[i - def_off])
            else:
                env[p.arg] = UNKNOWN
        if a.vararg is not None:
            env[a.vararg.arg] = SeqVal(items=None, elem=UNKNOWN)
        for i, p in enumerate(a.kwonlyargs):
            if p.arg in kwargs:
                env[p.arg] = kwargs[p.arg]
            elif a.kw_defaults[i] is not None:
                env[p.arg] = Interp(
                    self, fv.mod, {}, depth, budget
                ).eval(a.kw_defaults[i])
            else:
                env[p.arg] = UNKNOWN
        if a.kwarg is not None:
            env[a.kwarg.arg] = UNKNOWN
        interp = Interp(self, fv.mod, env, depth + 1, budget)
        if isinstance(node, ast.Lambda):
            try:
                return interp.eval(node.body)
            except (_Budget, RecursionError):
                return UNKNOWN
        try:
            interp.exec_block(node.body)
        except (_Budget, RecursionError):
            return UNKNOWN
        return interp.return_value()

    # -- standalone analysis entry ---------------------------------------
    def default_param(
        self, annotation: Optional[ast.AST], lane: bool = True
    ) -> AbsVal:
        """Parameter assumption under the canonical-limb contract.

        `lane` is True for device-lane files (ops/, mvcc_device): an
        unannotated parameter there is a canonical limb array.  Host
        big-int files (common/p256, common/fp256bn, crypto/hostec) work
        in Python ints, which cannot overflow."""
        ann = _dotted(annotation) if annotation is not None else None
        if isinstance(annotation, ast.Constant) and isinstance(
            annotation.value, str
        ):
            ann = annotation.value
        if isinstance(annotation, ast.Subscript):
            base = _dotted(annotation.value)
            leafb = (base or "").rsplit(".", 1)[-1]
            if leafb == "Optional":
                return self.default_param(annotation.slice, lane)
            if leafb in ("Sequence", "List", "Tuple"):
                inner = annotation.slice
                if isinstance(inner, ast.Tuple):
                    # Tuple[A, B, ...]: per-position element assumptions
                    elts = [e for e in inner.elts if not (
                        isinstance(e, ast.Constant) and e.value is Ellipsis
                    )]
                    if len(elts) == 1 and len(inner.elts) == 2:
                        elem = self.default_param(elts[0], lane)
                        if isinstance(elem, Num) and elem.dtype == LIMB_DTYPE:
                            return SeqVal(
                                items=[limb_num() for _ in range(NLIMBS)]
                            )
                        return SeqVal(items=None, elem=elem)
                    return SeqVal(
                        items=[self.default_param(e, lane) for e in elts]
                    )
                elem = self.default_param(inner, lane)
                if isinstance(elem, Num) and elem.dtype == LIMB_DTYPE:
                    # Sequence[jax.Array]: the canonical limb tuple
                    return SeqVal(items=[limb_num() for _ in range(NLIMBS)])
                return SeqVal(items=None, elem=elem)
        if ann is None:
            return limb_num() if lane else Num(TOP_IVL, "pyint")
        leaf = ann.rsplit(".", 1)[-1]
        if leaf == "int":
            return Num(TOP_IVL, "pyint")
        if leaf == "float":
            return Num(TOP_IVL, "pyfloat")
        if leaf == "bool":
            return num_bool()
        if leaf in ("bytes", "str"):
            return UNKNOWN
        if leaf in ("LimbVec", "Rows"):
            return SeqVal(items=[limb_num() for _ in range(NLIMBS)])
        if leaf in ("Array", "ndarray"):
            return limb_num()
        # hostec_np pair-limb contracts (string annotations on the numpy
        # kernels; bounds enforced at runtime by the _FE wrapper)
        if leaf == "PairMat":
            return Num(Interval(0, PAIR_MASK), "uint64")
        if leaf == "PairMatL4":
            return Num(Interval(0, PAIR_L4), "uint64")
        if leaf == "PairMatL32":
            return Num(Interval(0, PAIR_L32), "uint64")
        if leaf == "AccMat":
            # the REDC sweep's accumulator: the MAC phase's proven bound
            return Num(Interval(0, NPAIRS * (PAIR_L32 + 1) * (PAIR_L4 + 1)), "uint64")
        if leaf == "BiasMat":
            # the REDC complement-fold bias (K*m minus the constant
            # over-add, < m): canonical pair limbs
            return Num(Interval(0, PAIR_MASK), "uint64")
        if leaf in ("Lanes",):
            return SeqVal(items=None, elem=Num(TOP_IVL, "pyint"))
        if leaf == "MontCtx":
            return InstanceVal("MontCtx", contract="montctx")
        return UNKNOWN

    def analyze_function_standalone(
        self, mod: ModuleInfo, node, qualname: str, selfval: Optional[AbsVal]
    ) -> None:
        env: Dict[str, AbsVal] = {}
        lane = FileContext(mod.path).matches(LANE_FILES)
        a = node.args
        params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        # map parameter name -> default expression (aligned to the tail)
        pos_params = list(a.posonlyargs) + list(a.args)
        defaults: Dict[str, ast.AST] = {}
        for p, d in zip(pos_params[len(pos_params) - len(a.defaults):],
                        a.defaults):
            defaults[p.arg] = d
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None:
                defaults[p.arg] = d
        start = 0
        if selfval is not None and params:
            env[params[0].arg] = selfval
            start = 1
        for p in params[start:]:
            if p.annotation is None and p.arg in defaults:
                # an unannotated param with a scalar default is a config
                # scalar (bound counts, window sizes), never a limb lane
                d = defaults[p.arg]
                if isinstance(d, ast.Constant) and isinstance(
                    d.value, (int, float)
                ) and not isinstance(d.value, bool):
                    env[p.arg] = Num(
                        TOP_IVL,
                        "pyfloat" if isinstance(d.value, float) else "pyint",
                    )
                    continue
            env[p.arg] = self.default_param(p.annotation, lane)
        if a.vararg is not None:
            env[a.vararg.arg] = SeqVal(
                items=None, elem=limb_num() if lane else Num(TOP_IVL, "pyint")
            )
        if a.kwarg is not None:
            env[a.kwarg.arg] = UNKNOWN
        interp = Interp(self, mod, env, depth=1, budget=[FUNC_STEP_BUDGET])
        try:
            interp.exec_block(node.body)
        except (_Budget, RecursionError):
            pass


class NamedTupleVal(SeqVal):
    """NamedTuple instance: a known-length tuple with field names."""

    def __init__(self, items: List[AbsVal], fields: Dict[str, int]):
        super().__init__(items=items)
        self.fields = fields

    def key(self, depth: int = 3):
        return ("NT",) + tuple(v.key(depth - 1) for v in (self.items or []))


class DictVal(AbsVal):
    """Dict summary: join of values (keys untracked)."""

    __slots__ = ("vals",)

    def __init__(self, vals: AbsVal = UNKNOWN):
        self.vals = vals

    def key(self, depth: int = 3):
        return ("D", self.vals.key(depth - 1))


class DtypeVal(AbsVal):
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def key(self, depth: int = 3):
        return ("dt", self.name)


def as_dtype(v: AbsVal) -> Optional[str]:
    if isinstance(v, DtypeVal):
        return v.name
    if isinstance(v, ConstVal) and isinstance(v.value, str):
        return v.value if v.value in DTYPES else None
    return None


def numify(v: AbsVal) -> AbsVal:
    """Collapse a sequence to its lane summary (stack/concatenate)."""
    if isinstance(v, Num):
        return v
    if isinstance(v, SeqVal):
        s = v.summary()
        if isinstance(s, Num):
            return s
        if isinstance(s, SeqVal):
            inner = numify(s)
            return inner if isinstance(inner, Num) else UNKNOWN
    return UNKNOWN


def truth(v: AbsVal) -> Optional[bool]:
    if isinstance(v, Num):
        c = v.ivl.const()
        if c is not None and v.dtype in ("bool", "pyint"):
            return bool(c)
        if v.dtype in ("bool", "pyint") and v.ivl.lo is not None and v.ivl.lo > 0:
            return True
        return None
    if isinstance(v, NoneVal):
        return False
    if isinstance(v, ConstVal):
        return bool(v.value)
    if isinstance(v, SeqVal) and v.items is not None:
        return len(v.items) > 0
    return None


def join_env(e1: Dict[str, AbsVal], e2: Dict[str, AbsVal]) -> Dict[str, AbsVal]:
    out: Dict[str, AbsVal] = {}
    for k in set(e1) | set(e2):
        a, b = e1.get(k), e2.get(k)
        if a is None or b is None:
            out[k] = UNKNOWN if (a or b) is None else (a or b)
            if a is None and b is not None:
                out[k] = b
            elif b is None and a is not None:
                out[k] = a
        else:
            out[k] = join(a, b)
    return out


def env_key(env: Dict[str, AbsVal]) -> tuple:
    return tuple(sorted((k, v.key()) for k, v in env.items()))


class Interp:
    """Flow-sensitive abstract executor for one scope."""

    def __init__(self, analyzer: Analyzer, mod: ModuleInfo,
                 env: Dict[str, AbsVal], depth: int, budget: List[int]):
        self.an = analyzer
        self.mod = mod
        self.env = env
        self.depth = depth
        self.budget = budget
        self.returns: List[AbsVal] = []
        self.terminated = False
        ctx = FileContext(mod.path)
        self.check = ctx.matches(LIMB_TIER)

    # -- bookkeeping ------------------------------------------------------
    def step(self) -> None:
        self.budget[0] -= 1
        if self.budget[0] <= 0:
            raise _Budget()

    def return_value(self) -> AbsVal:
        out: Optional[AbsVal] = None
        for r in self.returns:
            out = r if out is None else join(out, r)
        if out is None or not self.terminated and self.returns:
            # fall-through path returns None too
            out = NONE if out is None else join(out, NONE)
        return out if out is not None else NONE

    # -- statements -------------------------------------------------------
    def exec_block(self, stmts: Sequence[ast.stmt]) -> None:
        for node in stmts:
            if self.terminated:
                return
            self.exec_stmt(node)

    def exec_stmt(self, node: ast.stmt) -> None:
        self.step()
        meth = getattr(self, "exec_" + type(node).__name__, None)
        if meth is not None:
            meth(node)
        elif isinstance(node, ast.Expr):
            self.eval(node.value)

    def exec_Expr(self, node) -> None:
        self.eval(node.value)

    def exec_Pass(self, node) -> None:
        pass

    def exec_Global(self, node) -> None:
        pass

    def exec_Nonlocal(self, node) -> None:
        pass

    def exec_Assert(self, node) -> None:
        self.eval(node.test)

    def exec_Delete(self, node) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.env.pop(t.id, None)

    def exec_Import(self, node) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.env[name] = self.resolve_import(
                alias.name if alias.asname else alias.name.split(".")[0]
            )

    def exec_ImportFrom(self, node) -> None:
        if not node.module:
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            self.env[alias.asname or alias.name] = self.resolve_from_import(
                node.module, alias.name
            )

    def resolve_import(self, dotted: str) -> AbsVal:
        if dotted in _INTRINSIC_MODULES:
            return ModVal(intrinsic=_INTRINSIC_MODULES[dotted])
        m = self.an.resolve_module(dotted)
        if m is not None:
            return ModVal(modinfo=m)
        return ModVal(intrinsic="opaque")

    def resolve_from_import(self, module: str, name: str) -> AbsVal:
        full = module + "." + name
        if full in _INTRINSIC_MODULES:
            return ModVal(intrinsic=_INTRINSIC_MODULES[full])
        if module in _INTRINSIC_MODULES:
            return intrinsic_attr(_INTRINSIC_MODULES[module], name)
        sub = self.an.resolve_module(full)
        if sub is not None:
            return ModVal(modinfo=sub)
        m = self.an.resolve_module(module)
        if m is not None:
            envm = self.an.module_env(m)
            if name in envm:
                return envm[name]
        # canonical-constant fallback: fixtures importing the limb
        # constants resolve even when bignum itself is not analyzed
        if module.endswith("bignum") or module.endswith(".common"):
            if name == "LIMB_BITS":
                return num_const(LIMB_BITS)
            if name == "NLIMBS":
                return num_const(NLIMBS)
            if name == "LIMB_MASK":
                return num_const(LIMB_MASK)
            if name == "RADIX_BITS":
                return num_const(RADIX_BITS)
        return UNKNOWN

    def exec_FunctionDef(self, node) -> None:
        self.env[node.name] = FuncVal(self.mod, node, node.name)

    exec_AsyncFunctionDef = exec_FunctionDef

    def exec_ClassDef(self, node) -> None:
        self.env[node.name] = ClassVal(self.mod, node)

    def exec_Return(self, node) -> None:
        self.returns.append(self.eval(node.value) if node.value else NONE)
        self.terminated = True

    def exec_Raise(self, node) -> None:
        if node.exc is not None:
            self.eval(node.exc)
        self.terminated = True

    def exec_Break(self, node) -> None:
        raise _BreakSig()

    def exec_Continue(self, node) -> None:
        raise _ContinueSig()

    def exec_Assign(self, node) -> None:
        val = self.eval(node.value)
        for t in node.targets:
            self.assign(t, val)

    def exec_AnnAssign(self, node) -> None:
        if node.value is not None:
            self.assign(node.target, self.eval(node.value))
        elif isinstance(node.target, ast.Name):
            self.env.setdefault(node.target.id, UNKNOWN)

    def exec_AugAssign(self, node) -> None:
        cur = self.eval(node.target)
        val = self.eval(node.value)
        out = self.binop(node.op, cur, val, node)
        self.assign(node.target, out)

    def assign(self, target: ast.AST, val: AbsVal) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            self.unpack(target.elts, val)
        elif isinstance(target, ast.Subscript):
            self.assign_subscript(target, val)
        elif isinstance(target, ast.Attribute):
            base = self.eval(target.value)
            if isinstance(base, InstanceVal):
                prev = base.attrs.get(target.attr)
                base.attrs[target.attr] = (
                    val if prev is None else join(prev, val)
                )
        elif isinstance(target, ast.Starred):
            self.assign(target.value, SeqVal(items=None, elem=UNKNOWN))

    def unpack(self, elts: Sequence[ast.AST], val: AbsVal) -> None:
        starred = [i for i, e in enumerate(elts) if isinstance(e, ast.Starred)]
        if isinstance(val, SeqVal) and val.items is not None and not starred:
            if len(val.items) == len(elts):
                for e, v in zip(elts, val.items):
                    self.assign(e, v)
                return
        if isinstance(val, Num):
            # unpacking an array's first axis: rows share interval/dtype
            for e in elts:
                self.assign(e, val if not isinstance(e, ast.Starred) else val)
            return
        elem = val.summary() if isinstance(val, SeqVal) else UNKNOWN
        for e in elts:
            if isinstance(e, ast.Starred):
                self.assign(e.value, SeqVal(items=None, elem=elem))
            else:
                self.assign(e, elem)

    def assign_subscript(self, target: ast.Subscript, val: AbsVal) -> None:
        base = self.eval(target.value)
        if isinstance(base, SeqVal) and base.items is not None:
            idx = self.eval(target.slice)
            c = idx.const() if isinstance(idx, Num) else None
            if c is not None and -len(base.items) <= c < len(base.items):
                base.items[c] = val
                return
            if isinstance(target.slice, ast.Slice):
                s = val.summary() if isinstance(val, SeqVal) else val
                base.items[:] = [join(x, s) for x in base.items]
                return
            base.items[:] = [join(x, val) for x in base.items]
            return
        if isinstance(base, DictVal):
            base.vals = join(base.vals, val)
            return
        if isinstance(base, Num) and isinstance(target.value, ast.Name):
            v = numify(val) if not isinstance(val, Num) else val
            if isinstance(v, Num):
                if (
                    self.check
                    and dtype_is_lane_int(base.dtype)
                    and v.ivl.lo is not None
                    and v.ivl.hi is not None
                    and not v.ivl.within(*DTYPES[base.dtype][:2])
                ):
                    self.an.report(
                        "dtype-narrowing", self.mod, target,
                        f"store of value in {v.ivl} into a {base.dtype} "
                        f"array truncates",
                    )
                self.env[target.value.id] = Num(
                    base.ivl.join(v.ivl), base.dtype
                )

    def _refine(self, test: ast.AST):
        """(then_bindings, else_bindings) for `x <op> const` tests —
        enough flow sensitivity for the carry/decomp guard idioms."""
        then_b: Dict[str, AbsVal] = {}
        else_b: Dict[str, AbsVal] = {}
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and len(test.comparators) == 1
        ):
            return then_b, else_b
        op = test.ops[0]
        l, r = test.left, test.comparators[0]
        flip = {ast.Lt: ast.Gt, ast.Gt: ast.Lt, ast.LtE: ast.GtE,
                ast.GtE: ast.LtE}
        if not isinstance(l, ast.Name) and isinstance(r, ast.Name):
            l, r = r, l
            if type(op) in flip:
                op = flip[type(op)]()
        if not isinstance(l, ast.Name):
            return then_b, else_b
        cur = self.env.get(l.id)
        rv = self.eval(r)
        if not (isinstance(cur, Num) and isinstance(rv, Num)):
            return then_b, else_b
        c_lo, c_hi = rv.ivl.lo, rv.ivl.hi

        def cap(lo, hi):
            return Num(
                Interval(
                    lo if cur.ivl.lo is None else (
                        cur.ivl.lo if lo is None else max(cur.ivl.lo, lo)
                    ),
                    hi if cur.ivl.hi is None else (
                        cur.ivl.hi if hi is None else min(cur.ivl.hi, hi)
                    ),
                ),
                cur.dtype,
            )

        if isinstance(op, ast.Lt):
            then_b[l.id] = cap(None, None if c_hi is None else c_hi - 1)
            else_b[l.id] = cap(c_lo, None)
        elif isinstance(op, ast.LtE):
            then_b[l.id] = cap(None, c_hi)
            else_b[l.id] = cap(None if c_lo is None else c_lo + 1, None)
        elif isinstance(op, ast.Gt):
            then_b[l.id] = cap(None if c_lo is None else c_lo + 1, None)
            else_b[l.id] = cap(None, c_hi)
        elif isinstance(op, ast.GtE):
            then_b[l.id] = cap(c_lo, None)
            else_b[l.id] = cap(None, None if c_hi is None else c_hi - 1)
        return then_b, else_b

    def exec_If(self, node) -> None:
        t = truth(self.eval(node.test))
        if t is True:
            self.exec_block(node.body)
            return
        if t is False:
            self.exec_block(node.orelse)
            return
        then_b, else_b = self._refine(node.test)
        saved = dict(self.env)
        term_a = term_b = False
        self.env.update(then_b)
        try:
            self.exec_block(node.body)
        except (_BreakSig, _ContinueSig):
            term_a = True
        env_a, term_a = self.env, self.terminated or term_a
        self.terminated = False
        self.env = dict(saved)
        self.env.update(else_b)
        try:
            self.exec_block(node.orelse)
        except (_BreakSig, _ContinueSig):
            term_b = True
        env_b, term_b = self.env, self.terminated or term_b
        self.terminated = False
        if term_a and term_b:
            self.terminated = True
            self.env = join_env(env_a, env_b)
        elif term_a:
            self.env = env_b
        elif term_b:
            self.env = env_a
        else:
            self.env = join_env(env_a, env_b)

    def exec_With(self, node) -> None:
        for item in node.items:
            v = self.eval(item.context_expr)
            if item.optional_vars is not None:
                self.assign(item.optional_vars, UNKNOWN if v is None else v)
        self.exec_block(node.body)

    exec_AsyncWith = exec_With

    def exec_Try(self, node) -> None:
        pre = dict(self.env)
        self.exec_block(node.body)
        body_env, body_term = dict(self.env), self.terminated
        self.terminated = False
        if not body_term:
            self.exec_block(node.orelse)
            body_env, body_term = dict(self.env), self.terminated
            self.terminated = False
        paths: List[Dict[str, AbsVal]] = []
        if not body_term:
            paths.append(body_env)
        for h in node.handlers:
            self.env = join_env(pre, body_env)
            self.terminated = False
            if h.name:
                self.env[h.name] = UNKNOWN
            if h.type is not None:
                self.eval(h.type)
            try:
                self.exec_block(h.body)
            except (_BreakSig, _ContinueSig):
                self.terminated = True
            if not self.terminated:
                paths.append(dict(self.env))
            self.terminated = False
        if paths:
            out = paths[0]
            for p in paths[1:]:
                out = join_env(out, p)
            self.env = out
            self.terminated = False
        else:
            self.env = join_env(pre, body_env)
            self.terminated = True
        term_after = self.terminated
        self.terminated = False
        self.exec_block(node.finalbody)
        self.terminated = self.terminated or term_after

    exec_TryStar = exec_Try

    # -- loops ------------------------------------------------------------
    def concrete_items(self, it: AbsVal) -> Optional[List[AbsVal]]:
        if isinstance(it, SeqVal) and it.items is not None:
            if len(it.items) <= MAX_UNROLL:
                return list(it.items)
            return None
        if isinstance(it, RangeVal) and it.step in (1, -1):
            lo, hi = it.lo.const(), it.hi.const()
            if lo is not None and hi is not None:
                vals = list(range(lo, hi, it.step))
                if len(vals) <= MAX_UNROLL:
                    return [num_const(v) for v in vals]
        return None

    def loop_elem(self, it: AbsVal) -> AbsVal:
        if isinstance(it, SeqVal):
            return it.summary()
        if isinstance(it, RangeVal):
            lo = it.lo.ivl.lo if it.lo.ivl.lo is not None else None
            hi = it.hi.ivl.hi
            return Num(Interval(lo, None if hi is None else hi - 1), "pyint")
        if isinstance(it, DictVal):
            return UNKNOWN
        if isinstance(it, Num):
            return it
        return UNKNOWN

    def exec_For(self, node) -> None:
        it = self.eval(node.iter)
        items = self.concrete_items(it)
        if items is not None:
            broke = False
            for v in items:
                self.assign(node.target, v)
                try:
                    self.exec_block(node.body)
                except _ContinueSig:
                    continue
                except _BreakSig:
                    broke = True
                    break
                if self.terminated:
                    return
            if not broke:
                self.exec_block(node.orelse)
            return
        elem = self.loop_elem(it)
        self.fixpoint(lambda: self.assign(node.target, elem), node.body)
        self.exec_block(node.orelse)

    exec_AsyncFor = exec_For

    def exec_While(self, node) -> None:
        t = truth(self.eval(node.test))
        if t is False:
            self.exec_block(node.orelse)
            return
        self.fixpoint(lambda: self.eval(node.test), node.body)
        self.exec_block(node.orelse)

    def fixpoint(self, bind, body: Sequence[ast.stmt]) -> None:
        """Run `body` to an abstract fixpoint with widening: the loop
        state converges onto the proof thresholds or tops out."""
        state = dict(self.env)
        skey = env_key(state)
        for i in range(MAX_FIXPOINT):
            self.env = dict(state)
            bind()
            try:
                self.exec_block(body)
            except (_BreakSig, _ContinueSig):
                pass
            if self.terminated:
                # a return/raise on every path through the body: the
                # post-loop state is the pre-iteration one
                self.terminated = False
                self.env = state
                return
            merged = join_env(state, self.env)
            if i >= 2:
                for k, v in list(merged.items()):
                    pv = state.get(k)
                    if pv is not None and v.key() != pv.key():
                        merged[k] = widen_val(pv, v)
            mkey = env_key(merged)
            if mkey == skey:
                self.env = merged
                return
            state, skey = merged, mkey
        # did not converge: top out everything that still moves
        self.env = {k: UNKNOWN for k in state}
        self.env.update(
            {k: v for k, v in state.items() if isinstance(v, (FuncVal, ClassVal, ModVal))}
        )

    # -- expressions ------------------------------------------------------
    def eval(self, node: Optional[ast.AST]) -> AbsVal:
        if node is None:
            return NONE
        self.step()
        meth = getattr(self, "eval_" + type(node).__name__, None)
        if meth is None:
            return UNKNOWN
        return meth(node)

    def eval_Constant(self, node) -> AbsVal:
        v = node.value
        if isinstance(v, bool):
            return num_bool(v)
        if isinstance(v, int):
            return num_const(v)
        if isinstance(v, float):
            return Num(TOP_IVL, "pyfloat")
        if v is None:
            return NONE
        if isinstance(v, (str, bytes)):
            return ConstVal(v)
        return UNKNOWN

    def eval_Name(self, node) -> AbsVal:
        name = node.id
        if name in self.env:
            return self.env[name]
        g = self.mod.globals
        if name in g:
            return g[name]
        if name in self.mod.import_froms:
            m, attr = self.mod.import_froms[name]
            return self.resolve_from_import(m, attr)
        if name in self.mod.imports:
            return self.resolve_import(self.mod.imports[name])
        if name in self.mod.functions:
            return FuncVal(self.mod, self.mod.functions[name], name)
        if name in self.mod.classes:
            return ClassVal(self.mod, self.mod.classes[name])
        return builtin_value(name)

    def eval_NamedExpr(self, node) -> AbsVal:
        v = self.eval(node.value)
        self.assign(node.target, v)
        return v

    def eval_Tuple(self, node) -> AbsVal:
        return self._seq_literal(node, mutable=False)

    def eval_List(self, node) -> AbsVal:
        return self._seq_literal(node, mutable=True)

    def _seq_literal(self, node, mutable: bool) -> AbsVal:
        items: List[AbsVal] = []
        for e in node.elts:
            if isinstance(e, ast.Starred):
                sv = self.eval(e.value)
                if isinstance(sv, SeqVal) and sv.items is not None:
                    items.extend(sv.items)
                else:
                    return SeqVal(
                        items=None,
                        elem=join(
                            sv.summary() if isinstance(sv, SeqVal) else UNKNOWN,
                            _join_all(items),
                        ),
                        mutable=mutable,
                    )
            else:
                items.append(self.eval(e))
        return SeqVal(items=items, mutable=mutable)

    def eval_Set(self, node) -> AbsVal:
        elems = [self.eval(e) for e in node.elts]
        return SeqVal(items=None, elem=_join_all(elems))

    def eval_Dict(self, node) -> AbsVal:
        vals = [self.eval(v) for v in node.values if v is not None]
        for k in node.keys:
            if k is not None:
                self.eval(k)
        return DictVal(vals=_join_all(vals))

    def eval_JoinedStr(self, node) -> AbsVal:
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self.eval(v.value)
        return ConstVal("")

    def eval_FormattedValue(self, node) -> AbsVal:
        self.eval(node.value)
        return ConstVal("")

    def eval_Starred(self, node) -> AbsVal:
        return self.eval(node.value)

    def eval_Slice(self, node) -> AbsVal:
        return UNKNOWN

    def eval_Lambda(self, node) -> AbsVal:
        return FuncVal(self.mod, node, f"<lambda:{node.lineno}>")

    def eval_IfExp(self, node) -> AbsVal:
        t = truth(self.eval(node.test))
        if t is True:
            return self.eval(node.body)
        if t is False:
            return self.eval(node.orelse)
        return join(self.eval(node.body), self.eval(node.orelse))

    def eval_BoolOp(self, node) -> AbsVal:
        vals = [self.eval(v) for v in node.values]
        truths = [truth(v) for v in vals]
        if isinstance(node.op, ast.And):
            for v, t in zip(vals, truths):
                if t is False:
                    return v
            if all(t is True for t in truths):
                return vals[-1]
        else:
            for v, t in zip(vals, truths):
                if t is True:
                    return v
            if all(t is False for t in truths):
                return vals[-1]
        return _join_all(vals)

    def eval_UnaryOp(self, node) -> AbsVal:
        v = self.eval(node.operand)
        if isinstance(node.op, ast.Not):
            t = truth(v)
            return num_bool(None if t is None else not t)
        if not isinstance(v, Num):
            return UNKNOWN
        if isinstance(node.op, ast.USub):
            ivl = v.ivl.neg()
            out = Num(ivl, v.dtype)
            self._overflow_check(out, node)
            return self._clamp(out)
        if isinstance(node.op, ast.UAdd):
            return v
        if isinstance(node.op, ast.Invert):
            ivl = v.ivl.neg().sub(Interval(1, 1))
            if v.dtype == "bool":
                return num_bool()
            out = Num(ivl, v.dtype)
            self._overflow_check(out, node)
            return self._clamp(out)
        return UNKNOWN

    def eval_Compare(self, node) -> AbsVal:
        # a chain is False if ANY link is definitely False, True only if
        # EVERY link is definitely True, else unknown
        left = self.eval(node.left)
        any_unknown = False
        cur = left
        for op, comp in zip(node.ops, node.comparators):
            right = self.eval(comp)
            one = self._compare_one(op, cur, right)
            if one is False:
                return num_bool(False)
            if one is None:
                any_unknown = True
            cur = right
        return num_bool(None) if any_unknown else num_bool(True)

    def _compare_one(self, op, l: AbsVal, r: AbsVal) -> Optional[bool]:
        if isinstance(op, (ast.Is, ast.IsNot)):
            l_none = isinstance(l, NoneVal)
            r_none = isinstance(r, NoneVal)
            if l_none or r_none:
                known_not_none = isinstance(
                    l if r_none else r, (Num, SeqVal, ConstVal, InstanceVal,
                                         FuncVal, ClassVal, DictVal)
                )
                if l_none and r_none:
                    same = True
                elif known_not_none:
                    same = False
                else:
                    return None
                return same if isinstance(op, ast.Is) else not same
            return None
        if isinstance(l, ConstVal) and isinstance(r, ConstVal):
            try:
                if isinstance(op, ast.Eq):
                    return l.value == r.value
                if isinstance(op, ast.NotEq):
                    return l.value != r.value
            except Exception:
                return None
            return None
        if not (isinstance(l, Num) and isinstance(r, Num)):
            return None
        a, b = l.ivl, r.ivl
        if a.lo is None or a.hi is None or b.lo is None or b.hi is None:
            return None
        if isinstance(op, ast.Lt):
            if a.hi < b.lo:
                return True
            if a.lo >= b.hi:
                return False
        elif isinstance(op, ast.LtE):
            if a.hi <= b.lo:
                return True
            if a.lo > b.hi:
                return False
        elif isinstance(op, ast.Gt):
            if a.lo > b.hi:
                return True
            if a.hi <= b.lo:
                return False
        elif isinstance(op, ast.GtE):
            if a.lo >= b.hi:
                return True
            if a.hi < b.lo:
                return False
        elif isinstance(op, ast.Eq):
            ca, cb = a.const(), b.const()
            if ca is not None and ca == cb:
                return True
            if a.hi < b.lo or a.lo > b.hi:
                return False
        elif isinstance(op, ast.NotEq):
            ca, cb = a.const(), b.const()
            if ca is not None and ca == cb:
                return False
            if a.hi < b.lo or a.lo > b.hi:
                return True
        return None

    # -- arithmetic with the overflow checks ------------------------------
    def _clamp(self, v: Num) -> Num:
        """After a reported overflow, continue with the full container
        range (the wrapped value is somewhere in it)."""
        if dtype_is_lane_int(v.dtype):
            lo, hi, _ = DTYPES[v.dtype]
            if not v.ivl.within(lo, hi):
                return Num(Interval(lo, hi), v.dtype)
        return v

    def _overflow_check(self, v: Num, node: ast.AST) -> None:
        if not self.check or not dtype_is_lane_int(v.dtype):
            return
        lo, hi, _ = DTYPES[v.dtype]
        if v.ivl.is_top or v.ivl.within(lo, hi):
            return
        if v.ivl.lo is None or v.ivl.hi is None:
            return  # half-open: provenance unknown, stay quiet
        self.an.report(
            "limb-overflow", self.mod, node,
            f"computed interval {v.ivl} exceeds {v.dtype} capacity "
            f"[{lo}, {hi}]",
        )

    def binop(self, op, l: AbsVal, r: AbsVal, node: ast.AST) -> AbsVal:
        # sequence algebra first: concat / repeat
        if isinstance(op, ast.Add) and isinstance(l, SeqVal) and isinstance(r, SeqVal):
            if l.items is not None and r.items is not None:
                return SeqVal(items=l.items + r.items)
            return SeqVal(items=None, elem=join(l.summary(), r.summary()))
        if isinstance(op, ast.Mult):
            if isinstance(l, SeqVal) and isinstance(r, Num):
                c = r.const()
                if l.items is not None and c is not None and 0 <= c * len(l.items) <= 4096:
                    return SeqVal(items=list(l.items) * c)
                return SeqVal(items=None, elem=l.summary())
            if isinstance(r, SeqVal) and isinstance(l, Num):
                return self.binop(op, r, l, node)
        if isinstance(l, ConstVal) or isinstance(r, ConstVal):
            return UNKNOWN
        ln = l if isinstance(l, Num) else numify(l)
        rn = r if isinstance(r, Num) else numify(r)
        if not (isinstance(ln, Num) and isinstance(rn, Num)):
            return UNKNOWN
        if isinstance(op, ast.Div):
            if self.check and (
                dtype_is_lane_int(ln.dtype) or dtype_is_lane_int(rn.dtype)
            ):
                self.an.report(
                    "float-contamination", self.mod, node,
                    "true division '/' on an integer kernel lane produces "
                    "a float; use // or a shift",
                )
            return Num(TOP_IVL, promote(ln.dtype, rn.dtype) if dtype_is_float(
                promote(ln.dtype, rn.dtype) or "float32") else "float32")
        dt = promote(ln.dtype, rn.dtype)
        if self.check and (
            (dtype_is_float(ln.dtype) and dtype_is_lane_int(rn.dtype))
            or (dtype_is_float(rn.dtype) and dtype_is_lane_int(ln.dtype))
        ):
            self.an.report(
                "float-contamination", self.mod, node,
                f"float operand meets integer lane "
                f"({ln.dtype} vs {rn.dtype}) in a limb kernel",
            )
        a, b = ln.ivl, rn.ivl
        if isinstance(op, ast.Add):
            ivl = a.add(b)
        elif isinstance(op, ast.Sub):
            ivl = a.sub(b)
        elif isinstance(op, ast.Mult):
            ivl = a.mul(b)
        elif isinstance(op, ast.LShift):
            ivl = a.lshift(b)
        elif isinstance(op, ast.RShift):
            ivl = a.rshift(b)
        elif isinstance(op, ast.BitAnd):
            ivl = a.and_(b)
        elif isinstance(op, ast.BitOr):
            ivl = a.or_(b)
        elif isinstance(op, ast.BitXor):
            ivl = a.xor(b)
        elif isinstance(op, ast.Mod):
            ivl = a.mod(b)
        elif isinstance(op, ast.FloorDiv):
            ivl = a.floordiv(b)
        elif isinstance(op, ast.Pow):
            ca, cb = a.const(), b.const()
            if ca is not None and cb is not None and 0 <= cb <= 512 and abs(ca) <= 2:
                ivl = Interval(ca ** cb, ca ** cb) if ca >= 0 else TOP_IVL
            else:
                ivl = TOP_IVL
        else:
            ivl = TOP_IVL
        if dtype_is_float(dt):
            return Num(TOP_IVL, dt)
        out = Num(ivl, dt)
        if isinstance(op, (ast.Add, ast.Sub, ast.Mult, ast.LShift)):
            self._overflow_check(out, node)
            out = self._clamp(out)
        return out

    def eval_BinOp(self, node) -> AbsVal:
        return self.binop(node.op, self.eval(node.left),
                          self.eval(node.right), node)

    # -- attribute / subscript -------------------------------------------
    def eval_Attribute(self, node) -> AbsVal:
        base = self.eval(node.value)
        name = node.attr
        if isinstance(base, ModVal):
            if base.intrinsic is not None:
                return intrinsic_attr(base.intrinsic, name)
            m = base.modinfo
            envm = self.an.module_env(m)
            if name in envm:
                return envm[name]
            if name in m.functions:
                return FuncVal(m, m.functions[name], name)
            if name in m.classes:
                return ClassVal(m, m.classes[name])
            sub = self.an.resolve_module(m.name + "." + name)
            if sub is not None:
                return ModVal(modinfo=sub)
            return UNKNOWN
        if isinstance(base, InstanceVal):
            if base.contract == "montctx":
                meth = _montctx_method(name)
                if meth is not None:
                    return IntrinsicVal("montctx." + name, meth)
                return _montctx_attr(name)
            if name in base.attrs:
                return base.attrs[name]
            if base.clsval is not None:
                fn = _class_method(base.clsval, name)
                if fn is not None:
                    return FuncVal(
                        base.clsval.mod, fn, base.cls_name + "." + name,
                        selfval=base,
                    )
            return UNKNOWN
        if isinstance(base, ClassVal):
            fn = _class_method(base, name)
            if fn is not None:
                static = any(
                    _dotted(d) == "staticmethod" for d in fn.decorator_list
                )
                cm = any(
                    _dotted(d) == "classmethod" for d in fn.decorator_list
                )
                if static:
                    return FuncVal(base.mod, fn, base.node.name + "." + name)
                if cm:
                    return FuncVal(
                        base.mod, fn, base.node.name + "." + name,
                        selfval=base,
                    )
                return FuncVal(base.mod, fn, base.node.name + "." + name)
            return UNKNOWN
        if isinstance(base, NamedTupleVal):
            if name in base.fields:
                return base.getitem(base.fields[name])
        if isinstance(base, Num):
            if name in _NUM_METHODS:
                return MethodVal(name, base)
            if name == "shape":
                return SeqVal(
                    items=None, elem=Num(Interval(0, None), "pyint")
                )
            if name == "ndim":
                return Num(Interval(0, 32), "pyint")
            if name == "at":
                return MethodVal("at", base)
            if name == "T":
                return base
            return UNKNOWN
        if isinstance(base, SeqVal):
            if name in _SEQ_METHODS:
                return MethodVal(name, base)
            return UNKNOWN
        if isinstance(base, DictVal):
            if name in _DICT_METHODS:
                return MethodVal(name, base)
            return UNKNOWN
        if isinstance(base, MethodVal) and base.name == "at_indexed":
            if name in ("set", "add", "multiply", "min", "max", "get"):
                return MethodVal("at_" + name, base.recv)
        if isinstance(base, ConstVal):
            return UNKNOWN
        return UNKNOWN

    def eval_Subscript(self, node) -> AbsVal:
        base = self.eval(node.value)
        if isinstance(base, MethodVal) and base.name == "at":
            self.eval(node.slice)
            return MethodVal("at_indexed", base.recv)
        idx = self.eval(node.slice)
        if isinstance(base, SeqVal):
            if isinstance(node.slice, ast.Slice):
                if base.items is not None:
                    lo = node.slice.lower
                    hi = node.slice.upper
                    step = node.slice.step
                    lo_c = self._const_or_none(lo)
                    hi_c = self._const_or_none(hi)
                    st_c = self._const_or_none(step) if step else 1
                    if (
                        (lo is None or lo_c is not None)
                        and (hi is None or hi_c is not None)
                        and st_c in (1, -1, 2, None)
                    ):
                        try:
                            return SeqVal(
                                items=base.items[lo_c:hi_c:st_c or 1]
                            )
                        except Exception:
                            pass
                return SeqVal(items=None, elem=base.summary())
            if isinstance(idx, Num):
                return base.getitem(idx.const())
            return base.summary()
        if isinstance(base, Num):
            return base  # array indexing/slicing preserves lane bounds
        if isinstance(base, DictVal):
            return base.vals
        return UNKNOWN

    def _const_or_none(self, node) -> Optional[int]:
        if node is None:
            return None
        v = self.eval(node)
        return v.const() if isinstance(v, Num) else None

    # -- calls ------------------------------------------------------------
    def eval_Call(self, node) -> AbsVal:
        fv = self.eval(node.func)
        args: List[AbsVal] = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                sv = self.eval(a.value)
                if isinstance(sv, SeqVal) and sv.items is not None:
                    args.extend(sv.items)
                else:
                    args.append(UNKNOWN)
            else:
                args.append(self.eval(a))
        kwargs: Dict[str, AbsVal] = {}
        for kw in node.keywords:
            if kw.arg is not None:
                kwargs[kw.arg] = self.eval(kw.value)
            else:
                self.eval(kw.value)
        return self.dispatch_call(fv, args, kwargs, node)

    def dispatch_call(self, fv, args, kwargs, node) -> AbsVal:
        if isinstance(fv, IntrinsicVal):
            try:
                return fv.handler(args, kwargs, self, node)
            except (_Budget, _BreakSig, _ContinueSig):
                raise
            except Exception:
                return UNKNOWN
        if isinstance(fv, DtypeVal):
            return self.cast(args[0] if args else UNKNOWN, fv.name, node)
        if isinstance(fv, FuncVal):
            return self.an.call_function(fv, args, kwargs, self.depth,
                                         self.budget)
        if isinstance(fv, ClassVal):
            return self.instantiate(fv, args, kwargs, node)
        if isinstance(fv, MethodVal):
            return self.call_method(fv, args, kwargs, node)
        return UNKNOWN

    def instantiate(self, cv: ClassVal, args, kwargs, node) -> AbsVal:
        cname = cv.node.name
        if cname == "MontCtx":
            return InstanceVal("MontCtx", contract="montctx", clsval=cv)
        base_names = {_dotted(b) for b in cv.node.bases}
        base_leaves = {
            (b or "").rsplit(".", 1)[-1] for b in base_names if b
        }
        if "NamedTuple" in base_leaves:
            fields: Dict[str, int] = {}
            defaults: Dict[str, AbsVal] = {}
            for stmt in cv.node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    fields[stmt.target.id] = len(fields)
                    if stmt.value is not None:
                        defaults[stmt.target.id] = self.eval(stmt.value)
            items: List[AbsVal] = [UNKNOWN] * len(fields)
            for name, i in fields.items():
                if i < len(args):
                    items[i] = args[i]
                elif name in kwargs:
                    items[i] = kwargs[name]
                elif name in defaults:
                    items[i] = defaults[name]
            return NamedTupleVal(items, fields)
        if "Exception" in base_leaves or cname.endswith("Error"):
            return UNKNOWN
        inst = InstanceVal(cname, clsval=cv)
        init = _class_method(cv, "__init__")
        if init is not None:
            self.an.call_function(
                FuncVal(cv.mod, init, cname + ".__init__", selfval=inst),
                args, kwargs, self.depth, self.budget,
            )
        return inst

    def cast(self, v: AbsVal, dtype: str, node: ast.AST) -> AbsVal:
        vn = v if isinstance(v, Num) else numify(v)
        if not isinstance(vn, Num):
            lo, hi, isf = DTYPES[dtype]
            return Num(TOP_IVL if isf else Interval(lo, hi), dtype)
        lo, hi, isf = DTYPES[dtype]
        if isf:
            return Num(TOP_IVL, dtype)
        if vn.ivl.is_top or vn.ivl.lo is None or vn.ivl.hi is None:
            return Num(Interval(lo, hi), dtype)
        if vn.ivl.within(lo, hi):
            return Num(vn.ivl, dtype)
        if self.check and not dtype_is_float(vn.dtype):
            self.an.report(
                "dtype-narrowing", self.mod, node,
                f"cast of value in {vn.ivl} to {dtype} "
                f"[{lo}, {hi}] can truncate",
            )
        return Num(Interval(lo, hi), dtype)

    def call_method(self, m: MethodVal, args, kwargs, node) -> AbsVal:
        name, recv = m.name, m.recv
        if isinstance(recv, Num):
            if name == "astype":
                dt = as_dtype(args[0]) if args else None
                if dt is None:
                    return Num(TOP_IVL, None)
                return self.cast(recv, dt, node)
            if name in ("reshape", "copy", "transpose", "ravel", "flatten",
                        "squeeze", "swapaxes", "view", "block_until_ready"):
                return recv
            if name in ("sum", "prod", "cumsum", "dot"):
                return Num(TOP_IVL, recv.dtype)
            if name in ("min", "max", "mean"):
                return recv if name != "mean" else Num(TOP_IVL, "float32")
            if name in ("all", "any"):
                return num_bool()
            if name == "tolist":
                return SeqVal(items=None, elem=Num(recv.ivl, "pyint"))
            if name == "item":
                return Num(recv.ivl, "pyint")
            if name == "bit_length":
                return Num(Interval(0, 520), "pyint")
            if name == "tobytes":
                return UNKNOWN
            if name == "at_set":
                v = numify(args[0]) if args else UNKNOWN
                if isinstance(v, Num):
                    return join(recv, Num(v.ivl, recv.dtype))
                return recv
            if name in ("at_add", "at_multiply", "at_min", "at_max"):
                v = numify(args[0]) if args else UNKNOWN
                if isinstance(v, Num):
                    opn = {"at_add": ast.Add, "at_multiply": ast.Mult,
                           "at_min": ast.Add, "at_max": ast.Add}[name]()
                    return join(recv, self.binop(opn, recv, v, node))
                return recv
            if name == "at_get":
                return recv
            return UNKNOWN
        if isinstance(recv, SeqVal):
            if name == "append":
                v = args[0] if args else UNKNOWN
                if recv.items is not None and len(recv.items) < 4096:
                    recv.items.append(v)
                else:
                    recv.items = None
                    recv.elem = join(recv.elem, v)
                return NONE
            if name == "extend":
                v = args[0] if args else UNKNOWN
                if (
                    isinstance(v, SeqVal)
                    and v.items is not None
                    and recv.items is not None
                    and len(recv.items) + len(v.items) <= 4096
                ):
                    recv.items.extend(v.items)
                else:
                    s = v.summary() if isinstance(v, SeqVal) else UNKNOWN
                    recv.elem = join(join(recv.summary(), s), recv.elem)
                    recv.items = None
                return NONE
            if name == "insert":
                if recv.items is not None and len(args) >= 2:
                    recv.items.insert(0, args[1])
                return NONE
            if name == "pop":
                if recv.items is not None and recv.items:
                    return recv.items.pop()
                return recv.summary()
            if name in ("sort", "reverse", "clear"):
                if name == "clear" and recv.items is not None:
                    recv.items.clear()
                return NONE
            if name == "copy":
                if recv.items is not None:
                    return SeqVal(items=list(recv.items))
                return SeqVal(items=None, elem=recv.elem)
            if name in ("count", "index"):
                return Num(Interval(0, None), "pyint")
            return UNKNOWN
        if isinstance(recv, DictVal):
            if name == "get":
                default = args[1] if len(args) > 1 else NONE
                return join(recv.vals, default)
            if name == "setdefault":
                if len(args) > 1:
                    recv.vals = join(recv.vals, args[1])
                return recv.vals
            if name in ("items",):
                return SeqVal(items=None, elem=SeqVal(
                    items=[UNKNOWN, recv.vals]
                ))
            if name in ("keys",):
                return SeqVal(items=None, elem=UNKNOWN)
            if name in ("values",):
                return SeqVal(items=None, elem=recv.vals)
            if name == "update":
                if args and isinstance(args[0], DictVal):
                    recv.vals = join(recv.vals, args[0].vals)
                return NONE
            if name == "pop":
                return join(recv.vals, args[1] if len(args) > 1 else NONE)
            if name == "clear":
                return NONE
            return UNKNOWN
        return UNKNOWN

    # -- comprehensions ---------------------------------------------------
    def eval_ListComp(self, node) -> AbsVal:
        return self._comp(node.generators, lambda: self.eval(node.elt))

    def eval_GeneratorExp(self, node) -> AbsVal:
        return self._comp(node.generators, lambda: self.eval(node.elt))

    def eval_SetComp(self, node) -> AbsVal:
        out = self._comp(node.generators, lambda: self.eval(node.elt))
        if isinstance(out, SeqVal):
            return SeqVal(items=None, elem=out.summary())
        return out

    def eval_DictComp(self, node) -> AbsVal:
        out = self._comp(node.generators, lambda: self.eval(node.value))
        if isinstance(out, SeqVal):
            return DictVal(vals=out.summary())
        return DictVal()

    def _comp(self, generators, eval_elt) -> AbsVal:
        saved = dict(self.env)
        try:
            items = self._comp_rec(list(generators), eval_elt, 0)
        finally:
            self.env = saved
        return items

    def _comp_rec(self, gens, eval_elt, gi) -> AbsVal:
        if gi >= len(gens):
            return SeqVal(items=[eval_elt()])
        gen = gens[gi]
        it = self.eval(gen.iter)
        items = self.concrete_items(it)
        if items is None:
            self.assign(gen.target, self.loop_elem(it))
            for cond in gen.ifs:
                self.eval(cond)
            inner = self._comp_rec(gens, eval_elt, gi + 1)
            elem = inner.summary() if isinstance(inner, SeqVal) else UNKNOWN
            return SeqVal(items=None, elem=elem)
        out: List[AbsVal] = []
        for v in items:
            self.assign(gen.target, v)
            keep = True
            for cond in gen.ifs:
                t = truth(self.eval(cond))
                if t is False:
                    keep = False
                    break
            if not keep:
                continue
            inner = self._comp_rec(gens, eval_elt, gi + 1)
            if isinstance(inner, SeqVal) and inner.items is not None:
                out.extend(inner.items)
                if len(out) > 4096:
                    return SeqVal(items=None, elem=_join_all(out))
            else:
                s = inner.summary() if isinstance(inner, SeqVal) else UNKNOWN
                return SeqVal(items=None, elem=join(_join_all(out), s))
        return SeqVal(items=out)

    def eval_Await(self, node) -> AbsVal:
        return self.eval(node.value)

    def eval_Yield(self, node) -> AbsVal:
        if node.value is not None:
            self.eval(node.value)
        return UNKNOWN

    def eval_YieldFrom(self, node) -> AbsVal:
        self.eval(node.value)
        return UNKNOWN


def _join_all(vals: Sequence[AbsVal]) -> AbsVal:
    out: Optional[AbsVal] = None
    for v in vals:
        out = v if out is None else join(out, v)
    return out if out is not None else UNKNOWN


def _class_method(cv: ClassVal, name: str):
    for stmt in cv.node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if stmt.name == name:
                return stmt
    return None


_NUM_METHODS = {
    "astype", "reshape", "copy", "transpose", "ravel", "flatten", "squeeze",
    "swapaxes", "view", "sum", "prod", "cumsum", "dot", "min", "max", "mean",
    "all", "any", "tolist", "item", "bit_length", "tobytes",
    "block_until_ready",
}
_SEQ_METHODS = {
    "append", "extend", "insert", "pop", "sort", "reverse", "clear", "copy",
    "count", "index",
}
_DICT_METHODS = {
    "get", "setdefault", "items", "keys", "values", "update", "pop", "clear",
}


# --------------------------------------------------------------------------
# Intrinsics: numpy / jax.numpy / jax.lax / builtins
# --------------------------------------------------------------------------

_DTYPE_NAMES = {
    "uint8", "uint16", "uint32", "uint64", "int8", "int16", "int32",
    "int64", "float16", "float32", "float64", "bool_",
}


def _kw_dtype(kwargs: Dict[str, AbsVal]) -> Optional[str]:
    if "dtype" in kwargs:
        return as_dtype(kwargs["dtype"])
    return None


def _h_cast(dtype: str):
    def handler(args, kwargs, interp, node):
        return interp.cast(args[0] if args else UNKNOWN, dtype, node)
    return handler


def _h_fill(value_of):
    def handler(args, kwargs, interp, node):
        dt = _kw_dtype(kwargs) or "float32"
        ivl = value_of(args, interp)
        return Num(ivl, dt)
    return handler


def _h_like(value_of):
    def handler(args, kwargs, interp, node):
        src = numify(args[0]) if args else UNKNOWN
        dt = _kw_dtype(kwargs) or (
            src.dtype if isinstance(src, Num) else None
        )
        return Num(value_of(args, interp), dt)
    return handler


def _h_passthrough(args, kwargs, interp, node):
    return numify(args[0]) if args else UNKNOWN


def _h_asarray(args, kwargs, interp, node):
    v = args[0] if args else UNKNOWN
    dt = _kw_dtype(kwargs)
    if dt is not None:
        return interp.cast(v, dt, node)
    vn = numify(v)
    return vn if isinstance(vn, Num) else Num(TOP_IVL, None)


def _h_where(args, kwargs, interp, node):
    if len(args) >= 3:
        a = numify(args[1]) if not isinstance(args[1], Num) else args[1]
        b = numify(args[2]) if not isinstance(args[2], Num) else args[2]
        if isinstance(a, Num) and isinstance(b, Num):
            return join(a, b)
        return join(args[1], args[2])
    return UNKNOWN


def _h_join_elems(args, kwargs, interp, node):
    v = args[0] if args else UNKNOWN
    if isinstance(v, SeqVal):
        return numify(v)
    if isinstance(v, Num):
        return v
    return UNKNOWN


def _h_arange(args, kwargs, interp, node):
    dt = _kw_dtype(kwargs) or "int32"
    if args:
        n = args[-1] if len(args) <= 1 else args[1]
        if isinstance(n, Num) and n.ivl.hi is not None:
            return Num(Interval(0, max(0, n.ivl.hi - 1)), dt)
    return Num(Interval(0, None), dt)


def _h_clip(args, kwargs, interp, node):
    if len(args) >= 3:
        a, lo, hi = (numify(x) if not isinstance(x, Num) else x for x in args[:3])
        if isinstance(a, Num):
            lo_b = lo.ivl.lo if isinstance(lo, Num) else None
            hi_b = hi.ivl.hi if isinstance(hi, Num) else None
            new_lo = a.ivl.lo if lo_b is None else (
                lo_b if a.ivl.lo is None else max(a.ivl.lo, lo_b)
            )
            new_hi = a.ivl.hi if hi_b is None else (
                hi_b if a.ivl.hi is None else min(a.ivl.hi, hi_b)
            )
            return Num(Interval(new_lo, new_hi), a.dtype)
    return _h_passthrough(args, kwargs, interp, node)


def _h_minmax(is_min: bool):
    def handler(args, kwargs, interp, node):
        nums = [numify(a) if not isinstance(a, Num) else a for a in args]
        nums = [n for n in nums if isinstance(n, Num)]
        if len(nums) == 2:
            a, b = nums
            if is_min:
                ivl = Interval(
                    None if a.ivl.lo is None or b.ivl.lo is None
                    else min(a.ivl.lo, b.ivl.lo),
                    None if a.ivl.hi is None and b.ivl.hi is None
                    else min(
                        x for x in (a.ivl.hi, b.ivl.hi) if x is not None
                    ),
                )
            else:
                ivl = Interval(
                    None if a.ivl.lo is None and b.ivl.lo is None
                    else max(
                        x for x in (a.ivl.lo, b.ivl.lo) if x is not None
                    ),
                    None if a.ivl.hi is None or b.ivl.hi is None
                    else max(a.ivl.hi, b.ivl.hi),
                )
            return Num(ivl, promote(a.dtype, b.dtype))
        if len(nums) == 1:
            return nums[0]
        return UNKNOWN
    return handler


def _h_reduce_same_dtype(args, kwargs, interp, node):
    v = numify(args[0]) if args else UNKNOWN
    if isinstance(v, Num):
        return Num(TOP_IVL, v.dtype)
    return UNKNOWN


def _h_bool_out(args, kwargs, interp, node):
    return num_bool()


def _h_einsum(args, kwargs, interp, node):
    dts = [a.dtype for a in (numify(x) for x in args[1:])
           if isinstance(a, Num)]
    dt = None
    for d in dts:
        dt = d if dt is None else promote(dt, d)
    return Num(TOP_IVL, dt)


def _h_unknown(args, kwargs, interp, node):
    return UNKNOWN


_NUMPY_FUNCS = {
    "asarray": _h_asarray,
    "array": _h_asarray,
    "ascontiguousarray": _h_asarray,
    "zeros": _h_fill(lambda a, i: Interval(0, 0)),
    "ones": _h_fill(lambda a, i: Interval(1, 1)),
    "empty": _h_fill(lambda a, i: TOP_IVL),
    "zeros_like": _h_like(lambda a, i: Interval(0, 0)),
    "ones_like": _h_like(lambda a, i: Interval(1, 1)),
    "full": _h_fill(
        lambda a, i: (
            a[1].ivl
            if len(a) > 1 and isinstance(a[1], Num)
            else TOP_IVL
        )
    ),
    "full_like": _h_like(
        lambda a, i: (
            a[1].ivl
            if len(a) > 1 and isinstance(a[1], Num)
            else TOP_IVL
        )
    ),
    "where": _h_where,
    "stack": _h_join_elems,
    "concatenate": _h_join_elems,
    "hstack": _h_join_elems,
    "vstack": _h_join_elems,
    "broadcast_to": _h_passthrough,
    "tile": _h_passthrough,
    "repeat": _h_passthrough,
    "moveaxis": _h_passthrough,
    "reshape": _h_passthrough,
    "transpose": _h_passthrough,
    "squeeze": _h_passthrough,
    "expand_dims": _h_passthrough,
    "ravel": _h_passthrough,
    "flip": _h_passthrough,
    "take": _h_passthrough,
    "arange": _h_arange,
    "clip": _h_clip,
    "minimum": _h_minmax(True),
    "maximum": _h_minmax(False),
    "sum": _h_reduce_same_dtype,
    "prod": _h_reduce_same_dtype,
    "cumsum": _h_reduce_same_dtype,
    "einsum": _h_einsum,
    "any": _h_bool_out,
    "all": _h_bool_out,
    "array_equal": _h_bool_out,
    "frombuffer": lambda a, k, i, n: Num(
        TOP_IVL if _kw_dtype(k) is None else Interval(*DTYPES[_kw_dtype(k)][:2]),
        _kw_dtype(k),
    ),
    "shape": lambda a, k, i, n: SeqVal(
        items=None, elem=Num(Interval(0, None), "pyint")
    ),
    "broadcast_shapes": _h_unknown,
    "dtype": lambda a, k, i, n: (
        DtypeVal(as_dtype(a[0])) if a and as_dtype(a[0]) else UNKNOWN
    ),
}


def _h_fori_loop(args, kwargs, interp, node):
    if len(args) < 4:
        return UNKNOWN
    lo_v, hi_v, body, init = args[0], args[1], args[2], args[3]
    lo = lo_v.const() if isinstance(lo_v, Num) else None
    hi = hi_v.const() if isinstance(hi_v, Num) else None
    carry = init
    if (
        lo is not None and hi is not None and 0 <= hi - lo <= MAX_UNROLL
        and isinstance(body, FuncVal)
    ):
        for i in range(lo, hi):
            carry = interp.dispatch_call(
                body, [num_const(i), carry], {}, node
            )
        return carry
    if not isinstance(body, FuncVal):
        return UNKNOWN
    i_num = Num(
        Interval(
            lo_v.ivl.lo if isinstance(lo_v, Num) else None,
            None if not isinstance(hi_v, Num) or hi_v.ivl.hi is None
            else hi_v.ivl.hi - 1,
        ),
        "pyint",
    )
    for it in range(MAX_FIXPOINT):
        out = interp.dispatch_call(body, [i_num, carry], {}, node)
        new = join(carry, out)
        if it >= 2:
            new = widen_val(carry, new)
        if new.key() == carry.key():
            return new
        carry = new
    return UNKNOWN


def _h_scan(args, kwargs, interp, node):
    if len(args) < 2:
        return UNKNOWN
    body, init = args[0], args[1]
    xs = args[2] if len(args) > 2 else kwargs.get("xs", NONE)
    elem: AbsVal
    if isinstance(xs, SeqVal):
        elem = xs.summary()
    elif isinstance(xs, Num):
        elem = xs
    else:
        elem = UNKNOWN
    if not isinstance(body, FuncVal):
        return UNKNOWN
    carry = init
    for it in range(MAX_FIXPOINT):
        out = interp.dispatch_call(body, [carry, elem], {}, node)
        new_c = (
            out.getitem(0)
            if isinstance(out, SeqVal) and out.items is not None
            and len(out.items) == 2
            else UNKNOWN
        )
        new = join(carry, new_c)
        if it >= 2:
            new = widen_val(carry, new)
        if new.key() == carry.key():
            return SeqVal(items=[new, UNKNOWN])
        carry = new
    return SeqVal(items=[UNKNOWN, UNKNOWN])


def _h_while_loop(args, kwargs, interp, node):
    if len(args) < 3:
        return UNKNOWN
    cond, body, init = args[0], args[1], args[2]
    if not isinstance(body, FuncVal):
        return UNKNOWN
    carry = init
    for it in range(MAX_FIXPOINT):
        if isinstance(cond, FuncVal):
            interp.dispatch_call(cond, [carry], {}, node)
        out = interp.dispatch_call(body, [carry], {}, node)
        new = join(carry, out)
        if it >= 2:
            new = widen_val(carry, new)
        if new.key() == carry.key():
            return new
        carry = new
    return UNKNOWN


def _h_switch(args, kwargs, interp, node):
    if len(args) < 2:
        return UNKNOWN
    branches = args[1]
    operands = args[2:]
    outs: List[AbsVal] = []
    if isinstance(branches, SeqVal) and branches.items is not None:
        for b in branches.items:
            if isinstance(b, (FuncVal, IntrinsicVal)):
                outs.append(
                    interp.dispatch_call(b, list(operands), {}, node)
                )
    return _join_all(outs) if outs else UNKNOWN


def _h_cond(args, kwargs, interp, node):
    outs = []
    for b in args[1:3]:
        if isinstance(b, (FuncVal, IntrinsicVal)):
            outs.append(interp.dispatch_call(b, list(args[3:]), {}, node))
    return _join_all(outs) if outs else UNKNOWN


_LAX_FUNCS = {
    "fori_loop": _h_fori_loop,
    "scan": _h_scan,
    "while_loop": _h_while_loop,
    "switch": _h_switch,
    "cond": _h_cond,
    "select": _h_where,
}


def _h_jit(args, kwargs, interp, node):
    return args[0] if args else UNKNOWN


_JAX_FUNCS = {
    "jit": _h_jit,
    "vmap": _h_jit,
    "grad": _h_jit,
    "default_backend": _h_unknown,
    "device_put": _h_passthrough,
    "devices": _h_unknown,
}

_JAXOPS_FUNCS = {
    "segment_max": _h_reduce_same_dtype,
    "segment_min": _h_reduce_same_dtype,
    "segment_sum": _h_reduce_same_dtype,
}


def intrinsic_attr(ns: str, name: str) -> AbsVal:
    if ns == "numpy":
        if name in _DTYPE_NAMES:
            return DtypeVal("bool" if name == "bool_" else name)
        if name in ("pi", "e", "inf", "nan"):
            return Num(TOP_IVL, "pyfloat")
        if name in _NUMPY_FUNCS:
            return IntrinsicVal("np." + name, _NUMPY_FUNCS[name])
        if name == "random":
            return ModVal(intrinsic="opaque")
        return UNKNOWN
    if ns == "jax":
        if name == "numpy":
            return ModVal(intrinsic="numpy")
        if name == "lax":
            return ModVal(intrinsic="lax")
        if name == "ops":
            return ModVal(intrinsic="jaxops")
        if name in _JAX_FUNCS:
            return IntrinsicVal("jax." + name, _JAX_FUNCS[name])
        return UNKNOWN
    if ns == "lax":
        if name in _LAX_FUNCS:
            return IntrinsicVal("lax." + name, _LAX_FUNCS[name])
        return UNKNOWN
    if ns == "jaxops":
        if name in _JAXOPS_FUNCS:
            return IntrinsicVal("jax.ops." + name, _JAXOPS_FUNCS[name])
        return UNKNOWN
    if ns == "math":
        if name in ("inf", "pi", "e", "nan", "tau"):
            return Num(TOP_IVL, "pyfloat")
        return IntrinsicVal(
            "math." + name, lambda a, k, i, n: Num(TOP_IVL, "pyfloat")
        )
    if ns == "functools":
        if name == "partial":
            return IntrinsicVal("functools.partial", _h_partial)
        return UNKNOWN
    return UNKNOWN


def _h_partial(args, kwargs, interp, node):
    # partial(f, ...): keep the callable; pre-bound args are dropped
    # (used here only for jit decorators and map helpers)
    return args[0] if args else UNKNOWN


# -- python builtins --------------------------------------------------------


def _h_range(args, kwargs, interp, node):
    nums = [a if isinstance(a, Num) else Num(TOP_IVL, "pyint") for a in args]
    if len(nums) == 1:
        return RangeVal(num_const(0), nums[0])
    if len(nums) >= 2:
        step = 1
        if len(nums) >= 3:
            c = nums[2].const()
            step = c if c in (1, -1) else 0
        return RangeVal(nums[0], nums[1], step if step else 1)
    return RangeVal(num_const(0), Num(TOP_IVL, "pyint"))


def _h_len(args, kwargs, interp, node):
    v = args[0] if args else UNKNOWN
    if isinstance(v, SeqVal) and v.items is not None:
        return num_const(len(v.items))
    if isinstance(v, ConstVal) and isinstance(v.value, (str, bytes)):
        return num_const(len(v.value))
    return Num(Interval(0, None), "pyint")


def _h_int(args, kwargs, interp, node):
    v = numify(args[0]) if args else num_const(0)
    if isinstance(v, Num) and not dtype_is_float(v.dtype):
        return Num(v.ivl, "pyint")
    return Num(TOP_IVL, "pyint")


def _h_zip(args, kwargs, interp, node):
    seqs = [a for a in args]
    known = []
    for s in seqs:
        items = interp.concrete_items(s)
        if items is None:
            elems = [interp.loop_elem(s) for s in seqs]
            return SeqVal(items=None, elem=SeqVal(items=elems, mutable=False))
        known.append(items)
    n = min((len(k) for k in known), default=0)
    return SeqVal(
        items=[
            SeqVal(items=[k[i] for k in known], mutable=False)
            for i in range(n)
        ]
    )


def _h_enumerate(args, kwargs, interp, node):
    v = args[0] if args else UNKNOWN
    items = interp.concrete_items(v)
    if items is not None:
        return SeqVal(
            items=[
                SeqVal(items=[num_const(i), x], mutable=False)
                for i, x in enumerate(items)
            ]
        )
    return SeqVal(
        items=None,
        elem=SeqVal(
            items=[Num(Interval(0, None), "pyint"), interp.loop_elem(v)],
            mutable=False,
        ),
    )


def _h_list(args, kwargs, interp, node):
    if not args:
        return SeqVal(items=[])
    v = args[0]
    items = interp.concrete_items(v)
    if items is not None:
        return SeqVal(items=list(items))
    if isinstance(v, SeqVal):
        return SeqVal(items=None, elem=v.summary())
    if isinstance(v, RangeVal):
        return SeqVal(items=None, elem=interp.loop_elem(v))
    return SeqVal(items=None, elem=UNKNOWN)


def _h_tuple(args, kwargs, interp, node):
    out = _h_list(args, kwargs, interp, node)
    if isinstance(out, SeqVal):
        out.mutable = False
    return out


def _h_divmod(args, kwargs, interp, node):
    if len(args) == 2 and all(isinstance(a, Num) for a in args):
        a, b = args
        q = Num(a.ivl.floordiv(b.ivl), promote(a.dtype, b.dtype))
        r = Num(a.ivl.mod(b.ivl), promote(a.dtype, b.dtype))
        return SeqVal(items=[q, r], mutable=False)
    return SeqVal(items=[UNKNOWN, UNKNOWN], mutable=False)


def _h_abs(args, kwargs, interp, node):
    v = numify(args[0]) if args else UNKNOWN
    if isinstance(v, Num) and v.ivl.lo is not None and v.ivl.hi is not None:
        cands = [abs(v.ivl.lo), abs(v.ivl.hi)]
        lo = 0 if v.ivl.lo <= 0 <= v.ivl.hi else min(cands)
        return Num(Interval(lo, max(cands)), v.dtype)
    return v if isinstance(v, Num) else UNKNOWN


def _h_pow(args, kwargs, interp, node):
    return Num(TOP_IVL, "pyint")


def _h_sum_builtin(args, kwargs, interp, node):
    v = args[0] if args else UNKNOWN
    if isinstance(v, SeqVal):
        s = numify(v)
        if isinstance(s, Num):
            return Num(TOP_IVL, s.dtype)
    return UNKNOWN


_BUILTINS: Dict[str, AbsVal] = {}


def _register_builtins() -> None:
    table = {
        "range": _h_range,
        "len": _h_len,
        "int": _h_int,
        "float": lambda a, k, i, n: Num(TOP_IVL, "pyfloat"),
        "bool": lambda a, k, i, n: num_bool(),
        "abs": _h_abs,
        "min": _h_minmax(True),
        "max": _h_minmax(False),
        "sum": _h_sum_builtin,
        "divmod": _h_divmod,
        "pow": _h_pow,
        "zip": _h_zip,
        "enumerate": _h_enumerate,
        "list": _h_list,
        "tuple": _h_tuple,
        "set": lambda a, k, i, n: SeqVal(items=None, elem=UNKNOWN),
        "dict": lambda a, k, i, n: DictVal(),
        "sorted": _h_list,
        "reversed": _h_list,
        "isinstance": lambda a, k, i, n: num_bool(),
        "issubclass": lambda a, k, i, n: num_bool(),
        "callable": lambda a, k, i, n: num_bool(),
        "hasattr": lambda a, k, i, n: num_bool(),
        "getattr": lambda a, k, i, n: UNKNOWN,
        "setattr": lambda a, k, i, n: NONE,
        "print": lambda a, k, i, n: NONE,
        "repr": lambda a, k, i, n: ConstVal(""),
        "str": lambda a, k, i, n: ConstVal(""),
        "bytes": lambda a, k, i, n: UNKNOWN,
        "bytearray": lambda a, k, i, n: UNKNOWN,
        "id": lambda a, k, i, n: Num(Interval(0, None), "pyint"),
        "hash": lambda a, k, i, n: Num(TOP_IVL, "pyint"),
        "any": lambda a, k, i, n: num_bool(),
        "all": lambda a, k, i, n: num_bool(),
        "iter": _h_list,
        "next": lambda a, k, i, n: (
            a[0].summary() if a and isinstance(a[0], SeqVal) else UNKNOWN
        ),
        "map": lambda a, k, i, n: SeqVal(items=None, elem=UNKNOWN),
        "filter": lambda a, k, i, n: (
            a[1] if len(a) > 1 else SeqVal(items=None, elem=UNKNOWN)
        ),
        "object": lambda a, k, i, n: UNKNOWN,
        "super": lambda a, k, i, n: UNKNOWN,
        "vars": lambda a, k, i, n: DictVal(),
        "globals": lambda a, k, i, n: DictVal(),
    }
    for name, h in table.items():
        _BUILTINS[name] = IntrinsicVal(name, h)


_register_builtins()


def builtin_value(name: str) -> AbsVal:
    if name in _BUILTINS:
        return _BUILTINS[name]
    if name in ("True", "False"):
        return num_bool(name == "True")
    if name == "None":
        return NONE
    if name.endswith("Error") or name in (
        "Exception", "BaseException", "KeyboardInterrupt", "StopIteration",
        "ArithmeticError", "Warning",
    ):
        return IntrinsicVal(name, _h_unknown)
    return UNKNOWN


# --------------------------------------------------------------------------
# const-drift: pure AST pass over the limb tier
# --------------------------------------------------------------------------


def check_const_drift(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    """A re-hardcoded 13/20/0x1fff/8192/260 in an arithmetic context.
    Only contexts where the literal plays the limb-constant role fire:
    shift amounts, mask operands, modulus/divmod bases, range() trip
    counts and 2**13 powers — `table[13]` as data stays quiet."""
    findings: List[Finding] = []
    if not ctx.matches(LIMB_TIER):
        return findings

    def lit(node) -> Optional[int]:
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool)
            and node.value in DRIFT_CONSTANTS
        ):
            return node.value
        return None

    def hit(node: ast.AST, value: int, role: str) -> None:
        findings.append(
            Finding(
                "const-drift", ctx.path, node.lineno, node.col_offset,
                f"hardcoded {value} as {role}; import "
                f"{DRIFT_CONSTANTS[value]} from fabric_tpu.ops.bignum "
                f"(fabric_tpu.common re-exports)",
            )
        )

    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, (ast.LShift, ast.RShift)):
                v = lit(node.right)
                if v is not None:
                    hit(node.right, v, "a shift amount")
            if isinstance(node.op, ast.BitAnd):
                for side in (node.left, node.right):
                    v = lit(side)
                    if v is not None and v in (8191,):
                        hit(side, v, "a limb mask")
            if isinstance(node.op, (ast.Mod, ast.FloorDiv)):
                v = lit(node.right)
                if v is not None and v in (8192, 8191):
                    hit(node.right, v, "a limb modulus")
            if isinstance(node.op, ast.Pow):
                base = node.left
                v = lit(node.right)
                if (
                    v == 13
                    and isinstance(base, ast.Constant)
                    and base.value == 2
                ):
                    hit(node.right, v, "2**13 (the limb radix)")
        elif isinstance(node, ast.Call):
            dn = _dotted(node.func)
            if dn == "range" and len(node.args) == 1:
                v = lit(node.args[0])
                if v in (13, 20, 260):
                    hit(node.args[0], v, "a limb-loop trip count")
            elif dn == "divmod" and len(node.args) == 2:
                v = lit(node.args[1])
                if v is not None:
                    hit(node.args[1], v, "a divmod base")
    return findings


# --------------------------------------------------------------------------
# mask-fail-open: pure AST pass over the mask tier
# --------------------------------------------------------------------------


def _code_member(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """'VALID' for TxValidationCode.VALID / a module-level alias of it."""
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        if base is not None and base.rsplit(".", 1)[-1] == "TxValidationCode":
            return node.attr
    if isinstance(node, ast.Name) and node.id in aliases:
        return aliases[node.id]
    return None


def _is_code_write(node: ast.AST, aliases: Dict[str, str]):
    """(member_or_None, is_write) for flag writes: x.code = M,
    flags[i] = M, set_flag(i, M), return M."""
    if isinstance(node, ast.Assign):
        member = _code_member(node.value, aliases)
        for t in node.targets:
            tn = None
            if isinstance(t, ast.Attribute):
                tn = t.attr
            elif isinstance(t, ast.Name):
                tn = t.id
            elif isinstance(t, ast.Subscript):
                tn = _dotted(t.value) or ""
                tn = tn.rsplit(".", 1)[-1]
            if tn is not None and (
                "code" in tn.lower() or "flag" in tn.lower()
            ):
                return member, True
        if member is not None:
            return member, True
        return None, False
    if isinstance(node, ast.Call):
        dn = _dotted(node.func)
        if dn is not None and dn.rsplit(".", 1)[-1] == "set_flag":
            if len(node.args) >= 2:
                return _code_member(node.args[1], aliases), True
        return None, False
    return None, False


def _function_nodes(fn: ast.AST, stop_nested: bool = True):
    """Walk a function's own body, not nested defs'."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if stop_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_flag_producing(fn: ast.AST, aliases: Dict[str, str]) -> bool:
    for node in _function_nodes(fn):
        if isinstance(node, ast.Name) and (
            node.id == "TxValidationCode"
            or node.id in aliases
            or node.id == "flags"  # the ValidationFlags result threading
            # boolean verdict masks (the serve plane's currency): a
            # function that BINDS a mask/verdicts name produces lane
            # verdicts, so its exception discipline is mask-load-bearing
            # even though no TxValidationCode appears (the sidecar
            # client/server trade raw bool masks; flags come later)
            or (
                isinstance(node.ctx, ast.Store)
                and node.id in ("mask", "verdicts", "ok_list")
            )
        ):
            return True
        if isinstance(node, ast.Attribute) and node.attr == "set_flag":
            return True
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Attribute) and t.attr == "code"
            for t in node.targets
        ):
            return True
    return False


def _stmt_accepts(
    stmt: ast.stmt, aliases: Dict[str, str], exc_name: Optional[str]
) -> bool:
    """One statement that, when reached, closes the failure path:
    raise, an INVALID-family code write, an accepting return, or a call
    handing the exception object onward."""
    if isinstance(stmt, ast.Raise):
        return True
    for node in ast.walk(stmt):
        member, is_write = _is_code_write(node, aliases)
        if is_write and member is not None and member not in FAIL_OPEN_MEMBERS:
            return True
    if isinstance(stmt, ast.Return):
        v = stmt.value
        member = _code_member(v, aliases) if v is not None else None
        if member is not None and member not in FAIL_OPEN_MEMBERS:
            return True
        if isinstance(v, ast.Constant) and isinstance(v.value, str) and v.value:
            return True  # error-string convention ("why tx is invalid")
        if v is not None and any(
            isinstance(sub, ast.Call) for sub in ast.walk(v)
        ):
            return True  # delegation: return fallback(...)
        return False
    if exc_name is not None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and sub.id == exc_name:
                            return True  # exception handed onward
    return False


def _path_closes(
    stmts: Sequence[ast.stmt], aliases: Dict[str, str],
    exc_name: Optional[str],
) -> bool:
    """EVERY control path through `stmts` must hit an accepting action.
    Path-sensitive on If: a delegation wrapped in `if cb is not None:`
    with no else does NOT close (the exact shape of the pipeline's
    pre-fix silent-drop bug)."""
    compound = (
        ast.If, ast.Try, ast.With, ast.AsyncWith, ast.For, ast.AsyncFor,
        ast.While,
    )
    saw_call_assign = False
    for i, s in enumerate(stmts):
        # compound statements are handled structurally below — walking
        # into them here would credit a GUARDED action to every path
        if not isinstance(s, compound) and _stmt_accepts(
            s, aliases, exc_name
        ):
            return True
        if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
            # out = fallback(...); ... return out  (delegation split
            # across statements: require the return on this same path)
            saw_call_assign = True
        if (
            saw_call_assign
            and isinstance(s, ast.Return)
            and s.value is not None
        ):
            return True
        if isinstance(s, ast.If):
            if _path_closes(s.body, aliases, exc_name) and _path_closes(
                s.orelse, aliases, exc_name
            ):
                return True
        if isinstance(s, ast.Try):
            closing = _path_closes(s.body, aliases, exc_name) or (
                _path_closes(s.orelse, aliases, exc_name)
            )
            if closing and all(
                _path_closes(h.body, aliases, exc_name) for h in s.handlers
            ):
                return True
            if _path_closes(s.finalbody, aliases, exc_name):
                return True
        if isinstance(s, (ast.With, ast.AsyncWith)):
            if _path_closes(s.body, aliases, exc_name):
                return True
    return False


def _handler_fails_closed(
    handler: ast.ExceptHandler, aliases: Dict[str, str]
) -> bool:
    """True when EVERY path through the handler raises, assigns/returns
    an INVALID-family code, returns an error string, delegates to a
    fallback call, or hands the exception object onward."""
    # narrow-typed retry idiom: `except queue.Empty: continue` decides
    # nothing — the loop re-polls.  Only NARROW exception types qualify;
    # `except Exception: continue` would silently skip a transaction.
    types = (
        [_dotted(e) for e in handler.type.elts]
        if isinstance(handler.type, ast.Tuple)
        else [_dotted(handler.type)] if handler.type is not None else [None]
    )
    narrow = all(
        t is not None and t.rsplit(".", 1)[-1] not in (
            "Exception", "BaseException"
        )
        for t in types
    )
    if narrow and all(
        isinstance(s, ast.Continue) for s in handler.body
    ):
        return True
    return _path_closes(handler.body, aliases, handler.name)


def check_mask_fail_open(tree: ast.Module, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    if not ctx.matches(MASK_TIER):
        return findings
    # module-level aliases: NAME = TxValidationCode.MEMBER
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            m = _code_member(node.value, {})
            if isinstance(t, ast.Name) and m is not None:
                aliases[t.id] = m

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _is_flag_producing(fn, aliases):
            continue
        last_stmt = fn.body[-1] if fn.body else None
        for node in _function_nodes(fn):
            if isinstance(node, ast.ExceptHandler):
                # forbidden writes first: VALID / NOT_VALIDATED in a
                # handler fail open or leave the flag unset
                bad = None
                for sub in ast.walk(node):
                    member, is_write = _is_code_write(sub, aliases)
                    if is_write and member in FAIL_OPEN_MEMBERS:
                        bad = (sub, member)
                        break
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        m = _code_member(sub.value, aliases)
                        if m in FAIL_OPEN_MEMBERS:
                            bad = (sub, m)
                            break
                if bad is not None:
                    findings.append(
                        Finding(
                            "mask-fail-open", ctx.path,
                            bad[0].lineno, bad[0].col_offset,
                            f"exception handler in flag-producing "
                            f"{fn.name!r} writes {bad[1]}: a failure path "
                            f"must assign an INVALID-family code",
                        )
                    )
                    continue
                if not _handler_fails_closed(node, aliases):
                    findings.append(
                        Finding(
                            "mask-fail-open", ctx.path,
                            node.lineno, node.col_offset,
                            f"exception handler in flag-producing "
                            f"{fn.name!r} neither raises, assigns an "
                            f"INVALID-family code, delegates, nor "
                            f"propagates the exception — the lane's flag "
                            f"can be left unset (fail-open)",
                        )
                    )
            elif isinstance(node, ast.Return) and node is not last_stmt:
                m = _code_member(node.value, aliases) if node.value else None
                if m == "VALID":
                    findings.append(
                        Finding(
                            "mask-fail-open", ctx.path,
                            node.lineno, node.col_offset,
                            f"early return of VALID from flag-producing "
                            f"{fn.name!r}: VALID may only be assigned at "
                            f"the designated end of code assembly",
                        )
                    )
    return findings


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------


def _build_universe(
    sources: Dict[str, str]
) -> Tuple[Dict[str, ModuleInfo], List[Finding]]:
    universe: Dict[str, ModuleInfo] = {}
    errors: List[Finding] = []
    for path, source in sources.items():
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            errors.append(
                Finding(
                    "syntax-error", path, exc.lineno or 1, exc.offset or 0,
                    f"cannot parse: {exc.msg}",
                )
            )
            continue
        universe[module_name_for(path)] = ModuleInfo(
            module_name_for(path), path, tree, source
        )
    return universe, errors


def analyze_sources(
    sources: Dict[str, str],
    rule_ids: Optional[Iterable[str]] = None,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Analyze a set of {path: source}. Cross-module calls resolve
    within the set; the LIMB/MASK tier path patterns decide which
    analyses run on each file.  ``collect_suppressed`` receives the
    findings per-line suppressions absorbed (fabreg's
    suppression-stale rule)."""
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    for rid in active:
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
    universe, findings = _build_universe(sources)
    suppressions = {
        mod.path: parse_suppressions(mod.source)
        for mod in universe.values()
    }
    an = Analyzer(universe, active, suppressions)

    # pure-AST passes
    ast_findings: List[Finding] = []
    for mod in universe.values():
        ctx = FileContext(mod.path)
        if "const-drift" in active:
            ast_findings.extend(check_const_drift(mod.tree, ctx))
        if "mask-fail-open" in active:
            ast_findings.extend(check_mask_fail_open(mod.tree, ctx))
    suppressed = 0
    for f in ast_findings:
        sup = suppressions.get(f.path, {}).get(f.line)
        if sup is not None and (f.rule in sup[0] or "all" in sup[0]):
            suppressed += 1
            if collect_suppressed is not None:
                collect_suppressed.append(f)
        else:
            findings.append(f)

    # value-range / dtype interpretation over the limb tier
    if active & {"limb-overflow", "dtype-narrowing", "float-contamination"}:
        limb_mods = [
            mod
            for mod in universe.values()
            if FileContext(mod.path).matches(LIMB_TIER)
        ]
        for mod in limb_mods:
            an.module_env(mod)
        for mod in limb_mods:
            for name, fn in mod.functions.items():
                an.analyze_function_standalone(mod, fn, name, None)
            for cname, cls in mod.classes.items():
                cv = ClassVal(mod, cls)
                inst: AbsVal
                if cname == "MontCtx":
                    inst = InstanceVal(cname, contract="montctx", clsval=cv)
                else:
                    inst = InstanceVal(cname, clsval=cv)
                for stmt in cls.body:
                    if isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        static = any(
                            _dotted(d) == "staticmethod"
                            for d in stmt.decorator_list
                        )
                        an.analyze_function_standalone(
                            mod, stmt, f"{cname}.{stmt.name}",
                            None if static else inst,
                        )
        findings.extend(an.findings.values())
        suppressed += an.suppressed
        if collect_suppressed is not None:
            collect_suppressed.extend(an.suppressed_findings)

    findings.sort(key=Finding.key)
    stats = {"files": len(sources), "suppressed": suppressed}
    return findings, stats


def analyze_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], int]:
    """Single-blob convenience (fixtures/tests)."""
    findings, stats = analyze_sources({path: source}, rule_ids)
    return findings, stats["suppressed"]


def analyze_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths, excludes)
    sources, io_findings = toolkit.read_sources(files)
    findings, stats = analyze_sources(sources, rule_ids, collect_suppressed)
    findings.extend(io_findings)
    findings.sort(key=Finding.key)
    stats["files"] = len(files)
    return findings, stats


def suppression_reasons(
    paths: Sequence[str], excludes: Sequence[str] = DEFAULT_EXCLUDES
) -> List[Tuple[str, int, Set[str], str]]:
    """Every fabflow suppression in the tree: (path, line, rules,
    reason).  The self-check test requires a computed bound (a number)
    in every reason."""
    out = []
    for f in iter_py_files(paths, excludes):
        try:
            source = Path(f).read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            continue
        for line, (rules, reason) in parse_suppressions(source).items():
            out.append((f, line, rules, reason))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fabflow",
        "value-range + dtype abstract interpreter for "
        "fabric-tpu (dependency-free; never imports the analyzed code)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=20)
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fabflow", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fabflow")
    if rc:
        return rc

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = analyze_paths(args.paths, rule_ids, excludes)

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fabflow: {len(findings)} finding(s) in {stats['files']} "
            f"file(s) ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
