"""fabreg — declarative-contract drift analyzer for fabric-tpu.

fablint/fabdep/fabflow pin code-level invariants; fabreg pins the
*metadata* layer: the declarative tables the runtime and the gates
trust but nothing statically checks.  Four control surfaces drifted
into existence across PRs 6-10 — scattered ``FABRIC_TPU_*`` env reads,
the canonical metric-family table in ``common/fabobs.py``, the
``fault_point`` site set, and the per-line analyzer suppressions — and
each is exactly the config/registry drift that silently breaks the
"every family live on a scrape" and byte-identical-scorecard
guarantees.  Like its siblings, fabreg is pure ``ast`` + ``tokenize``:
it never imports analyzed code and runs without numpy/jax/cryptography.

Rules
-----
env-undeclared    an ``os.environ``/``os.getenv`` read of a
                  ``FABRIC_TPU_*`` name with no row in the central
                  registry ``fabric_tpu/common/envreg.py``.
env-dead          a registry row with no surviving reference anywhere
                  in the scanned tree (bench.py and tests count as
                  readers — deprecation grace).
metric-unknown    a ``obs_count``/``obs_gauge``/``obs_observe`` emit
                  naming a family absent from ``CANONICAL_METRICS``
                  (the registry swallows it at runtime; the scrape
                  silently loses the series).
metric-label-drift an emit whose label set or sink kind disagrees with
                  the family's declaration.
metric-orphan     a canonical family with no emitter outside fabobs
                  itself (a dead ``# TYPE`` line on every scrape).
fault-site-drift  a ``fault_point(site=...)`` literal missing from the
                  README fault-point table or not exercised by any
                  fabchaos scenario (suppress with a reason to allow a
                  deliberately unexercised site).
suppression-stale a ``# fablint:/fabdep:/fabflow:/fabreg: disable=``
                  comment whose rule no longer fires at that line —
                  fabreg re-runs the owning analyzer scoped to the
                  suppressed rules and requires every comment to still
                  absorb a finding.  Suppressions must not outlive
                  their cause.

The byte-determinism taint rules that used to live here (the
``det-hazard`` rule over chaos scorecards) are fabdet's whole-program
job now — see ``fabric_tpu/tools/fabdet.py`` and ``tools/det.toml``.

Suppression
-----------
Per line, same grammar as the siblings:
``# fabreg: disable=rule-id[,rule-id...]  # <reason>``.  A
``disable=suppression-stale`` comment is never itself reported stale
(the check is one level deep by design).

Usage
-----
    python -m fabric_tpu.tools.fabreg [--json] [--list-rules]
        [--rules a,b] [--readme FILE] PATH...

Exit status: 0 = clean, 1 = findings, 2 = usage/IO error.
"""

from __future__ import annotations

import ast
import io
import json
import re
import sys
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    FileContext,
    Finding,
    iter_py_files,
)

__version__ = "1.0"

# --------------------------------------------------------------------------
# Configuration
# --------------------------------------------------------------------------

RULES: Dict[str, str] = {
    "env-undeclared": (
        "os.environ/os.getenv read of a FABRIC_TPU_* name with no row in "
        "the central registry common/envreg.py"
    ),
    "env-dead": (
        "envreg.py row with no surviving reference in the scanned tree "
        "(bench.py/tests count as readers)"
    ),
    "metric-unknown": (
        "obs_count/obs_gauge/obs_observe emit naming a family absent from "
        "CANONICAL_METRICS (swallowed at runtime, lost on the scrape)"
    ),
    "metric-label-drift": (
        "emit whose label set or sink kind disagrees with the family's "
        "CANONICAL_METRICS declaration"
    ),
    "metric-orphan": (
        "canonical metric family with no emitter outside fabobs itself"
    ),
    "fault-site-drift": (
        "fault_point site literal missing from the README fault-point "
        "table or not exercised by any fabchaos scenario"
    ),
    "suppression-stale": (
        "a fablint/fabdep/fabflow/fabreg disable= comment whose rule no "
        "longer fires at that line"
    ),
}

ENV_PREFIX = "FABRIC_TPU_"
_ENV_NAME_RE = re.compile(r"^FABRIC_TPU_[A-Z0-9_]+$")

#: calls whose string arg is an env *read* (must be declared)
_ENV_READ_CALLS = {
    "os.environ.get", "environ.get",
    "os.getenv", "getenv",
    "os.environ.setdefault", "environ.setdefault",
}
#: env accessors that only *reference* a name (count for liveness)
_ENV_REF_CALLS = {"os.environ.pop", "environ.pop"}
_ENV_REF_LEAVES = {"setenv", "delenv"}  # pytest monkeypatch

#: obs sink -> (declared kind it implies, value-param kwarg to ignore)
_EMIT_SINKS = {
    "obs_count": ("counter", "n"),
    "obs_gauge": ("gauge", "value"),
    "obs_observe": ("histogram", "value"),
}

#: the runtime package scope: metric/fault/suppression discipline
#: applies inside the package; env rules cover everything scanned
#: (tests + bench read env vars too).
PKG_SCOPE = ("*fabric_tpu/*",)
ENVREG_FILE = ("*fabric_tpu/common/envreg.py",)
FABOBS_FILE = ("*fabric_tpu/common/fabobs.py",)
CHAOS_FILE = ("*fabric_tpu/tools/fabchaos.py",)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# --------------------------------------------------------------------------
# Collected facts
# --------------------------------------------------------------------------


@dataclass
class EmitSite:
    family: str
    sink_kind: str          # counter | gauge | histogram (from the sink)
    labels: Optional[Set[str]]  # None when **labels defeats static check
    path: str
    line: int
    col: int


@dataclass
class SuppComment:
    tool: str
    path: str
    line: int
    col: int
    rules: Set[str]
    reason: str


@dataclass
class Scan:
    """Everything one pass over the sources collects; rules evaluate
    against this."""

    sources: Dict[str, str] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)  # syntax errors
    env_reads: List[Tuple[str, str, int, int]] = field(default_factory=list)
    env_refs: Set[str] = field(default_factory=set)
    emits: List[EmitSite] = field(default_factory=list)
    fault_sites: List[Tuple[str, str, int, int]] = field(default_factory=list)
    comments: List[SuppComment] = field(default_factory=list)
    #: path -> fabreg suppressions (for applying to our own findings)
    suppressions: Dict[str, Dict[int, Set[str]]] = field(default_factory=dict)
    envreg_path: Optional[str] = None
    envreg_rows: Dict[str, int] = field(default_factory=dict)  # name -> line
    fabobs_path: Optional[str] = None
    #: family -> (kind, labels, line)
    metric_table: Dict[str, Tuple[str, Tuple[str, ...], int]] = field(
        default_factory=dict
    )
    chaos_path: Optional[str] = None
    chaos_source: str = ""


def _extract_envreg(tree: ast.Module, scan: Scan) -> None:
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not any(
            isinstance(t, ast.Name) and t.id == "ENV_VARS" for t in targets
        ):
            continue
        if not isinstance(value, (ast.Tuple, ast.List)):
            continue
        for elt in value.elts:
            if not (
                isinstance(elt, ast.Call)
                and (_dotted(elt.func) or "").rsplit(".", 1)[-1] == "EnvVar"
            ):
                continue
            name: Optional[str] = None
            if elt.args and isinstance(elt.args[0], ast.Constant) and isinstance(
                elt.args[0].value, str
            ):
                name = elt.args[0].value
            for kw in elt.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
            if name:
                scan.envreg_rows[name] = elt.lineno


def _extract_metric_table(tree: ast.Module, scan: Scan) -> None:
    for node in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not any(
            isinstance(t, ast.Name) and t.id == "CANONICAL_METRICS"
            for t in targets
        ):
            continue
        if not isinstance(value, (ast.Tuple, ast.List)):
            continue
        for elt in value.elts:
            if not (
                isinstance(elt, ast.Call)
                and (_dotted(elt.func) or "").rsplit(".", 1)[-1]
                == "MetricSpec"
            ):
                continue
            fields: Dict[str, ast.expr] = {}
            for i, arg in enumerate(elt.args):
                key = ("name", "kind", "labels")[i] if i < 3 else None
                if key:
                    fields[key] = arg
            for kw in elt.keywords:
                if kw.arg:
                    fields[kw.arg] = kw.value
            name_n = fields.get("name")
            kind_n = fields.get("kind")
            labels_n = fields.get("labels")
            if not (
                isinstance(name_n, ast.Constant)
                and isinstance(name_n.value, str)
                and isinstance(kind_n, ast.Constant)
            ):
                continue
            labels: Tuple[str, ...] = ()
            if isinstance(labels_n, (ast.Tuple, ast.List)):
                labels = tuple(
                    e.value
                    for e in labels_n.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
            scan.metric_table[name_n.value] = (
                str(kind_n.value), labels, elt.lineno
            )


def _scan_comments(path: str, source: str, scan: Scan) -> None:
    """Genuine COMMENT tokens only: a ``disable=`` inside a test
    fixture *string* is data, not a suppression, and must not feed the
    stale check."""
    if "disable=" not in source:
        return
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [
            (tok.start[0], tok.start[1], tok.string)
            for tok in tokens
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return
    for line, col, text in comments:
        for tool in toolkit.ANALYZER_TOOLS:
            m = toolkit.disable_re(tool).search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            scan.comments.append(
                SuppComment(
                    tool, path, line, col, rules, (m.group(2) or "").strip()
                )
            )


def _first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def _scan_file(path: str, source: str, scan: Scan) -> None:
    ctx = FileContext(path)
    scan.sources[path] = source
    scan.suppressions[path] = toolkit.suppressed_rules(source, "fabreg")
    _scan_comments(path, source, scan)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        scan.findings.append(
            Finding(
                "syntax-error", path, exc.lineno or 1, exc.offset or 0,
                f"cannot parse: {exc.msg}",
            )
        )
        return

    is_envreg = ctx.matches(ENVREG_FILE)
    if is_envreg:
        scan.envreg_path = path
        _extract_envreg(tree, scan)
    if ctx.matches(FABOBS_FILE):
        scan.fabobs_path = path
        _extract_metric_table(tree, scan)
    if ctx.matches(CHAOS_FILE):
        scan.chaos_path = path
        scan.chaos_source = source
    in_pkg = ctx.matches(PKG_SCOPE)
    is_fabobs = ctx.matches(FABOBS_FILE)

    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            # any full env-name string keeps a registry row alive —
            # except inside the registry itself (self-reference)
            if not is_envreg and _ENV_NAME_RE.match(node.value):
                scan.env_refs.add(node.value)
            continue
        if isinstance(node, ast.Subscript):
            base = _dotted(node.value)
            if base in ("os.environ", "environ"):
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    if sl.value.startswith(ENV_PREFIX):
                        scan.env_refs.add(sl.value)
                        if isinstance(node.ctx, ast.Load):
                            scan.env_reads.append(
                                (sl.value, path, node.lineno,
                                 node.col_offset)
                            )
            continue
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func)
        if dn is None:
            continue
        leaf = dn.rsplit(".", 1)[-1]
        arg0 = _first_str_arg(node)

        if arg0 is not None and _ENV_NAME_RE.match(arg0) and not is_envreg:
            scan.env_refs.add(arg0)
            if dn in _ENV_REF_CALLS or leaf in _ENV_REF_LEAVES:
                pass  # setenv/delenv/pop reference a name, don't read it
            else:
                # a full FABRIC_TPU_* name as a call's first argument is
                # presumed an env read: direct accessors, and helper
                # wrappers like idemix/batch._env_int("FABRIC_TPU_...")
                # — a wrapper must not launder a read past the registry
                scan.env_reads.append(
                    (arg0, path, node.lineno, node.col_offset)
                )

        if in_pkg and not is_fabobs and leaf in _EMIT_SINKS:
            sink_kind, value_param = _EMIT_SINKS[leaf]
            if arg0 is not None:
                labels: Optional[Set[str]] = set()
                for kw in node.keywords:
                    if kw.arg is None:  # **labels — not statically known
                        labels = None
                        break
                    if kw.arg != value_param:
                        labels.add(kw.arg)
                scan.emits.append(
                    EmitSite(
                        arg0, sink_kind, labels, path, node.lineno,
                        node.col_offset,
                    )
                )

        if in_pkg and leaf == "fault_point" and arg0 is not None:
            scan.fault_sites.append(
                (arg0, path, node.lineno, node.col_offset)
            )


# --------------------------------------------------------------------------
# Rule evaluation
# --------------------------------------------------------------------------


def _check_env(scan: Scan, active: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    have_reg = scan.envreg_path is not None
    if "env-undeclared" in active:
        for name, path, line, col in scan.env_reads:
            if name in scan.envreg_rows:
                continue
            where = (
                f"declare it in {scan.envreg_path}"
                if have_reg
                else "no env registry (common/envreg.py) found in the "
                "scanned tree"
            )
            out.append(
                Finding(
                    "env-undeclared", path, line, col,
                    f"read of undeclared env var {name!r}: {where} "
                    f"(name/type/default/consumer/doc)",
                )
            )
    if "env-dead" in active and have_reg:
        for name, line in sorted(scan.envreg_rows.items()):
            if name not in scan.env_refs:
                out.append(
                    Finding(
                        "env-dead", scan.envreg_path or "", line, 0,
                        f"registry row {name!r} has no reader anywhere in "
                        f"the scanned tree (bench.py/tests count); delete "
                        f"the row or the feature it described",
                    )
                )
    return out


def _check_metrics(scan: Scan, active: Set[str]) -> List[Finding]:
    out: List[Finding] = []
    have_table = scan.fabobs_path is not None
    for e in scan.emits:
        spec = scan.metric_table.get(e.family)
        if spec is None:
            if "metric-unknown" in active:
                where = (
                    f"add it to CANONICAL_METRICS in {scan.fabobs_path}"
                    if have_table
                    else "no CANONICAL_METRICS table (common/fabobs.py) "
                    "found in the scanned tree"
                )
                out.append(
                    Finding(
                        "metric-unknown", e.path, e.line, e.col,
                        f"emit names unknown family {e.family!r}: the "
                        f"registry drops it at runtime; {where}",
                    )
                )
            continue
        if "metric-label-drift" not in active:
            continue
        kind, labels, _line = spec
        if e.sink_kind != kind:
            out.append(
                Finding(
                    "metric-label-drift", e.path, e.line, e.col,
                    f"{e.family!r} is declared a {kind} but emitted via "
                    f"the {e.sink_kind} sink",
                )
            )
        if e.labels is not None and e.labels != set(labels):
            declared = ",".join(labels) or "(none)"
            got = ",".join(sorted(e.labels)) or "(none)"
            out.append(
                Finding(
                    "metric-label-drift", e.path, e.line, e.col,
                    f"{e.family!r} declares labels ({declared}) but this "
                    f"emit passes ({got}); the SPI raises and the sample "
                    f"is swallowed",
                )
            )
    if "metric-orphan" in active and have_table:
        emitted = {e.family for e in scan.emits}
        for family, (_kind, _labels, line) in sorted(
            scan.metric_table.items()
        ):
            if family not in emitted:
                out.append(
                    Finding(
                        "metric-orphan", scan.fabobs_path or "", line, 0,
                        f"canonical family {family!r} has no emitter "
                        f"outside fabobs: a dead # TYPE line on every "
                        f"scrape; emit it or delete the row",
                    )
                )
    return out


def _check_fault_sites(
    scan: Scan, active: Set[str], readme_text: Optional[str]
) -> List[Finding]:
    if "fault-site-drift" not in active:
        return []
    out: List[Finding] = []
    for site, path, line, col in scan.fault_sites:
        problems: List[str] = []
        if readme_text is not None and site not in readme_text:
            problems.append("missing from the README fault-point table")
        if scan.chaos_path is None:
            problems.append(
                "no fabchaos scenario file (tools/fabchaos.py) in the "
                "scanned tree"
            )
        elif site not in scan.chaos_source:
            problems.append(
                "not exercised by any fabchaos scenario"
            )
        if problems:
            out.append(
                Finding(
                    "fault-site-drift", path, line, col,
                    f"fault site {site!r} is {'; '.join(problems)} "
                    f"(document + exercise it, or suppress with a reason)",
                )
            )
    return out


# -- suppression-stale -------------------------------------------------------


# the staleness protocol's shared normalizer (both sides of the
# live-keys comparison must match byte-for-byte)
_norm = toolkit.normalize_path


def _pkg_root_for(path: str) -> Optional[Path]:
    """The topmost package dir containing ``path`` (walk up while
    __init__.py is present) — what fabdep.analyze wants as its root."""
    p = Path(path).resolve()
    if not p.exists():
        return None
    cur = p.parent
    root: Optional[Path] = None
    while (cur / "__init__.py").exists():
        root = cur
        cur = cur.parent
    return root


def _live_keys_fablint(
    comments: List[SuppComment], scan: Scan
) -> Set[Tuple[str, int, str]]:
    from fabric_tpu.tools import fablint

    live: Set[Tuple[str, int, str]] = set()
    by_file: Dict[str, Set[str]] = {}
    for c in comments:
        by_file.setdefault(c.path, set()).update(c.rules)
    for path, rules in by_file.items():
        source = scan.sources.get(path)
        if source is None:
            continue
        needed = set(fablint.RULES) if "all" in rules else (
            rules & set(fablint.RULES)
        )
        if not needed:
            continue
        suppressed: List[Finding] = []
        fablint.lint_source(source, path, needed, suppressed)
        for f in suppressed:
            live.add((_norm(f.path), f.line, f.rule))
    return live


def _live_keys_fabflow(
    comments: List[SuppComment], scan: Scan
) -> Set[Tuple[str, int, str]]:
    from fabric_tpu.tools import fabflow

    needed: Set[str] = set()
    for c in comments:
        needed |= c.rules
    needed = set(fabflow.RULES) if "all" in needed else (
        needed & set(fabflow.RULES)
    )
    if not needed:
        return set()
    # mirror the flow_gate scope: fabflow analyzes the package tree,
    # not tests/bench (and skipping those files saves ~1s per gate run)
    pkg_sources = {
        path: src
        for path, src in scan.sources.items()
        if FileContext(path).matches(PKG_SCOPE)
    }
    suppressed: List[Finding] = []
    fabflow.analyze_sources(pkg_sources, needed, suppressed)
    return {(_norm(f.path), f.line, f.rule) for f in suppressed}


def _live_keys_fabdep(
    comments: List[SuppComment],
) -> Set[Tuple[str, int, str]]:
    from fabric_tpu.tools import fabdep

    live: Set[Tuple[str, int, str]] = set()
    roots: Dict[Path, Set[str]] = {}
    for c in comments:
        root = _pkg_root_for(c.path)
        if root is not None:
            roots.setdefault(root, set()).update(c.rules)
    for root, rules in roots.items():
        needed = set(fabdep.RULES) if "all" in rules else (
            rules & set(fabdep.RULES)
        )
        if not needed:
            continue
        layer_map = None
        layer_file = fabdep.default_layer_file(root)
        if layer_file is not None:
            try:
                layer_map = fabdep.LayerMap.parse(
                    layer_file.read_text(encoding="utf-8"), str(layer_file)
                )
            except (OSError, ValueError):
                layer_map = None
        program, _findings = fabdep.analyze(
            root,
            layer_map,
            fabdep.default_ref_paths(root),
            needed,
            skip_unneeded_passes=True,
        )
        for f in program.suppressed_findings:
            live.add((_norm(f.path), f.line, f.rule))
    return live


def _live_keys_registered(
    tool: str, comments: List[SuppComment], scan: Scan
) -> Set[Tuple[str, int, str]]:
    """Staleness for a registry-declared analyzer: lazily import its
    module and ask its ``live_suppression_keys(sources, rules)``
    protocol hook (see toolkit.AnalyzerSpec)."""
    spec = toolkit.analyzer_spec(tool)
    if spec is None:
        return set()
    try:
        import importlib

        module = importlib.import_module(spec.module)
        hook = getattr(module, "live_suppression_keys")
    except (ImportError, AttributeError):
        # a registry row without a reachable protocol hook judges
        # nothing (its comments are all reported stale — loud, so the
        # drift is fixed, rather than silently un-checked)
        return set()
    needed: Set[str] = set()
    for c in comments:
        needed |= c.rules
    try:
        return set(hook(dict(scan.sources), needed))
    except (OSError, ValueError):
        # unreadable/malformed analyzer config (e.g. pairs.toml gone):
        # judge nothing — the comments all read stale, loudly
        return set()


def _check_suppression_stale(
    scan: Scan, active: Set[str], own_suppressed: List[Finding]
) -> List[Finding]:
    if "suppression-stale" not in active:
        return []
    by_tool: Dict[str, List[SuppComment]] = {}
    for c in scan.comments:
        spec = toolkit.analyzer_spec(c.tool)
        if spec is not None and spec.pkg_scope_only and not (
            FileContext(c.path).matches(PKG_SCOPE)
        ):
            # a gate that only analyzes the package tree never honors
            # comments outside it — they are inert, not stale; tools
            # whose gates also scan tests/ and bench.py (fabreg,
            # fablife) declare pkg_scope_only=False in the registry and
            # are judged everywhere they are honored
            continue
        by_tool.setdefault(c.tool, []).append(c)

    live: Dict[str, Set[Tuple[str, int, str]]] = {}
    if by_tool.get("fablint"):
        live["fablint"] = _live_keys_fablint(by_tool["fablint"], scan)
    if by_tool.get("fabflow"):
        live["fabflow"] = _live_keys_fabflow(by_tool["fabflow"], scan)
    if by_tool.get("fabdep"):
        live["fabdep"] = _live_keys_fabdep(by_tool["fabdep"])
    live["fabreg"] = {
        (_norm(f.path), f.line, f.rule) for f in own_suppressed
    }
    # post-toolkit analyzers (fablife, and any future registry row):
    # resolved through the toolkit registry's staleness protocol, so a
    # sixth analyzer is picked up without editing this function
    for tool, comments in by_tool.items():
        if tool in toolkit.LEGACY_ANALYZER_TOOLS:
            continue
        live[tool] = _live_keys_registered(tool, comments, scan)

    out: List[Finding] = []
    for tool, comments in sorted(by_tool.items()):
        tool_live = live.get(tool, set())
        tool_rules = None
        if tool == "fabreg":
            tool_rules = set(RULES)
        for c in comments:
            key_path = _norm(c.path)
            fired_any = any(
                k[0] == key_path and k[1] == c.line for k in tool_live
            )
            for rule in sorted(c.rules):
                if tool == "fabreg" and rule == "suppression-stale":
                    continue  # one level deep: never self-report
                if rule == "all":
                    dead = not fired_any
                else:
                    dead = (key_path, c.line, rule) not in tool_live
                    if tool_rules is not None and rule not in tool_rules:
                        # unknown rule id in a fabreg comment: dead by
                        # definition (typo'd suppressions silence nothing)
                        dead = True
                if dead:
                    out.append(
                        Finding(
                            "suppression-stale", c.path, c.line, c.col,
                            f"'# {tool}: disable={rule}' no longer "
                            f"suppresses anything here (the {tool} "
                            f"finding it absorbed is gone); delete the "
                            f"comment so the suppression does not "
                            f"outlive its cause",
                        )
                    )
    return out


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------


def find_readme(paths: Sequence[str]) -> Optional[str]:
    """Default README resolution: next to or one level above any
    scanned directory."""
    for raw in paths:
        p = Path(raw)
        base = p if p.is_dir() else p.parent
        for cand in (base / "README.md", base.parent / "README.md"):
            if cand.is_file():
                return str(cand)
    return None


def analyze_sources(
    sources: Dict[str, str],
    rule_ids: Optional[Iterable[str]] = None,
    readme_text: Optional[str] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Analyze {path: source}.  Paths that exist on disk additionally
    feed the fabdep half of suppression-stale (fabdep needs a real
    package root); fablint/fabflow/fabreg staleness is computed
    in-memory."""
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    for rid in active:
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
    scan = Scan()
    for path, source in sources.items():
        _scan_file(path, source, scan)

    # suppression-stale judges fabreg's OWN comments by whether their
    # rule fires at that line — that baseline needs every rule
    # evaluated even when the caller asked for a subset (only the
    # active rules are *reported*)
    eval_rules = (
        set(RULES) if "suppression-stale" in active else set(active)
    )
    raw: List[Finding] = list(scan.findings)  # syntax errors
    raw += _check_env(scan, eval_rules)
    raw += _check_metrics(scan, eval_rules)
    raw += _check_fault_sites(scan, eval_rules, readme_text)

    findings: List[Finding] = []
    suppressed_all: List[Finding] = []
    n_suppressed = 0
    for f in raw:
        kept_f, supp_f = toolkit.apply_suppressions(
            [f], scan.suppressions.get(f.path, {})
        )
        findings += [
            k for k in kept_f if k.rule in active or k.rule == "syntax-error"
        ]
        suppressed_all += supp_f
        n_suppressed += sum(1 for s in supp_f if s.rule in active)

    stale = _check_suppression_stale(scan, active, suppressed_all)
    for f in stale:
        kept_f, supp_f = toolkit.apply_suppressions(
            [f], scan.suppressions.get(f.path, {})
        )
        findings += kept_f
        n_suppressed += len(supp_f)

    findings.sort(key=Finding.key)
    stats = {"files": len(sources), "suppressed": n_suppressed}
    return findings, stats


def analyze_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
    readme_text: Optional[str] = None,
) -> Tuple[List[Finding], int]:
    """Single-blob convenience (fixtures/tests)."""
    findings, stats = analyze_sources({path: source}, rule_ids, readme_text)
    return findings, stats["suppressed"]


def analyze_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    readme: Optional[str] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    files = iter_py_files(paths, excludes)
    sources, io_findings = toolkit.read_sources(files)
    readme_text: Optional[str] = None
    readme_path = readme if readme is not None else find_readme(paths)
    if readme_path:
        try:
            readme_text = Path(readme_path).read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as exc:
            io_findings.append(
                Finding("io-error", readme_path, 1, 0, str(exc))
            )
    findings, stats = analyze_sources(sources, rule_ids, readme_text)
    findings.extend(io_findings)
    findings.sort(key=Finding.key)
    stats["files"] = len(files)
    return findings, stats


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fabreg",
        "declarative-contract drift analyzer for fabric-tpu "
        "(dependency-free; never imports the analyzed code)",
    )
    parser.add_argument(
        "--readme",
        metavar="FILE",
        help="README carrying the fault-point table (default: "
        "README.md beside or above a scanned directory)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(RULES, width=20)
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fabreg", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fabreg")
    if rc:
        return rc
    if args.readme and not Path(args.readme).is_file():
        print(
            f"fabreg: error: no such file: {args.readme}", file=sys.stderr
        )
        return 2

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = analyze_paths(
        args.paths, rule_ids, excludes, readme=args.readme
    )

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fabreg: {len(findings)} finding(s) in {stats['files']} "
            f"file(s) ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
