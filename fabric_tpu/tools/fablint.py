"""fablint — AST-based invariant linter for the fabric-tpu codebase.

The pipeline's correctness contract is bit-exactness of the VALID/INVALID
mask across backend tiers.  The bug classes that silently break that
contract — swallowed exceptions in verify paths, module-scope imports of
optional packages that kill test collection — are exactly what static
analysis catches before a bench run ever does.  fablint walks the AST of
every source file (it never imports the code it inspects, so it runs in
minimal environments without ``cryptography``/``jax``) and enforces ~10
project-specific rules.  (The jit-impure rule moved to fabtrace in PR 18,
promoted from this file's name heuristic to real dataflow over traced
bodies.)

Rules
-----
module-import    module-scope import of a heavy/optional third-party
                 package (cryptography, grpc, jax) outside the allowlist
                 and not guarded by try/except ImportError.  Generalizes
                 the collect-gate: one unguarded import poisons
                 ``pytest --collect-only`` in minimal environments.
broad-except     bare ``except:`` anywhere, or ``except Exception`` in
                 the mask-critical paths (crypto/, validation/, ledger/,
                 ops/, msp/, policy/, idemix/, parallel/, serve/) whose handler
                 neither re-raises nor logs: a silently swallowed
                 exception in a verify path flips lanes VALID.
mutable-default  ``def f(x=[])`` — the default is shared across calls.
limb-dtype       integer literal > 2**32 fed to an array constructor
                 without an explicit ``dtype=``: platform-default int
                 truncates limbs and corrupts the bignum pipeline.
assert-security  ``assert`` in crypto/, validation/, msp/, idemix/ —
                 asserts vanish under ``python -O``; a validation
                 decision must be an explicit raise or mask write.
digest-compare   ``==``/``!=`` on digest/mac/checksum values; use
                 ``hmac.compare_digest`` for constant-time comparison.
                 (deliberately NOT ``signature``: ECDSA r/s are public
                 values here, and the token matches policy-type enums
                 like ``P.SIGNATURE`` all over the codebase.)
shell-injection  ``subprocess`` with ``shell=True``, ``os.system``,
                 ``os.popen``.
fork-start       multiprocessing ``"fork"`` start method — fork with
                 live threads (gRPC, XLA) wedges workers; the repo
                 invariant is forkserver/spawn (crypto/hostec.py).
all-drift        a name exported in a package ``__init__``'s ``__all__``
                 that is not actually defined/imported in the module.

Suppression
-----------
Per line: ``# fablint: disable=rule-id[,rule-id...]  # <reason>`` on the
line the finding is reported at (for an except clause: the ``except``
line; for a def: the ``def`` line).  ``disable=all`` silences every rule
for that line.  Suppressions should carry a justification comment.

Exclusions
----------
Generated and non-Python artifacts are skipped: ``*_pb2.py``,
``__pycache__``, ``native/``, ``protos/src/``.

Usage
-----
    python -m fabric_tpu.tools.fablint [--json] [--list-rules]
                                       [--rules a,b] PATH...

Exit status: 0 = clean, 1 = findings, 2 = usage/IO error.
"""

from __future__ import annotations

import ast
import json
import sys
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from fabric_tpu.tools import toolkit
from fabric_tpu.tools.toolkit import (  # noqa: F401 - re-exported API
    DEFAULT_EXCLUDES,
    FileContext,
    Finding,
    iter_py_files,
)

__version__ = "1.0"

# --------------------------------------------------------------------------
# Configuration
# --------------------------------------------------------------------------

#: Heavy / optional third-party roots whose module-scope import breaks
#: collection in minimal environments (or costs seconds at import time).
HEAVY_PACKAGES = {"cryptography", "grpc", "jax", "jaxlib", "numpy"}

#: Files allowed to import a heavy package at module scope: the device
#: kernel layer imports jax unconditionally by design (nothing imports it
#: in a CPU-only test run without wanting jax), and comm/ IS the gRPC
#: layer.  numpy is the data plane's array substrate — the flags
#: bitmask, device kernels, validators and shard plumbing are
#: numpy-native by design — but everywhere else (the host crypto
#: ladder, tools, msp, common/p256) it must stay out of module scope or
#: ride a guarded import, so the hostec_np tier degrades instead of
#: breaking imports when numpy is absent.  Patterns are fnmatch globs
#: against the posix path.
MODULE_IMPORT_ALLOW: Dict[str, Tuple[str, ...]] = {
    "jax": (
        "*fabric_tpu/ops/*",
        "*fabric_tpu/ledger/mvcc_device.py",
        "*fabric_tpu/policy/evaluator.py",
    ),
    "jaxlib": ("*fabric_tpu/ops/*",),
    "grpc": ("*fabric_tpu/comm/*",),
    "numpy": (
        "*fabric_tpu/ops/*",
        "*fabric_tpu/common/txflags.py",
        "*fabric_tpu/crypto/tpu_provider.py",
        "*fabric_tpu/ledger/mvcc_device.py",
        "*fabric_tpu/parallel/*",
        "*fabric_tpu/policy/evaluator.py",
        "*fabric_tpu/policy/manager.py",
        "*fabric_tpu/utils/native.py",
        "*fabric_tpu/validation/blockparse.py",
        "*fabric_tpu/validation/validator.py",
    ),
}

#: Directories whose exception discipline is load-bearing for the
#: VALID/INVALID mask: a swallowed exception here flips lanes silently.
MASK_CRITICAL_DIRS = (
    "*fabric_tpu/crypto/*",
    "*fabric_tpu/validation/*",
    "*fabric_tpu/ledger/*",
    "*fabric_tpu/ops/*",
    "*fabric_tpu/msp/*",
    "*fabric_tpu/policy/*",
    "*fabric_tpu/idemix/*",
    "*fabric_tpu/parallel/*",
    "*fabric_tpu/serve/*",
)

#: Directories where ``assert`` must not guard validation decisions.
ASSERT_SECURITY_DIRS = (
    "*fabric_tpu/crypto/*",
    "*fabric_tpu/validation/*",
    "*fabric_tpu/msp/*",
    "*fabric_tpu/idemix/*",
)

_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
}

_SECRET_TOKENS = {"digest", "hmac", "mac", "checksum"}

_ARRAY_CTORS = {
    "array", "asarray", "full", "full_like", "arange", "constant",
}
_ARRAY_ROOTS = {"np", "jnp", "numpy", "jax"}

_LIMB_LIMIT = 2 ** 32


# --------------------------------------------------------------------------
# Core machinery (Finding/FileContext/walker live in tools.toolkit —
# the chassis shared with fabdep/fabflow/fabreg)
# --------------------------------------------------------------------------


RuleFn = Callable[[ast.Module, str, FileContext], List[Finding]]

#: rule-id -> (one-line doc, checker)
RULES: Dict[str, Tuple[str, RuleFn]] = {}


def rule(rule_id: str, doc: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        RULES[rule_id] = (doc, fn)
        return fn

    return deco


def parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map 1-based line number -> set of rule ids disabled on that line."""
    return toolkit.suppressed_rules(source, "fablint")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ident_tokens(node: ast.AST) -> Set[str]:
    """Lower-cased underscore-split tokens of a Name/Attribute identifier."""
    name: Optional[str] = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name is not None:
            name = name.rsplit(".", 1)[-1]
    if not name:
        return set()
    return {tok for tok in name.lower().split("_") if tok}


# --------------------------------------------------------------------------
# Rules
# --------------------------------------------------------------------------


def _catches_import_error(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    names = []
    if isinstance(handler.type, ast.Tuple):
        names = [_dotted(e) for e in handler.type.elts]
    else:
        names = [_dotted(handler.type)]
    return any(
        n in ("ImportError", "ModuleNotFoundError", "Exception", "BaseException")
        for n in names
        if n
    )


@rule(
    "module-import",
    "module-scope import of a heavy/optional package (cryptography, grpc, "
    "jax) outside the allowlist and not guarded by try/except ImportError",
)
def check_module_import(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []

    def heavy_roots(node: ast.stmt) -> List[Tuple[str, int, int]]:
        out = []
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in HEAVY_PACKAGES:
                    out.append((root, node.lineno, node.col_offset))
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            root = node.module.split(".")[0]
            if root in HEAVY_PACKAGES:
                out.append((root, node.lineno, node.col_offset))
        return out

    def scan(body: Sequence[ast.stmt], guarded: bool) -> None:
        for node in body:
            for root, line, col in heavy_roots(node):
                if guarded:
                    continue
                allow = MODULE_IMPORT_ALLOW.get(root, ())
                if ctx.matches(allow):
                    continue
                findings.append(
                    Finding(
                        "module-import", ctx.path, line, col,
                        f"module-scope import of {root!r} is unguarded: wrap "
                        f"in try/except ImportError or move into the "
                        f"function that needs it (breaks collection in "
                        f"minimal environments)",
                    )
                )
            if isinstance(node, ast.Try):
                has_guard = any(_catches_import_error(h) for h in node.handlers)
                scan(node.body, guarded or has_guard)
                scan(node.orelse, guarded)
                scan(node.finalbody, guarded)
                for h in node.handlers:
                    scan(h.body, guarded)
            elif isinstance(node, ast.If):
                test = _dotted(node.test)
                type_checking = test in ("TYPE_CHECKING", "typing.TYPE_CHECKING")
                scan(node.body, guarded or type_checking)
                scan(node.orelse, guarded)
            elif isinstance(node, ast.With):
                scan(node.body, guarded)

    scan(tree.body, guarded=False)
    return findings


def _is_logging_call(call: ast.Call) -> bool:
    """A log-method call on a logger-ish receiver: ``logger.warning(...)``,
    ``warnings.warn(...)``, ``self._log.debug(...)``,
    ``must_get_logger(...).error(...)`` — but NOT ``math.log(2)`` or
    ``obj.error()`` (an unrelated leaf-name match must not silence the
    broad-except rule)."""
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in _LOG_METHODS:
        return False
    recv = func.value
    if isinstance(recv, ast.Call):
        return True  # logger factory: must_get_logger(...)/getLogger(...)
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    else:
        return False
    name = name.lower()
    return "log" in name or name == "warnings"


def _handler_handles(handler: ast.ExceptHandler) -> bool:
    """True if the handler re-raises or logs (incl. warnings.warn)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and _is_logging_call(node):
            return True
    return False


@rule(
    "broad-except",
    "bare 'except:' anywhere, or 'except Exception' in mask-critical paths "
    "(crypto/, validation/, ledger/, ops/, msp/, policy/, idemix/, serve/, "
    "parallel/) that neither re-raises nor logs",
)
def check_broad_except(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    mask_critical = ctx.matches(MASK_CRITICAL_DIRS)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(
                Finding(
                    "broad-except", ctx.path, node.lineno, node.col_offset,
                    "bare 'except:' also swallows KeyboardInterrupt/"
                    "SystemExit; catch Exception (or narrower) and handle it",
                )
            )
            continue
        types = (
            [_dotted(e) for e in node.type.elts]
            if isinstance(node.type, ast.Tuple)
            else [_dotted(node.type)]
        )
        broad = any(t in ("Exception", "BaseException") for t in types if t)
        if broad and mask_critical and not _handler_handles(node):
            findings.append(
                Finding(
                    "broad-except", ctx.path, node.lineno, node.col_offset,
                    "broad except in a mask-critical path must re-raise, "
                    "log, or explicitly mark the affected lane INVALID "
                    "(suppress with a justification if the catch is "
                    "deliberate)",
                )
            )
    return findings


_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "defaultdict", "deque", "Counter"}


@rule(
    "mutable-default",
    "mutable default argument (list/dict/set literal or constructor) is "
    "shared across calls",
)
def check_mutable_default(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for d in defaults:
            bad = isinstance(
                d, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
            )
            if isinstance(d, ast.Call):
                dn = _dotted(d.func)
                if dn and dn.rsplit(".", 1)[-1] in _MUTABLE_CTORS:
                    bad = True
            if bad:
                findings.append(
                    Finding(
                        "mutable-default", ctx.path, node.lineno, node.col_offset,
                        f"function {node.name!r} has a mutable default "
                        f"argument; use None and create it in the body",
                    )
                )
    return findings


# jit-impure lived here through PR 17 as a name heuristic over
# syntactically-jitted functions; PR 18 moved it to fabtrace, which owns
# the traced-body dataflow (mutable module state, os.environ) the
# heuristic could not see.


def _looks_like_dtype(node: ast.AST) -> bool:
    """A positional arg that is itself a dtype: np.uint64, jnp.uint32,
    object, np.dtype(...) — dtype is the documented second positional
    arg of array/asarray (third of full)."""
    if isinstance(node, ast.Call):
        node = node.func
    dn = _dotted(node)
    if dn is None:
        return False
    leaf = dn.rsplit(".", 1)[-1].lower()
    return any(
        t in leaf for t in ("int", "float", "bool", "complex", "object", "dtype")
    )


@rule(
    "limb-dtype",
    "integer literal > 2**32 passed to an array constructor without an "
    "explicit dtype= (platform-default int truncates limbs)",
)
def check_limb_dtype(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func)
        if dn is None or "." not in dn:
            continue
        root, leaf = dn.split(".", 1)[0], dn.rsplit(".", 1)[-1]
        if root not in _ARRAY_ROOTS or leaf not in _ARRAY_CTORS:
            continue
        if any(kw.arg == "dtype" for kw in node.keywords):
            continue
        if any(_looks_like_dtype(a) for a in node.args[1:]):
            continue
        for arg in node.args:
            for sub in ast.walk(arg):
                if (
                    isinstance(sub, ast.Constant)
                    and isinstance(sub.value, int)
                    and not isinstance(sub.value, bool)
                    and abs(sub.value) >= _LIMB_LIMIT
                ):
                    findings.append(
                        Finding(
                            "limb-dtype", ctx.path, node.lineno, node.col_offset,
                            f"integer literal {sub.value:#x} fed to {dn} "
                            f"without dtype=: pass an explicit uint32/uint64 "
                            f"(or object) dtype",
                        )
                    )
                    break
            else:
                continue
            break
    return findings


@rule(
    "assert-security",
    "'assert' in crypto/, validation/, msp/, idemix/ — asserts vanish "
    "under python -O; use an explicit raise",
)
def check_assert_security(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    if not ctx.matches(ASSERT_SECURITY_DIRS):
        return []
    return [
        Finding(
            "assert-security", ctx.path, node.lineno, node.col_offset,
            "assert is compiled out under python -O; validation/crypto "
            "decisions must use an explicit raise or mask write",
        )
        for node in ast.walk(tree)
        if isinstance(node, ast.Assert)
    ]


@rule(
    "digest-compare",
    "==/!= on digest/mac/checksum values; use hmac.compare_digest for "
    "constant-time comparison",
)
def check_digest_compare(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            continue
        sides = [node.left] + list(node.comparators)
        # Comparing against None/sentinel literals is not a timing oracle.
        if any(isinstance(s, ast.Constant) and s.value is None for s in sides):
            continue
        if any(_ident_tokens(s) & _SECRET_TOKENS for s in sides):
            findings.append(
                Finding(
                    "digest-compare", ctx.path, node.lineno, node.col_offset,
                    "digest/mac compared with ==: use hmac.compare_digest "
                    "to avoid a timing side channel",
                )
            )
    return findings


@rule(
    "shell-injection",
    "subprocess with shell=True, os.system, or os.popen",
)
def check_shell_injection(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func)
        if dn in ("os.system", "os.popen"):
            findings.append(
                Finding(
                    "shell-injection", ctx.path, node.lineno, node.col_offset,
                    f"{dn} runs through the shell; use subprocess with an "
                    f"argv list",
                )
            )
            continue
        is_subprocess = bool(dn) and (
            dn.startswith("subprocess.") or dn in ("Popen", "run", "check_output", "check_call", "call")
        )
        if not is_subprocess:
            continue
        for kw in node.keywords:
            if (
                kw.arg == "shell"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                findings.append(
                    Finding(
                        "shell-injection", ctx.path, node.lineno, node.col_offset,
                        "shell=True interpolates arguments through the "
                        "shell; pass an argv list instead",
                    )
                )
    return findings


@rule(
    "fork-start",
    "multiprocessing 'fork' start method; the repo invariant is "
    "forkserver/spawn (fork with live gRPC/XLA threads wedges workers)",
)
def check_fork_start(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dn = _dotted(node.func)
        if dn is None:
            continue
        leaf = dn.rsplit(".", 1)[-1]
        if leaf not in ("get_context", "set_start_method"):
            continue
        values = [a for a in node.args] + [kw.value for kw in node.keywords]
        if any(
            isinstance(v, ast.Constant) and v.value == "fork" for v in values
        ):
            findings.append(
                Finding(
                    "fork-start", ctx.path, node.lineno, node.col_offset,
                    f"{leaf}('fork') is unsafe with live threads "
                    f"(gRPC/XLA); use 'forkserver' or 'spawn'",
                )
            )
    return findings


def _module_scope_names(body: Sequence[ast.stmt]) -> Tuple[Set[str], bool]:
    """Names bound at module scope (recursing into try/if/with).

    Returns (names, has_star_import).
    """
    names: Set[str] = set()
    star = False
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    star = True
                else:
                    names.add(alias.asname or alias.name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
        elif isinstance(node, ast.Try):
            for sub_body in (node.body, node.orelse, node.finalbody):
                n, s = _module_scope_names(sub_body)
                names |= n
                star |= s
            for h in node.handlers:
                n, s = _module_scope_names(h.body)
                names |= n
                star |= s
        elif isinstance(node, (ast.If, ast.For, ast.While)):
            n, s = _module_scope_names(node.body)
            names |= n
            star |= s
            n, s = _module_scope_names(node.orelse)
            names |= n
            star |= s
        elif isinstance(node, ast.With):
            n, s = _module_scope_names(node.body)
            names |= n
            star |= s
    return names, star


@rule(
    "all-drift",
    "__all__ exports a name the package __init__ never defines or imports",
)
def check_all_drift(tree: ast.Module, source: str, ctx: FileContext) -> List[Finding]:
    if Path(ctx.path).name != "__init__.py":
        return []
    exported: List[Tuple[str, int, int]] = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
        ):
            if isinstance(node.value, (ast.List, ast.Tuple)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        exported.append((elt.value, elt.lineno, elt.col_offset))
    if not exported:
        return []
    defined, star = _module_scope_names(tree.body)
    if star:
        return []  # can't resolve star imports statically
    return [
        Finding(
            "all-drift", ctx.path, line, col,
            f"__all__ exports {name!r} but the module never defines or "
            f"imports it",
        )
        for name, line, col in exported
        if name not in defined
    ]


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------


def lint_source(
    source: str,
    path: str,
    rule_ids: Optional[Iterable[str]] = None,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], int]:
    """Lint one source blob.  Returns (findings, suppressed_count).
    When ``collect_suppressed`` is given, the findings a per-line
    suppression absorbed are appended to it (fabreg's
    suppression-stale rule uses this to prove each comment still
    earns its keep)."""
    ctx = FileContext(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return (
            [
                Finding(
                    "syntax-error", path, exc.lineno or 1, exc.offset or 0,
                    f"cannot parse: {exc.msg}",
                )
            ],
            0,
        )
    suppressions = parse_suppressions(source)
    active = set(rule_ids) if rule_ids is not None else set(RULES)
    raw: List[Finding] = []
    for rid in sorted(active):
        if rid not in RULES:
            raise ValueError(f"unknown rule id {rid!r}")
        _, fn = RULES[rid]
        raw.extend(fn(tree, source, ctx))
    findings, suppressed = toolkit.apply_suppressions(raw, suppressions)
    if collect_suppressed is not None:
        collect_suppressed.extend(suppressed)
    findings.sort(key=Finding.key)
    return findings, len(suppressed)


def lint_paths(
    paths: Sequence[str],
    rule_ids: Optional[Iterable[str]] = None,
    excludes: Sequence[str] = DEFAULT_EXCLUDES,
    collect_suppressed: Optional[List[Finding]] = None,
) -> Tuple[List[Finding], Dict[str, int]]:
    """Lint files/directories.  Returns (findings, stats)."""
    files = iter_py_files(paths, excludes)
    findings: List[Finding] = []
    suppressed = 0
    for f in files:
        try:
            source = Path(f).read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as exc:
            findings.append(Finding("io-error", f, 1, 0, str(exc)))
            continue
        file_findings, file_suppressed = lint_source(
            source, f, rule_ids, collect_suppressed
        )
        findings.extend(file_findings)
        suppressed += file_suppressed
    findings.sort(key=Finding.key)
    stats = {"files": len(files), "suppressed": suppressed}
    return findings, stats


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = toolkit.build_parser(
        "fablint",
        "AST-based invariant linter for fabric-tpu "
        "(dependency-free; never imports the linted code)",
        paths_help="files or directories to lint",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        toolkit.print_rule_list(
            {rid: doc for rid, (doc, _fn) in RULES.items()}, width=18
        )
        return 0

    rc = toolkit.check_paths_exist(args.paths, "fablint", parser)
    if rc:
        return rc
    rule_ids, rc = toolkit.parse_rule_arg(args.rules, RULES, "fablint")
    if rc:
        return rc

    excludes = tuple(DEFAULT_EXCLUDES) + tuple(args.exclude)
    findings, stats = lint_paths(args.paths, rule_ids, excludes)

    if args.json:
        print(
            json.dumps(
                {
                    "version": __version__,
                    "files": stats["files"],
                    "suppressed": stats["suppressed"],
                    "findings": [f.to_dict() for f in findings],
                },
                indent=2,
            )
        )
    else:
        toolkit.print_findings(findings)
        print(
            f"fablint: {len(findings)} finding(s) in {stats['files']} file(s)"
            f" ({stats['suppressed']} suppressed)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
